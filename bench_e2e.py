"""End-to-end (rollout + learner) benchmarks: the five BASELINE.md configs.

Counterpart of the reference's tuned-example benchmark runs
(``rllib/tuned_examples/ppo/pong-ppo.yaml:1``,
``impala/pong-impala.yaml:1-5``, ``sac/halfcheetah-sac.yaml:1``): each
config builds the real Algorithm (CPU rollout actors + TPU learner),
trains under a wall-clock budget, and records a reward-vs-env-steps
curve plus end-to-end env-steps/s (total wall clock, sampling AND
learning included).

Stand-ins, documented: ALE and PettingZoo are not in this image, so
Pong/Breakout run on the in-repo Atari-shaped ``PongLite-v0``
(``ray_tpu/env/pong_lite.py``: 84x84 uint8 pixels, framestack 4,
genuine tracking task; random ~-11/episode, oracle +21) and the
multi-agent pistonball slot runs shared-policy PPO on N-agent
multi-CartPole (``env/multi_agent_env.py make_multi_agent``).
HalfCheetah is the real MuJoCo task. The driver host exposes ONE CPU
core, so rollout throughput is host-bound in a way the reference's
32-128-worker clusters were not; the learner-side headline lives in
``bench.py``.

Writes one JSON artifact per config under ``benchmarks/e2e/`` and
prints ONE summary JSON line. Usage:

    python bench.py --e2e [--only NAME] [--budget SECONDS]
"""

import json
import pathlib
import sys
import time

import numpy as np

ARTIFACT_DIR = pathlib.Path(__file__).parent / "benchmarks" / "e2e"


def _ppo_cartpole():
    # FUSED LANE (ROADMAP 5a): the jax-native CartPole rolls out ON
    # the learner mesh and rollout+GAE+the SGD nest dispatch as one
    # fused superstep program (jax_fused_rollout, superstep="auto") —
    # zero rollout bytes over H2D. The old actor-lane variant of this
    # config lives on as `plumbing_ppo` (SyntheticFast) for sampler-
    # loop trend continuity; fixed-seed trajectory parity between the
    # two lanes is tests/test_jax_env.py's contract.
    import ray_tpu.env.jax_control  # noqa: F401  registers CartPoleJax-v0
    from ray_tpu.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment("CartPoleJax-v0", env_backend="jax")
        .rollouts(
            num_rollout_workers=0,
            num_envs_per_worker=32,
            rollout_fragment_length=64,
        )
        .training(
            gamma=0.99, lr=3e-4, lambda_=0.95,
            train_batch_size=2048, sgd_minibatch_size=256,
            num_sgd_iter=8, entropy_coeff=0.01, clip_param=0.2,
            kl_coeff=0.0, model={"fcnet_hiddens": [256, 256]},
        )
        .debugging(seed=0)
    )


def _ppo_pong():
    # reference geometry: ppo/pong-ppo.yaml (1 GPU + 32 workers).
    # FUSED LANE (ROADMAP 5a): PongLiteJax rolls the pixel env out on
    # the learner mesh — the rollout+learn superstep replaces the
    # 2-worker CPU sampler ensemble the earlier rounds measured
    import ray_tpu.env.jax_pong  # noqa: F401  registers PongLiteJax-v0
    from ray_tpu.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment("PongLiteJax-v0", env_backend="jax")
        .rollouts(
            num_rollout_workers=0,
            num_envs_per_worker=16,
            rollout_fragment_length=128,
        )
        .training(
            gamma=0.99, lr=2.5e-4, lambda_=0.95,
            train_batch_size=2048, sgd_minibatch_size=512,
            num_sgd_iter=6, entropy_coeff=0.01, clip_param=0.2,
            kl_coeff=0.0, vf_clip_param=10.0,
        )
        .debugging(seed=0)
    )


def _impala_pong():
    # reference geometry: impala/pong-impala.yaml (async learner)
    import ray_tpu.env.pong_lite  # noqa: F401
    from ray_tpu.algorithms.impala import IMPALAConfig

    return (
        IMPALAConfig()
        .environment("PongLite-v0")
        .rollouts(
            num_rollout_workers=2,
            num_envs_per_worker=8,
            rollout_fragment_length=64,
        )
        .training(
            train_batch_size=1024, lr=4e-4, entropy_coeff=0.01,
            vf_loss_coeff=0.5, grad_clip=40.0,
        )
        .debugging(seed=0)
    )


def _sac_halfcheetah():
    # reference geometry: sac/halfcheetah-sac.yaml (9k @ 400k steps)
    from ray_tpu.algorithms.sac import SACConfig

    return (
        SACConfig()
        .environment("HalfCheetah-v4")
        # fragment 32 amortizes the rollout round trip; the reference's
        # 1-update-per-env-step ratio (halfcheetah-sac.yaml fragment 1,
        # batch 256) is restored via training_intensity=256 — the 32
        # updates per round fuse into ONE lax.scan dispatch
        # (sac.py learn_on_stacked_batch), and sample_async overlaps
        # the next fragment with the update chain
        .rollouts(num_rollout_workers=1, rollout_fragment_length=32)
        .training(
            train_batch_size=256,
            gamma=0.99, tau=0.005,
            training_intensity=256,
            num_steps_sampled_before_learning_starts=10000,
            sample_async=True,
            optimization={
                "actor_learning_rate": 3e-4,
                "critic_learning_rate": 3e-4,
                "entropy_learning_rate": 3e-4,
            },
            replay_buffer_config={"capacity": 400000},
        )
        .debugging(seed=0)
    )


def _ma_cartpole():
    # pistonball slot: shared-params multi-agent PPO (pettingzoo absent)
    import gymnasium as gym

    from ray_tpu.algorithms.ppo import PPOConfig
    from ray_tpu.env.multi_agent_env import make_multi_agent
    from ray_tpu.env.registry import register_env

    register_env(
        "ma_cartpole4",
        lambda cfg: make_multi_agent("CartPole-v1")({"num_agents": 4}),
    )
    obs_sp = gym.spaces.Box(-np.inf, np.inf, (4,), np.float64)
    act_sp = gym.spaces.Discrete(2)
    return (
        PPOConfig()
        .environment("ma_cartpole4")
        .rollouts(num_rollout_workers=1, rollout_fragment_length=256)
        .training(
            train_batch_size=2048, sgd_minibatch_size=256,
            num_sgd_iter=8, lr=3e-4, entropy_coeff=0.01,
            model={"fcnet_hiddens": [128, 128]},
        )
        .multi_agent(
            policies={"shared": (None, obs_sp, act_sp, {})},
            policy_mapping_fn=lambda aid, **kw: "shared",
        )
        .debugging(seed=0)
    )


def _plumbing_ppo():
    # framework-bound config: near-free env (SyntheticEnv) + tiny MLP,
    # so steps/s measures the plumbing (sampler loop, shipping, learner
    # queue), not env or model compute
    import ray_tpu.env.synthetic_env  # noqa: F401  registers SyntheticFast-v0
    from ray_tpu.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment("SyntheticFast-v0")
        .rollouts(
            num_rollout_workers=2,
            num_envs_per_worker=16,
            rollout_fragment_length=256,
            sample_prefetch=1,
        )
        .training(
            train_batch_size=8192, sgd_minibatch_size=1024,
            num_sgd_iter=2, lr=3e-4,
            model={"fcnet_hiddens": [64, 64]},
        )
        .debugging(seed=0)
    )


def _plumbing_impala():
    import ray_tpu.env.synthetic_env  # noqa: F401
    from ray_tpu.algorithms.impala import IMPALAConfig

    return (
        IMPALAConfig()
        .environment("SyntheticFast-v0")
        .rollouts(
            num_rollout_workers=2,
            num_envs_per_worker=16,
            rollout_fragment_length=64,
        )
        .training(
            train_batch_size=4096, lr=3e-4,
            model={"fcnet_hiddens": [64, 64]},
        )
        .debugging(seed=0)
    )


CONFIGS = {
    # name -> (builder, default_budget_s, reward_target_note)
    "ppo_cartpole": (_ppo_cartpole, 150, "reward 150 (ref: @<=100k steps)"),
    "ppo_pong": (_ppo_pong, 420, "reward rising from ~-12 (ref: Pong max)"),
    "impala_pong": (
        _impala_pong,
        420,
        "throughput-focused async config; flat at <=1.8M-step "
        "budgets (ref IMPALA-Pong consumes >20M frames across "
        "32-128 workers)",
    ),
    "sac_halfcheetah": (_sac_halfcheetah, 300, "reward rising (ref: 9k@400k)"),
    "ma_cartpole": (_ma_cartpole, 150, "shared-policy reward 150"),
}

# not part of the headline sweep: throughput-only, no learning target
PLUMBING_CONFIGS = {
    "plumbing_ppo": (_plumbing_ppo, 90, "throughput only (synthetic env)"),
    "plumbing_impala": (
        _plumbing_impala, 90, "throughput only (synthetic env)",
    ),
}


def run_plumbing(budget_s=None):
    """Framework-bound throughput: the five-config sweep's configs keep
    the chip ~5% busy, but nothing there separates "rollout-starved by
    the 1-core host" from "framework overhead". These two runs remove
    env and model cost; the resulting steps/s IS the plumbing bound
    (sampler loop + object shipping + queues + learner dispatch) on
    this host. Writes ``benchmarks/e2e/plumbing_bound.json``."""
    results = {}
    for name in PLUMBING_CONFIGS:
        r = run_config(name, budget_s)
        results[name] = {
            "env_steps_per_sec": r["env_steps_per_sec"],
            "env_steps": r["env_steps"],
            "wall_clock_s": r["wall_clock_s"],
        }
    out = {
        "what": (
            "e2e throughput with env.step ~1us and a 64x64 MLP: the "
            "framework plumbing bound on this host (cf. ppo_pong/"
            "impala_pong, where the 1-core host splits between CPU "
            "CNN inference and per-step obs byte handling, and sync "
            "PPO additionally serializes rollout against the learner "
            "phase)"
        ),
        "hardware": "1 TPU v5e chip (axon tunnel) + 1 host CPU core",
        "results": results,
    }
    ARTIFACT_DIR.mkdir(parents=True, exist_ok=True)
    (ARTIFACT_DIR / "plumbing_bound.json").write_text(
        json.dumps(out, indent=1)
    )
    print(json.dumps({"metric": "plumbing_bound", **out}))
    return out


def run_config(name, budget_s=None, overrides=None, artifact_suffix=""):
    builder, default_budget, note = CONFIGS.get(name) or (
        PLUMBING_CONFIGS[name]
    )
    budget = float(budget_s or default_budget)
    cfg = builder()
    for k, v in (overrides or {}).items():
        setattr(cfg, k, v)
    algo = cfg.build()
    curve = []
    t0 = time.perf_counter()
    steps = 0
    try:
        while time.perf_counter() - t0 < budget:
            result = algo.train()
            steps = int(result.get("num_env_steps_sampled", 0))
            rew = result.get("episode_reward_mean")
            curve.append(
                {
                    "wall_s": round(time.perf_counter() - t0, 1),
                    "env_steps": steps,
                    "episode_reward_mean": (
                        None if rew is None or not np.isfinite(rew)
                        else round(float(rew), 2)
                    ),
                }
            )
    finally:
        try:
            algo.cleanup()
        except Exception:
            pass
    wall = time.perf_counter() - t0
    rewards = [
        c["episode_reward_mean"]
        for c in curve
        if c["episode_reward_mean"] is not None
    ]
    if len(curve) > 200:  # thin long runs; endpoints kept
        idx = np.unique(
            np.linspace(0, len(curve) - 1, 200).astype(int)
        )
        curve = [curve[i] for i in idx]
    out = {
        "name": name + artifact_suffix,
        "note": note,
        "env_steps": steps,
        "wall_clock_s": round(wall, 1),
        "env_steps_per_sec": round(steps / wall, 1),
        "first_reward": rewards[0] if rewards else None,
        "best_reward": max(rewards) if rewards else None,
        "final_reward": rewards[-1] if rewards else None,
        "curve": curve,
        "hardware": "1 TPU v5e chip (axon tunnel) + 1 host CPU core",
    }
    ARTIFACT_DIR.mkdir(parents=True, exist_ok=True)
    (ARTIFACT_DIR / f"{name}{artifact_suffix}.json").write_text(
        json.dumps(out, indent=1)
    )
    return out


def main():
    args = sys.argv
    only = None
    if "--only" in args:
        only = args[args.index("--only") + 1]
    budget = None
    if "--budget" in args:
        budget = float(args[args.index("--budget") + 1])
    # --prefetch N overrides config.sample_prefetch for A/B runs of the
    # pipelined vs synchronous sampling path (0 = force synchronous);
    # artifacts get a _prefetchN suffix so both sides persist
    overrides = None
    suffix = ""
    if "--prefetch" in args:
        n = int(args[args.index("--prefetch") + 1])
        overrides = {"sample_prefetch": n}
        suffix = f"_prefetch{n}"
    if "--plumbing" in args:
        run_plumbing(budget)
        return
    names = [only] if only else list(CONFIGS)
    summary = {}
    for name in names:
        r = run_config(name, budget, overrides, suffix)
        summary[name] = {
            "env_steps_per_sec": r["env_steps_per_sec"],
            "best_reward": r["best_reward"],
            "final_reward": r["final_reward"],
            "env_steps": r["env_steps"],
        }
        print(f"# {name}: {summary[name]}", file=sys.stderr)
    agg = round(
        float(np.mean([s["env_steps_per_sec"] for s in summary.values()])), 1
    )
    print(
        json.dumps(
            {
                "metric": "e2e_env_steps_per_sec_mean",
                "value": agg,
                "unit": "env_steps/s",
                "vs_baseline": None,
                "configs": summary,
                "artifacts": str(ARTIFACT_DIR),
            }
        )
    )


if __name__ == "__main__":
    main()
