"""Headline benchmark: PPO learner env-steps/sec on TPU vs torch-CPU.

Measures the north-star metric from BASELINE.md: PPO learner throughput
(env frames consumed per second of learner wall-clock) on Atari-shaped
batches with the Nature-CNN policy, at the reference's pong-ppo.yaml
geometry (train batch ~4096, minibatch 512, 10 SGD epochs). Compares:

  - ray_tpu JAX/TPU learner: ONE jitted shard_map SGD nest per train
    batch, host→device transfer overlapped with compute via DeviceFeeder
    (the reference's _MultiGPULoaderThread role).
  - torch-CPU learner: a faithful implementation of the reference's
    minibatch SGD loop (``rllib/policy/torch_policy.py:498-624``).

Observations are structured (block-textured) frames, matching real Atari
content rather than incompressible noise. Prints ONE JSON line.
"""

import json
import time

import numpy as np

B, MB, ITERS = 4096, 512, 10
H, W, C, NUM_ACTIONS = 84, 84, 4, 6
TIMED_ROUNDS = 4


def make_frames(rng, n):
    """Blocky 84x84 frames approximating Atari content."""
    base = rng.integers(0, 255, (n, H // 4, W // 4, C), dtype=np.uint8)
    return np.kron(base, np.ones((1, 4, 4, 1), np.uint8))


def make_batch(rng):
    return {
        "obs": make_frames(rng, B),
        "actions": rng.integers(0, NUM_ACTIONS, B).astype(np.int64),
        "action_logp": np.full(B, -1.79, np.float32),
        "action_dist_inputs": rng.standard_normal(
            (B, NUM_ACTIONS)
        ).astype(np.float32),
        "advantages": rng.standard_normal(B).astype(np.float32),
        "value_targets": rng.standard_normal(B).astype(np.float32),
    }


def bench_jax() -> float:
    import jax

    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.execution.device_feed import DeviceFeeder

    obs_space = gym.spaces.Box(0, 255, (H, W, C), np.uint8)
    act_space = gym.spaces.Discrete(NUM_ACTIONS)
    policy = PPOJaxPolicy(
        obs_space,
        act_space,
        {
            "train_batch_size": B,
            "sgd_minibatch_size": MB,
            "num_sgd_iter": ITERS,
            "lr": 5e-5,
        },
    )
    rng = np.random.default_rng(0)
    host_batches = [make_batch(rng) for _ in range(3)]

    fn = policy._build_learn_fn(B)
    policy._learn_fns[B] = fn
    coeffs = policy._coeff_array()
    r = jax.random.PRNGKey(0)

    feeder = DeviceFeeder(policy._data_sharding)
    feeder.put(host_batches[0])
    dev = feeder.get()
    # compile + warm
    params, opt_state, stats = fn(
        policy.params, policy.opt_state, dev, r, coeffs
    )
    float(stats["total_loss"])

    # steady state: feeder transfers batch k+1 while learner runs batch k
    feeder.put(host_batches[1 % 3])
    t0 = time.perf_counter()
    for k in range(TIMED_ROUNDS):
        dev = feeder.get()
        feeder.put(host_batches[(k + 2) % 3])
        params, opt_state, stats = fn(params, opt_state, dev, r, coeffs)
        loss = float(stats["total_loss"])  # sync
    dt = (time.perf_counter() - t0) / TIMED_ROUNDS
    feeder.stop()
    return B / dt


def bench_torch() -> float:
    """Reference-semantics torch CPU learner: same net, same SGD nest."""
    import torch
    import torch.nn as nn

    torch.manual_seed(0)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = nn.Sequential(
                nn.Conv2d(C, 32, 8, 4), nn.ReLU(),
                nn.Conv2d(32, 64, 4, 2), nn.ReLU(),
                nn.Conv2d(64, 64, 3, 1), nn.ReLU(),
            )
            self.fc = nn.Sequential(nn.Linear(64 * 7 * 7, 512), nn.ReLU())
            self.pi = nn.Linear(512, NUM_ACTIONS)
            self.vf = nn.Linear(512, 1)

        def forward(self, x):
            h = self.fc(self.conv(x).flatten(1))
            return self.pi(h), self.vf(h).squeeze(-1)

    net = Net()
    opt = torch.optim.Adam(net.parameters(), lr=5e-5)
    rng = np.random.default_rng(0)
    b = make_batch(rng)
    obs_u8 = torch.from_numpy(b["obs"].transpose(0, 3, 1, 2).copy())
    actions = torch.from_numpy(b["actions"])
    old_logp = torch.from_numpy(b["action_logp"])
    adv = torch.from_numpy(b["advantages"])
    vt = torch.from_numpy(b["value_targets"])

    def one_round(iters):
        n_mb = B // MB
        for _ in range(iters):
            perm = torch.randperm(B)
            for i in range(n_mb):
                idx = perm[i * MB : (i + 1) * MB]
                x = obs_u8[idx].float() / 255.0
                logits, value = net(x)
                logp = torch.log_softmax(logits, -1).gather(
                    1, actions[idx, None]
                ).squeeze(1)
                ratio = torch.exp(logp - old_logp[idx])
                surr = torch.minimum(
                    adv[idx] * ratio,
                    adv[idx] * ratio.clamp(0.7, 1.3),
                )
                vf_loss = (value - vt[idx]).pow(2).clamp(0, 10.0)
                loss = (-surr + vf_loss).mean()
                opt.zero_grad()
                loss.backward()
                opt.step()

    one_round(1)  # warmup
    t0 = time.perf_counter()
    one_round(1)
    dt = (time.perf_counter() - t0) * ITERS  # extrapolate to full nest
    return B / dt


def main():
    jax_sps = bench_jax()
    torch_sps = bench_torch()
    print(
        json.dumps(
            {
                "metric": "ppo_learner_env_steps_per_sec",
                "value": round(jax_sps, 1),
                "unit": "env_steps/s",
                "vs_baseline": round(jax_sps / torch_sps, 2),
                "baseline_torch_cpu": round(torch_sps, 1),
                "config": {
                    "train_batch": B,
                    "minibatch": MB,
                    "num_sgd_iter": ITERS,
                    "obs": [H, W, C],
                },
            }
        )
    )


if __name__ == "__main__":
    main()
