"""Headline benchmark: PPO learner env-steps/sec on TPU vs torch-CPU.

Measures the north-star metric from BASELINE.md: PPO learner throughput
(env frames consumed per second of learner wall-clock) on Atari-shaped
batches with the Nature-CNN policy, at the reference's pong-ppo.yaml
geometry (train batch ~4096, minibatch 512, 10 SGD epochs). Compares:

  - ray_tpu JAX/TPU learner, through the PUBLIC two-phase policy API
    (``prepare_batch`` → DeviceFeeder → ``learn_on_device_batch``): ONE
    jitted shard_map SGD nest per train batch, host→device transfer of
    batch k+1 overlapped with the compute of batch k (the reference's
    _MultiGPULoaderThread role).
  - torch-CPU learner: a faithful implementation of the reference's
    minibatch SGD loop (``rllib/policy/torch_policy.py:498-624``), run in
    full (no extrapolation).

Observations are structured (block-textured) frames, matching real Atari
content rather than incompressible noise. Prints ONE JSON line.
"""

import json
import time

import numpy as np

B, MB, ITERS = 4096, 512, 10
H, W, C, NUM_ACTIONS = 84, 84, 4, 6
TIMED_ROUNDS = 4


def make_frames(rng, n, h=H, w=W, c=1):
    """Blocky 84x84 single frames approximating Atari content."""
    base = rng.integers(0, 255, (n, h // 4, w // 4, c), dtype=np.uint8)
    return np.kron(base, np.ones((1, 4, 4, 1), np.uint8))


def make_batch(rng, b=B, h=H, w=W, c=C, num_actions=NUM_ACTIONS):
    """A trajectory-shaped PPO train batch: rows are sliding
    ``c``-frame stacks over one contiguous frame stream (real Atari
    layout), shipped in the deduplicated frame-pool format
    (``ray_tpu.ops.framestack``) — the obs column moves host→device
    once per unique frame instead of ``c`` times."""
    from ray_tpu.ops.framestack import frame_stream_columns

    frames = make_frames(rng, b + c - 1, h, w, 1)
    return {
        **frame_stream_columns(frames, b, c),
        "actions": rng.integers(0, num_actions, b).astype(np.int64),
        "action_logp": np.full(b, -1.79, np.float32),
        "action_dist_inputs": rng.standard_normal(
            (b, num_actions)
        ).astype(np.float32),
        "advantages": rng.standard_normal(b).astype(np.float32),
        "value_targets": rng.standard_normal(b).astype(np.float32),
    }


def materialize_stacks(batch, c=C):
    """(N, H, W, c) stacked obs from a frame-pool batch — what the
    torch baseline (and the reference's loader thread) moves per row."""
    frames = batch["obs_frames"]
    idx = batch["obs_frame_idx"]
    return np.stack(
        [
            np.concatenate(
                [frames[i + j] for j in range(c)], axis=-1
            )
            for i in idx
        ]
    )


def bench_jax(
    b=B, mb=MB, iters=ITERS, timed_rounds=TIMED_ROUNDS, h=H, w=W, c=C
) -> float:
    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.execution.device_feed import DeviceFeeder

    obs_space = gym.spaces.Box(0, 255, (h, w, c), np.uint8)
    act_space = gym.spaces.Discrete(NUM_ACTIONS)
    policy = PPOJaxPolicy(
        obs_space,
        act_space,
        {
            "train_batch_size": b,
            "sgd_minibatch_size": mb,
            "num_sgd_iter": iters,
            "lr": 5e-5,
        },
    )
    rng = np.random.default_rng(0)
    host_batches = [
        policy.prepare_batch(make_batch(rng, b, h, w, c))
        for _ in range(3)
    ]

    feeder = DeviceFeeder(policy.batch_shardings)
    feeder.put(*host_batches[0])
    dev, bsize = feeder.get()
    # compile + warm through the supported entry point (this batch is
    # in the deduplicated frame-pool format; the stacks rebuild on
    # device before the SGD nest)
    policy.learn_on_device_batch(dev, bsize)

    # steady state: feeder transfers batch k+1 while learner runs batch k
    feeder.put(*host_batches[1 % 3])
    t0 = time.perf_counter()
    for k in range(timed_rounds):
        dev, bsize = feeder.get()
        feeder.put(*host_batches[(k + 2) % 3])
        stats = policy.learn_on_device_batch(dev, bsize)
        stats["total_loss"]  # host sync already done by device_get
    dt = (time.perf_counter() - t0) / timed_rounds
    feeder.stop()
    return b / dt


def bench_torch(b=B, mb=MB, iters=ITERS) -> float:
    """Reference-semantics torch CPU learner: same net, same SGD nest,
    run in full (``rllib/policy/torch_policy.py:498-624``)."""
    import torch
    import torch.nn as nn

    torch.manual_seed(0)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = nn.Sequential(
                nn.Conv2d(C, 32, 8, 4), nn.ReLU(),
                nn.Conv2d(32, 64, 4, 2), nn.ReLU(),
                nn.Conv2d(64, 64, 3, 1), nn.ReLU(),
            )
            self.fc = nn.Sequential(nn.Linear(64 * 7 * 7, 512), nn.ReLU())
            self.pi = nn.Linear(512, NUM_ACTIONS)
            self.vf = nn.Linear(512, 1)

        def forward(self, x):
            h = self.fc(self.conv(x).flatten(1))
            return self.pi(h), self.vf(h).squeeze(-1)

    net = Net()
    opt = torch.optim.Adam(net.parameters(), lr=5e-5)
    rng = np.random.default_rng(0)
    batch = make_batch(rng, b)
    # the reference's collector hands the loader fully-materialized
    # (N, H, W, c) stacks; same data, same compute
    obs_u8 = torch.from_numpy(
        materialize_stacks(batch).transpose(0, 3, 1, 2).copy()
    )
    actions = torch.from_numpy(batch["actions"])
    old_logp = torch.from_numpy(batch["action_logp"])
    adv = torch.from_numpy(batch["advantages"])
    vt = torch.from_numpy(batch["value_targets"])

    def one_nest():
        n_mb = b // mb
        for _ in range(iters):
            perm = torch.randperm(b)
            for i in range(n_mb):
                idx = perm[i * mb : (i + 1) * mb]
                x = obs_u8[idx].float() / 255.0
                logits, value = net(x)
                logp = torch.log_softmax(logits, -1).gather(
                    1, actions[idx, None]
                ).squeeze(1)
                ratio = torch.exp(logp - old_logp[idx])
                surr = torch.minimum(
                    adv[idx] * ratio,
                    adv[idx] * ratio.clamp(0.7, 1.3),
                )
                vf_loss = (value - vt[idx]).pow(2).clamp(0, 10.0)
                loss = (-surr + vf_loss).mean()
                opt.zero_grad()
                loss.backward()
                opt.step()

    # warmup: one epoch to settle allocators/threads
    n_mb = b // mb
    for i in range(n_mb):
        idx = torch.arange(i * mb, (i + 1) * mb)
        logits, value = net(obs_u8[idx].float() / 255.0)
        (logits.sum() + value.sum()).backward()
        opt.zero_grad()
    t0 = time.perf_counter()
    one_nest()
    dt = time.perf_counter() - t0
    return b / dt


def main():
    jax_sps = bench_jax()
    torch_sps = bench_torch()
    print(
        json.dumps(
            {
                "metric": "ppo_learner_env_steps_per_sec",
                "value": round(jax_sps, 1),
                "unit": "env_steps/s",
                "vs_baseline": round(jax_sps / torch_sps, 2),
                "baseline_torch_cpu": round(torch_sps, 1),
                "config": {
                    "train_batch": B,
                    "minibatch": MB,
                    "num_sgd_iter": ITERS,
                    "obs": [H, W, C],
                },
            }
        )
    )


if __name__ == "__main__":
    main()
