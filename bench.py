"""Headline benchmark: PPO learner env-steps/sec on TPU vs torch-CPU.

Measures the north-star metric from BASELINE.md: PPO learner throughput
(env frames consumed per second of learner wall-clock) on Atari-shaped
batches with the Nature-CNN policy, at the reference's pong-ppo.yaml
geometry (train batch ~4096, minibatch 512, 10 SGD epochs). Compares:

  - ray_tpu JAX/TPU learner, through the PUBLIC two-phase policy API
    (``prepare_batch`` → DeviceFeeder → ``learn_on_device_batch``): ONE
    jitted shard_map SGD nest per train batch, host→device transfer of
    batch k+1 overlapped with the compute of batch k (the reference's
    _MultiGPULoaderThread role).
  - torch-CPU learner: a faithful implementation of the reference's
    minibatch SGD loop (``rllib/policy/torch_policy.py:498-624``), run in
    full (no extrapolation).

Also reports an MFU estimate: the pure-compute time of the SGD nest is
isolated by scaling the epoch count (the marginal cost of extra epochs
excludes the fixed per-dispatch overhead, which on a tunneled/remote
TPU backend can exceed the compute itself), and divided into the
analytic fwd+bwd FLOPs of the Nature CNN.

Per-round times use the MEDIAN across rounds: the remote-TPU tunnel
this bench runs over shows multi-x tail latency unrelated to the
framework under test.

Observations are structured (block-textured) frames, matching real Atari
content rather than incompressible noise. Prints ONE JSON line.

Flags:  --profile       run ONE telemetry-instrumented PPO iteration
                        (docs/observability.md): writes the chrome
                        trace to benchmarks/e2e/ppo_iteration_trace.json
                        plus a telemetry-overhead A/B entry
                        (benchmarks/e2e/telemetry_overhead.json)
        --xprof DIR     capture a jax.profiler trace of the timed rounds
        --e2e           run the five BASELINE.md end-to-end configs
                        (rollout+learner; see bench_e2e.py) instead
        --chaos         fault-injection A/B (docs/resilience.md):
                        steady-state vs worker-kill + NaN-batch run,
                        writes benchmarks/e2e/chaos_recovery.json
        --replay-ab     host-ring vs device-resident replay A/B on
                        the SAC geometry (docs/data_plane.md): writes
                        benchmarks/e2e/replay_device_ab.json with
                        steps/s, per-iteration H2D bytes by path, and
                        a bitwise parity flag
        --superstep     fused K-updates-per-dispatch A/B
                        (docs/data_plane.md): per-update dispatch
                        overhead at K=1 (deferred) vs K=8 on device-
                        resident batches at the CPU smoke geometry;
                        writes benchmarks/e2e/superstep_ab.json (the
                        full bench's bench_mfu gains a `superstep`
                        sub-entry at the headline geometry)
        --jax-env       rollout-lane A/B (docs/pipeline.md): CPU-actor
                        lane vs device (jax) lane vs fused
                        rollout+learn superstep on the same
                        JaxVectorEnv, same seed, same step count;
                        writes benchmarks/e2e/jax_env_ab.json
                        (bench_mfu gains a `fused_rollout` sub-entry
                        on the jittable pong_lite port)
        --serve         inference-plane A/B (docs/serving.md):
                        continuous batching vs naive per-request
                        inference on the same fixed-seed request
                        stream at 1/8/32/128 concurrent clients —
                        latency/throughput curve, zero-recompile and
                        bitwise-parity checks; writes
                        benchmarks/e2e/serve_ab.json (bench_mfu gains
                        a `serve_forward` sub-entry at the pixel
                        geometry for the next TPU round)
        --ingress       serving front-door A/B (docs/serving.md "the
                        front door"): batched ingress (HTTP →
                        coalescing router → fused replica forwards)
                        vs the per-request serve-core HTTP path, both
                        over REAL sockets, sweeping client counts —
                        throughput + p50/p99 per path, bitwise
                        response parity, zero recompiles in the timed
                        window — plus the AOT cold-start A/B: fresh-
                        replica warmup wall + time-to-first-response
                        with an empty vs warm compile cache (warm =
                        ZERO fresh compiles, test-asserted); writes
                        benchmarks/e2e/ingress_ab.json
        --flood         OPEN-loop flood harness for the horizontal
                        front door (docs/serving.md "Scaling the
                        front door"): Poisson + recorded-burst
                        arrival schedules with a deadline mix, swept
                        upward to locate each config's saturation
                        knee (goodput >= 90% of offered), for 1 vs N
                        ingress worker processes on ONE shared port;
                        at 2x the knee every response must be a
                        200-inside-deadline / 429 / 503 / 504 (never
                        a hang, never a late 200); bitwise parity
                        across configs, zero recompiles per worker;
                        add --smoke for the shrunk tier-1 variant;
                        writes benchmarks/e2e/flood.json
        --elastic       elastic-fleet chaos A/B (docs/resilience.md
                        "elastic fleets & preemption"): PPO fleet
                        forced 4→2→6 via noticed preemptions +
                        autoscaler scale-up vs the PR-4 kill-only
                        path (steps/s per fleet size, drain vs kill
                        recovery cost), plus work lost on a mid-run
                        driver crash with streamed vs periodic
                        checkpoints; writes
                        benchmarks/e2e/elastic_fleet.json
        --fleet         elastic learner-mesh lane (docs/fleet.md):
                        gloo CPU fleets of 1 and 2 hosts through the
                        full rendezvous → epoch → lockstep-learn
                        protocol — steps/s by fleet size, drain
                        (noticed) vs kill (heartbeat) recovery wall,
                        and the resize wall with a pre-seeded AOT
                        cache vs cold (warm resize = zero fresh
                        compiles); writes benchmarks/e2e/fleet.json
        --fleet-chaos   control-plane failover lane (docs/fleet.md
                        "failure model & leadership"): coordinator
                        kill → fenced standby takeover → failover
                        epoch cut, walls vs lease TTL (gate: median
                        < 2x TTL), clean-handover comparison, and
                        the zombie's stale-term write fenced every
                        trial; control-plane only — no learners;
                        writes benchmarks/e2e/fleet_chaos.json
        --fleetobs      fleet-observability overhead A/B
                        (docs/observability.md "Fleet view"): the
                        SAME fixed-seed 2-host lockstep learn, bare
                        vs with per-host HostExporters + the rank-0
                        FleetAggregator live — median-step-wall
                        overhead (budget < 2%), bitwise-identical
                        per-step losses (hard gate), both hosts
                        host=-labeled in the merged exposition;
                        writes
                        benchmarks/e2e/fleet_observability.json
        --obs           device-ledger overhead A/B
                        (docs/observability.md "device ledger"): the
                        SAME fixed-seed superstep PPO chain with
                        telemetry fully off vs the compiled-program
                        ledger on vs ledger+tracing — steady-state
                        per-superstep wall, the one-time AOT analysis
                        compile cost, and a bitwise parity flag;
                        writes benchmarks/e2e/observability.json
                        (acceptance: ledger overhead < 2% of
                        superstep wall)
        --lint          device-contract static-analysis pass
                        (docs/static_analysis.md): whole-ray_tpu/
                        scan wall time, per-rule finding counts,
                        baseline/suppression totals; writes
                        benchmarks/e2e/static_analysis.json (pure
                        AST — runs even where jax is broken)
"""

import json
import sys
import time

import numpy as np

B, MB, ITERS = 4096, 512, 10
H, W, C, NUM_ACTIONS = 84, 84, 4, 6
# median over more rounds: the tunneled backend's per-call latency
# swings several-fold minute to minute; a wider sample keeps the
# median representative
TIMED_ROUNDS = 12


def make_frames(rng, n, h=H, w=W, c=1):
    """Blocky 84x84 single frames approximating Atari content."""
    base = rng.integers(0, 255, (n, h // 4, w // 4, c), dtype=np.uint8)
    return np.kron(base, np.ones((1, 4, 4, 1), np.uint8))


def make_batch(rng, b=B, h=H, w=W, c=C, num_actions=NUM_ACTIONS):
    """A trajectory-shaped PPO train batch: rows are sliding
    ``c``-frame stacks over one contiguous frame stream (real Atari
    layout), shipped in the deduplicated frame-pool format
    (``ray_tpu.ops.framestack``) — the obs column moves host→device
    once per unique frame instead of ``c`` times."""
    from ray_tpu.ops.framestack import frame_stream_columns

    frames = make_frames(rng, b + c - 1, h, w, 1)
    return {
        **frame_stream_columns(frames, b, c),
        "actions": rng.integers(0, num_actions, b).astype(np.int64),
        "action_logp": np.full(b, -1.79, np.float32),
        "action_dist_inputs": rng.standard_normal(
            (b, num_actions)
        ).astype(np.float32),
        "advantages": rng.standard_normal(b).astype(np.float32),
        "value_targets": rng.standard_normal(b).astype(np.float32),
    }


def materialize_stacks(batch, c=C):
    """(N, H, W, c) stacked obs from a frame-pool batch — what the
    torch baseline (and the reference's loader thread) moves per row."""
    frames = batch["obs_frames"]
    idx = batch["obs_frame_idx"]
    return np.stack(
        [
            np.concatenate(
                [frames[i + j] for j in range(c)], axis=-1
            )
            for i in idx
        ]
    )


def nature_cnn_train_flops_per_sample(h=H, w=W, c=C, num_actions=NUM_ACTIONS):
    """Analytic fwd+bwd FLOPs/sample for the Nature CNN
    (models/cnn.py NATURE_FILTERS + 512 post-fc + heads), using the
    standard train ≈ 3 × forward convention."""
    from ray_tpu.models.cnn import NATURE_FILTERS

    macs = 0
    hh, ww, ch = h, w, c
    for out_ch, (kh, kw), (sh, sw) in NATURE_FILTERS:
        hh = (hh - kh) // sh + 1
        ww = (ww - kw) // sw + 1
        macs += hh * ww * out_ch * kh * kw * ch
        ch = out_ch
    flat = hh * ww * ch
    macs += flat * 512            # post_fc
    macs += 512 * num_actions + 512  # heads
    return 3 * 2 * macs


def chip_peak_tflops():
    """Best-effort bf16 peak for the attached chip (public specs)."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    table = [
        ("v6", 918.0),      # v6e (Trillium)
        ("v5p", 459.0),
        ("v5 lite", 197.0), # v5e
        ("v5e", 197.0),
        ("v5", 459.0),
        ("v4", 275.0),
        ("v3", 123.0),
        ("v2", 45.0),
    ]
    for key, peak in table:
        if key in kind:
            return peak, jax.devices()[0].device_kind
    return 197.0, jax.devices()[0].device_kind


def _make_policy(b, mb, iters, h=H, w=W, c=C):
    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy

    return PPOJaxPolicy(
        gym.spaces.Box(0, 255, (h, w, c), np.uint8),
        gym.spaces.Discrete(NUM_ACTIONS),
        {
            "train_batch_size": b,
            "sgd_minibatch_size": mb,
            "num_sgd_iter": iters,
            "lr": 5e-5,
        },
    )


def bench_jax(
    b=B, mb=MB, iters=ITERS, timed_rounds=TIMED_ROUNDS, h=H, w=W, c=C,
    profile_dir=None,
):
    """End-to-end learner loop (feeder-overlapped transfer + SGD nest +
    per-batch stats fetch). Returns (env_steps/s from median round
    time, per-round times)."""
    from ray_tpu.execution.device_feed import DeviceFeeder

    policy = _make_policy(b, mb, iters, h, w, c)
    rng = np.random.default_rng(0)
    host_batches = [
        policy.prepare_batch(make_batch(rng, b, h, w, c))
        for _ in range(3)
    ]

    feeder = DeviceFeeder(policy.batch_shardings)
    feeder.put(*host_batches[0])
    dev, bsize = feeder.get()
    # compile + warm through the supported entry point (this batch is
    # in the deduplicated frame-pool format; the stacks rebuild on
    # device before the SGD nest)
    policy.learn_on_device_batch(dev, bsize)

    ctx = None
    if profile_dir:
        import jax

        try:
            ctx = jax.profiler.trace(profile_dir)
            ctx.__enter__()
        except Exception as e:  # tunneled backends may not support it
            print(f"# profiler unavailable: {e}", file=sys.stderr)
            ctx = None

    # steady state: feeder transfers batch k+1 while learner runs batch k
    feeder.put(*host_batches[1 % 3])
    times = []
    for k in range(timed_rounds):
        t0 = time.perf_counter()
        dev, bsize = feeder.get()
        feeder.put(*host_batches[(k + 2) % 3])
        stats = policy.learn_on_device_batch(dev, bsize)
        stats["total_loss"]  # host sync already done by device_get
        times.append(time.perf_counter() - t0)

    # pipelined phase: defer the stats fetch so consecutive nests queue
    # on-device and the fixed per-dispatch latency (dominant on a
    # tunneled backend) amortizes across the stream — the LearnerThread
    # runs exactly this protocol (execution/learner_thread.py). Lag is
    # bounded like there (STATS_LAG) so device memory stays bounded.
    import collections

    import jax

    lazy = collections.deque()
    K = timed_rounds
    t0 = time.perf_counter()
    for k in range(K):
        dev, bsize = feeder.get()
        feeder.put(*host_batches[k % 3])
        lazy.append(
            policy.learn_on_device_batch(dev, bsize, defer_stats=True)
        )
        while len(lazy) > 3:
            jax.device_get(lazy.popleft())
    while lazy:
        jax.device_get(lazy.popleft())
    pipelined_wall = (time.perf_counter() - t0) / K

    # device-resident phase: the SAME pipelined protocol but the 3
    # batches were put on device once up front — no H2D inside the
    # loop. This isolates dispatch amortization from tunnel H2D
    # bandwidth: if the learner-thread pipelining works, steady-state
    # wall per nest here approaches pure nest compute, and effective
    # MFU approaches the epoch-isolated mfu_pct (the reference's
    # multi_gpu_learner_thread.py:20-140 keeps its GPUs fed the same
    # way — loader threads hide transfer, so the accelerator only
    # ever waits on compute).
    from ray_tpu.policy.jax_policy import _FRAMES as _F

    dev_batches = []
    for hb, bs_ in host_batches:
        hb2 = dict(hb)
        fr = hb2.pop(_F, None)
        dev_b = jax.device_put(hb2, policy.batch_shardings(hb2))
        if fr is not None:
            dev_b = dict(
                dev_b,
                **{_F: jax.device_put(fr, policy._param_sharding)},
            )
        dev_batches.append((dev_b, bs_))
    for dev_b, bs_ in dev_batches:
        jax.block_until_ready(dev_b)
    # stats drain in BATCHES of 4: every blocking device interaction
    # costs a full tunnel round trip regardless of payload (the stats
    # are scalars), so fetching per-nest would re-serialize the stream
    # on RTT; one batched fetch per 4 nests amortizes it the way the
    # reference's learner thread reads stats asynchronously
    lazy = collections.deque()
    t0 = time.perf_counter()
    for k in range(K):
        dev_b, bs_ = dev_batches[k % 3]
        lazy.append(
            policy.learn_on_device_batch(
                dev_b, bs_, defer_stats=True
            )
        )
        if len(lazy) >= 8:
            drain = [lazy.popleft() for _ in range(4)]
            jax.device_get(drain)
    jax.device_get(list(lazy))
    lazy.clear()
    resident_wall = (time.perf_counter() - t0) / K

    if ctx is not None:
        ctx.__exit__(None, None, None)
    feeder.stop()
    return (
        b / float(np.median(times)),
        times,
        b / pipelined_wall,
        pipelined_wall,
        b / resident_wall,
        resident_wall,
    )


def bench_mfu(b=B, mb=MB, iters=ITERS, reps=4, h=H, w=W, c=C):
    """Isolate pure SGD-nest compute by epoch scaling: time the nest at
    ``iters`` and ``4*iters`` epochs on a device-resident batch; the
    marginal time per epoch × iters is the compute of the headline
    nest, free of fixed per-dispatch overhead (which dominates over a
    remote-TPU tunnel and would otherwise be misread as low MFU)."""
    import jax

    lo, hi = iters, 4 * iters
    rng = np.random.default_rng(0)
    t_med = {}
    setups = {}
    for it in (lo, hi):
        p = _make_policy(b, mb, it, h, w, c)
        host, bsize = p.prepare_batch(make_batch(rng, b, h, w, c))
        dev = jax.device_put(host, p.batch_shardings(host))
        p.learn_on_device_batch(dict(dev), bsize)  # compile+warm
        setups[it] = (p, dev, bsize, host)
    ts = {lo: [], hi: []}
    for _ in range(reps):  # interleave against tunnel drift
        for it, (p, dev, bsize, _host) in setups.items():
            t0 = time.perf_counter()
            p.learn_on_device_batch(dict(dev), bsize)
            ts[it].append(time.perf_counter() - t0)
    for it in (lo, hi):
        t_med[it] = float(np.median(ts[it]))
    compute_per_nest = (t_med[hi] - t_med[lo]) / (hi - lo) * iters

    # deferred-stats A/B (docs/data_plane.md): the same headline nest
    # under the one-call-lag protocol (config["deferred_stats"]):
    # each call dispatches program k and fetches the stats of k-1 —
    # already finished — so the per-call stats round trip (a full
    # tunnel RTT on a remote backend, serialized after the program on
    # the blocking path) overlaps device compute. Steady-state wall
    # per nest minus the epoch-isolated compute is the deferred
    # dispatch overhead.
    K = 2 * reps
    p, dev, bsize, host = setups[lo]
    p.config["deferred_stats"] = True
    try:
        p.learn_on_device_batch(dict(dev), bsize)  # prime the lag
        t0 = time.perf_counter()
        for _ in range(K):
            p.learn_on_device_batch(dict(dev), bsize)
        p.flush_deferred_stats()  # final program drains on the clock
        deferred_wall = (time.perf_counter() - t0) / K
    finally:
        p.config["deferred_stats"] = False
        p.flush_deferred_stats()
    deferred = {
        "wall_s_per_nest": round(deferred_wall, 4),
        "dispatch_overhead_s": round(
            max(deferred_wall - compute_per_nest, 0.0), 4
        )
        if compute_per_nest > 0
        else None,
        "lag": 1,
    }

    # superstep sub-entry (docs/data_plane.md): K nests fused into ONE
    # dispatched program (JaxPolicy.learn_superstep), so the fixed
    # per-call overhead — the 0.123 s the r05 TPU bench measured
    # against 0.046 s of nest compute — amortizes 1/K. Same
    # device-resident batch repeated K times (dispatch isolation, like
    # the deferred entry above).
    superstep = None
    try:
        from ray_tpu.policy.jax_policy import _FRAMES as _F

        Ksup = 8
        stacked = {
            cn: np.repeat(np.asarray(v)[None], Ksup, axis=0)
            for cn, v in host.items()
        }
        from ray_tpu import sharding as sharding_lib

        shard = {
            cn: (
                sharding_lib.replicated(p.mesh)
                if cn == _F
                else sharding_lib.batch_sharded(p.mesh, ndim_prefix=2)
            )
            for cn in stacked
        }
        dev_stacked = jax.device_put(stacked, shard)
        jax.block_until_ready(dev_stacked)
        p.learn_superstep(
            Ksup, bsize, stacked=dict(dev_stacked), k_max=Ksup
        )  # compile+warm
        sup_reps = max(2, reps // 2)
        t0 = time.perf_counter()
        for _ in range(sup_reps):
            p.learn_superstep(
                Ksup, bsize, stacked=dict(dev_stacked), k_max=Ksup
            )
        sup_wall = (time.perf_counter() - t0) / (sup_reps * Ksup)
        superstep = {
            "k": Ksup,
            "wall_s_per_nest": round(sup_wall, 4),
            "dispatch_overhead_s": round(
                max(sup_wall - compute_per_nest, 0.0), 4
            )
            if compute_per_nest > 0
            else None,
        }
    except Exception as e:  # keep the headline bench alive
        superstep = {"error": str(e)}

    # fused-rollout sub-entry (docs/pipeline.md "two rollout lanes"):
    # rollout(T)+GAE+the SGD nest as ONE dispatched program on the
    # jittable pong_lite port — the zero-H2D lane the next TPU round
    # measures at scale. Smoke geometry here; env_steps/s and the
    # per-dispatch wall are the comparable numbers.
    fused_rollout = None
    try:
        from ray_tpu.algorithms.ppo.ppo import (
            PPOConfig as _PPOCfg,
            PPOJaxPolicy as _PPOPol,
        )
        from ray_tpu.env.jax_pong import PongLiteJax
        from ray_tpu.execution.jax_rollout import JaxRolloutEngine
        from ray_tpu.sharding.compile import compile_stats

        n_env, t_ro = 8, 16
        cfgj = _PPOCfg().to_dict()
        cfgj.update(
            seed=0,
            train_batch_size=n_env * t_ro,
            sgd_minibatch_size=64,
            num_sgd_iter=2,
            lr=3e-4,
        )
        cfgj["lambda"] = 0.95
        envj = PongLiteJax({})
        pj = _PPOPol(
            envj.observation_space, envj.action_space, cfgj
        )
        eng = JaxRolloutEngine(
            pj, envj, n_env, t_ro, seed=0
        )
        feed = eng.superstep_feed()
        infos, carry, mets, _ = pj.learn_rollout_superstep(
            1, eng.batch_size, feed, k_max=1
        )  # compile+warm
        eng.advance(carry, mets)
        traces0 = compile_stats()["traces"]
        fr_reps = max(2, reps // 2)
        t0 = time.perf_counter()
        for _ in range(fr_reps):
            feed = eng.superstep_feed()
            infos, carry, mets, _ = pj.learn_rollout_superstep(
                1, eng.batch_size, feed, k_max=1
            )
            eng.advance(carry, mets)
        fr_wall = (time.perf_counter() - t0) / fr_reps
        fused_rollout = {
            "env": "PongLiteJax-v0",
            "num_envs": n_env,
            "rollout_length": t_ro,
            "wall_s_per_dispatch": round(fr_wall, 4),
            "env_steps_per_s": round(eng.batch_size / fr_wall, 1),
            "recompiles_in_timed_window": (
                compile_stats()["traces"] - traces0
            ),
        }
    except Exception as e:  # keep the headline bench alive
        fused_rollout = {"error": str(e)}

    # serve_forward sub-entry (docs/serving.md): the inference plane's
    # fused batched forward at the pixel geometry — one dispatch of a
    # bucket of Nature-CNN action forwards on the learner-style mesh
    # (vectorized mode: the wide-hardware throughput formulation the
    # next TPU round measures at scale; the exact/bitwise mode is the
    # contract bench.py --serve asserts on MLPs).
    serve_forward = None
    try:
        from ray_tpu.serve.policy_server import BatchedPolicyServer
        from ray_tpu.sharding.compile import compile_stats

        bucket = 16
        psrv = setups[lo][0]
        srv = BatchedPolicyServer(
            psrv,
            max_batch_size=bucket,
            buckets=(bucket,),
            explore=False,
            vectorized=True,
            start=False,
        )
        obs_rows = make_frames(rng, bucket + c - 1, h, w, 1)
        obs_rows = np.concatenate(
            [obs_rows[i : i + bucket] for i in range(c)], axis=-1
        )
        srv.forward_padded(obs_rows)  # compile+warm
        traces0 = compile_stats()["traces"]
        sf_reps = max(2, reps // 2)
        t0 = time.perf_counter()
        for _ in range(sf_reps):
            srv.forward_padded(obs_rows)
        sf_wall = (time.perf_counter() - t0) / sf_reps
        serve_forward = {
            "bucket": bucket,
            "wall_s_per_forward": round(sf_wall, 4),
            "actions_per_s": round(bucket / sf_wall, 1),
            "recompiles_in_timed_window": (
                compile_stats()["traces"] - traces0
            ),
        }
    except Exception as e:  # keep the headline bench alive
        serve_forward = {"error": str(e)}

    # transformer_nest sub-entry (docs/sharding.md "2-D mesh & param
    # partitioning"): the decoder-transformer SGD nest — the
    # architecture-agnostic learner proof — timed like the headline
    # nest, so the next TPU round measures the tensor-parallel torso
    # at real widths next to the Nature-CNN number (pair with
    # bench.py --model-parallel for the replicated-vs-partitioned A/B).
    transformer_nest = None
    try:
        import gymnasium as _gym

        from ray_tpu.algorithms.ppo.ppo import (
            PPOJaxPolicy as _TPPOPol,
        )
        from ray_tpu.sharding.compile import compile_stats

        t_b, t_mb, t_obs = 256, 128, 64
        pt = _TPPOPol(
            _gym.spaces.Box(-1, 1, (t_obs,), np.float32),
            _gym.spaces.Discrete(8),
            {
                "train_batch_size": t_b,
                "sgd_minibatch_size": t_mb,
                "num_sgd_iter": iters,
                "lr": 3e-4,
                "seed": 0,
                "model": {
                    "use_transformer": True,
                    "transformer_dim": 128,
                    "transformer_num_layers": 2,
                    "transformer_num_heads": 4,
                    "transformer_ff_dim": 512,
                    "transformer_seq_len": 8,
                },
            },
        )
        t_rng = np.random.default_rng(0)
        t_host = {
            "obs": t_rng.standard_normal((t_b, t_obs)).astype(
                np.float32
            ),
            "actions": t_rng.integers(0, 8, t_b).astype(np.int64),
            "action_logp": np.full(t_b, -2.0, np.float32),
            "action_dist_inputs": t_rng.standard_normal(
                (t_b, 8)
            ).astype(np.float32),
            "advantages": t_rng.standard_normal(t_b).astype(
                np.float32
            ),
            "value_targets": t_rng.standard_normal(t_b).astype(
                np.float32
            ),
        }
        t_prep, t_bsize = pt.prepare_batch(dict(t_host))
        t_dev = jax.device_put(t_prep, pt.batch_shardings(t_prep))
        pt.learn_on_device_batch(dict(t_dev), t_bsize)  # compile+warm
        traces0 = compile_stats()["traces"]
        tn_reps = max(2, reps // 2)
        t0 = time.perf_counter()
        for _ in range(tn_reps):
            pt.learn_on_device_batch(dict(t_dev), t_bsize)
        tn_wall = (time.perf_counter() - t0) / tn_reps
        transformer_nest = {
            "params": int(pt.model.num_params()),
            "batch": t_b,
            "wall_s_per_nest": round(tn_wall, 4),
            "recompiles_in_timed_window": (
                compile_stats()["traces"] - traces0
            ),
        }
    except Exception as e:  # keep the headline bench alive
        transformer_nest = {"error": str(e)}

    # replay_sample sub-entry (docs/data_plane.md "device sum tree"):
    # one fused prioritized draw→gather dispatch — prefix-descent over
    # the f64 device tree + clip + IS weights + packed-uint8 pixel row
    # gather as ONE program, zero payload H2D (only the generator's
    # raw uniform stream crosses). The wall per dispatch at the pixel
    # geometry is what the next TPU round measures at scale.
    replay_sample = None
    try:
        from ray_tpu.execution.replay_buffer import (
            DevicePrioritizedReplayBuffer,
        )
        from ray_tpu.sharding.compile import compile_stats

        rs_cap, rs_b = 1 << 14, 256
        rs_rng = np.random.default_rng(0)
        rbuf = DevicePrioritizedReplayBuffer(
            capacity=rs_cap, alpha=0.6, seed=1,
            device_tree=True, label="bench_mfu",
        )
        chunk = 2048
        rows = {
            "obs": rs_rng.integers(
                0, 255, (chunk, h, w, c), dtype=np.uint8
            ),
            "actions": rs_rng.integers(0, 4, chunk).astype(np.int32),
            "rewards": rs_rng.standard_normal(chunk).astype(
                np.float32
            ),
        }
        for _ in range(rs_cap // chunk):
            rbuf.add_tree({k: v for k, v in rows.items()})
        batch = rbuf.sample(rs_b, beta=0.4)  # compile+warm
        jax.block_until_ready(batch.tree["obs"])
        traces0 = compile_stats()["traces"]
        rs_reps = 2 * reps
        t0 = time.perf_counter()
        for _ in range(rs_reps):
            batch = rbuf.sample(rs_b, beta=0.4)
        jax.block_until_ready(batch.tree["obs"])
        rs_wall = (time.perf_counter() - t0) / rs_reps
        replay_sample = {
            "capacity": rs_cap,
            "batch": rs_b,
            "wall_s_per_draw": round(rs_wall, 5),
            "rows_per_s": round(rs_b / rs_wall, 1),
            "recompiles_in_timed_window": (
                compile_stats()["traces"] - traces0
            ),
        }
    except Exception as e:  # keep the headline bench alive
        replay_sample = {"error": str(e)}

    peak, kind = chip_peak_tflops()
    if compute_per_nest <= 0:
        # tunnel jitter inverted the medians; a clamped value would
        # report garbage TFLOP/s — flag instead
        return {
            "achieved_tflops": None,
            "peak_tflops": peak,
            "mfu_pct": None,
            "device": kind,
            "unstable_timing": True,
            "deferred_stats": deferred,
            "superstep": superstep,
            "fused_rollout": fused_rollout,
            "serve_forward": serve_forward,
            "transformer_nest": transformer_nest,
            "replay_sample": replay_sample,
        }
    flops = b * iters * nature_cnn_train_flops_per_sample(h, w, c)
    achieved = flops / compute_per_nest / 1e12
    return {
        "achieved_tflops": round(achieved, 1),
        "peak_tflops": peak,
        "mfu_pct": round(100.0 * achieved / peak, 1),
        "device": kind,
        "nest_compute_s": round(compute_per_nest, 4),
        "dispatch_overhead_s": round(
            max(t_med[lo] - compute_per_nest, 0.0), 4
        ),
        "deferred_stats": deferred,
        "superstep": superstep,
        "fused_rollout": fused_rollout,
        "serve_forward": serve_forward,
        "transformer_nest": transformer_nest,
        "replay_sample": replay_sample,
    }


def bench_torch(b=B, mb=MB, iters=ITERS) -> float:
    """Reference-semantics torch CPU learner: same net, same SGD nest,
    run in full (``rllib/policy/torch_policy.py:498-624``)."""
    import torch
    import torch.nn as nn

    torch.manual_seed(0)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = nn.Sequential(
                nn.Conv2d(C, 32, 8, 4), nn.ReLU(),
                nn.Conv2d(32, 64, 4, 2), nn.ReLU(),
                nn.Conv2d(64, 64, 3, 1), nn.ReLU(),
            )
            self.fc = nn.Sequential(nn.Linear(64 * 7 * 7, 512), nn.ReLU())
            self.pi = nn.Linear(512, NUM_ACTIONS)
            self.vf = nn.Linear(512, 1)

        def forward(self, x):
            h = self.fc(self.conv(x).flatten(1))
            return self.pi(h), self.vf(h).squeeze(-1)

    net = Net()
    opt = torch.optim.Adam(net.parameters(), lr=5e-5)
    rng = np.random.default_rng(0)
    batch = make_batch(rng, b)
    # the reference's collector hands the loader fully-materialized
    # (N, H, W, c) stacks; same data, same compute
    obs_u8 = torch.from_numpy(
        materialize_stacks(batch).transpose(0, 3, 1, 2).copy()
    )
    actions = torch.from_numpy(batch["actions"])
    old_logp = torch.from_numpy(batch["action_logp"])
    adv = torch.from_numpy(batch["advantages"])
    vt = torch.from_numpy(batch["value_targets"])

    def one_nest():
        n_mb = b // mb
        for _ in range(iters):
            perm = torch.randperm(b)
            for i in range(n_mb):
                idx = perm[i * mb : (i + 1) * mb]
                x = obs_u8[idx].float() / 255.0
                logits, value = net(x)
                logp = torch.log_softmax(logits, -1).gather(
                    1, actions[idx, None]
                ).squeeze(1)
                ratio = torch.exp(logp - old_logp[idx])
                surr = torch.minimum(
                    adv[idx] * ratio,
                    adv[idx] * ratio.clamp(0.7, 1.3),
                )
                vf_loss = (value - vt[idx]).pow(2).clamp(0, 10.0)
                loss = (-surr + vf_loss).mean()
                opt.zero_grad()
                loss.backward()
                opt.step()

    # warmup: one epoch to settle allocators/threads
    n_mb = b // mb
    for i in range(n_mb):
        idx = torch.arange(i * mb, (i + 1) * mb)
        logits, value = net(obs_u8[idx].float() / 255.0)
        (logits.sum() + value.sum()).backward()
        opt.zero_grad()
    t0 = time.perf_counter()
    one_nest()
    dt = time.perf_counter() - t0
    return b / dt


def bench_sharding_ab(
    b=1024, mb=256, iters=2, rounds=20, out_path=None
):
    """Mesh-vs-pmap sharding-backend A/B on the SAME fixed-seed PPO
    learn step (ISSUE 2): median per-step latency, compile time, and
    recompile counts per backend, plus a bitwise parity check of the
    resulting params. Small MLP geometry — the A/B isolates the
    *backend* cost (placement, dispatch, donation), not model compute.
    Writes one JSON to ``benchmarks/sharding_ab.json``."""
    import gymnasium as gym
    import jax

    from ray_tpu import sharding as sl
    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.data.sample_batch import SampleBatch
    from ray_tpu.parallel import mesh as legacy

    rng = np.random.default_rng(0)
    cols = {
        SampleBatch.OBS: rng.standard_normal((b, 16)).astype(
            np.float32
        ),
        SampleBatch.ACTIONS: rng.integers(0, 6, b).astype(np.int64),
        SampleBatch.ACTION_LOGP: np.full(b, -1.79, np.float32),
        SampleBatch.ACTION_DIST_INPUTS: rng.standard_normal(
            (b, 6)
        ).astype(np.float32),
        SampleBatch.ADVANTAGES: rng.standard_normal(b).astype(
            np.float32
        ),
        SampleBatch.VALUE_TARGETS: rng.standard_normal(b).astype(
            np.float32
        ),
    }
    report = {
        "metric": "sharding_backend_ab_learn_step",
        "devices": len(jax.devices()),
        "config": {
            "train_batch": b,
            "minibatch": mb,
            "num_sgd_iter": iters,
        },
        "backends": {},
    }
    weights = {}
    for backend in ("mesh", "pmap"):
        mesh = (
            sl.get_mesh()
            if backend == "mesh"
            else legacy.make_mesh()
        )
        policy = PPOJaxPolicy(
            gym.spaces.Box(-10.0, 10.0, (16,), np.float32),
            gym.spaces.Discrete(6),
            {
                "_mesh": mesh,
                "sharding_backend": backend,
                "model": {"fcnet_hiddens": [64, 64]},
                "train_batch_size": b,
                "sgd_minibatch_size": mb,
                "num_sgd_iter": iters,
                "lr": 1e-4,
                "seed": 0,
            },
        )
        policy.learn_on_batch(SampleBatch(cols))  # compile
        times = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            policy.learn_on_batch(SampleBatch(cols))
            times.append(time.perf_counter() - t0)
        fn = policy.learn_fn(b)
        report["backends"][backend] = {
            "step_ms_median": round(
                1e3 * float(np.median(times)), 3
            ),
            "step_ms_p90": round(
                1e3 * float(np.quantile(times, 0.9)), 3
            ),
            "compile_s": round(
                getattr(fn, "compile_time_s", 0.0), 3
            ),
            "recompiles": getattr(fn, "recompiles", None),
            "transfer_s_last": round(
                policy.last_learn_timers.get(
                    "learn_transfer_s", 0.0
                ),
                5,
            ),
        }
        weights[backend] = jax.device_get(policy.params)
    import jax.tree_util as jtu

    report["parity_bitwise"] = all(
        np.array_equal(x, y)
        for x, y in zip(
            jtu.tree_leaves(weights["mesh"]),
            jtu.tree_leaves(weights["pmap"]),
        )
    )
    m = report["backends"]["mesh"]["step_ms_median"]
    p = report["backends"]["pmap"]["step_ms_median"]
    report["mesh_vs_pmap"] = round(p / m, 3) if m else None
    if out_path is None:
        import os

        os.makedirs("benchmarks", exist_ok=True)
        out_path = "benchmarks/sharding_ab.json"
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_telemetry_overhead(b=1024, mb=256, iters=2, rounds=20):
    """Disabled-vs-enabled tracing A/B on the SAME fixed-seed PPO
    learn step (small MLP geometry — isolates per-call instrumentation
    cost, not model compute). The ``tracing_off`` median is the
    regression sentinel for the default path: telemetry off must stay
    within noise of an uninstrumented build."""
    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.data.sample_batch import SampleBatch
    from ray_tpu.util import tracing

    rng = np.random.default_rng(0)
    cols = {
        SampleBatch.OBS: rng.standard_normal((b, 16)).astype(
            np.float32
        ),
        SampleBatch.ACTIONS: rng.integers(0, 6, b).astype(np.int64),
        SampleBatch.ACTION_LOGP: np.full(b, -1.79, np.float32),
        SampleBatch.ACTION_DIST_INPUTS: rng.standard_normal(
            (b, 6)
        ).astype(np.float32),
        SampleBatch.ADVANTAGES: rng.standard_normal(b).astype(
            np.float32
        ),
        SampleBatch.VALUE_TARGETS: rng.standard_normal(b).astype(
            np.float32
        ),
    }
    policy = PPOJaxPolicy(
        gym.spaces.Box(-10.0, 10.0, (16,), np.float32),
        gym.spaces.Discrete(6),
        {
            "model": {"fcnet_hiddens": [64, 64]},
            "train_batch_size": b,
            "sgd_minibatch_size": mb,
            "num_sgd_iter": iters,
            "lr": 1e-4,
            "seed": 0,
        },
    )
    policy.learn_on_batch(SampleBatch(cols))  # compile
    out = {}
    was_enabled = tracing.is_enabled()
    for mode in ("tracing_off", "tracing_on"):
        (tracing.enable if mode == "tracing_on" else tracing.disable)()
        times = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            policy.learn_on_batch(SampleBatch(cols))
            times.append(time.perf_counter() - t0)
        out[mode] = {
            "learn_step_ms_median": round(
                1e3 * float(np.median(times)), 3
            ),
            "learn_step_ms_p90": round(
                1e3 * float(np.quantile(times, 0.9)), 3
            ),
        }
    (tracing.enable if was_enabled else tracing.disable)()
    tracing.clear()
    off = out["tracing_off"]["learn_step_ms_median"]
    on = out["tracing_on"]["learn_step_ms_median"]
    out["on_vs_off"] = round(on / off, 3) if off else None
    return out


def bench_profile(trace_path=None, overhead_path=None):
    """One telemetry-instrumented PPO run (plumbing geometry, pipelined
    sampling): writes the chrome trace of the last iterations and a
    telemetry-overhead A/B entry; prints ONE summary JSON line with the
    ``info/telemetry`` roll-up (stage wall-times + overlap fraction)."""
    import os
    import urllib.request

    import ray_tpu.env.synthetic_env  # noqa: F401 registers SyntheticFast-v0
    from ray_tpu.algorithms.ppo import PPOConfig

    os.makedirs("benchmarks/e2e", exist_ok=True)
    trace_path = trace_path or "benchmarks/e2e/ppo_iteration_trace.json"
    overhead_path = (
        overhead_path or "benchmarks/e2e/telemetry_overhead.json"
    )
    cfg = (
        PPOConfig()
        .environment("SyntheticFast-v0")
        .rollouts(
            num_rollout_workers=2,
            num_envs_per_worker=8,
            rollout_fragment_length=128,
            sample_prefetch=1,
        )
        .training(
            train_batch_size=2048,
            sgd_minibatch_size=512,
            num_sgd_iter=2,
            lr=3e-4,
            model={"fcnet_hiddens": [64, 64]},
        )
        .debugging(seed=0)
        .telemetry(metrics_port=0, trace=True)
    )
    algo = cfg.build()
    try:
        tel = {}
        for _ in range(4):  # iter 1 compiles; spans settle by 3-4
            result = algo.train()
            tel = result["info"].get("telemetry", tel)
        algo.export_timeline(trace_path, last_n=2)
        port = algo._telemetry.metrics_port
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        series = sorted(
            {
                ln.split("{")[0].split(" ")[0]
                for ln in scrape.splitlines()
                if ln.startswith("ray_tpu_")
            }
        )
    finally:
        algo.cleanup()
    overhead = bench_telemetry_overhead()
    with open(overhead_path, "w") as f:
        json.dump(overhead, f, indent=1)
    report = {
        "metric": "ppo_iteration_profile",
        "telemetry": tel,
        "trace": trace_path,
        "metrics_series": series,
        "telemetry_overhead": overhead,
        "artifacts": [trace_path, overhead_path],
    }
    print(json.dumps(report))
    return report


def bench_replay_ab(out_path=None, iters=10):
    """Host-ring vs device-resident replay A/B on the SAC geometry
    (docs/data_plane.md): the SAME fixed-seed run — same env steps,
    same learn steps, bit-identical final params (asserted) — differing
    only in where replay rows live. Reports per-iteration H2D bytes by
    path: the host ring re-transfers every sampled train batch
    (``learn``), the device plane transfers each transition once at
    insert (``replay_insert``) — at this replay ratio (train batch 256
    over 32-step fragments) that is an 8× byte diet. Writes
    ``benchmarks/e2e/replay_device_ab.json``.

    On this 1-core CPU container the steps/s of the two sides is
    expected ~flat (device arrays live in the same RAM and compute
    shares the core); the byte columns and the parity flag are the
    result. On a tunneled/remote TPU the byte diet is wall-clock: the
    r05 bench measured 13.8 MB/s effective H2D, so every byte NOT
    re-crossing the wire is learner time."""
    import os

    import jax

    from ray_tpu.algorithms.sac import SACConfig
    from ray_tpu.telemetry import metrics as telemetry_metrics

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/replay_device_ab.json"

    def run(device_resident):
        cfg = (
            SACConfig()
            .environment("Pendulum-v1")
            .rollouts(
                num_rollout_workers=0, rollout_fragment_length=32
            )
            .training(
                train_batch_size=256,
                num_steps_sampled_before_learning_starts=256,
                replay_device_resident=device_resident,
            )
            .reporting(min_time_s_per_iteration=0)
            .debugging(seed=0)
        )
        algo = cfg.build()
        try:
            # warmup to learning-start + compile outside the clock
            while (
                algo._counters["num_env_steps_sampled"] < 256 + 32
            ):
                algo.train()
            h2d0 = telemetry_metrics.h2d_bytes_by_path()
            steps0 = algo._counters["num_env_steps_sampled"]
            t0 = time.perf_counter()
            for _ in range(iters):
                algo.train()
            wall = time.perf_counter() - t0
            env_steps = (
                algo._counters["num_env_steps_sampled"] - steps0
            )
            h2d1 = telemetry_metrics.h2d_bytes_by_path()
            params = jax.device_get(algo.get_policy().params)
            buf = algo.local_replay_buffer.buffers["default_policy"]
            resident = bool(
                getattr(buf, "is_device_resident", False)
                and not getattr(buf, "spilled", False)
            )
        finally:
            algo.cleanup()
        h2d = {
            k: h2d1.get(k, 0.0) - h2d0.get(k, 0.0)
            for k in set(h2d1) | set(h2d0)
        }
        return {
            "env_steps_per_s": round(env_steps / wall, 1),
            "env_steps": int(env_steps),
            "h2d_bytes_per_iter": {
                k: round(v / iters, 1) for k, v in h2d.items()
            },
            "buffer_device_resident": resident,
        }, params

    host_side, host_params = run(False)
    dev_side, dev_params = run(True)
    parity = all(
        np.array_equal(a, b)
        for a, b in zip(
            jax.tree_util.tree_leaves(host_params),
            jax.tree_util.tree_leaves(dev_params),
        )
    )
    learn_bytes = host_side["h2d_bytes_per_iter"].get("learn", 0.0)
    insert_bytes = dev_side["h2d_bytes_per_iter"].get(
        "replay_insert", 0.0
    )
    report = {
        "metric": "replay_device_ab",
        "config": {
            "env": "Pendulum-v1",
            "train_batch_size": 256,
            "rollout_fragment_length": 32,
            "iters": iters,
            "seed": 0,
        },
        "host_ring": host_side,
        "device_resident": dev_side,
        "h2d_learn_vs_insert_ratio": round(
            learn_bytes / insert_bytes, 2
        )
        if insert_bytes
        else None,
        "parity_bitwise": parity,
        "note": (
            "steps/s is expected ~flat on this 1-core CPU container "
            "(no real H2D wire, compute shares the core); the byte "
            "diet is the result — on the tunneled TPU of BENCH_r05 "
            "(13.8 MB/s effective H2D) every re-crossed byte is "
            "learner wall-clock"
        ),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_superstep(
    out_path=None, b=256, mb=64, iters=2, kmax=8, reps=2,
):
    """Dispatch-amortization A/B of the fused superstep
    (docs/data_plane.md): per-update wall and dispatch/readback
    overhead at K=1 (the ``deferred_stats`` per-update protocol — the
    best the un-fused path can do) vs K∈{2, kmax} updates per
    dispatch (``JaxPolicy.learn_superstep``), on device-resident
    batches so the numbers isolate the host-boundary cost from H2D.
    Nest compute is epoch-isolated exactly like ``bench_mfu``, so
    ``overhead = wall − compute`` on both sides and the "nest compute
    unchanged" check is the in-scan marginal cost. Writes
    ``benchmarks/e2e/superstep_ab.json``. Defaults are a CPU smoke
    geometry (1/16 batch of the headline bench, 2 epochs — the Nature
    CNN runs minutes per full nest on a 1-core box); the TPU driver
    run re-measures at the r05 geometry via the ``superstep``
    sub-entry of ``bench_mfu``."""
    import os

    import jax

    from ray_tpu import sharding as sharding_lib
    from ray_tpu.policy.jax_policy import _FRAMES as _F

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/superstep_ab.json"
    rng = np.random.default_rng(0)

    # epoch-isolated nest compute (bench_mfu method)
    lo, hi = iters, 4 * iters
    setups = {}
    for it in (lo, hi):
        p = _make_policy(b, mb, it)
        host, bsize = p.prepare_batch(make_batch(rng, b))
        dev = jax.device_put(host, p.batch_shardings(host))
        p.learn_on_device_batch(dict(dev), bsize)  # compile+warm
        setups[it] = (p, dev, bsize, host)
    ts = {lo: [], hi: []}
    for _ in range(reps):
        for it, (p, dev, bsize, _h) in setups.items():
            t0 = time.perf_counter()
            p.learn_on_device_batch(dict(dev), bsize)
            ts[it].append(time.perf_counter() - t0)
    compute = float(
        (np.median(ts[hi]) - np.median(ts[lo])) / (hi - lo) * iters
    )

    p, dev, bsize, host = setups[lo]

    # K=1 baseline: deferred-stats per-update dispatch
    n1 = 2 * reps
    p.config["deferred_stats"] = True
    try:
        p.learn_on_device_batch(dict(dev), bsize)  # prime the lag
        t0 = time.perf_counter()
        for _ in range(n1):
            p.learn_on_device_batch(dict(dev), bsize)
        p.flush_deferred_stats()
        wall1 = (time.perf_counter() - t0) / n1
    finally:
        p.config["deferred_stats"] = False
        p.flush_deferred_stats()

    walls = {}
    for k in (2, kmax):
        stacked = {
            cn: np.repeat(np.asarray(v)[None], k, axis=0)
            for cn, v in host.items()
        }
        shard = {
            cn: (
                sharding_lib.replicated(p.mesh)
                if cn == _F
                else sharding_lib.batch_sharded(p.mesh, ndim_prefix=2)
            )
            for cn in stacked
        }
        dev_stacked = jax.device_put(stacked, shard)
        jax.block_until_ready(dev_stacked)
        p.learn_superstep(
            k, bsize, stacked=dict(dev_stacked), k_max=k
        )  # compile+warm
        n = max(2, reps)
        t0 = time.perf_counter()
        for _ in range(n):
            p.learn_superstep(
                k, bsize, stacked=dict(dev_stacked), k_max=k
            )
        walls[k] = (time.perf_counter() - t0) / (n * k)

    # "nest compute unchanged": the overhead-free marginal cost per
    # update INSIDE the scan — (T_kmax − T_2)/(kmax − 2) per dispatch.
    # Overheads subtract the LOWER of the two compute estimates (the
    # epoch-scaling one carries its own measurement noise and can land
    # a hair above a fused wall, which would clamp real overhead to 0)
    compute_in_scan = (walls[kmax] * kmax - walls[2] * 2) / (kmax - 2)
    compute_best = min(compute, compute_in_scan)

    def overhead(wall):
        return round(max(wall - compute_best, 0.0), 4)

    per_update = {
        "k1_deferred": {
            "wall_s": round(wall1, 4),
            "dispatch_overhead_s": overhead(wall1),
        },
    }
    for k in (2, kmax):
        per_update[f"k{k}"] = {
            "wall_s": round(walls[k], 4),
            "dispatch_overhead_s": overhead(walls[k]),
        }
    o1 = max(wall1 - compute_best, 0.0)
    ok = max(walls[kmax] - compute_best, 1e-4)
    report = {
        "metric": "superstep_dispatch_ab",
        "config": {
            "train_batch": b,
            "minibatch": mb,
            "num_sgd_iter": iters,
            "obs": [H, W, C],
            "kmax": kmax,
            "reps": reps,
            "device": jax.devices()[0].device_kind,
        },
        "nest_compute_s": round(compute, 4),
        "nest_compute_in_scan_s": round(compute_in_scan, 4),
        "per_update": per_update,
        "overhead_reduction_kmax_vs_k1": round(o1 / ok, 1),
        "note": (
            "device-resident feeds on both sides: the A/B isolates "
            "the per-dispatch host-boundary cost. k1_deferred is the "
            "un-fused path's best protocol (stats lag 1); the "
            "superstep pays one dispatch + one stats drain per K "
            "updates, so its per-update overhead is ~1/K of the "
            "baseline's. nest_compute_in_scan_s ≈ nest_compute_s "
            "checks the scan added no per-update compute"
        ),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_model_parallel(out_path=None, m=4, reps=4):
    """Replicated vs 2-D-partitioned transformer A/B
    (docs/sharding.md "2-D mesh & param partitioning"): the SAME
    fixed-seed transformer-PPO learn step on [("batch", D)] with
    replicated params vs [("batch", D//M), ("model", M)] with
    megatron-rule param placement — the geometry where replication is
    the memory wall: every device holds the full tree on the left,
    ~1/M of it on the right. Asserts per-shard ``params_bytes`` ~
    total/M, fixed-seed parity (model_parallel=1 bitwise; M-way to
    float-assoc tolerance — cross-shard reduction order), and zero
    recompiles in the timed window. Writes
    ``benchmarks/e2e/model_parallel_ab.json``. Runs itself under 8
    simulated host devices when the process has fewer."""
    import os
    import subprocess

    import jax

    from ray_tpu import sharding as sharding_lib

    if (
        len(jax.devices()) < 2 * m
        and not os.environ.get("_RT_MP_CHILD")
    ):
        env = {
            **os.environ,
            **sharding_lib.simulated_device_env(2 * m),
            "_RT_MP_CHILD": "1",
        }
        subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--model-parallel"],
            env=env,
            check=True,
        )
        return

    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.sharding.compile import compile_stats

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/model_parallel_ab.json"
    n_dev = len(jax.devices())
    b, obs_dim = 512, 64
    model = {
        "use_transformer": True,
        "transformer_dim": 256,
        "transformer_num_layers": 4,
        "transformer_num_heads": 8,
        "transformer_ff_dim": 1024,
        "transformer_seq_len": 8,
    }

    def make(mesh):
        return PPOJaxPolicy(
            gym.spaces.Box(-1, 1, (obs_dim,), np.float32),
            gym.spaces.Discrete(8),
            {
                "train_batch_size": b,
                "sgd_minibatch_size": b // 2,
                "num_sgd_iter": 2,
                "lr": 3e-4,
                "seed": 0,
                "model": dict(model),
                "_mesh": mesh,
            },
        )

    rng = np.random.default_rng(0)
    host = {
        "obs": rng.standard_normal((b, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, 8, b).astype(np.int64),
        "action_logp": np.full(b, -2.0, np.float32),
        "action_dist_inputs": rng.standard_normal((b, 8)).astype(
            np.float32
        ),
        "advantages": rng.standard_normal(b).astype(np.float32),
        "value_targets": rng.standard_normal(b).astype(np.float32),
    }

    arms = {
        "replicated": sharding_lib.get_mesh(
            devices=jax.devices()[:n_dev]
        ),
        "model_parallel_1": sharding_lib.get_mesh(
            devices=jax.devices()[:n_dev],
            axis_shapes=[("batch", n_dev), ("model", 1)],
        ),
        f"model_parallel_{m}": sharding_lib.get_mesh(
            devices=jax.devices()[:n_dev],
            axis_shapes=[("batch", n_dev // m), ("model", m)],
        ),
    }
    results = {}
    weights = {}
    for name, mesh in arms.items():
        p = make(mesh)
        prep, bsize = p.prepare_batch(dict(host))
        dev = jax.device_put(prep, p.batch_shardings(prep))
        stats = p.learn_on_device_batch(dict(dev), bsize)  # warm
        weights[name] = p.get_weights()
        total = sharding_lib.tree_nbytes(p.params)
        per_shard = (
            sharding_lib.tree_shard_nbytes(
                p.params, p.param_pspecs, p.mesh
            )
            if p.param_pspecs is not None
            else total
        )
        traces0 = compile_stats()["traces"]
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            p.learn_on_device_batch(dict(dev), bsize)
            ts.append(time.perf_counter() - t0)
        results[name] = {
            "params_bytes_total": int(total),
            "params_bytes_per_shard": int(per_shard),
            "learn_wall_s_median": round(float(np.median(ts)), 4),
            "first_step_total_loss": float(stats["total_loss"]),
            "recompiles_in_timed_window": (
                compile_stats()["traces"] - traces0
            ),
        }

    # parity reference for the M-way arm: replicated on the SAME
    # data-shard count (D//M shards), so the per-shard shuffle streams
    # match and the ONLY difference is the model-axis split
    p_ref = make(
        sharding_lib.get_mesh(devices=jax.devices()[: n_dev // m])
    )
    prep, bsize = p_ref.prepare_batch(dict(host))
    dev = jax.device_put(prep, p_ref.batch_shardings(prep))
    p_ref.learn_on_device_batch(dict(dev), bsize)
    weights["replicated_ref"] = p_ref.get_weights()

    la = jax.tree_util.tree_leaves(weights["replicated"])
    l1 = jax.tree_util.tree_leaves(weights["model_parallel_1"])
    lr_ = jax.tree_util.tree_leaves(weights["replicated_ref"])
    lm = jax.tree_util.tree_leaves(weights[f"model_parallel_{m}"])
    parity_bitwise_mp1 = all(
        np.array_equal(a, c) for a, c in zip(la, l1)
    )
    parity_allclose_mpm = all(
        np.allclose(a, c, atol=5e-3) for a, c in zip(lr_, lm)
    )
    mp = results[f"model_parallel_{m}"]
    shard_ratio = (
        mp["params_bytes_per_shard"] / mp["params_bytes_total"]
    )
    out = {
        "metric": "model_parallel_ab",
        "devices": n_dev,
        "model_parallel": m,
        "geometry": {
            "batch": b,
            **{k: v for k, v in model.items() if k != "use_transformer"},
        },
        "arms": results,
        # the memory-wall headline: one device's param bytes, and how
        # close the split tree sits to the ideal total/M
        "per_shard_over_total": round(shard_ratio, 4),
        "ideal_over_total": round(1.0 / m, 4),
        "parity_bitwise_mp1": bool(parity_bitwise_mp1),
        f"parity_allclose_mp{m}": bool(parity_allclose_mpm),
    }
    assert parity_bitwise_mp1, "model_parallel=1 must be bitwise"
    assert parity_allclose_mpm, f"{m}-way parity failed"
    assert shard_ratio < 1.0 / m + 0.1, (
        f"per-shard bytes {shard_ratio:.3f} of total; expected ~1/{m}"
    )
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    return out


def bench_chaos(out_path=None, iters=6):
    """Chaos A/B (docs/resilience.md): steady-state PPO iteration time
    vs the same run with a rollout-worker kill and one NaN learn batch
    injected mid-run. Measures what a failure actually costs — the
    recovery time (probe + recreate + resync) and its
    iterations-lost equivalent — and proves the run completes with the
    fleet restored. Writes benchmarks/e2e/chaos_recovery.json."""
    import os

    import ray_tpu.env.synthetic_env  # noqa: F401 registers SyntheticFast-v0
    from ray_tpu.algorithms.ppo import PPOConfig
    from ray_tpu.telemetry import metrics as telemetry_metrics

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/chaos_recovery.json"

    def build(fault_injection):
        return (
            PPOConfig()
            .environment("SyntheticFast-v0")
            .rollouts(
                num_rollout_workers=4,
                num_envs_per_worker=4,
                rollout_fragment_length=64,
            )
            .training(
                train_batch_size=1024,
                sgd_minibatch_size=256,
                num_sgd_iter=2,
                lr=3e-4,
                model={"fcnet_hiddens": [32, 32]},
            )
            .fault_tolerance(
                recreate_failed_workers=True,
                nan_guard=True,
                worker_health_probe_timeout_s=10.0,
                fault_injection=fault_injection,
            )
            .debugging(seed=0)
            .build()
        )

    def timed_run(algo, n):
        times, last = [], {}
        for _ in range(n):
            t0 = time.perf_counter()
            last = algo.train()
            times.append(time.perf_counter() - t0)
        return times, last

    # A: steady state (injector inert, same guard/recreate config)
    algo = build({})
    try:
        timed_run(algo, 1)  # compile + fleet spin-up
        steady_times, _ = timed_run(algo, iters)
    finally:
        algo.cleanup()
    steady_median = float(np.median(steady_times))

    # B: kill one worker on its 2nd sample call, poison one learn batch
    restarts0 = telemetry_metrics.counter_total(
        telemetry_metrics.WORKER_RESTARTS_TOTAL
    )
    skipped0 = telemetry_metrics.counter_total(
        telemetry_metrics.SKIPPED_BATCHES_TOTAL
    )
    algo = build(
        {
            "kill_worker": [{"worker_index": 2, "on_call": 2}],
            "nan_batch": {"on_learn_call": 3},
        }
    )
    try:
        timed_run(algo, 1)
        chaos_times, last = timed_run(algo, iters)
        fleet_after = algo.workers.num_remote_workers()
        recovery = last["info"]["recovery"]
    finally:
        algo.cleanup()

    lost_s = max(0.0, sum(chaos_times) - iters * steady_median)
    report = {
        "metric": "chaos_recovery",
        "steady_state_s_per_iter_median": round(steady_median, 4),
        "chaos_iter_times_s": [round(t, 4) for t in chaos_times],
        "recovery_time_s": round(recovery["time_lost_s"], 4),
        "excess_wall_clock_s": round(lost_s, 4),
        "iterations_lost_equiv": round(lost_s / steady_median, 2)
        if steady_median
        else None,
        "worker_restarts": telemetry_metrics.counter_total(
            telemetry_metrics.WORKER_RESTARTS_TOTAL
        )
        - restarts0,
        "skipped_nan_batches": telemetry_metrics.counter_total(
            telemetry_metrics.SKIPPED_BATCHES_TOTAL
        )
        - skipped0,
        "fleet_restored_to": fleet_after,
        "config": {
            "num_rollout_workers": 4,
            "train_batch_size": 1024,
            "faults": "kill worker 2 @ sample call 2; "
            "NaN batch @ learn call 3",
        },
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_elastic(out_path=None):
    """Elastic-fleet chaos A/B (docs/resilience.md "elastic fleets &
    preemption"). Three phases:

    A) **elastic**: a PPO fleet forced 4 → 2 via two noticed
       preemptions (drained gracefully, zero recovery budget), then
       → 6 via an autoscaler scale-up; per-iteration steps/s grouped
       by fleet size.
    B) **kill-only** (the PR-4 path): the same two workers die with
       NO notice; recovery = probe + recreate. Drain vs kill cost.
    C) **driver crash**: work lost restoring from the continuous
       checkpoint stream (≤ 1 superstep) vs the periodic path (up to
       ``checkpoint_frequency`` iterations), plus the streamer's
       off-critical-path overhead (iteration time with streaming on
       vs off).

    Writes benchmarks/e2e/elastic_fleet.json."""
    import os
    import shutil

    import ray_tpu.env.synthetic_env  # noqa: F401 registers SyntheticFast-v0
    from ray_tpu.algorithms.ppo import PPOConfig

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/elastic_fleet.json"

    def build(elastic, fault_injection=None, **ft):
        cfg = (
            PPOConfig()
            .environment("SyntheticFast-v0")
            .rollouts(
                num_rollout_workers=4,
                num_envs_per_worker=4,
                rollout_fragment_length=64,
            )
            .training(
                train_batch_size=1024,
                sgd_minibatch_size=256,
                num_sgd_iter=2,
                lr=3e-4,
                model={"fcnet_hiddens": [32, 32]},
            )
            .fault_tolerance(
                recreate_failed_workers=True,
                worker_health_probe_timeout_s=10.0,
                fault_injection=fault_injection or {},
                **ft,
            )
            .debugging(seed=0)
        )
        if elastic:
            cfg.fault_tolerance(
                elastic=True,
                min_workers=2,
                max_workers=6,
                drain_grace_s=120.0,
                fleet_interval_s=0.2,
            )
        return cfg.build()

    def timed_iters(algo, n):
        out = []
        for _ in range(n):
            t0 = time.perf_counter()
            r = algo.train()
            dt = time.perf_counter() - t0
            out.append(
                (
                    dt,
                    algo.workers.num_remote_workers(),
                    r["info"]["recovery"],
                )
            )
        return out

    # ---- A: elastic — 4 → 2 (noticed preemptions) → 6 (scale-up) ----
    faults = {
        "preempt_worker": [
            {"worker_index": 2, "on_call": 2, "grace_s": 120.0},
            {"worker_index": 3, "on_call": 3, "grace_s": 120.0},
        ]
    }
    algo = build(elastic=True, fault_injection=faults)
    per_fleet = {}
    try:
        timed_iters(algo, 1)  # compile + spin-up
        rows = timed_iters(algo, 4)
        # bounded patience for the async notice polls to drain both
        for _ in range(8):
            if rows[-1][2]["preemptions_drained"] >= 2:
                break
            rows += timed_iters(algo, 1)
        drain_rows = list(rows)
        algo._fleet.request_scale(+4)  # → max_workers = 6
        rows += timed_iters(algo, 3)
        steps_per_iter = 1024.0
        for dt, fleet, _ in rows:
            per_fleet.setdefault(fleet, []).append(
                steps_per_iter / dt
            )
        rec = rows[-1][2]
        elastic_report = {
            "fleet_trajectory": [fleet for _, fleet, _ in rows],
            "steps_per_s_by_fleet_size": {
                str(k): round(float(np.median(v)), 1)
                for k, v in sorted(per_fleet.items())
            },
            "preemptions_drained": rec["preemptions_drained"],
            "recovery_budget_spent": rec["failures"],
            "drain_iter_times_s": [
                round(dt, 4) for dt, _, _ in drain_rows
            ],
            "fleet": rec["fleet"],
        }
    finally:
        algo.cleanup()

    # ---- B: kill-only (unnoticed) — the PR-4 recovery path ----
    algo = build(
        elastic=False,
        fault_injection={
            "kill_worker": [
                {"worker_index": 2, "on_call": 2},
                {"worker_index": 3, "on_call": 3},
            ]
        },
        max_failures=10,
    )
    try:
        timed_iters(algo, 1)
        rows = timed_iters(algo, 6)
        rec = rows[-1][2]
        kill_report = {
            "iter_times_s": [round(dt, 4) for dt, _, _ in rows],
            "recovery_time_s": rec["time_lost_s"],
            "worker_restarts": rec["worker_restarts"],
            "recovery_budget_spent": rec["failures"],
        }
    finally:
        algo.cleanup()

    # ---- C: driver crash — streamed vs periodic work lost ----
    root = "/tmp/ray_tpu_bench_elastic_ckpt"
    shutil.rmtree(root, ignore_errors=True)

    def build_local(streaming):
        return (
            PPOConfig()
            .environment("SyntheticFast-v0")
            .rollouts(
                num_rollout_workers=0,
                num_envs_per_worker=4,
                rollout_fragment_length=64,
            )
            .training(
                train_batch_size=256,
                sgd_minibatch_size=128,
                num_sgd_iter=2,
                lr=3e-4,
                model={"fcnet_hiddens": [32, 32]},
            )
            .fault_tolerance(
                checkpoint_streaming=streaming,
                checkpoint_frequency=5,
                checkpoint_root=root,
                restore_on_failure=True,
            )
            .debugging(seed=0)
            .build()
        )

    # streaming off: baseline iteration time + the periodic loss bound
    algo = build_local(streaming=False)
    try:
        timed_iters(algo, 1)
        base_times = [dt for dt, _, _ in timed_iters(algo, 6)]
        crashed_iter = algo.iteration
        # newest periodic save at checkpoint_frequency = 5
        periodic_ckpt_iter = (crashed_iter // 5) * 5
    finally:
        algo.cleanup()
    periodic_lost_iters = crashed_iter - periodic_ckpt_iter

    shutil.rmtree(root, ignore_errors=True)
    algo = build_local(streaming=True)
    try:
        timed_iters(algo, 1)
        stream_times = [dt for dt, _, _ in timed_iters(algo, 6)]
        head = algo._ckpt_streamer._superstep
        algo._ckpt_streamer.flush()
    finally:
        algo.cleanup()  # the "crash"
    restored = build_local(streaming=True)
    try:
        path = restored._recovery.restore_latest()
        from ray_tpu.resilience.streamer import CheckpointStreamer

        tail = CheckpointStreamer.peek(path)["superstep"]
    finally:
        restored.cleanup()

    crash_report = {
        "streamed_lost_supersteps": head - tail,
        "periodic_lost_iterations": periodic_lost_iters,
        "iter_s_streaming_off_median": round(
            float(np.median(base_times)), 4
        ),
        "iter_s_streaming_on_median": round(
            float(np.median(stream_times)), 4
        ),
        "restored_from": path,
    }

    report = {
        "metric": "elastic_fleet",
        "elastic": elastic_report,
        "kill_only": kill_report,
        "driver_crash": crash_report,
        "config": {
            "num_rollout_workers": 4,
            "min_workers": 2,
            "max_workers": 6,
            "train_batch_size": 1024,
            "faults_elastic": "preempt worker 2 @ call 2, worker 3 "
            "@ call 3 (grace 120 s); scale-up +4 after drains",
            "faults_kill": "kill workers 2, 3 (no notice)",
        },
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_fleet_worker():
    """Subprocess entry for the --fleet lane (one learner host of a
    gloo CPU fleet). Mirrors tests/_multihost_worker.py's protocol but
    measures walls: steps/s over the epoch mesh, then (2-host modes)
    the drain-vs-kill recovery and the resize wall. Rank 0 prints one
    ``FLEETBENCH {json}`` line."""
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    import gymnasium as gym

    from ray_tpu import fleet
    from ray_tpu import sharding as sharding_lib
    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.data.sample_batch import SampleBatch
    from ray_tpu.parallel import distributed as dist

    rank = int(os.environ["RAY_TPU_PROCESS_ID"])
    world = int(os.environ["RAY_TPU_NUM_PROCESSES"])
    mode = os.environ.get("RAY_TPU_FLEET_BENCH_MODE", "drain")
    aot_root = os.environ.get("RAY_TPU_FLEET_BENCH_AOT", "")
    if world > 1:
        dist.initialize()

    kv = fleet.KVClient(os.environ["RAY_TPU_KV_ADDRESS"])
    coord = fleet.FleetCoordinator(kv) if rank == 0 else None
    agent = fleet.HostAgent(
        kv, f"host{rank}", rank_hint=rank, heartbeat_interval=0.5
    )
    agent.join()
    if rank == 0:
        coord.wait_for_members(world, timeout=60.0)
        coord.propose_epoch(reason="bootstrap")
    epoch1 = agent.wait_for_epoch(1)
    mesh = fleet.epoch_mesh(epoch1)

    B = 64
    config = {
        "_mesh": mesh,
        "model": {"fcnet_hiddens": [32, 32]},
        "train_batch_size": B,
        "sgd_minibatch_size": 32,
        "num_sgd_iter": 2,
        "lr": 3e-4,
        "seed": 0,
    }
    if aot_root:
        config["aot_cache_dir"] = os.path.join(
            aot_root, f"rank{rank}"
        )
    obs_space = gym.spaces.Box(-1.0, 1.0, (16,), np.float32)
    act_space = gym.spaces.Discrete(4)
    policy = PPOJaxPolicy(obs_space, act_space, config)
    rng = np.random.default_rng(7)
    host = {
        SampleBatch.OBS: rng.standard_normal((B, 16)).astype(
            np.float32
        ),
        SampleBatch.ACTIONS: rng.integers(0, 4, B).astype(np.int64),
        SampleBatch.ACTION_LOGP: np.full(B, -1.4, np.float32),
        SampleBatch.ACTION_DIST_INPUTS: rng.standard_normal(
            (B, 4)
        ).astype(np.float32),
        SampleBatch.ADVANTAGES: rng.standard_normal(B).astype(
            np.float32
        ),
        SampleBatch.VALUE_TARGETS: rng.standard_normal(B).astype(
            np.float32
        ),
    }
    tree, bsize = policy.prepare_batch(SampleBatch(host))
    global_batch = {
        k: sharding_lib.put_global(v, policy.data_sharding)
        for k, v in tree.items()
    }
    policy.learn_on_device_batch(global_batch, bsize)  # compile
    walls = []
    for _ in range(6):
        t0 = time.perf_counter()
        policy.learn_on_device_batch(global_batch, bsize)
        walls.append(time.perf_counter() - t0)
    steps_per_s = B / float(np.median(walls))

    if world == 1:
        print(
            "FLEETBENCH "
            + json.dumps(
                {"hosts": 1, "steps_per_s": round(steps_per_s, 1)}
            )
        )
        agent.stop()
        coord.stop()
        return

    if mode == "kill":
        # the victim dies with NO notice; the survivor's heartbeat
        # sweep must detect it (the gcs_heartbeat_manager path)
        if rank == 1:
            kv.put("bench/kill_ts", time.time())
            agent.stop()
            os._exit(0)
        kill_ts = kv.get("bench/kill_ts", timeout=60.0)
        deadline = time.monotonic() + 60.0
        while True:
            coord.reconcile()
            coord.expire_dead(horizon=2.0)
            ep = coord.current_epoch()
            if ep is not None and ep.gen >= 2:
                break
            if time.monotonic() >= deadline:
                raise TimeoutError("kill never detected")
            time.sleep(0.05)
        survivor = fleet.resize_policy(
            policy, fleet.epoch_mesh(coord.current_epoch())
        )
        survivor.learn_on_batch(SampleBatch(host))
        recovery_wall = time.time() - kill_ts
        fn = survivor.learn_fn(bsize)
        print(
            "FLEETBENCH "
            + json.dumps(
                {
                    "hosts": 2,
                    "mode": "kill",
                    "steps_per_s": round(steps_per_s, 1),
                    "recovery_wall_s": round(recovery_wall, 3),
                    "resize_aot_source": fn.aot_source,
                    "resize_traces": fn.traces,
                }
            )
        )
        # rank1 is gone: skip jax.distributed teardown
        os._exit(0)

    # drain mode: provider-noticed preemption of host1
    if rank == 1:
        kv.put("bench/notice_ts", time.time())
        agent.announce_notice(reason="preempted")
    if rank == 0:
        deadline = time.monotonic() + 60.0
        while agent.poll_drain(1) is None:
            coord.reconcile()
            if time.monotonic() >= deadline:
                raise TimeoutError("drain never posted")
            time.sleep(0.02)
    agent.await_drain(1)
    policy.learn_on_device_batch(global_batch, bsize)  # drain step
    agent.barrier("drained", epoch1)
    if rank == 1:
        agent.leave()
        kv.get("bench/solo_done", timeout=120.0)
        agent.stop()
        return
    notice_ts = kv.get("bench/notice_ts", timeout=10.0)
    epoch2 = agent.wait_for_epoch(2)
    t0 = time.perf_counter()
    survivor = fleet.resize_policy(policy, fleet.epoch_mesh(epoch2))
    survivor.learn_on_batch(SampleBatch(host))
    resize_wall = time.perf_counter() - t0
    recovery_wall = time.time() - notice_ts
    fn = survivor.learn_fn(bsize)
    print(
        "FLEETBENCH "
        + json.dumps(
            {
                "hosts": 2,
                "mode": "drain",
                "steps_per_s": round(steps_per_s, 1),
                "recovery_wall_s": round(recovery_wall, 3),
                "resize_wall_s": round(resize_wall, 3),
                "resize_aot_source": fn.aot_source,
                "resize_traces": fn.traces,
            }
        )
    )
    kv.put("bench/solo_done", True)
    coord.stop()
    agent.stop()


def bench_fleet(out_path=None):
    """Elastic learner-fleet lane (docs/fleet.md): gloo CPU fleets of
    1 and 2 hosts (2 virtual devices each) through the full
    rendezvous → epoch → lockstep-learn protocol. Reports

      - steps/s at hosts ∈ {1, 2} and the DCN scaling efficiency;
      - drain (provider-noticed) vs kill (heartbeat-detected)
        recovery wall: notice/death → first post-resize step done;
      - the resize wall with a pre-seeded AOT cache vs cold — the
        warm-cache-restart headline (warm resize performs zero fresh
        compiles; `resize_traces` in the JSON asserts it).

    Writes benchmarks/e2e/fleet.json."""
    import os
    import shutil
    import socket
    import subprocess
    import tempfile

    from ray_tpu.fleet import KVServer

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/fleet.json"
    aot_root = tempfile.mkdtemp(prefix="ray_tpu_fleet_bench_aot_")

    def run(world, mode="drain", preseed=True):
        kv = KVServer(host="127.0.0.1")
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coord_port = s.getsockname()[1]
        env_base = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "RAY_TPU_PLATFORM": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "RAY_TPU_NUM_PROCESSES": str(world),
            "RAY_TPU_KV_ADDRESS": f"127.0.0.1:{kv.port}",
            "RAY_TPU_FLEET_BENCH_MODE": mode,
            "RAY_TPU_FLEET_BENCH_AOT": aot_root if preseed else "",
            "RAY_TPU_FLEET_PRESEED": "1" if preseed else "0",
        }
        if world > 1:
            env_base["RAY_TPU_COORDINATOR"] = (
                f"127.0.0.1:{coord_port}"
            )
        procs = []
        for rank in range(world):
            env = {**env_base, "RAY_TPU_PROCESS_ID": str(rank)}
            procs.append(
                subprocess.Popen(
                    [sys.executable, __file__, "--fleet-worker"],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            kv.shutdown()
        for rank, (p, out) in enumerate(zip(procs, outs)):
            if p.returncode != 0:
                raise RuntimeError(
                    f"fleet bench rank {rank} failed:\n{out}"
                )
        for line in outs[0].splitlines():
            if line.startswith("FLEETBENCH "):
                return json.loads(line[len("FLEETBENCH ") :])
        raise RuntimeError(f"no FLEETBENCH line:\n{outs[0]}")

    one = run(world=1)
    warm = run(world=2, mode="drain", preseed=True)
    cold = run(world=2, mode="drain", preseed=False)
    kill = run(world=2, mode="kill", preseed=True)
    shutil.rmtree(aot_root, ignore_errors=True)

    report = {
        "metric": "fleet_elastic_learner_mesh",
        "steps_per_s_by_hosts": {
            "1": one["steps_per_s"],
            "2": warm["steps_per_s"],
        },
        # 2 hosts double the devices over a CPU "DCN": efficiency is
        # steps/s parity at the SAME global batch (weak scaling of
        # the collective, not more throughput)
        "dcn_scaling_efficiency": round(
            warm["steps_per_s"] / one["steps_per_s"], 3
        ),
        "drain_recovery_wall_s": warm["recovery_wall_s"],
        "kill_recovery_wall_s": kill["recovery_wall_s"],
        "resize_wall_s": {
            "preseeded_aot": warm["resize_wall_s"],
            "cold": cold["resize_wall_s"],
        },
        "resize_speedup_from_preseed": round(
            cold["resize_wall_s"] / max(warm["resize_wall_s"], 1e-9),
            2,
        ),
        "warm_resize_fresh_compiles": warm["resize_traces"],
        "warm_resize_aot_source": warm["resize_aot_source"],
        "config": {
            "world": 2,
            "devices_per_host": 2,
            "train_batch_size": 64,
            "collectives": "gloo (CPU stand-in for DCN)",
            "kill_detection_horizon_s": 2.0,
        },
        "note": (
            "on the gloo/localhost stand-in every gradient pmean is "
            "a socket round trip, so 2-host steps/s measures the "
            "protocol's lockstep correctness, not DCN bandwidth — "
            "the scaling headline belongs to the TPU round; the "
            "portable numbers here are the recovery walls and the "
            "preseed speedup"
        ),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_fleet_chaos(out_path=None):
    """Control-plane failover lane (docs/fleet.md "failure model &
    leadership"): how long the fleet is headless after its coordinator
    dies, as a function of the lease TTL.

    Per TTL, three trials of the chaos choreography on an in-process
    KV server — no learners, because the coordinator is never on the
    data path, so the portable number is pure control-plane wall: a
    leader at term 1 registers 2 hosts and cuts epoch 1; the leader
    "crashes" (renew loop stops, lease NOT released — a SIGKILL; the
    TTL must run out); an armed standby polls the lease, wins at term
    2, rebuilds the member/epoch mirror from the KV table, and cuts
    the failover epoch. The recorded wall runs kill → failover epoch
    cut (the moment hosts can resume), and the acceptance gate is
    median wall < 2x the lease TTL. Every trial also proves the
    fence: the zombie's stale-term write must raise StaleTermError
    and land in the store's fenced-write count. A clean-handover
    trial (lease released on stop) rides along per TTL — its wall is
    TTL-independent, which is the lane's point: the price of
    crash-failover IS the TTL you chose.

    Writes benchmarks/e2e/fleet_chaos.json."""
    import statistics

    from ray_tpu import fleet
    from ray_tpu.fleet import KVClient, KVServer, StaleTermError

    out_path = out_path or "benchmarks/e2e/fleet_chaos.json"
    ttls = [0.5, 1.0, 2.0]
    trials = 3

    def one_trial(ttl, release):
        server = KVServer(host="127.0.0.1")
        kv = KVClient(f"127.0.0.1:{server.port}")
        try:
            leader = fleet.FleetCoordinator(
                kv, lease_ttl=ttl, holder="leader", subscribe=False
            )
            leader.register_host("host0", rank_hint=0)
            leader.register_host("host1", rank_hint=1)
            leader.propose_epoch(reason="bootstrap")
            standby = fleet.FleetCoordinator(
                kv,
                standby=True,
                lease_ttl=ttl,
                holder="standby",
                subscribe=False,
            )
            t0 = time.perf_counter()
            leader.stop(release_lease=release)
            term = standby.acquire_leadership(timeout=10.0 + 3 * ttl)
            assert term == 2 and standby.is_leader, term
            # warm-cache restart: mirror rebuilt from the KV table
            assert sorted(standby.members()) == ["host0", "host1"]
            assert standby.current_epoch().gen == 1
            epoch = standby.propose_epoch(reason="failover")
            wall = time.perf_counter() - t0
            assert epoch.gen == 2 and epoch.hosts == (
                "host0",
                "host1",
            ), epoch
            # split-brain counter-proof: the zombie acts at term 1
            try:
                leader._put("fleet/members", {})
                raise AssertionError("zombie write was not fenced")
            except StaleTermError:
                pass
            info = kv.lease_info(fleet.LEASE_NAME)
            assert info["fenced_writes"] >= 1, info
            standby.stop()
            return wall
        finally:
            server.shutdown()

    rows = []
    for ttl in ttls:
        kills = [one_trial(ttl, release=False) for _ in range(trials)]
        clean = one_trial(ttl, release=True)
        med = statistics.median(kills)
        # the acceptance gate: a crashed coordinator costs at most
        # two TTLs of headless fleet (in practice ~1x: lease residue
        # at kill + the standby's poll cadence of TTL/4)
        assert med < 2.0 * ttl, (med, ttl)
        rows.append(
            {
                "lease_ttl_s": ttl,
                "kill_failover_walls_s": [round(w, 3) for w in kills],
                "kill_failover_median_s": round(med, 3),
                "clean_handover_wall_s": round(clean, 3),
                "median_wall_over_ttl": round(med / ttl, 2),
            }
        )

    report = {
        "metric": "fleet_chaos_failover",
        "failover_by_ttl": rows,
        "budget": "median kill-failover wall < 2x lease TTL",
        "fenced_write_proof": (
            "every trial: the killed leader's term-1 write raised "
            "StaleTermError and incremented the store's fenced count"
        ),
        "config": {
            "hosts": 2,
            "trials_per_ttl": trials,
            "fault_family": [
                "kv_drop:op@K",
                "kv_delay:ms@K",
                "partition_host:H@K",
                "kill_coordinator:@K",
            ],
        },
        "note": (
            "clean handover (lease released) is TTL-independent — "
            "headless time after a crash is dominated by the lease "
            "residue, so the TTL knob trades steady-state renew "
            "traffic against worst-case failover wall"
        ),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_fleetobs_worker():
    """Subprocess entry for the --fleetobs lane (one learner host of a
    2-host gloo CPU fleet). Same rendezvous → epoch → fixed-seed
    lockstep-learn protocol as the --fleet lane, but the variable
    under test is the fleetview plane itself: with
    ``RAY_TPU_FLEETOBS_ON=1`` every host runs a periodic
    ``HostExporter`` and rank 0 additionally runs the subscribing
    ``FleetAggregator`` — the exact coordinator-side topology of
    docs/observability.md "Fleet view". Each rank prints one
    ``FLEETOBSBENCH {json}`` line with its step walls and the
    per-step ``total_loss`` stream (bitwise parity across the A/B is
    asserted by the driver: observation must not perturb training)."""
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    import gymnasium as gym

    from ray_tpu import fleet
    from ray_tpu import sharding as sharding_lib
    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.data.sample_batch import SampleBatch
    from ray_tpu.parallel import distributed as dist

    rank = int(os.environ["RAY_TPU_PROCESS_ID"])
    world = int(os.environ["RAY_TPU_NUM_PROCESSES"])
    obs_on = os.environ.get("RAY_TPU_FLEETOBS_ON") == "1"
    if world > 1:
        dist.initialize()

    kv = fleet.KVClient(os.environ["RAY_TPU_KV_ADDRESS"])
    coord = fleet.FleetCoordinator(kv) if rank == 0 else None
    agent = fleet.HostAgent(
        kv, f"host{rank}", rank_hint=rank, heartbeat_interval=0.5
    )
    agent.join()
    if rank == 0:
        coord.wait_for_members(world, timeout=60.0)
        coord.propose_epoch(reason="bootstrap")
    epoch1 = agent.wait_for_epoch(1)
    mesh = fleet.epoch_mesh(epoch1)

    exporter = aggregator = None
    if obs_on:
        from ray_tpu.telemetry.fleetview import (
            FleetAggregator,
            HostExporter,
        )

        if rank == 0:
            aggregator = FleetAggregator(
                kv=kv, publish_aggregate=False
            )
        # short interval so the periodic publish actually fires
        # several times inside the timed window (the overhead under
        # measurement is the steady-state one, not a single flush)
        exporter = HostExporter(kv, f"host{rank}", interval=0.2)

    B = 64
    config = {
        "_mesh": mesh,
        "model": {"fcnet_hiddens": [32, 32]},
        "train_batch_size": B,
        "sgd_minibatch_size": 32,
        "num_sgd_iter": 2,
        "lr": 3e-4,
        "seed": 0,
    }
    obs_space = gym.spaces.Box(-1.0, 1.0, (16,), np.float32)
    act_space = gym.spaces.Discrete(4)
    policy = PPOJaxPolicy(obs_space, act_space, config)
    rng = np.random.default_rng(7)
    host = {
        SampleBatch.OBS: rng.standard_normal((B, 16)).astype(
            np.float32
        ),
        SampleBatch.ACTIONS: rng.integers(0, 4, B).astype(np.int64),
        SampleBatch.ACTION_LOGP: np.full(B, -1.4, np.float32),
        SampleBatch.ACTION_DIST_INPUTS: rng.standard_normal(
            (B, 4)
        ).astype(np.float32),
        SampleBatch.ADVANTAGES: rng.standard_normal(B).astype(
            np.float32
        ),
        SampleBatch.VALUE_TARGETS: rng.standard_normal(B).astype(
            np.float32
        ),
    }
    tree, bsize = policy.prepare_batch(SampleBatch(host))
    global_batch = {
        k: sharding_lib.put_global(v, policy.data_sharding)
        for k, v in tree.items()
    }
    policy.learn_on_device_batch(global_batch, bsize)  # compile
    walls, losses = [], []
    for _ in range(12):
        t0 = time.perf_counter()
        stats = policy.learn_on_device_batch(global_batch, bsize)
        walls.append(time.perf_counter() - t0)
        losses.append(float(stats["total_loss"]))
    steps_per_s = B / float(np.median(walls))

    hosts_in_exposition = []
    if obs_on:
        exporter.flush()  # final snapshot so the merge sees this run
        exporter.stop()
        if aggregator is not None:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                hosts_in_exposition = aggregator.hosts()
                if len(hosts_in_exposition) >= world:
                    break
                time.sleep(0.1)
            text = aggregator.merged_exposition()
            for h in hosts_in_exposition:
                assert f'host="{h}"' in text, (h, text[:400])
            aggregator.stop()
    print(
        "FLEETOBSBENCH "
        + json.dumps(
            {
                "rank": rank,
                "fleetobs_on": obs_on,
                "steps_per_s": round(steps_per_s, 1),
                "median_step_wall_s": float(np.median(walls)),
                "losses": losses,
                "hosts_in_exposition": sorted(hosts_in_exposition),
            }
        )
    )
    agent.barrier("fleetobs_done", epoch1)
    agent.stop()
    if coord is not None:
        coord.stop()


def bench_fleetobs(out_path=None):
    """Fleet-observability overhead A/B (docs/observability.md "Fleet
    view"): the SAME fixed-seed 2-host gloo lockstep learn, once bare
    and once with the full fleetview plane live (per-host periodic
    ``HostExporter`` + rank-0 subscribing ``FleetAggregator``).
    Reports

      - aggregator_overhead_pct: median-step-wall delta, budget < 2%;
      - losses_bitwise_identical: the per-step ``total_loss`` stream
        must match bit for bit across the A/B on every rank —
        observation reads training state, never perturbs it;
      - hosts_in_exposition: both hosts must appear (``host=``-labeled)
        in the merged exposition produced during the run.

    Writes benchmarks/e2e/fleet_observability.json."""
    import os
    import socket
    import subprocess

    from ray_tpu.fleet import KVServer

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/fleet_observability.json"
    world = 2

    def run(obs_on):
        kv = KVServer(host="127.0.0.1")
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coord_port = s.getsockname()[1]
        env_base = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "RAY_TPU_PLATFORM": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "RAY_TPU_NUM_PROCESSES": str(world),
            "RAY_TPU_KV_ADDRESS": f"127.0.0.1:{kv.port}",
            "RAY_TPU_COORDINATOR": f"127.0.0.1:{coord_port}",
            "RAY_TPU_FLEETOBS_ON": "1" if obs_on else "0",
        }
        procs = []
        for rank in range(world):
            env = {**env_base, "RAY_TPU_PROCESS_ID": str(rank)}
            procs.append(
                subprocess.Popen(
                    [sys.executable, __file__, "--fleetobs-worker"],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            kv.shutdown()
        recs = {}
        for rank, (p, out) in enumerate(zip(procs, outs)):
            if p.returncode != 0:
                raise RuntimeError(
                    f"fleetobs bench rank {rank} failed:\n{out}"
                )
            for line in out.splitlines():
                if line.startswith("FLEETOBSBENCH "):
                    recs[rank] = json.loads(
                        line[len("FLEETOBSBENCH ") :]
                    )
        if len(recs) != world:
            raise RuntimeError(
                f"missing FLEETOBSBENCH lines: {sorted(recs)}"
            )
        return recs

    off = run(obs_on=False)
    on = run(obs_on=True)

    overhead_pct = round(
        100.0
        * (on[0]["median_step_wall_s"] - off[0]["median_step_wall_s"])
        / off[0]["median_step_wall_s"],
        2,
    )
    bitwise = all(
        on[r]["losses"] == off[r]["losses"] for r in range(world)
    )
    report = {
        "metric": "fleet_observability_overhead",
        "steps_per_s": {
            "fleetobs_off": off[0]["steps_per_s"],
            "fleetobs_on": on[0]["steps_per_s"],
        },
        "median_step_wall_s": {
            "fleetobs_off": off[0]["median_step_wall_s"],
            "fleetobs_on": on[0]["median_step_wall_s"],
        },
        "aggregator_overhead_pct": overhead_pct,
        "overhead_budget_pct": 2.0,
        "losses_bitwise_identical": bitwise,
        "hosts_in_exposition": on[0]["hosts_in_exposition"],
        "config": {
            "world": world,
            "devices_per_host": 2,
            "train_batch_size": 64,
            "timed_steps": 12,
            "exporter_interval_s": 0.2,
            "collectives": "gloo (CPU stand-in for DCN)",
        },
        "note": (
            "the exporter threads publish snapshots on their own "
            "cadence while the lockstep learn runs; overhead is the "
            "median per-step wall delta, so one-off flush costs and "
            "the aggregator's subscriber thread (rank 0 only) are "
            "both in frame — the bitwise loss check is the hard "
            "gate, the percentage is the budget headline"
        ),
    }
    if not bitwise:
        raise RuntimeError(
            "fleetview observation perturbed training: per-step "
            "losses differ between fleetobs on/off"
        )
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_jax_env(out_path=None, iters=3, n_envs=32, t_rollout=64):
    """Rollout-lane A/B (docs/pipeline.md "two rollout lanes"): the
    SAME JaxVectorEnv (CartPoleJax), same fixed seed, same total env
    steps, three lanes through the full PPO Algorithm —

      - actor:  the CPU-actor lane (local SyncSampler drives the env
        through the jitted adapter; train batch crosses H2D per iter);
      - device: JAX-native rollouts on the learner mesh, rollout and
        learn as separate dispatches (env_backend="jax",
        jax_fused_rollout=False);
      - fused:  rollout(T) + GAE + the SGD nest as ONE dispatched
        program (the superstep's rollout feed) — per-iteration H2D is
        the key stacks only.

    Writes benchmarks/e2e/jax_env_ab.json with steps/s, per-iteration
    rollout H2D bytes by lane, and the fused-vs-actor speedup (the
    acceptance criterion is ≥ 4× at this geometry)."""
    from ray_tpu.algorithms.ppo.ppo import PPOConfig
    from ray_tpu.sharding.compile import compile_stats
    from ray_tpu.telemetry import metrics as telemetry_metrics

    out_path = out_path or "benchmarks/e2e/jax_env_ab.json"
    steps_per_iter = n_envs * t_rollout

    def build(backend, fused=True):
        cfg = (
            PPOConfig()
            .environment(
                "CartPoleJax-v0",
                env_backend=backend,
                jax_fused_rollout=fused,
            )
            .rollouts(
                num_rollout_workers=0,
                num_envs_per_worker=n_envs,
                rollout_fragment_length=t_rollout,
            )
            .training(
                train_batch_size=steps_per_iter,
                sgd_minibatch_size=512,
                num_sgd_iter=4,
                lr=3e-4,
                model={"fcnet_hiddens": [64, 64]},
            )
            .debugging(seed=0)
        )
        cfg.lambda_ = 0.95
        return cfg.build()

    def run(backend, fused=True):
        algo = build(backend, fused)
        try:
            algo.train()  # warmup: compiles + first episode stream
            h2d0 = telemetry_metrics.h2d_bytes_by_path()
            traces0 = compile_stats()["traces"]
            t0 = time.perf_counter()
            for _ in range(iters):
                r = algo.train()
            wall = time.perf_counter() - t0
            h2d1 = telemetry_metrics.h2d_bytes_by_path()
            d = {
                p: h2d1.get(p, 0.0) - h2d0.get(p, 0.0)
                for p in set(h2d0) | set(h2d1)
            }
            rollout_bytes = (
                d.get("rollout", 0.0)
                if backend == "jax"
                else d.get("learn", 0.0) + d.get("feeder", 0.0)
            )
            return {
                "steps_per_s": round(iters * steps_per_iter / wall, 1),
                "wall_s_per_iteration": round(wall / iters, 4),
                "rollout_h2d_bytes_per_iteration": round(
                    rollout_bytes / iters, 1
                ),
                "recompiles_in_timed_window": (
                    compile_stats()["traces"] - traces0
                ),
                "episode_reward_mean": r.get("episode_reward_mean"),
            }
        finally:
            algo.cleanup()

    report = {
        "metric": "jax_env_rollout_lane_ab",
        "env": "CartPoleJax-v0",
        "geometry": {
            "num_envs": n_envs,
            "rollout_length": t_rollout,
            "env_steps_per_iteration": steps_per_iter,
            "timed_iterations": iters,
        },
        "actor_lane": run("actor"),
        "device_lane": run("jax", fused=False),
        "fused_lane": run("jax", fused=True),
    }
    report["speedup_fused_vs_actor"] = round(
        report["fused_lane"]["steps_per_s"]
        / report["actor_lane"]["steps_per_s"],
        1,
    )
    report["speedup_device_vs_actor"] = round(
        report["device_lane"]["steps_per_s"]
        / report["actor_lane"]["steps_per_s"],
        1,
    )
    import os

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_serve(
    out_path=None,
    n_requests=512,
    clients_list=(1, 8, 32, 128),
    max_batch_size=128,
):
    """Inference-plane A/B (docs/serving.md): continuous batching vs
    naive per-request inference, same fixed-seed request stream on
    both sides, at 1/8/32/128 concurrent clients.

      - per_request: one ``compute_actions`` dispatch per request (the
        serve core's one-call-per-actor-call shape), clients serialized
        on the policy exactly like calls arriving at one replica;
      - batched: the ``BatchedPolicyServer`` coalesces the SAME stream
        into bucket-padded fused forwards (greedy flush, donated rng
        carry, zero recompiles after warmup — asserted off
        ``compile_stats``).

    Acceptance (ISSUE 9): >= 4x throughput at >= 32 clients, batched
    p99 latency no worse than 2x the per-request p99, zero recompiles
    in the timed window, and batched results bit-identical to the
    sequential reference. Writes benchmarks/e2e/serve_ab.json."""
    import threading

    import gymnasium as gym
    import jax

    from ray_tpu import sharding as sharding_lib
    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy
    from ray_tpu.serve.policy_server import (
        BatchedPolicyServer,
        default_buckets,
    )
    from ray_tpu.sharding.compile import compile_stats

    out_path = out_path or "benchmarks/e2e/serve_ab.json"
    obs_space = gym.spaces.Box(-1.0, 1.0, (8,), np.float32)
    act_space = gym.spaces.Discrete(4)

    def make_policy():
        return PPOJaxPolicy(
            obs_space,
            act_space,
            {
                "seed": 0,
                "lr": 3e-4,
                "train_batch_size": 64,
                "sgd_minibatch_size": 64,
                "num_sgd_iter": 1,
                "model": {"fcnet_hiddens": [64, 64]},
                # bitwise parity is a 1-shard-mesh contract
                "_mesh": sharding_lib.get_mesh(
                    devices=jax.devices()[:1]
                ),
            },
        )

    rng = np.random.default_rng(0)
    obs_stream = rng.uniform(-1, 1, (n_requests, 8)).astype(
        np.float32
    )

    def run_clients(n_clients, issue):
        latencies = np.zeros(n_requests)
        next_i = [0]
        ilock = threading.Lock()

        def worker():
            while True:
                with ilock:
                    i = next_i[0]
                    if i >= n_requests:
                        return
                    next_i[0] += 1
                t0 = time.perf_counter()
                issue(i)
                latencies[i] = time.perf_counter() - t0

        threads = [
            threading.Thread(target=worker)
            for _ in range(n_clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return {
            "throughput_rps": round(n_requests / wall, 1),
            "wall_s": round(wall, 4),
            "p50_ms": round(
                float(np.percentile(latencies, 50)) * 1e3, 3
            ),
            "p99_ms": round(
                float(np.percentile(latencies, 99)) * 1e3, 3
            ),
        }

    # -- per-request side (explore=False: rng-independent, so the
    # thread interleave can't change results)
    naive = make_policy()
    naive_lock = threading.Lock()
    naive_actions = np.zeros(n_requests, np.int64)
    naive.compute_actions(obs_stream[:1], explore=False)  # compile

    def issue_naive(i):
        with naive_lock:
            a, _, _ = naive.compute_actions(
                obs_stream[i][None], explore=False
            )
        naive_actions[i] = a[0]

    # -- batched side: ONE server reused across the whole sweep
    server = BatchedPolicyServer(
        make_policy(),
        max_batch_size=max_batch_size,
        batch_wait_timeout_s=0.001,
        explore=False,
        start=False,
    )
    server.warmup()
    server.start()
    batched_actions = np.zeros(n_requests, np.int64)
    batched_logp = np.zeros(n_requests, np.float32)

    def issue_batched(i):
        a, ex = server.submit(obs_stream[i]).result(120.0)
        batched_actions[i] = a
        batched_logp[i] = ex["action_logp"]

    curve = []
    traces0 = compile_stats()["traces"]
    for c in clients_list:
        per_request = run_clients(c, issue_naive)
        batches0 = server.batches_total
        rows0 = server.batch_rows_total
        batched = run_clients(c, issue_batched)
        nb = server.batches_total - batches0
        batched["mean_batch_rows"] = round(
            (server.batch_rows_total - rows0) / max(1, nb), 2
        )
        entry = {
            "clients": c,
            "per_request": per_request,
            "batched": batched,
            "speedup": round(
                batched["throughput_rps"]
                / per_request["throughput_rps"],
                2,
            ),
            "p99_ratio": round(
                batched["p99_ms"] / per_request["p99_ms"], 2
            ),
        }
        curve.append(entry)
    recompiles = compile_stats()["traces"] - traces0

    # -- bitwise parity of the batched stream vs a fresh sequential
    # reference (same seed, same order)
    ref = make_policy()
    parity = True
    for i in range(n_requests):
        a, _, ex = ref.compute_actions(
            obs_stream[i][None], explore=False
        )
        if a[0] != batched_actions[i] or not np.array_equal(
            ex["action_logp"][0], batched_logp[i]
        ):
            parity = False
            break
    server.stop()

    wide = [e for e in curve if e["clients"] >= 32]
    report = {
        "metric": "serve_continuous_batching_ab",
        "n_requests": n_requests,
        "obs_dim": 8,
        "model": [64, 64],
        "max_batch_size": max_batch_size,
        "buckets": list(default_buckets(max_batch_size)),
        "curve": curve,
        "recompiles_in_timed_window": recompiles,
        "parity_bitwise": parity,
        "criteria": {
            "speedup_ge_4x_at_32plus_clients": all(
                e["speedup"] >= 4.0 for e in wide
            ),
            "p99_no_worse_than_2x": all(
                e["p99_ratio"] <= 2.0 for e in wide
            ),
            "zero_recompiles": recompiles == 0,
        },
    }
    import os

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_ingress(
    out_path=None,
    n_requests=256,
    clients_list=(1, 8, 32),
    max_batch_size=32,
):
    """Serving front-door A/B (docs/serving.md "the front door"),
    everything over REAL sockets:

      - per_request: the serve-core HTTP path — one request per
        replica actor call (``serve.run(policy_deployment(...),
        http_host=...)`` with ``max_batch_size=1``), exactly the
        pre-ingress architecture;
      - ingress: ``PolicyIngress`` → ``CoalescingRouter`` → one
        in-process ``BatchedPolicyServer`` replica restored from the
        SAME checkpoint — requests coalesce across connections into
        power-of-two buckets before dispatch.

    Plus the AOT cold-start A/B: a fresh replica's warmup wall and
    time-to-first-response with an empty compile cache (live XLA
    compiles, which also SEED the cache) vs a warm one (every bucket
    restored from disk — zero fresh compiles, trace-count-asserted).

    Acceptance (ISSUE 14): ingress throughput >= 4x per-request at
    32 clients, bitwise response parity, 0 recompiles in the timed
    window, AOT cold start with 0 fresh compiles of cached buckets.
    Writes benchmarks/e2e/ingress_ab.json."""
    import os
    import shutil
    import tempfile
    import threading
    import urllib.request

    import ray_tpu as ray
    from ray_tpu.algorithms.ppo.ppo import PPO
    from ray_tpu.ingress import (
        CoalescingRouter,
        LocalReplica,
        PolicyIngress,
    )
    from ray_tpu.serve import serve
    from ray_tpu.serve.policy_server import (
        BatchedPolicyServer,
        policy_deployment,
        restore_policy,
    )
    from ray_tpu.sharding.aot import AOTCompileCache
    from ray_tpu.sharding.compile import compile_stats

    out_path = out_path or "benchmarks/e2e/ingress_ab.json"
    workdir = tempfile.mkdtemp(prefix="ingress_bench_")
    ckpt_root = os.path.join(workdir, "ckpts")

    cfg = {
        "env": "CartPole-v1",
        "seed": 0,
        "num_workers": 0,
        "train_batch_size": 64,
        "sgd_minibatch_size": 64,
        "num_sgd_iter": 1,
        "lr": 3e-4,
        "model": {"fcnet_hiddens": [64, 64]},
    }
    algo = PPO(config=cfg)
    try:
        algo.save(os.path.join(ckpt_root, "checkpoint_000001"))
    finally:
        algo.cleanup()

    rng = np.random.default_rng(0)
    obs_stream = rng.uniform(
        -1.0, 1.0, (n_requests, 4)
    ).astype(np.float32)

    def post(url, payload, timeout=120.0, retries=3):
        import http.client

        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        # the stdlib ThreadingHTTPServer on the per-request side
        # occasionally resets a fresh connection under rapid
        # open/close churn; a transient-layer retry keeps the A/B
        # about the serving architecture, not loopback TCP flakes
        # (retries stay inside the request's timed latency)
        for attempt in range(retries):
            try:
                with urllib.request.urlopen(
                    req, timeout=timeout
                ) as resp:
                    return json.loads(resp.read())
            except (
                ConnectionError,
                http.client.RemoteDisconnected,
            ):
                if attempt == retries - 1:
                    raise
                time.sleep(0.01 * (attempt + 1))

    def run_clients(full_url, n_clients):
        latencies = np.zeros(n_requests)
        results = [None] * n_requests
        errors = []
        next_i = [0]
        ilock = threading.Lock()

        def worker():
            while True:
                with ilock:
                    i = next_i[0]
                    if i >= n_requests:
                        return
                    next_i[0] += 1
                t0 = time.perf_counter()
                try:
                    out = post(
                        full_url, {"obs": obs_stream[i].tolist()}
                    )
                except Exception as e:
                    with ilock:
                        errors.append((i, repr(e)))
                    continue
                latencies[i] = time.perf_counter() - t0
                results[i] = out.get("result", out)

        threads = [
            threading.Thread(target=worker)
            for _ in range(n_clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errors:
            raise RuntimeError(
                f"{len(errors)} request(s) failed against "
                f"{full_url}; first: {errors[0]}"
            )
        return {
            "throughput_rps": round(n_requests / wall, 1),
            "wall_s": round(wall, 4),
            "p50_ms": round(
                float(np.percentile(latencies, 50)) * 1e3, 3
            ),
            "p99_ms": round(
                float(np.percentile(latencies, 99)) * 1e3, 3
            ),
        }, results

    # -- per-request side: the serve-core HTTP path ------------------
    serve.run(
        policy_deployment(
            ckpt_root,
            name="bench_naive",
            max_batch_size=1,
            watch=False,
        ),
        http_host="127.0.0.1",
    )
    naive_url = (
        f"http://127.0.0.1:{serve.http_port()}/bench_naive"
    )
    naive_curve = {}
    naive_results = None
    try:
        for c in clients_list:
            naive_curve[c], naive_results = run_clients(
                naive_url, c
            )
    finally:
        serve.shutdown()
        ray.shutdown()

    # -- ingress side: front door + router + batched replica ---------
    policy, prep, obs_filter, _info = restore_policy(ckpt_root)
    server = BatchedPolicyServer(
        policy,
        name="bench_ingress",
        max_batch_size=max_batch_size,
        batch_wait_timeout_s=0.002,
        explore=False,
        obs_filter=obs_filter,
        preprocessor=prep,
        start=False,
    )
    server.warmup()
    server.start()
    router = CoalescingRouter(
        "bench",
        [LocalReplica(server)],
        max_batch_size=max_batch_size,
        batch_wait_timeout_s=0.002,
    )
    ingress = PolicyIngress().start()
    ingress.add_policy("bench", router)
    ingress_curve = {}
    ingress_results = None
    traces0 = compile_stats()["traces"]
    try:
        for c in clients_list:
            ingress_curve[c], ingress_results = run_clients(
                ingress.url + "/v1/policy/bench/actions", c
            )
        recompiles = compile_stats()["traces"] - traces0
        router_stats = router.stats()
    finally:
        ingress.stop()
        router.stop()
        server.stop()

    parity = all(
        int(a["action"]) == int(b["action"])
        for a, b in zip(ingress_results, naive_results)
    )

    # -- AOT cold-start A/B ------------------------------------------
    def cold_start(cache, name):
        p, pr, fl, _ = restore_policy(ckpt_root)
        srv = BatchedPolicyServer(
            p,
            name=name,
            max_batch_size=max_batch_size,
            explore=False,
            obs_filter=fl,
            preprocessor=pr,
            aot_cache=cache,
            start=False,
        )
        t0 = time.perf_counter()
        srv.warmup()
        warmup_s = time.perf_counter() - t0
        srv.start()
        t0 = time.perf_counter()
        srv.submit(obs_stream[0]).result(120.0)
        first_response_s = time.perf_counter() - t0
        fresh_compiles = sum(
            fn.traces for fn in srv._fns.values()
        )
        sources = sorted(
            {fn.aot_source for fn in srv._fns.values()}
        )
        srv.stop()
        return {
            "warmup_s": round(warmup_s, 4),
            "first_response_s": round(first_response_s, 5),
            "fresh_compiles": fresh_compiles,
            "sources": sources,
        }

    cache = AOTCompileCache(os.path.join(workdir, "aot_cache"))
    # cold replica, empty cache: live AOT compiles seed the cache
    cold_live = cold_start(cache, "bench_cold")
    cache.flush()
    # fresh replica, warm cache: every bucket restores from disk
    cold_aot = cold_start(cache, "bench_cold")
    cache.stop()
    aot_ab = {
        "live": cold_live,
        "aot_cache": cold_aot,
        "warmup_speedup": round(
            cold_live["warmup_s"]
            / max(cold_aot["warmup_s"], 1e-9),
            2,
        ),
    }

    curve = [
        {
            "clients": c,
            "per_request": naive_curve[c],
            "ingress": ingress_curve[c],
            "speedup": round(
                ingress_curve[c]["throughput_rps"]
                / naive_curve[c]["throughput_rps"],
                2,
            ),
        }
        for c in clients_list
    ]
    wide = [e for e in curve if e["clients"] >= 32]
    report = {
        "metric": "ingress_front_door_ab",
        "n_requests": n_requests,
        "model": [64, 64],
        "max_batch_size": max_batch_size,
        "transport": "real sockets (HTTP/1.1, keep-alive)",
        "curve": curve,
        "router": {
            "batches_total": router_stats["batches_total"],
            "mean_merged_rows": round(
                router_stats["mean_merged_rows"], 2
            ),
        },
        "recompiles_in_timed_window": recompiles,
        "parity_bitwise": parity,
        "aot_cold_start": aot_ab,
        "criteria": {
            "speedup_ge_4x_at_32_clients": all(
                e["speedup"] >= 4.0 for e in wide
            ),
            "zero_recompiles": recompiles == 0,
            "parity_bitwise": parity,
            "aot_cold_start_zero_fresh_compiles": (
                cold_aot["fresh_compiles"] == 0
                and cold_aot["sources"] == ["aot_cache"]
            ),
        },
    }
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_flood(out_path=None, smoke=False):
    """OPEN-loop flood harness for the horizontal front door
    (docs/serving.md "Scaling the front door"): find the saturation
    knee of 1 vs N ingress worker processes and prove the overload
    contract past it.

    Closed-loop clients (bench_ingress) can never overload a server —
    they wait for answers before sending more. This harness fires
    requests on a fixed ARRIVAL SCHEDULE regardless of completions
    (Poisson inter-arrivals per offered rate, plus a recorded bursty
    on/off stream), with a deadline mix riding along, and sweeps
    offered rates upward until goodput stops tracking offered load:

      - knee = highest offered rate whose goodput (200s inside their
        deadline) stays >= 90% of offered;
      - at 2x the knee EVERY response must be a 200-inside-deadline,
        429 (inflight/quota), 503 (queue-wait shed) or 504 (deadline)
        — never a hang, never a 200 past its deadline;
      - both configs serve the SAME checkpoint from a pre-seeded AOT
        cache (fixed-seed obs stream, bitwise parity across configs,
        zero fresh compiles per worker, heartbeat-asserted).

    Each config is a real ``IngressSupervisor`` bank on one shared
    port (SO_REUSEPORT where available). Writes
    benchmarks/e2e/flood.json. NOTE the honesty caveat in the report:
    on a single-core host N worker processes time-slice one CPU, so
    the knee ratio measures isolation overhead, not the >= 2.5x
    scale-out a multi-core front door shows.

    ``--smoke`` shrinks rates/durations for the tier-1 test."""
    import os
    import shutil
    import socket as socket_mod
    import subprocess
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from ray_tpu.ingress import IngressSupervisor
    from ray_tpu.telemetry import metrics as telemetry_metrics

    out_path = out_path or "benchmarks/e2e/flood.json"
    workdir = tempfile.mkdtemp(prefix="flood_bench_")
    ckpt_root = os.path.join(workdir, "ckpts")
    cache_dir = os.path.join(workdir, "aot_cache")
    repo = os.path.dirname(os.path.abspath(__file__))
    max_batch_size = 16

    if smoke:
        workers_list = (1, 2)
        rates = [10.0, 25.0]
        duration_s = 1.2
        n_obs = 32
        n_senders = 8
        overload_factor = 1.5
        run_recorded = False
        parity_n = 16
    else:
        workers_list = (1, 3)
        rates = [
            60.0, 120.0, 240.0, 480.0, 960.0, 1920.0, 3840.0,
        ]
        duration_s = 3.0
        n_obs = 128
        n_senders = 64
        overload_factor = 2.0
        run_recorded = True
        parity_n = 64

    # the checkpoint + AOT cache are built in a SUBPROCESS so this
    # process never initializes the XLA client before forking worker
    # banks (fork-after-jax-init is the classic deadlock); workers
    # restore every bucket from the warm cache — zero fresh compiles
    seed_code = (
        "import json, sys\n"
        "from ray_tpu.algorithms.ppo.ppo import PPO\n"
        "ckpt, cache_dir, mbs = (\n"
        "    sys.argv[1], sys.argv[2], int(sys.argv[3]))\n"
        "cfg = {'env': 'CartPole-v1', 'seed': 0, 'num_workers': 0,\n"
        "       'train_batch_size': 64, 'sgd_minibatch_size': 64,\n"
        "       'num_sgd_iter': 1, 'lr': 3e-4,\n"
        "       'model': {'fcnet_hiddens': [64, 64]}}\n"
        "algo = PPO(config=cfg)\n"
        "algo.save(ckpt)\n"
        "algo.cleanup()\n"
        "from ray_tpu.serve.policy_server import (\n"
        "    BatchedPolicyServer, restore_policy)\n"
        "from ray_tpu.sharding.aot import AOTCompileCache\n"
        "p, prep, filt, _ = restore_policy(ckpt)\n"
        "cache = AOTCompileCache(cache_dir)\n"
        "srv = BatchedPolicyServer(\n"
        "    p, name='flood', max_batch_size=mbs, explore=False,\n"
        "    obs_filter=filt, preprocessor=prep, aot_cache=cache,\n"
        "    start=False)\n"
        "srv.warmup()\n"
        "cache.flush()\n"
        "srv.stop()\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
    subprocess.run(
        [
            sys.executable, "-c", seed_code,
            os.path.join(ckpt_root, "checkpoint_000001"),
            cache_dir, str(max_batch_size),
        ],
        check=True, env=env, cwd=repo,
    )

    rng = np.random.default_rng(0)
    obs_stream = rng.uniform(-1.0, 1.0, (n_obs, 4)).astype(
        np.float32
    )
    # the deadline mix every run carries: most requests unbounded, a
    # slice with a meetable budget, a slice tight enough to expire
    # under congestion (ms, weight)
    deadline_mix = [(None, 0.6), (400.0, 0.25), (120.0, 0.15)]

    def worker_init(ctx):
        # runs INSIDE each forked ingress worker: full replica stack
        # per process, restored from the shared checkpoint + cache
        from ray_tpu.ingress import CoalescingRouter, LocalReplica
        from ray_tpu.serve.policy_server import (
            BatchedPolicyServer,
            restore_policy,
        )
        from ray_tpu.sharding.aot import AOTCompileCache
        from ray_tpu.sharding.compile import compile_stats

        policy, prep, obs_filter, _ = restore_policy(ckpt_root)
        cache = AOTCompileCache(cache_dir)
        server = BatchedPolicyServer(
            policy,
            name="flood",
            max_batch_size=max_batch_size,
            batch_wait_timeout_s=0.002,
            explore=False,
            obs_filter=obs_filter,
            preprocessor=prep,
            aot_cache=cache,
            start=False,
        )
        server.warmup()
        server.start()
        router = CoalescingRouter(
            "flood",
            [LocalReplica(server)],
            max_batch_size=max_batch_size,
            batch_wait_timeout_s=0.002,
        )
        ctx.ingress.add_policy("flood", router)
        traces0 = compile_stats()["traces"]
        fresh0 = sum(fn.traces for fn in server._fns.values())
        sources = sorted(
            {fn.aot_source for fn in server._fns.values()}
        )

        def extra_stats():
            return {
                "recompiles": compile_stats()["traces"] - traces0,
                "warmup_fresh_compiles": fresh0,
                "aot_sources": sources,
            }

        ctx.ingress.extra_stats = extra_stats

    def poisson_schedule(rate, dur, seed):
        r = np.random.default_rng(seed)
        gaps = r.exponential(1.0 / rate, int(rate * dur * 2) + 16)
        t = np.cumsum(gaps)
        return t[t < dur].tolist()

    def recorded_schedule(rate, dur, seed):
        # the "recorded stream": a fixed-seed bursty on/off arrival
        # trace (0.5 s periods, 3x the mean rate while on, 0.2x
        # while off) — the shape production front doors actually see
        r = np.random.default_rng(seed)
        out, t, period = [], 0.0, 0.5
        while t < dur:
            on = int(t / period) % 2 == 0
            cur = rate * (3.0 if on else 0.2)
            t += float(r.exponential(1.0 / cur))
            if t < dur:
                out.append(t)
        return out

    def run_flood(url, schedule, label, nominal_rps=None):
        """Fire the schedule OPEN-loop; classify every response."""
        n = len(schedule)
        dl_r = np.random.default_rng(1)
        choices = [d for d, _ in deadline_mix]
        weights = [w for _, w in deadline_mix]
        deadlines = [
            choices[dl_r.choice(len(choices), p=weights)]
            for _ in range(n)
        ]
        counts = {
            k: 0
            for k in (
                "ok", "late_200", "shed_429", "shed_503",
                "expired_504", "hang", "error",
            )
        }
        ok_lat = []
        lock = threading.Lock()
        idx = [0]
        t_start = time.perf_counter() + 0.1

        import http.client
        from urllib.parse import urlsplit

        parts = urlsplit(url)
        host, port = parts.hostname, parts.port
        path = parts.path

        def sender():
            # each sender owns a persistent keep-alive connection:
            # timing starts at the request WRITE (the deadline budget
            # the payload declares), not at a per-request TCP connect
            # whose accept-queue wait the server cannot observe
            conn = [None]

            def send_one(body):
                for attempt in (0, 1):
                    try:
                        if conn[0] is None:
                            conn[0] = http.client.HTTPConnection(
                                host, port, timeout=10.0
                            )
                        conn[0].request(
                            "POST",
                            path,
                            body,
                            {"Content-Type": "application/json"},
                        )
                        resp = conn[0].getresponse()
                        resp.read()
                        if (
                            resp.headers.get("Connection", "")
                            .lower()
                            == "close"
                        ):
                            conn[0].close()
                            conn[0] = None
                        return resp.status
                    except socket_mod.timeout:
                        if conn[0] is not None:
                            conn[0].close()
                            conn[0] = None
                        return "hang"
                    except Exception:
                        # a dropped keep-alive connection: retry
                        # once on a fresh one before calling it an
                        # error
                        if conn[0] is not None:
                            conn[0].close()
                            conn[0] = None
                        if attempt == 1:
                            return "error"

            while True:
                with lock:
                    i = idx[0]
                    if i >= n:
                        if conn[0] is not None:
                            conn[0].close()
                        return
                    idx[0] += 1
                delay = (
                    t_start + schedule[i] - time.perf_counter()
                )
                if delay > 0:
                    time.sleep(delay)
                dl = deadlines[i]
                payload = {
                    "obs": obs_stream[i % n_obs].tolist()
                }
                if dl is not None:
                    payload["deadline_ms"] = dl
                body = json.dumps(payload).encode()
                t0 = time.perf_counter()
                status = send_one(body)
                lat = time.perf_counter() - t0
                if status == 200:
                    # a 200 must land INSIDE its deadline (100 ms
                    # slack for time the request sat in transport
                    # buffers before the server's own deadline
                    # clock could start — everything the server CAN
                    # observe as late it already 504s)
                    if (
                        dl is not None
                        and lat * 1e3 > dl + 100.0
                    ):
                        kind = "late_200"
                    else:
                        kind = "ok"
                elif status in ("hang", "error"):
                    kind = status
                    lat = None
                else:
                    kind = {
                        429: "shed_429",
                        503: "shed_503",
                        504: "expired_504",
                    }.get(status, "error")
                    lat = None
                with lock:
                    counts[kind] += 1
                    if kind == "ok" and lat is not None:
                        ok_lat.append(lat)
                telemetry_metrics.inc_flood_response(kind)

        threads = [
            threading.Thread(target=sender, name=f"flood_{j}")
            for j in range(n_senders)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = max(
            time.perf_counter() - t_start, schedule[-1] if n else 0.0
        )
        offered = n / wall if wall else 0.0
        goodput = counts["ok"] / wall if wall else 0.0
        telemetry_metrics.set_flood_offered_rps(offered)
        telemetry_metrics.set_flood_goodput_rps(goodput)
        shed = (
            counts["shed_429"]
            + counts["shed_503"]
            + counts["expired_504"]
        )
        arr = np.asarray(ok_lat) if ok_lat else None
        return {
            "label": label,
            "n_requests": n,
            "wall_s": round(wall, 3),
            "nominal_rps": nominal_rps,
            # the sender pool has its own ceiling: offered falling
            # well short of nominal means the GENERATOR saturated,
            # not the server — the knee is a lower bound there
            "generator_capped": (
                nominal_rps is not None
                and offered < 0.8 * nominal_rps
            ),
            "offered_rps": round(offered, 1),
            "goodput_rps": round(goodput, 1),
            "p50_ms": (
                round(float(np.percentile(arr, 50)) * 1e3, 3)
                if arr is not None
                else None
            ),
            "p99_ms": (
                round(float(np.percentile(arr, 99)) * 1e3, 3)
                if arr is not None
                else None
            ),
            "shed_fraction": round(shed / n, 4) if n else 0.0,
            "counts": dict(counts),
        }

    def collect_worker_extras(sup):
        extras = []
        for _, stats in sorted(sup.worker_stats().items()):
            if stats and stats.get("extra"):
                extras.append(stats["extra"])
        return extras

    def parity_pass(url):
        """Closed-loop, sequential: the fixed-seed obs stream's
        actions, for cross-config bitwise comparison."""
        actions = []
        for i in range(parity_n):
            req = urllib.request.Request(
                url,
                data=json.dumps(
                    {"obs": obs_stream[i % n_obs].tolist()}
                ).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=30.0) as r:
                actions.append(
                    int(json.loads(r.read())["action"])
                )
        return actions

    configs = {}
    parity_actions = {}
    for n_workers in workers_list:
        sup = IngressSupervisor(
            num_workers=n_workers,
            worker_init=worker_init,
            heartbeat_s=0.25,
            metrics_interval_s=1.0,
            # per-PROCESS budgets: the bank's effective in-flight
            # budget scales with the worker count, which is the
            # point — and small enough that the sender pool can
            # actually overrun it at 2x knee (429s are reachable)
            ingress_kwargs={
                "max_inflight": 32,
                "shed_queue_wait_s": 0.2,
            },
        )
        sup.start(timeout_s=600.0)
        url = sup.url + "/v1/policy/flood/actions"
        try:
            parity_actions[n_workers] = parity_pass(url)
            sweep = []
            knee = None
            saturated_streak = 0
            for rate in rates:
                entry = run_flood(
                    url,
                    poisson_schedule(rate, duration_s, seed=3),
                    f"poisson@{rate:g}",
                    nominal_rps=rate,
                )
                sweep.append(entry)
                if (
                    entry["goodput_rps"]
                    >= 0.9 * entry["offered_rps"]
                ):
                    knee = entry["offered_rps"]
                    if entry["generator_capped"]:
                        break
                    saturated_streak = 0
                else:
                    saturated_streak += 1
                    # past the knee twice: the curve is told, stop
                    if saturated_streak >= 2:
                        break
            if knee is None:  # saturated from the first rate
                knee = max(e["goodput_rps"] for e in sweep)
            overload = run_flood(
                url,
                poisson_schedule(
                    overload_factor * knee, duration_s, seed=5
                ),
                f"overload@{overload_factor:g}x_knee",
                nominal_rps=overload_factor * knee,
            )
            c = overload["counts"]
            contract_ok = (
                c["hang"] == 0
                and c["late_200"] == 0
                and c["error"] <= max(2, overload["n_requests"] // 100)
            )
            recorded = None
            if run_recorded:
                recorded = run_flood(
                    url,
                    recorded_schedule(knee, duration_s, seed=11),
                    "recorded_burst@knee",
                    nominal_rps=knee,
                )
            # wait one heartbeat so extra stats reflect the flood
            time.sleep(0.6)
            extras = collect_worker_extras(sup)
            configs[str(n_workers)] = {
                "num_workers": n_workers,
                "reuseport": sup.stats()["reuseport"],
                "sweep": sweep,
                "knee_rps": round(knee, 1),
                "overload": overload,
                "overload_contract_ok": contract_ok,
                "recorded": recorded,
                "workers": extras,
            }
        finally:
            sup.stop()

    lo, hi = str(workers_list[0]), str(workers_list[-1])
    knee_lo = configs[lo]["knee_rps"]
    knee_hi = configs[hi]["knee_rps"]
    scale_ratio = round(knee_hi / max(knee_lo, 1e-9), 2)
    parity = (
        parity_actions[workers_list[0]]
        == parity_actions[workers_list[-1]]
    )
    all_extras = [
        e for c in configs.values() for e in c["workers"]
    ]
    zero_recompiles = bool(all_extras) and all(
        e["recompiles"] == 0 for e in all_extras
    )
    aot_warm = bool(all_extras) and all(
        e["warmup_fresh_compiles"] == 0
        and e["aot_sources"] == ["aot_cache"]
        for e in all_extras
    )
    report = {
        "metric": "ingress_flood",
        "smoke": smoke,
        "model": [64, 64],
        "max_batch_size": max_batch_size,
        "deadline_mix_ms": deadline_mix,
        "transport": "real sockets (HTTP/1.1), open-loop senders",
        "cpu_count": os.cpu_count(),
        "configs": configs,
        "scaleout": {
            "workers": [workers_list[0], workers_list[-1]],
            "knee_rps": [knee_lo, knee_hi],
            "ratio": scale_ratio,
            # True when the N-worker knee is a LOWER bound because
            # the load generator saturated before the bank did
            "hi_knee_generator_capped": any(
                e.get("generator_capped")
                for e in configs[hi]["sweep"]
            ),
        },
        "parity_bitwise": parity,
        "criteria": {
            "knee_found_per_config": all(
                c["knee_rps"] > 0 for c in configs.values()
            ),
            "overload_contract_429_503_504": all(
                c["overload_contract_ok"]
                for c in configs.values()
            ),
            "parity_bitwise": parity,
            "zero_recompiles": zero_recompiles,
            "aot_warm_start_all_workers": aot_warm,
            "scaleout_knee_ge_2p5x": scale_ratio >= 2.5,
        },
        "caveats": [
            (
                f"host has {os.cpu_count()} CPU core(s): worker "
                "processes time-slice the same core, so the knee "
                "ratio here measures process-isolation overhead, "
                "not the multi-core scale-out the >=2.5x target "
                "describes; rerun on a multi-core front-door host "
                "for the headline number"
            )
        ]
        if (os.cpu_count() or 1) <= max(workers_list)
        else [],
    }
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_apex(out_path=None, iters=4):
    """Host sum tree vs device sum tree A/B at a training_intensity-
    heavy DQN geometry, plus the learn-while-rollout interleave A/B
    (docs/data_plane.md "device sum tree & sharded Ape-X"). Writes
    ``benchmarks/e2e/apex_device_ab.json``.

    Three sections:

    - ``tree_micro``: the sample+update wall of one fused K-window at
      the heavy geometry (capacity 2^17, B=512, K=8) — the superstep's
      draw schedule + PER refresh per window, excluding the in-scan
      row gather common to both planes. Host: K sequential numpy tree
      walks + K incremental tree writes. Device: ONE draw program +
      ONE stacked update program. Asserts ≥2× and 0 steady-state
      recompiles, and that the device sample path ships zero payload
      bytes H2D (telemetry-counted; the generator's raw uniform
      stream reports separately).
    - ``dqn_e2e``: fixed-seed DQN+PER on the fused jax rollout lane,
      training_intensity-heavy, host tree vs device tree — bitwise
      param parity plus per-iteration replay byte accounting.
    - ``interleave``: serial fill→learn vs learn-while-rollout on the
      same geometry, with the measured overlap fraction
      ((serial − interleaved) / min(rollout, learn) walls; ≈0 on this
      1-core container — the cadence exists for the mesh round)."""
    import os

    import jax

    from ray_tpu.execution.replay_buffer import (
        DevicePrioritizedReplayBuffer,
    )
    from ray_tpu.sharding.compile import compile_stats
    from ray_tpu.telemetry import metrics as telemetry_metrics

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/apex_device_ab.json"

    # ---- 1. tree micro A/B: sample+update wall per fused K-window ----
    CAP, BS, K = 1 << 17, 512, 8
    rng = np.random.default_rng(0)

    def build_buf(device_tree):
        buf = DevicePrioritizedReplayBuffer(
            capacity=CAP, alpha=0.6, seed=1,
            device_tree=device_tree,
            label=f"bench_apex_{'dev' if device_tree else 'host'}",
        )
        chunk = {
            "obs": rng.standard_normal((4096, 16)).astype(np.float32),
            "actions": rng.integers(0, 4, 4096).astype(np.int32),
            "rewards": rng.standard_normal(4096).astype(np.float32),
        }
        for _ in range(CAP // 4096):
            buf.add_tree({k: v for k, v in chunk.items()})
        return buf

    td_mat = (rng.standard_normal((K, BS)).astype(np.float32)) ** 2 + 0.01
    active = [True] * K

    def window(buf):
        if buf._dtree is not None:
            idx, _w = buf.draw_prioritized_sets_device(K, K, BS, 0.4)
            buf.refresh_priorities_stacked(idx, td_mat, active)
            jax.block_until_ready(buf._dtree.sum_value)
        else:
            idx, _w = buf.draw_prioritized_sets(K, BS, 0.4)
            for i in range(K):
                buf.update_priorities(idx[i], td_mat[i] + 1e-6)

    def timed(buf, reps=30):
        for _ in range(3):
            window(buf)  # warmup/compile
        sample_b = telemetry_metrics.h2d_bytes_by_path().get(
            "replay_sample", 0.0
        )
        traces0 = compile_stats()["traces"]
        t0 = time.perf_counter()
        for _ in range(reps):
            window(buf)
        wall = (time.perf_counter() - t0) / reps
        return {
            "wall_s_per_window": round(wall, 5),
            "recompiles_in_timed_window": (
                compile_stats()["traces"] - traces0
            ),
            "sample_payload_h2d_bytes": (
                telemetry_metrics.h2d_bytes_by_path().get(
                    "replay_sample", 0.0
                )
                - sample_b
            ),
        }

    host_buf, dev_buf = build_buf(False), build_buf(True)
    micro_host, micro_dev = timed(host_buf), timed(dev_buf)
    speedup = (
        micro_host["wall_s_per_window"]
        / micro_dev["wall_s_per_window"]
    )
    tree_micro = {
        "capacity": CAP,
        "batch": BS,
        "k": K,
        "host_tree": micro_host,
        "device_tree": micro_dev,
        "sample_update_speedup": round(speedup, 2),
        "criteria": {
            "speedup_ge_2x": speedup >= 2.0,
            "zero_recompiles": (
                micro_dev["recompiles_in_timed_window"] == 0
            ),
            "zero_sample_payload_h2d": (
                micro_dev["sample_payload_h2d_bytes"] == 0.0
            ),
        },
    }

    # ---- 2. fixed-seed DQN e2e: host tree vs device tree ----
    from ray_tpu.algorithms.dqn.dqn import DQNConfig

    def build_algo(device_tree, interleave=False):
        return (
            DQNConfig()
            .environment("CartPoleJax-v0", env_backend="jax")
            .rollouts(
                num_rollout_workers=0,
                rollout_fragment_length=8,
                num_envs_per_worker=8,
            )
            .training(
                train_batch_size=256,
                num_steps_sampled_before_learning_starts=256,
                replay_buffer_config={
                    "prioritized_replay": True,
                    "capacity": 1 << 14,
                },
                training_intensity=32.0,  # 8 fused updates / round
                superstep=8,
                replay_device_resident=True,
                replay_device_tree=device_tree,
                learn_while_rollout=interleave,
                target_network_update_freq=2048,
                model={"fcnet_hiddens": [64, 64]},
            )
            .reporting(min_time_s_per_iteration=0)
            .debugging(seed=0)
            .build()
        )

    def run(device_tree, interleave=False):
        algo = build_algo(device_tree, interleave)
        try:
            algo.train()  # warmup to learning start + compile
            h2d0 = telemetry_metrics.h2d_bytes_by_path()
            d2h0 = telemetry_metrics.d2h_bytes_by_path()
            traces0 = compile_stats()["traces"]
            walls = []
            t0 = time.perf_counter()
            for _ in range(iters):
                t1 = time.perf_counter()
                algo.train()
                walls.append(time.perf_counter() - t1)
            wall = time.perf_counter() - t0
            h2d1 = telemetry_metrics.h2d_bytes_by_path()
            d2h1 = telemetry_metrics.d2h_bytes_by_path()
            params = jax.device_get(algo.get_policy().params)
            return {
                "wall_s_per_iter": round(wall / iters, 4),
                "wall_s_per_iter_median": round(
                    float(np.median(walls)), 4
                ),
                "trained_steps": int(
                    algo._counters["num_env_steps_trained"]
                ),
                "sample_h2d_bytes_per_iter": round(
                    (
                        h2d1.get("replay_sample", 0.0)
                        - h2d0.get("replay_sample", 0.0)
                    )
                    / iters,
                    1,
                ),
                "rng_h2d_bytes_per_iter": round(
                    (
                        h2d1.get("replay_rng", 0.0)
                        - h2d0.get("replay_rng", 0.0)
                    )
                    / iters,
                    1,
                ),
                "priority_d2h_bytes_per_iter": round(
                    (
                        d2h1.get("replay_priorities", 0.0)
                        - d2h0.get("replay_priorities", 0.0)
                    )
                    / iters,
                    1,
                ),
                "recompiles_in_timed_window": (
                    compile_stats()["traces"] - traces0
                ),
            }, params
        finally:
            algo.cleanup()

    e2e_host, p_host = run(False)
    e2e_dev, p_dev = run(True)
    parity = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(p_host),
            jax.tree_util.tree_leaves(p_dev),
        )
    )
    dqn_e2e = {
        "host_tree": e2e_host,
        "device_tree": e2e_dev,
        "parity_bitwise": parity,
    }

    # ---- 3. interleave A/B: learn-while-rollout overlap ----
    # serial component walls (explicit syncs): one rollout fill, one
    # fused replay window
    algo = build_algo(True)
    try:
        # warm past learning start so the replay phase actually runs
        while (
            algo._counters["num_env_steps_sampled"] < 256 + 64
        ):
            algo.train()
        eng = algo._jax_rollout_engine_get()
        t0 = time.perf_counter()
        for _ in range(8):
            tree, _ = eng.rollout()
            jax.block_until_ready(tree)
            algo._insert_rollout_tree(tree)
        rollout_wall = (time.perf_counter() - t0) / 8
        t0 = time.perf_counter()
        for _ in range(8):
            algo._replay_update_phase(64)
        learn_wall = (time.perf_counter() - t0) / 8
    finally:
        algo.cleanup()
    serial_iter = e2e_dev["wall_s_per_iter_median"]
    e2e_int, _ = run(True, interleave=True)
    # per-round wall medians (iteration == one fill+learn round at
    # min_time 0); the max possible win per round is the smaller of
    # the two component walls — saved/min(...) is the fraction of
    # that ceiling the interleave actually recovered
    saved = max(
        0.0, serial_iter - e2e_int["wall_s_per_iter_median"]
    )
    overlap_fraction = max(
        0.0, min(1.0, saved / max(min(rollout_wall, learn_wall), 1e-9))
    )
    interleave = {
        "rollout_wall_s": round(rollout_wall, 4),
        "learn_wall_s": round(learn_wall, 4),
        "serial_wall_s_per_iter": serial_iter,
        "interleaved_wall_s_per_iter": e2e_int[
            "wall_s_per_iter_median"
        ],
        "overlap_fraction": round(overlap_fraction, 3),
        "note": (
            "≈0 expected on this 1-core CPU container (one execution "
            "stream, no real H2D wire); the cadence removes the "
            "host-side fill→learn serialization the mesh round "
            "measures"
        ),
    }

    report = {
        "metric": "apex_device_ab",
        "config": {
            "tree_micro": {"capacity": CAP, "batch": BS, "k": K},
            "dqn_e2e": {
                "env": "CartPoleJax-v0",
                "train_batch_size": 256,
                "training_intensity": 32.0,
                "superstep": 8,
                "iters": iters,
                "seed": 0,
            },
        },
        "tree_micro": tree_micro,
        "dqn_e2e": dqn_e2e,
        "interleave": interleave,
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_observability(
    out_path=None, b=64, mb=32, iters=1, kmax=2, reps=4,
):
    """Device-ledger overhead A/B (docs/observability.md "device
    ledger"): the same fixed-seed superstep PPO chain three ways —
    telemetry fully off, the compiled-program ledger on (full
    cost/memory analysis), and ledger + span tracing. Reports the
    steady-state per-superstep wall of each, the ledger's overhead as
    a fraction of the baseline superstep wall (< 2% is the acceptance
    bar — the steady-state hooks are timestamps and dict bumps; the
    cost-analysis AOT compile is one-time and reported separately),
    and a bitwise parity flag between the off and on chains. Writes
    ``benchmarks/e2e/observability.json``."""
    import os

    import jax

    from ray_tpu import sharding as sharding_lib
    from ray_tpu.policy.jax_policy import _FRAMES as _F
    from ray_tpu.telemetry import device as device_ledger
    from ray_tpu.util import tracing

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/observability.json"

    def run_phase(ledger: bool, trace: bool):
        device_ledger.disable()
        device_ledger.clear()
        tracing.disable()
        tracing.clear()
        if ledger:
            device_ledger.enable(analyze=True)
        if trace:
            tracing.enable()
        rng = np.random.default_rng(0)
        p = _make_policy(b, mb, iters)
        host, bsize = p.prepare_batch(make_batch(rng, b))
        stacked = {
            cn: np.repeat(np.asarray(v)[None], kmax, axis=0)
            for cn, v in host.items()
        }
        shard = {
            cn: (
                sharding_lib.replicated(p.mesh)
                if cn == _F
                else sharding_lib.batch_sharded(
                    p.mesh, ndim_prefix=2
                )
            )
            for cn in stacked
        }
        dev = jax.device_put(stacked, shard)
        jax.block_until_ready(dev)
        t0 = time.perf_counter()
        p.learn_superstep(
            kmax, bsize, stacked=dict(dev), k_max=kmax
        )  # compile + (with the ledger) the AOT analysis compile
        warm_s = time.perf_counter() - t0
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            p.learn_superstep(
                kmax, bsize, stacked=dict(dev), k_max=kmax
            )
            walls.append(time.perf_counter() - t0)
        wall = float(np.median(walls))
        snap = device_ledger.snapshot() if ledger else None
        params = jax.device_get(p.params)
        device_ledger.disable()
        tracing.disable()
        tracing.clear()
        return wall, warm_s, snap, params

    wall_off, warm_off, _, params_off = run_phase(False, False)
    wall_led, warm_led, snap, params_led = run_phase(True, False)
    wall_all, warm_all, _, _ = run_phase(True, True)

    la = jax.tree_util.tree_leaves(params_off)
    lb = jax.tree_util.tree_leaves(params_led)
    bitwise = len(la) == len(lb) and all(
        np.array_equal(x, y) for x, y in zip(la, lb)
    )
    sup = next(
        (
            p
            for p in (snap or {}).get("programs", ())
            if p["label"].startswith("superstep[PPOJaxPolicy:")
        ),
        None,
    )
    overhead_ledger = (wall_led - wall_off) / wall_off
    overhead_all = (wall_all - wall_off) / wall_off
    report = {
        "metric": "device_ledger_overhead",
        "config": {
            "train_batch": b,
            "minibatch": mb,
            "num_sgd_iter": iters,
            "kmax": kmax,
            "reps": reps,
            "device": jax.devices()[0].device_kind,
        },
        "superstep_wall_s": {
            "telemetry_off": round(wall_off, 4),
            "ledger": round(wall_led, 4),
            "ledger_and_trace": round(wall_all, 4),
        },
        "ledger_overhead_fraction": round(overhead_ledger, 4),
        "ledger_and_trace_overhead_fraction": round(
            overhead_all, 4
        ),
        "analysis_compile_s": {
            # one-time: the warmup call pays trace+compile, plus
            # (ledger phases) the disjoint AOT analysis compile
            "telemetry_off": round(warm_off, 3),
            "ledger": round(warm_led, 3),
            "ledger_and_trace": round(warm_all, 3),
        },
        "superstep_program": sup
        and {
            "flops": sup["flops"],
            "bytes_accessed": sup["bytes_accessed"],
            "memory": sup["memory"],
            "executions": sup["executions"],
            "mfu": sup["mfu"],
        },
        "bitwise_parity": bool(bitwise),
        "ok": overhead_ledger < 0.02 and bool(bitwise),
        "note": (
            "steady-state ledger hooks are timestamps + dict "
            "bumps per dispatch/drain; the cost/memory analysis "
            "pays ONE extra AOT compile per traced signature "
            "(jit execution cache and AOT cache are disjoint), "
            "visible in analysis_compile_s, never per step"
        ),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_lint(out_path=None, reps=2):
    """Device-contract static-analysis pass over all of ``ray_tpu/``
    (docs/static_analysis.md): reports scan wall time (the cost the
    tier-1 gate pays every run — the gate test budgets against the
    recorded number), file count, per-rule finding counts,
    baseline/suppression totals, and the ``--since`` incremental
    wall (empty change set: the parse+program-build floor every
    pre-commit run pays). Pure AST — no jax import, so it benches
    identically on broken-accelerator images. Writes
    ``benchmarks/e2e/static_analysis.json``."""
    import os

    from ray_tpu.analysis import (
        SCHEMA_VERSION,
        default_baseline_path,
        load_baseline,
        scan_paths,
    )
    from ray_tpu.analysis.rules import all_rules

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/static_analysis.json"
    baseline_path = default_baseline_path()
    baseline = (
        load_baseline(baseline_path)
        if os.path.exists(baseline_path)
        else []
    )
    # a couple of timed repetitions: the first pass pays cold file
    # reads, the second is the steady-state CI cost
    walls = []
    for _ in range(max(1, int(reps))):
        res = scan_paths(["ray_tpu"], baseline=baseline)
        walls.append(round(res.duration_s, 3))
    # the incremental floor: parse + whole-program build with zero
    # rule work (what `--since <rev>` costs on an unchanged tree)
    since = scan_paths(["ray_tpu"], baseline=baseline, changed=[])
    report = {
        "metric": "static_analysis",
        "schema_version": SCHEMA_VERSION,
        "rules": len(all_rules()),
        "scan_wall_s": walls[-1],
        "scan_wall_s_cold": walls[0],
        "since_wall_s": round(since.duration_s, 3),
        "files": res.files,
        "findings_unbaselined": len(res.findings),
        "findings_by_rule": res.counts(),
        "baselined": len(res.baselined),
        "baseline_entries": len(baseline),
        "stale_baseline": len(res.stale_baseline),
        "parse_errors": len(res.parse_errors),
        "ok": res.ok,
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def _make_mlp_policy(b, mb, iters=1, obs_dim=8, acts=4, hiddens=(32, 32)):
    """A micro MLP PPO policy: the dispatch benches want per-call HOST
    cost, so the device program should be as small as a real fused
    learner's is large."""
    import gymnasium as gym

    from ray_tpu.algorithms.ppo.ppo import PPOJaxPolicy

    return PPOJaxPolicy(
        gym.spaces.Box(-1, 1, (obs_dim,), np.float32),
        gym.spaces.Discrete(acts),
        {
            "train_batch_size": b,
            "sgd_minibatch_size": mb,
            "num_sgd_iter": iters,
            "lr": 5e-5,
            "model": {"fcnet_hiddens": list(hiddens)},
        },
    )


def _mlp_batch(rng, b, obs_dim=8, acts=4):
    return {
        "obs": rng.standard_normal((b, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, acts, b).astype(np.int64),
        "action_logp": np.full(b, -1.38, np.float32),
        "action_dist_inputs": rng.standard_normal((b, acts)).astype(
            np.float32
        ),
        "advantages": rng.standard_normal(b).astype(np.float32),
        "value_targets": rng.standard_normal(b).astype(np.float32),
    }


def bench_dispatch(out_path=None, b=64, kmax=8, rounds=5, n=30):
    """Per-dispatch HOST overhead microharness (the pjit-suite method
    over ``sharded_jit``): the same compiled superstep executable is
    dispatched with the diet on vs off (``sharding.set_dispatch_diet``
    — off IS the parent-commit host path), so any wall difference is
    host-side by construction. Device compute inside the scan is
    estimated from the K∈{2, kmax} wall scaling (the per-update
    in-scan marginal — per-dispatch host work cancels in the
    difference), and ``overhead = wall − kmax·per_update`` on both
    sides. Also reports the trivial-program per-call cost (cached
    1-element add — the raw ``ShardedFunction.__call__`` bookkeeping
    shave) and the ``specs.sharding_tree`` memo hit vs full
    re-derivation. Writes ``benchmarks/e2e/dispatch_diet.json``."""
    import os

    import jax

    from ray_tpu import sharding as sharding_lib
    from ray_tpu.sharding import specs as specs_lib

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/dispatch_diet.json"
    rng = np.random.default_rng(0)

    p = _make_mlp_policy(b, b)
    host, bsize = p.prepare_batch(_mlp_batch(rng, b))

    def feed(k):
        stacked = {
            cn: np.repeat(np.asarray(v)[None], k, axis=0)
            for cn, v in host.items()
        }
        shard = {
            cn: sharding_lib.batch_sharded(p.mesh, ndim_prefix=2)
            for cn in stacked
        }
        d = jax.device_put(stacked, shard)
        jax.block_until_ready(d)
        return d

    feeds = {k: feed(k) for k in (2, kmax)}
    prev = sharding_lib.set_dispatch_diet(True)
    try:
        for k, f in feeds.items():
            p.learn_superstep(k, bsize, stacked=dict(f), k_max=k)
        sharding_lib.set_dispatch_diet(False)
        p.learn_superstep(
            kmax, bsize, stacked=dict(feeds[kmax]), k_max=kmax
        )

        def wall(k, diet):
            sharding_lib.set_dispatch_diet(diet)
            best = float("inf")
            for _ in range(rounds):
                t0 = time.perf_counter()
                for _ in range(n):
                    p.learn_superstep(
                        k, bsize, stacked=dict(feeds[k]), k_max=k
                    )
                best = min(best, (time.perf_counter() - t0) / n)
            return best

        w2_on = wall(2, True)
        wk_on = wall(kmax, True)
        wk_off = wall(kmax, False)
        # per-update in-scan device compute: host per-dispatch work is
        # K-independent, so it cancels in the K difference
        pu = (wk_on - w2_on) / (kmax - 2)
        oh_on = max(wk_on - kmax * pu, 1e-7)
        oh_off = max(wk_off - kmax * pu, 1e-7)

        # trivial-program per-call cost: raw __call__ bookkeeping
        x = jax.device_put(
            np.ones((8, 8), np.float32),
            sharding_lib.replicated(p.mesh),
        )
        tfn = sharding_lib.sharded_jit(
            lambda a: a + 1.0, label="dispatch_micro"
        )
        tfn(x)

        def call_us(diet, nn=3000):
            sharding_lib.set_dispatch_diet(diet)
            best = float("inf")
            for _ in range(rounds):
                t0 = time.perf_counter()
                for _ in range(nn):
                    tfn(x)
                best = min(best, (time.perf_counter() - t0) / nn)
            return best * 1e6

        call_on = call_us(True)
        call_off = call_us(False)
    finally:
        sharding_lib.set_dispatch_diet(prev)

    # sharding_tree: signature-memo hit vs full re-derivation
    mesh = p.mesh
    tree = {cn: np.asarray(v) for cn, v in host.items()}
    specs_lib.sharding_tree(tree, mesh)

    def tree_us(clear, nn=2000):
        best = float("inf")
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(nn):
                if clear:
                    specs_lib.clear_sharding_caches()
                specs_lib.sharding_tree(dict(tree), mesh)
            best = min(best, (time.perf_counter() - t0) / nn)
        return best * 1e6

    tree_memo = tree_us(False)
    tree_full = tree_us(True)

    report = {
        "metric": "dispatch_diet_ab",
        "config": {
            "train_batch": b,
            "kmax": kmax,
            "rounds": rounds,
            "calls_per_round": n,
            "device": jax.devices()[0].device_kind,
        },
        "superstep_k8": {
            "wall_us_diet_on": round(wk_on * 1e6, 1),
            "wall_us_diet_off": round(wk_off * 1e6, 1),
            "per_update_in_scan_us": round(pu * 1e6, 1),
            "host_overhead_us_diet_on": round(oh_on * 1e6, 1),
            "host_overhead_us_diet_off": round(oh_off * 1e6, 1),
            "overhead_reduction": round(oh_off / oh_on, 1),
        },
        "trivial_call": {
            "us_diet_on": round(call_on, 2),
            "us_diet_off": round(call_off, 2),
        },
        "sharding_tree": {
            "us_memo_hit": round(tree_memo, 2),
            "us_full_derivation": round(tree_full, 2),
        },
        "note": (
            "diet-off restores the parent host path on the SAME "
            "compiled executables, so wall deltas are host-side by "
            "construction. The K=8 overhead reduction is the "
            "acceptance number: the fused key-schedule chain (one "
            "program for the k split dispatches), cached sharding "
            "trees, and the two-clock __call__ fast path together "
            "must at least halve per-dispatch host work"
        ),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def bench_pallas_kernels(out_path=None):
    """Per-kernel Pallas-vs-XLA A/B + parity for the PR's hot-op
    kernels (ops/framestack.py gather/scatter, ops/gae.py fragment
    scan, ops/segment_tree.py prefix descent), ledger-backed where the
    lane engages. On a CPU container the TPU lanes cannot engage
    (Mosaic needs a TPU backend); the kernels run through the Pallas
    interpreter for PARITY, the XLA walls are recorded as the
    reference, and ``engaged: false`` carries the why-not — the TPU
    driver round re-measures speedups from the same entry points.
    Writes ``benchmarks/e2e/pallas_kernels.json``."""
    import os

    import jax
    import jax.numpy as jnp

    from ray_tpu import sharding as sharding_lib
    from ray_tpu.ops import framestack as fs
    from ray_tpu.ops import gae as gae_lib
    from ray_tpu.ops import segment_tree as st

    os.makedirs("benchmarks/e2e", exist_ok=True)
    out_path = out_path or "benchmarks/e2e/pallas_kernels.json"
    rng = np.random.default_rng(0)
    on_tpu = jax.default_backend() == "tpu"

    def timed(fn, *args, n=20):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        jax.block_until_ready(out)
        return out, (time.perf_counter() - t0) / n

    kernels = []

    # 1. frame-pool gather (uint32-lane row copy)
    frames = jnp.asarray(
        rng.integers(0, 256, (2048, 16, 16, 1), dtype=np.uint8)
    )
    idx = jnp.asarray(rng.integers(0, 2044, 512), jnp.int32)
    xla = jax.jit(lambda f, i: fs.build_stacks(f, i, 4))
    pal = jax.jit(
        lambda f, i: fs.build_stacks(
            f, i, 4, use_pallas=True, interpret=not on_tpu
        )
    )
    a, t_x = timed(xla, frames, idx)
    b_, t_p = timed(pal, frames, idx)
    engaged = on_tpu and fs._rows_lower(
        1, int(np.prod(frames.shape[1:])) // 4, "uint32", False
    )
    kernels.append(
        {
            "kernel": "framestack_gather_rows",
            "engaged": bool(engaged),
            "reason": None
            if engaged
            else (
                "no TPU backend on this container: Mosaic lowering "
                "unavailable, interpreter-mode parity measured"
            ),
            "xla_wall_us": round(t_x * 1e6, 1),
            "pallas_wall_us": round(t_p * 1e6, 1),
            "pallas_mode": "tpu" if engaged else "interpret",
            "speedup": round(t_x / t_p, 2) if engaged else None,
            "parity": {
                "contract": "bitwise",
                "max_abs_diff": int(
                    np.max(
                        np.abs(
                            np.asarray(a, np.int32)
                            - np.asarray(b_, np.int32)
                        )
                    )
                ),
            },
        }
    )

    # 2. replay ring scatter (insert lane, aliased ring)
    ring = jnp.asarray(
        rng.integers(0, 2**32, (4096, 64), dtype=np.uint32)
    )
    pos = jnp.asarray(rng.integers(0, 4096, 256), jnp.int32)
    vals = jnp.asarray(
        rng.integers(0, 2**32, (256, 64), dtype=np.uint32)
    )
    xla = jax.jit(lambda r, p_, v: r.at[p_].set(v))
    pal = jax.jit(
        lambda r, p_, v: fs.scatter_rows(
            r, p_, v, use_pallas=True, interpret=not on_tpu
        )
    )
    a, t_x = timed(xla, ring, pos, vals)
    b_, t_p = timed(pal, ring, pos, vals)
    engaged = on_tpu and fs._rows_lower(256, 64, "uint32", True)
    kernels.append(
        {
            "kernel": "replay_scatter_rows",
            "engaged": bool(engaged),
            "reason": None
            if engaged
            else (
                "no TPU backend on this container: Mosaic lowering "
                "unavailable, interpreter-mode parity measured"
            ),
            "xla_wall_us": round(t_x * 1e6, 1),
            "pallas_wall_us": round(t_p * 1e6, 1),
            "pallas_mode": "tpu" if engaged else "interpret",
            "speedup": round(t_x / t_p, 2) if engaged else None,
            "parity": {
                "contract": "bitwise",
                "max_abs_diff": int(
                    np.max(
                        np.abs(
                            np.asarray(a, np.int64)
                            - np.asarray(b_, np.int64)
                        )
                    )
                ),
            },
        }
    )

    # 3. GAE fragment scan (sequential kernel vs associative_scan)
    B_, T_ = 64, 128
    r_ = jnp.asarray(rng.standard_normal((B_, T_)).astype(np.float32))
    v_ = jnp.asarray(rng.standard_normal((B_, T_)).astype(np.float32))
    nv = jnp.asarray(rng.standard_normal((B_, T_)).astype(np.float32))
    term = jnp.asarray(rng.random((B_, T_)) < 0.02)
    done = term | jnp.asarray(rng.random((B_, T_)) < 0.02)
    xla = jax.jit(
        lambda *x: gae_lib.compute_gae_fragment(*x, use_pallas=False)
    )
    pal = jax.jit(
        lambda *x: gae_lib.compute_gae_fragment(
            *x, use_pallas=True, interpret=not on_tpu
        )
    )
    (a, _), t_x = timed(xla, r_, v_, nv, term, done)
    (b2, _), t_p = timed(pal, r_, v_, nv, term, done)
    engaged = on_tpu and gae_lib._gae_lowers(B_, T_)
    gae_diff = float(jnp.max(jnp.abs(a - b2)))
    kernels.append(
        {
            "kernel": "gae_fragment_scan",
            "engaged": bool(engaged),
            "reason": None
            if engaged
            else (
                "no TPU backend on this container: Mosaic lowering "
                "unavailable, interpreter-mode parity measured"
            ),
            "xla_wall_us": round(t_x * 1e6, 1),
            "pallas_wall_us": round(t_p * 1e6, 1),
            "pallas_mode": "tpu" if engaged else "interpret",
            "speedup": round(t_x / t_p, 2) if engaged else None,
            "parity": {
                "contract": "float32 tolerance 1e-4 (sequential "
                "recurrence vs associative-scan reassociation)",
                "max_abs_diff": gae_diff,
            },
        }
    )

    # 4. sum-tree prefix descent (f64)
    cap = 4096
    host = st.SumSegmentTree(cap)
    leaf = rng.random(cap) + 0.01
    host.set_items(np.arange(cap), leaf)
    with sharding_lib.f64_scope():
        value = jnp.asarray(host.value, jnp.float64)
        pfx = jnp.asarray(
            rng.random(256) * host.sum(0, cap), jnp.float64
        )
        xla = jax.jit(
            lambda v_, p_: st.find_prefixsum_body(v_, p_, cap)
        )
        pal = jax.jit(
            lambda v_, p_: st.find_prefixsum_pallas(
                v_, p_, cap, interpret=True
            )
        )
        a, t_x = timed(xla, value, pfx)
        b2, t_p = timed(pal, value, pfx)
        engaged = on_tpu and st._descent_lowers(cap, 256)
    kernels.append(
        {
            "kernel": "sumtree_prefix_descent",
            "engaged": bool(engaged),
            "reason": None
            if engaged
            else (
                "f64 tree (the bit-exactness contract) does not "
                "lower through Mosaic on this container's backends; "
                "interpreter-mode parity measured — the kernel is "
                "the template for f64-capable backends"
            ),
            "xla_wall_us": round(t_x * 1e6, 1),
            "pallas_wall_us": round(t_p * 1e6, 1),
            "pallas_mode": "tpu" if engaged else "interpret",
            "speedup": round(t_x / t_p, 2) if engaged else None,
            "parity": {
                "contract": "bitwise (identical f64 op sequence)",
                "max_abs_diff": int(
                    np.max(
                        np.abs(np.asarray(a) - np.asarray(b2))
                    )
                ),
            },
        }
    )

    report = {
        "metric": "pallas_kernel_ab",
        "device": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "kernels": kernels,
        "note": (
            "speedup is reported only where the TPU lane engages "
            "(interpreter walls measure the reference semantics, not "
            "performance); use_pallas='auto' resolves per backend "
            "through each kernel's lowering probe, so these entry "
            "points self-select on the TPU round"
        ),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return report


def main():
    if "--lint" in sys.argv:
        bench_lint()
        return
    if "--dispatch" in sys.argv:
        bench_dispatch()
        return
    if "--pallas" in sys.argv:
        bench_pallas_kernels()
        return
    if "--e2e" in sys.argv:
        from bench_e2e import main as e2e_main

        e2e_main()
        return
    if "--sharding-ab" in sys.argv:
        bench_sharding_ab()
        return
    if "--replay-ab" in sys.argv:
        bench_replay_ab()
        return
    if "--apex" in sys.argv:
        bench_apex()
        return
    if "--superstep" in sys.argv:
        bench_superstep()
        return
    if "--jax-env" in sys.argv:
        bench_jax_env()
        return
    if "--serve" in sys.argv:
        bench_serve()
        return
    if "--ingress" in sys.argv:
        bench_ingress()
        return
    if "--flood" in sys.argv:
        bench_flood(smoke="--smoke" in sys.argv)
        return
    if "--model-parallel" in sys.argv:
        bench_model_parallel()
        return
    if "--obs" in sys.argv:
        bench_observability()
        return
    if "--profile" in sys.argv:
        bench_profile()
        return
    if "--chaos" in sys.argv:
        bench_chaos()
        return
    if "--fleet-worker" in sys.argv:
        bench_fleet_worker()
        return
    if "--fleetobs-worker" in sys.argv:
        bench_fleetobs_worker()
        return
    if "--fleetobs" in sys.argv:
        bench_fleetobs()
        return
    if "--fleet-chaos" in sys.argv:
        bench_fleet_chaos()
        return
    if "--fleet" in sys.argv:
        bench_fleet()
        return
    if "--elastic" in sys.argv:
        bench_elastic()
        return
    profile_dir = None
    if "--xprof" in sys.argv:
        i = sys.argv.index("--xprof")
        profile_dir = (
            sys.argv[i + 1] if len(sys.argv) > i + 1 else "/tmp/ray_tpu_trace"
        )
    (
        jax_sps,
        times,
        pipe_sps,
        pipe_wall,
        res_sps,
        res_wall,
    ) = bench_jax(profile_dir=profile_dir)
    mfu = bench_mfu()
    torch_sps = bench_torch()
    # Effective (wall-clock) MFU of the pipelined stream — the number
    # that includes transfer and amortized dispatch, not just the
    # epoch-isolated nest compute. Its physical ceiling on a tunneled
    # backend is the H2D bandwidth: a fresh train batch must cross the
    # wire every nest, so report the transfer bound alongside (bytes
    # per batch over the nest-compute time = the bandwidth that would
    # make compute the bottleneck).
    flops_per_nest = B * ITERS * nature_cnn_train_flops_per_sample()
    peak = mfu.get("peak_tflops") or chip_peak_tflops()[0]
    effective_mfu_pct = round(
        100.0 * flops_per_nest / pipe_wall / 1e12 / peak, 1
    )
    rng = np.random.default_rng(0)
    # bytes that actually cross the wire per nest: the PREPARED tree
    # (frame-pool format), not the materialized stacks
    _p = _make_policy(B, MB, ITERS)
    _tree, _ = _p.prepare_batch(make_batch(rng))
    batch_bytes = sum(v.nbytes for v in _tree.values())
    nest_s = mfu.get("nest_compute_s")
    breakeven_mb_s = (
        round(batch_bytes / nest_s / 1e6, 1) if nest_s else None
    )
    # measured H2D bandwidth: a fresh batch must cross the wire every
    # nest, so min(measured/breakeven, 1) bounds achievable wall-clock
    # MFU on this backend no matter how deep the pipeline
    import jax

    t0 = time.perf_counter()
    devd = jax.device_put(
        {"x": np.zeros(batch_bytes, np.uint8)}
    )
    jax.block_until_ready(devd["x"])
    h2d_mb_s = round(batch_bytes / (time.perf_counter() - t0) / 1e6, 1)
    print(
        json.dumps(
            {
                # the HEADLINE is the fused-lane number (ROADMAP 5a):
                # device-resident batches + pipelined dispatch — what
                # the subsystems built since r05 actually deliver. The
                # legacy tunnel-H2D walk rides below as
                # `legacy_tunnel` for trend continuity.
                "metric": "ppo_learner_env_steps_per_sec",
                "value": round(res_sps, 1),
                "unit": "env_steps/s",
                "lane": "pipelined_device_resident",
                "vs_baseline": round(res_sps / torch_sps, 2),
                "baseline_torch_cpu": round(torch_sps, 1),
                "legacy_tunnel": {
                    "env_steps_per_sec": round(jax_sps, 1),
                    "vs_baseline": round(jax_sps / torch_sps, 2),
                    "round_times_s": [round(t, 3) for t in times],
                },
                "pipelined": {
                    "env_steps_per_sec": round(pipe_sps, 1),
                    "wall_s_per_nest": round(pipe_wall, 4),
                    "effective_mfu_pct": effective_mfu_pct,
                    "batch_bytes": int(batch_bytes),
                    "h2d_mb_s_measured": h2d_mb_s,
                    "h2d_mb_s_for_compute_bound": breakeven_mb_s,
                    "note": (
                        "wall-clock MFU is H2D-bandwidth-bound on the "
                        "tunneled backend: a fresh (already 4x frame-"
                        "deduplicated) batch crosses the wire each "
                        "nest, so its ceiling is mfu_pct x measured/"
                        "compute-bound bandwidth; on direct-attached "
                        "TPU (GB/s DMA) the same program is nest-bound"
                    ),
                },
                "pipelined_device_resident": {
                    "env_steps_per_sec": round(res_sps, 1),
                    "wall_s_per_nest": round(res_wall, 4),
                    "effective_mfu_pct": round(
                        100.0
                        * flops_per_nest
                        / res_wall
                        / 1e12
                        / peak,
                        1,
                    ),
                    "note": (
                        "same pipelined protocol, batches pre-"
                        "resident on device: isolates dispatch "
                        "amortization from tunnel H2D — this is "
                        "the number a direct-attached TPU's "
                        "feeder-fed learner sees"
                    ),
                },
                "mfu": mfu,
                "config": {
                    "train_batch": B,
                    "minibatch": MB,
                    "num_sgd_iter": ITERS,
                    "obs": [H, W, C],
                },
            }
        )
    )


if __name__ == "__main__":
    main()
