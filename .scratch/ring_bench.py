import numpy as np, time, ray_tpu as ray

def bench(env, shape):
    ray.init(num_cpus=1, ignore_reinit_error=True, worker_env=env)
    try:
        payload = np.ones(shape, np.float32)
        @ray.remote
        def produce():
            return payload
        ray.get(produce.remote())
        t0 = time.perf_counter()
        for _ in range(30):
            ray.get(produce.remote())
        return (time.perf_counter() - t0) / 30
    finally:
        ray.shutdown()

if __name__ == "__main__":
    for kb in (16, 48, 96, 192, 512):
        shape = (kb * 256,)
        tr = bench({}, shape)
        tp = bench({"RAY_TPU_DISABLE_RING": "1"}, shape)
        print(f"{kb:4d}KB  ring={tr*1e3:7.3f}ms  no-ring={tp*1e3:7.3f}ms  ratio={tp/tr:5.2f}x", flush=True)
