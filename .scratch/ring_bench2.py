import numpy as np, time, ray_tpu as ray

def bench(env, kb, n=64):
    ray.init(num_cpus=2, ignore_reinit_error=True, worker_env=env)
    try:
        payload = np.ones((kb * 256,), np.float32)
        @ray.remote
        def produce():
            return payload
        ray.get([produce.remote() for _ in range(4)])
        t0 = time.perf_counter()
        refs = [produce.remote() for _ in range(n)]
        ray.get(refs)
        return (time.perf_counter() - t0) / n
    finally:
        ray.shutdown()

if __name__ == "__main__":
    for kb in (48, 96, 192):
        tr = bench({}, kb)
        tp = bench({"RAY_TPU_DISABLE_RING": "1"}, kb)
        print(f"pipelined {kb:4d}KB  ring={tr*1e3:7.3f}ms  no-ring={tp*1e3:7.3f}ms  ratio={tp/tr:5.2f}x", flush=True)
