"""Convolutional vision networks.

Counterpart of the reference's ``rllib/models/torch/visionnet.py`` with the
standard Atari "Nature CNN" filter stack (reference
``rllib/models/utils.py get_filter_config``). Convolutions run in bfloat16 by
default — conv FLOPs dominate Atari learner time and the MXU natively prefers
bf16 — with float32 heads for logits/value.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ray_tpu.models.base import RTModel, get_activation

# (out_channels, kernel, stride) — Nature CNN for 84x84
NATURE_FILTERS = ((32, (8, 8), (4, 4)), (64, (4, 4), (2, 2)), (64, (3, 3), (1, 1)))
# for 42x42 downsampled (reference get_filter_config)
SMALL_FILTERS = ((16, (4, 4), (2, 2)), (32, (4, 4), (2, 2)), (256, (11, 11), (1, 1)))


def get_filter_config(shape) -> Tuple:
    """Pick a conv stack for the obs resolution (reference models/utils.py)."""
    if len(shape) == 3 and shape[0] in (84, 80) :
        return NATURE_FILTERS
    if len(shape) == 3 and shape[0] == 42:
        return SMALL_FILTERS
    return NATURE_FILTERS


class VisionNet(RTModel):
    num_outputs: int
    conv_filters: Tuple = NATURE_FILTERS
    conv_activation: str = "relu"
    post_fcnet_hiddens: Sequence[int] = (512,)
    post_fcnet_activation: str = "relu"
    vf_share_layers: bool = True
    dtype_: str = "bfloat16"

    @nn.compact
    def __call__(self, obs, state=(), seq_lens=None):
        dtype = jnp.dtype(self.dtype_)
        act = get_activation(self.conv_activation)
        post_act = get_activation(self.post_fcnet_activation)

        x = obs.astype(dtype)
        if x.dtype == jnp.uint8 or obs.dtype == jnp.uint8:
            x = obs.astype(dtype) / 255.0
        for i, (ch, kernel, stride) in enumerate(self.conv_filters):
            x = act(
                nn.Conv(
                    ch, kernel, strides=stride, padding="VALID",
                    name=f"conv_{i}", dtype=dtype,
                )(x)
            )
        x = x.reshape(x.shape[0], -1)
        for i, size in enumerate(self.post_fcnet_hiddens):
            x = post_act(nn.Dense(size, name=f"post_fc_{i}", dtype=dtype)(x))

        logits = nn.Dense(
            self.num_outputs, name="logits", dtype=jnp.float32,
            kernel_init=nn.initializers.variance_scaling(
                0.01, "fan_in", "truncated_normal"),
        )(x.astype(jnp.float32))
        if self.vf_share_layers:
            value = nn.Dense(1, name="value", dtype=jnp.float32)(
                x.astype(jnp.float32)
            )
        else:
            y = obs.astype(dtype)
            if obs.dtype == jnp.uint8:
                y = obs.astype(dtype) / 255.0
            for i, (ch, kernel, stride) in enumerate(self.conv_filters):
                y = act(
                    nn.Conv(ch, kernel, strides=stride, padding="VALID",
                            name=f"vf_conv_{i}", dtype=dtype)(y)
                )
            y = y.reshape(y.shape[0], -1).astype(jnp.float32)
            value = nn.Dense(1, name="value", dtype=jnp.float32)(y)
        return logits, value.squeeze(-1), ()
