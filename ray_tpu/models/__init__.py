from ray_tpu.models.base import RTModel
from ray_tpu.models.catalog import ModelCatalog, MODEL_DEFAULTS
from ray_tpu.models.fcnet import FCNet
from ray_tpu.models.cnn import VisionNet
from ray_tpu.models.rnn import LSTMWrapper
from ray_tpu.models.attention import GTrXLNet

__all__ = [
    "RTModel",
    "ModelCatalog",
    "MODEL_DEFAULTS",
    "FCNet",
    "VisionNet",
    "LSTMWrapper",
    "GTrXLNet",
]
