"""Observation preprocessors (host-side, numpy).

Counterpart of the reference's ``rllib/models/preprocessors.py:24``. Runs on
CPU rollout actors before observations enter SampleBatch columns, so the
learner only ever sees flat fixed-shape float/uint8 arrays.
"""

from __future__ import annotations

import numpy as np

try:
    import gymnasium as gym
    from gymnasium import spaces
except ImportError:  # pragma: no cover
    gym = None
    spaces = None


class Preprocessor:
    def __init__(self, obs_space):
        self._obs_space = obs_space
        self.shape = self._init_shape(obs_space)
        self._size = int(np.prod(self.shape))

    def _init_shape(self, obs_space):
        raise NotImplementedError

    def transform(self, observation) -> np.ndarray:
        raise NotImplementedError

    @property
    def size(self) -> int:
        return self._size

    @property
    def observation_space(self):
        space = spaces.Box(-1.0, 1.0, self.shape, dtype=np.float32)
        space.original_space = self._obs_space
        return space


class NoPreprocessor(Preprocessor):
    def _init_shape(self, obs_space):
        return obs_space.shape

    def transform(self, observation):
        return np.asarray(observation)

    @property
    def observation_space(self):
        return self._obs_space


class OneHotPreprocessor(Preprocessor):
    """Discrete → one-hot (reference preprocessors.py OneHotPreprocessor)."""

    def _init_shape(self, obs_space):
        if isinstance(obs_space, spaces.Discrete):
            return (int(obs_space.n),)
        # MultiDiscrete
        return (int(np.sum(obs_space.nvec)),)

    def transform(self, observation):
        out = np.zeros(self.shape, dtype=np.float32)
        if isinstance(self._obs_space, spaces.Discrete):
            out[int(observation)] = 1.0
        else:
            offset = 0
            for i, n in enumerate(self._obs_space.nvec):
                out[offset + int(observation[i])] = 1.0
                offset += int(n)
        return out


class FlattenPreprocessor(Preprocessor):
    def _init_shape(self, obs_space):
        return (int(np.prod(obs_space.shape)),)

    def transform(self, observation):
        return np.asarray(observation, dtype=np.float32).reshape(-1)


class DictFlatteningPreprocessor(Preprocessor):
    """Dict/Tuple spaces → single flat vector (reference
    DictFlatteningPreprocessor / TupleFlatteningPreprocessor)."""

    def _init_shape(self, obs_space):
        self._children = []
        if isinstance(obs_space, spaces.Dict):
            items = [obs_space.spaces[k] for k in sorted(obs_space.spaces)]
            self._keys = sorted(obs_space.spaces)
        else:
            items = list(obs_space.spaces)
            self._keys = None
        size = 0
        for sp in items:
            child = get_preprocessor_for_space(sp)
            self._children.append(child)
            size += child.size
        return (size,)

    def transform(self, observation):
        if self._keys is not None:
            parts = [
                self._children[i].transform(observation[k]).reshape(-1)
                for i, k in enumerate(self._keys)
            ]
        else:
            parts = [
                c.transform(o).reshape(-1)
                for c, o in zip(self._children, observation)
            ]
        return np.concatenate(
            [p.astype(np.float32) for p in parts]
        )


def get_preprocessor_for_space(obs_space) -> Preprocessor:
    """Reference ModelCatalog.get_preprocessor (catalog.py:768)."""
    if isinstance(obs_space, (spaces.Discrete, spaces.MultiDiscrete)):
        return OneHotPreprocessor(obs_space)
    if isinstance(obs_space, (spaces.Dict, spaces.Tuple)):
        return DictFlatteningPreprocessor(obs_space)
    if isinstance(obs_space, spaces.Box):
        # Images (3D uint8) pass through unchanged for the CNN path.
        if len(obs_space.shape) == 3:
            return NoPreprocessor(obs_space)
        return NoPreprocessor(obs_space)
    return NoPreprocessor(obs_space)
