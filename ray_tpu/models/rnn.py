"""Recurrent (LSTM) model wrapper.

Counterpart of the reference's ``rllib/models/torch/recurrent_net.py``
(LSTMWrapper). TPU-first differences:
  - time is unrolled with ``nn.scan`` (compiles to an XLA while loop with
    static (B, T) shapes) instead of cuDNN packed sequences;
  - episode boundaries inside a fragment are handled by a per-step ``resets``
    mask that zeroes the carried state, so fragments never need re-chopping
    to episode boundaries (the reference chops + zero-pads via
    ``rllib/policy/rnn_sequencing.py:216``).

Call contract: obs is (B, T, ...); state is a (h, c) pair each (B, cell);
returns logits (B*T, num_outputs), value (B*T,), new state.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.models.base import RTModel, get_activation


class LSTMWrapper(RTModel):
    num_outputs: int
    cell_size: int = 256
    hiddens: Sequence[int] = (256,)
    activation: str = "tanh"
    use_prev_action: bool = False
    use_prev_reward: bool = False
    dtype_: str = "float32"

    @property
    def is_recurrent(self) -> bool:
        return True

    @property
    def supports_stored_train_state(self) -> bool:
        # the resets mask zeroes the carry at episode boundaries, so
        # feeding the sampler's stored chunk-start (h, c) makes the
        # train-time forward match the rollout-time forward exactly
        # for mid-episode chunks (reference precedent: R2D2's
        # stored-state mode, rllib r2d2.py zero_init_states=False)
        return True

    def initial_state(self, batch_size: int = 1):
        return (
            jnp.zeros((batch_size, self.cell_size), jnp.float32),
            jnp.zeros((batch_size, self.cell_size), jnp.float32),
        )

    @nn.compact
    def __call__(self, obs, state, seq_lens=None, resets=None,
                 prev_actions=None, prev_rewards=None):
        dtype = jnp.dtype(self.dtype_)
        act = get_activation(self.activation)
        B, T = obs.shape[0], obs.shape[1]
        x = obs.astype(dtype).reshape(B, T, -1)
        extras = []
        if self.use_prev_action and prev_actions is not None:
            extras.append(prev_actions.astype(dtype).reshape(B, T, -1))
        if self.use_prev_reward and prev_rewards is not None:
            extras.append(prev_rewards.astype(dtype).reshape(B, T, 1))
        if extras:
            x = jnp.concatenate([x] + extras, axis=-1)
        for i, size in enumerate(self.hiddens):
            x = act(nn.Dense(size, name=f"fc_{i}", dtype=dtype)(x))

        cell = nn.OptimizedLSTMCell(self.cell_size, dtype=dtype)
        if resets is None:
            resets = jnp.zeros((B, T), jnp.float32)
        resets = resets.astype(jnp.float32)

        def step(cell, carry, inputs):
            xt, reset_t = inputs
            keep = (1.0 - reset_t)[:, None]
            carry = (carry[0] * keep, carry[1] * keep)
            carry, y = cell(carry, xt)
            return carry, y

        scan = nn.scan(
            step,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=1,
            out_axes=1,
        )
        carry0 = (state[1].astype(dtype), state[0].astype(dtype))  # (c, h)
        carry, y = scan(cell, carry0, (x, resets))
        new_state = (
            carry[1].astype(jnp.float32),  # h
            carry[0].astype(jnp.float32),  # c
        )
        y = y.reshape(B * T, -1)
        logits = nn.Dense(
            self.num_outputs, name="logits", dtype=jnp.float32,
            kernel_init=nn.initializers.variance_scaling(
                0.01, "fan_in", "truncated_normal"),
        )(y.astype(jnp.float32))
        value = nn.Dense(1, name="value", dtype=jnp.float32)(
            y.astype(jnp.float32)
        ).squeeze(-1)
        return logits, value, new_state
