"""Fully-connected policy/value network.

Counterpart of the reference's ``rllib/models/torch/fcnet.py`` (and the jax
stub ``rllib/models/jax/fcnet.py``). Supports the same knobs: ``hiddens``,
``activation``, ``vf_share_layers``, ``free_log_std`` (a state-independent
log-std appended to the mean output for DiagGaussian policies).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ray_tpu.models.base import RTModel, get_activation


class FCNet(RTModel):
    num_outputs: int
    hiddens: Sequence[int] = (256, 256)
    activation: str = "tanh"
    vf_share_layers: bool = False
    free_log_std: bool = False
    dtype_: str = "float32"

    @nn.compact
    def __call__(self, obs, state=(), seq_lens=None):
        dtype = jnp.dtype(self.dtype_)
        x = obs.astype(dtype)
        x = x.reshape(x.shape[0], -1)
        act = get_activation(self.activation)

        num_outputs = self.num_outputs
        if self.free_log_std:
            num_outputs = num_outputs // 2

        h = x
        for i, size in enumerate(self.hiddens):
            h = act(nn.Dense(size, name=f"fc_{i}", dtype=dtype)(h))
        logits = nn.Dense(
            num_outputs,
            name="logits",
            dtype=dtype,
            kernel_init=nn.initializers.variance_scaling(
                0.01, "fan_in", "truncated_normal"
            ),
        )(h)

        if self.free_log_std:
            log_std = self.param(
                "free_log_std",
                nn.initializers.zeros,
                (num_outputs,),
                jnp.float32,
            )
            logits = jnp.concatenate(
                [logits, jnp.broadcast_to(log_std, logits.shape)], axis=-1
            )

        if self.vf_share_layers:
            vf_h = h
        else:
            vf_h = x
            for i, size in enumerate(self.hiddens):
                vf_h = act(nn.Dense(size, name=f"vf_fc_{i}", dtype=dtype)(vf_h))
        value = nn.Dense(
            1,
            name="value",
            dtype=dtype,
            kernel_init=nn.initializers.variance_scaling(
                1.0, "fan_in", "truncated_normal"
            ),
        )(vf_h)
        return logits.astype(jnp.float32), value.squeeze(-1).astype(jnp.float32), ()
