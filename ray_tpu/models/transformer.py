"""Decoder-style transformer policy torso, tensor-parallel over the
mesh's ``"model"`` axis.

The concrete proof that the learner path is architecture-agnostic
(ROADMAP item 4): a policy whose params need NOT fit replicated on one
device. Observations are chunked into a short token sequence, run
through pre-LN causal decoder blocks — attention through the tested
``ops/flash_attention`` core — and read out at the last token into
policy-logits and value heads.

Partitioning is megatron-style and happens at two cooperating layers:

  - **placement**: ``partition_rules()`` (the
    ``sharding.specs.default_partition_rules`` grammar) split the QKV
    projections on the head dim, the output projection on its input
    dim, and the MLP up/down kernels on their wide dim; embeddings,
    layernorms, heads, and reduced-output biases replicate.
  - **compute**: inside a ``shard_map``-lowered learn program the
    model sees its LOCAL param slices, so :meth:`apply` inserts the
    Megatron f/g boundary collectives itself — ``copy_to_model_shards``
    (identity forward, ``psum`` backward) entering each parallel
    region, ``lax.psum`` leaving each row-parallel projection. Whether
    the model axis is bound is probed at trace time, so the SAME apply
    serves three regimes: the partitioned learn program (manual
    collectives over local slices), plain jit inference over globally
    shaped sharded arrays (GSPMD inserts the collectives), and the
    legacy replicated path (no collectives at all). On a size-1 model
    axis every collective is an exact identity, which is what makes
    ``model_parallel=1`` bit-identical to the replicated path (the
    tests/test_model_parallel.py parity contract).

Not a flax module on purpose: flax validates param shapes against the
module config at apply time, which would reject the local slices a
``shard_map`` body sees. Params are a plain nested dict; every head /
width is derived from the param shapes actually passed in, so global
and local shapes flow through the same code.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.sharding.mesh import MODEL_AXIS


def _bound_parallel_axis(name: Optional[str]) -> Optional[str]:
    """Trace-time probe: ``name`` if it is a bound mesh axis here
    (i.e. we are inside a shard_map over it) AND its size exceeds 1 —
    else None. The discarded axis_index is dead code when bound;
    unbound raises before building anything. A size-1 axis returns
    None on purpose: its collectives would be exact no-ops, and
    emitting none keeps the ``model_parallel=1`` program literally the
    replicated program (the bitwise-parity geometry). ``axis_size``
    folds to a static int at trace time (parallel/__init__ shim)."""
    if not name:
        return None
    try:
        jax.lax.axis_index(name)
    except Exception:
        return None
    try:
        if int(jax.lax.axis_size(name)) <= 1:
            return None
    except Exception:  # non-static size: keep the collectives (safe)
        pass
    return name


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_model_shards(x, axis):
    """Megatron's *f* operator: identity forward into a tensor-parallel
    region, all-reduce backward — collects each model shard's partial
    gradient contribution to the (replicated) activations feeding a
    column-parallel projection."""
    return x


def _copy_fwd(x, axis):
    return x, None


def _copy_bwd(axis, _res, g):
    return (jax.lax.psum(g, axis),)


copy_to_model_shards.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_model_shards(x, axis):
    """Megatron's *g* operator: all-reduce forward out of a
    row-parallel projection, identity backward. Spelled as a
    custom_vjp rather than a bare ``lax.psum`` because under
    ``check_rep=False`` (the jax<0.5 shard_map shim) psum transposes
    to psum, which would double-reduce the cotangent."""
    return jax.lax.psum(x, axis)


def _reduce_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _reduce_bwd(axis, _res, g):
    return (g,)


reduce_from_model_shards.defvjp(_reduce_fwd, _reduce_bwd)


def _layer_norm(x, p, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


class TransformerPolicyNet:
    """Duck-typed :class:`~ray_tpu.models.base.RTModel` surface
    (``init`` / ``apply`` / ``initial_state`` / ``is_recurrent``) over
    plain-dict params. Registered via
    ``model_config["use_transformer"]`` (models/catalog.py)."""

    is_recurrent = False
    supports_stored_train_state = False
    _partition_rules_override = None

    def __init__(
        self,
        num_outputs: int,
        d_model: int = 64,
        num_layers: int = 2,
        num_heads: int = 4,
        head_dim: Optional[int] = None,
        ff_dim: Optional[int] = None,
        seq_len: int = 8,
        dtype_: str = "float32",
    ):
        self.num_outputs = int(num_outputs)
        self.d_model = int(d_model)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim or self.d_model // self.num_heads)
        self.ff_dim = int(ff_dim or 4 * self.d_model)
        self.seq_len = int(seq_len)
        self.dtype_ = dtype_

    # -- RTModel surface -------------------------------------------------

    def initial_state(self, batch_size: int = 1) -> Sequence:
        return ()

    def partition_rules(self):
        if self._partition_rules_override is not None:
            return tuple(self._partition_rules_override)
        from ray_tpu.sharding.specs import default_partition_rules

        return default_partition_rules()

    @classmethod
    def with_logical_rules(cls, rules):
        return type(
            cls.__name__ + "WithRules",
            (cls,),
            {"_partition_rules_override": tuple(rules)},
        )

    # -- params ----------------------------------------------------------

    def _tokens(self, x):
        """Chunk a flat (B, F) feature row into (B, S, ceil(F/S))
        tokens (zero-padded tail) — the decoder's input sequence."""
        B, F = x.shape
        S = self.seq_len
        tok = -(-F // S)
        if S * tok != F:
            x = jnp.pad(x, ((0, 0), (0, S * tok - F)))
        return x.reshape(B, S, tok)

    def init(self, rng, obs):
        """Global-shape param tree (leaf names are what the partition
        rules pattern-match)."""
        obs = jnp.asarray(obs)
        F = int(np.prod(obs.shape[1:]))
        tok = -(-F // self.seq_len)
        D, H, Dh, FF = (
            self.d_model, self.num_heads, self.head_dim, self.ff_dim,
        )
        lecun = jax.nn.initializers.lecun_normal()
        small = jax.nn.initializers.variance_scaling(
            0.01, "fan_in", "truncated_normal"
        )
        keys = iter(jax.random.split(rng, 4 + 8 * self.num_layers))

        def ln():
            return {
                "scale": jnp.ones((D,), jnp.float32),
                "bias": jnp.zeros((D,), jnp.float32),
            }

        params = {
            "in_proj": {
                "kernel": lecun(next(keys), (tok, D), jnp.float32),
                "bias": jnp.zeros((D,), jnp.float32),
            },
            "pos": small(next(keys), (self.seq_len, D), jnp.float32),
        }
        for i in range(self.num_layers):
            params[f"layer_{i}"] = {
                "ln1": ln(),
                "attn": {
                    "wq": lecun(
                        next(keys), (D, H * Dh), jnp.float32
                    ).reshape(D, H, Dh),
                    "wk": lecun(
                        next(keys), (D, H * Dh), jnp.float32
                    ).reshape(D, H, Dh),
                    "wv": lecun(
                        next(keys), (D, H * Dh), jnp.float32
                    ).reshape(D, H, Dh),
                    "bq": jnp.zeros((H, Dh), jnp.float32),
                    "bk": jnp.zeros((H, Dh), jnp.float32),
                    "bv": jnp.zeros((H, Dh), jnp.float32),
                    "wo": lecun(
                        next(keys), (H * Dh, D), jnp.float32
                    ).reshape(H, Dh, D),
                    "bo": jnp.zeros((D,), jnp.float32),
                },
                "ln2": ln(),
                "mlp": {
                    "w_up": lecun(next(keys), (D, FF), jnp.float32),
                    "b_up": jnp.zeros((FF,), jnp.float32),
                    "w_down": lecun(next(keys), (FF, D), jnp.float32),
                    "b_down": jnp.zeros((D,), jnp.float32),
                },
            }
        params["ln_f"] = ln()
        params["logits"] = {
            "kernel": small(
                next(keys), (D, self.num_outputs), jnp.float32
            ),
            "bias": jnp.zeros((self.num_outputs,), jnp.float32),
        }
        params["value"] = {
            "kernel": jax.nn.initializers.variance_scaling(
                1.0, "fan_in", "truncated_normal"
            )(next(keys), (D, 1), jnp.float32),
            "bias": jnp.zeros((1,), jnp.float32),
        }
        return params

    # -- forward ---------------------------------------------------------

    def _attn(self, ap, x, axis):
        # local head count comes off the param slice, not config: the
        # same einsums serve global arrays and shard_map-local slices
        if axis:
            x = copy_to_model_shards(x, axis)
        q = jnp.einsum("bsd,dhk->bhsk", x, ap["wq"]) + ap["bq"][
            None, :, None, :
        ]
        k = jnp.einsum("bsd,dhk->bhsk", x, ap["wk"]) + ap["bk"][
            None, :, None, :
        ]
        v = jnp.einsum("bsd,dhk->bhsk", x, ap["wv"]) + ap["bv"][
            None, :, None, :
        ]
        o = flash_attention(q, k, v, causal_offset=0)
        y = jnp.einsum("bhsk,hkd->bsd", o, ap["wo"])
        if axis:
            y = reduce_from_model_shards(y, axis)
        return y + ap["bo"]

    def _mlp(self, mp, x, axis):
        if axis:
            x = copy_to_model_shards(x, axis)
        h = jax.nn.gelu(x @ mp["w_up"] + mp["b_up"])
        y = h @ mp["w_down"]
        if axis:
            y = reduce_from_model_shards(y, axis)
        return y + mp["b_down"]

    def apply(self, params, obs, state=(), seq_lens=None):
        axis = _bound_parallel_axis(MODEL_AXIS)
        dtype = jnp.dtype(self.dtype_)
        x = jnp.asarray(obs).astype(dtype)
        x = x.reshape(x.shape[0], -1)
        t = self._tokens(x)
        h = (
            t @ params["in_proj"]["kernel"]
            + params["in_proj"]["bias"]
            + params["pos"]
        )
        for i in range(self.num_layers):
            lp = params[f"layer_{i}"]
            h = h + self._attn(lp["attn"], _layer_norm(h, lp["ln1"]), axis)
            h = h + self._mlp(lp["mlp"], _layer_norm(h, lp["ln2"]), axis)
        feat = _layer_norm(h, params["ln_f"])[:, -1]
        logits = feat @ params["logits"]["kernel"] + params["logits"]["bias"]
        value = (
            feat @ params["value"]["kernel"] + params["value"]["bias"]
        ).squeeze(-1)
        return (
            logits.astype(jnp.float32),
            value.astype(jnp.float32),
            (),
        )

    def num_params(self) -> int:
        """Static param count at the configured geometry (bench
        reporting)."""
        D, H, Dh, FF, S = (
            self.d_model,
            self.num_heads,
            self.head_dim,
            self.ff_dim,
            self.seq_len,
        )
        per_layer = (
            3 * (D * H * Dh + H * Dh)  # qkv
            + H * Dh * D + D           # out proj
            + D * FF + FF + FF * D + D  # mlp
            + 4 * D                    # 2 layernorms
        )
        return (
            self.num_layers * per_layer
            + S * D + 2 * D            # pos + final ln
            + D * self.num_outputs + self.num_outputs
            + D + 1                    # value head
        )
