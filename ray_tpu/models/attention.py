"""GTrXL attention network (stabilized transformer for RL).

Counterpart of the reference's ``rllib/models/torch/attention_net.py:37``
(GTrXLNet, from "Stabilizing Transformers for RL", Parisotto et al. 2019).
TPU-first: attention over the (memory + fragment) window is a single fused
(B, H, T, S) dot-product batch that maps straight onto the MXU; recurrent
"memory" per layer is carried as state arrays of static shape
(B, memory_len, dim), so inference and training use one compiled graph.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from ray_tpu.models.base import RTModel
from ray_tpu.ops.flash_attention import flash_attention


class _GRUGate(nn.Module):
    dim: int
    init_bias: float = 2.0

    @nn.compact
    def __call__(self, x, y):
        # x = residual input, y = transformed branch
        wr = nn.Dense(self.dim, use_bias=False, name="wr")
        ur = nn.Dense(self.dim, use_bias=False, name="ur")
        wz = nn.Dense(self.dim, use_bias=False, name="wz")
        uz = nn.Dense(self.dim, use_bias=False, name="uz")
        wg = nn.Dense(self.dim, use_bias=False, name="wg")
        ug = nn.Dense(self.dim, use_bias=False, name="ug")
        bz = self.param(
            "bz", nn.initializers.constant(self.init_bias), (self.dim,)
        )
        r = nn.sigmoid(wr(y) + ur(x))
        z = nn.sigmoid(wz(y) + uz(x) - bz)
        h = nn.tanh(wg(y) + ug(r * x))
        return (1.0 - z) * x + z * h


def _rel_positional_embedding(seq_len: int, dim: int) -> jnp.ndarray:
    pos = jnp.arange(seq_len - 1, -1, -1.0)
    inv_freq = 1.0 / (10000 ** (jnp.arange(0, dim, 2.0) / dim))
    inp = pos[:, None] * inv_freq[None, :]
    return jnp.concatenate([jnp.sin(inp), jnp.cos(inp)], axis=-1)


class GTrXLNet(RTModel):
    num_outputs: int
    attention_dim: int = 64
    num_transformer_units: int = 1
    num_heads: int = 2
    head_dim: int = 32
    memory_len: int = 50
    position_wise_mlp_dim: int = 32
    init_gru_gate_bias: float = 2.0

    @property
    def is_recurrent(self) -> bool:
        return True

    @property
    def supports_stored_train_state(self) -> bool:
        # KNOWN APPROXIMATION (kept deliberately): the learn-path
        # unroll feeds zero memory, while rollouts acted with real
        # carried memory — for mid-episode chunks the stored
        # ACTION_LOGP was produced under a different memory than the
        # train-time forward, slightly biasing PPO/APPO importance
        # ratios (episode-initial chunks are exact). Feeding stored
        # memory would need per-SEGMENT memory swaps inside a chunk
        # (after an in-chunk reset the rollout attended the FRESH zero
        # memory, not the chunk-start memory), which one fixed-shape
        # forward cannot express. The reference's attention path has
        # the mirror-image compromise: it feeds stored memory and
        # lets post-reset rows attend stale pre-reset memory. Use
        # max_seq_len <= typical episode length to bound the bias.
        return False

    def initial_state(self, batch_size: int = 1):
        return tuple(
            jnp.zeros(
                (batch_size, self.memory_len, self.attention_dim), jnp.float32
            )
            for _ in range(self.num_transformer_units)
        )

    @nn.compact
    def __call__(self, obs, state, seq_lens=None, resets=None):
        B, T = obs.shape[0], obs.shape[1]
        x = obs.reshape(B, T, -1).astype(jnp.float32)
        x = nn.Dense(self.attention_dim, name="embed")(x)

        new_state = []
        M = self.memory_len
        S = M + T
        # the causal band over the concatenated [memory | fragment]
        # window (query t attends all memory plus fragment steps <= t)
        # is expressed as flash_attention's causal_offset=M below

        pos_emb = _rel_positional_embedding(S, self.attention_dim)

        for layer in range(self.num_transformer_units):
            mem = state[layer]  # (B, M, D)
            new_state.append(
                jnp.concatenate([mem, x], axis=1)[:, -M:].astype(jnp.float32)
            )
            kv_in = jnp.concatenate([mem, x], axis=1)  # (B, S, D)
            ln_x = nn.LayerNorm(name=f"ln_q_{layer}")(x)
            ln_kv = nn.LayerNorm(name=f"ln_kv_{layer}")(kv_in)

            H, Dh = self.num_heads, self.head_dim
            q = nn.Dense(H * Dh, name=f"q_{layer}")(ln_x)
            k = nn.Dense(H * Dh, name=f"k_{layer}")(ln_kv + pos_emb[None])
            v = nn.Dense(H * Dh, name=f"v_{layer}")(ln_kv)
            q = q.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
            k = k.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
            v = v.reshape(B, S, H, Dh).transpose(0, 2, 1, 3)
            if resets is None:
                # the [memory | fragment] band (k_pos - M <= q_pos) is
                # flash_attention's causal_offset=M; fused Pallas
                # kernel on TPU, identical XLA math elsewhere
                out = flash_attention(q, k, v, causal_offset=M)
            else:
                # train-path episode isolation: attention must not
                # cross a reset. Segment ids (cumsum of resets) gate
                # FRAGMENT keys only; memory keys stay attendable like
                # at inference (train-path memory is the zero state, so
                # attending it reproduces the rollout-time softmax
                # exactly — masking it would shift the denominator and
                # bias the stored-logp ratios). Dynamic mask → XLA path.
                seg = jnp.cumsum(
                    resets.astype(jnp.int32), axis=1
                )  # (B, T)
                band = (
                    jnp.arange(S)[None, :] - M
                    <= jnp.arange(T)[:, None]
                )  # (T, S)
                frag_ok = (
                    seg[:, :, None] == seg[:, None, :]
                )  # (B, T, T)
                mem_ok = jnp.ones((B, T, M), bool)
                full_mask = band[None] & jnp.concatenate(
                    [mem_ok, frag_ok], axis=-1
                )  # (B, T, S)
                scores = jnp.einsum(
                    "bhtd,bhsd->bhts", q, k
                ) / jnp.sqrt(jnp.float32(Dh))
                scores = jnp.where(
                    full_mask[:, None], scores, -1e9
                )
                out = jnp.einsum(
                    "bhts,bhsd->bhtd",
                    nn.softmax(scores, axis=-1),
                    v,
                )
            out = out.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)
            out = nn.Dense(self.attention_dim, name=f"proj_{layer}")(out)
            x = _GRUGate(
                self.attention_dim, self.init_gru_gate_bias,
                name=f"gate_attn_{layer}",
            )(x, nn.relu(out))

            ln2 = nn.LayerNorm(name=f"ln_mlp_{layer}")(x)
            mlp = nn.Dense(
                self.position_wise_mlp_dim, name=f"mlp0_{layer}"
            )(ln2)
            mlp = nn.relu(mlp)
            mlp = nn.Dense(self.attention_dim, name=f"mlp1_{layer}")(mlp)
            x = _GRUGate(
                self.attention_dim, self.init_gru_gate_bias,
                name=f"gate_mlp_{layer}",
            )(x, nn.relu(mlp))

        y = x.reshape(B * T, self.attention_dim)
        logits = nn.Dense(
            self.num_outputs, name="logits",
            kernel_init=nn.initializers.variance_scaling(
                0.01, "fan_in", "truncated_normal"),
        )(y)
        value = nn.Dense(1, name="value")(y).squeeze(-1)
        return logits, value, tuple(new_state)
