"""Model catalog: space + config → model / action-distribution.

Counterpart of the reference's ``rllib/models/catalog.py:195`` (ModelCatalog:
``get_action_dist :212``, ``get_model_v2 :414``, ``get_preprocessor :768``).
Returns flax module instances plus a distribution *class*; policies
instantiate distributions from the model's ``dist_inputs`` output inside jit.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple, Type

import numpy as np

from ray_tpu.models.base import RTModel
from ray_tpu.models.cnn import VisionNet, get_filter_config
from ray_tpu.models.fcnet import FCNet
from ray_tpu.models.rnn import LSTMWrapper
from ray_tpu.models.attention import GTrXLNet
from ray_tpu.models import distributions as dists
from ray_tpu.models.preprocessors import (
    Preprocessor,
    get_preprocessor_for_space,
)

try:
    from gymnasium import spaces
except ImportError:  # pragma: no cover
    spaces = None

# Reference MODEL_DEFAULTS (rllib/models/catalog.py:52).
MODEL_DEFAULTS: Dict[str, Any] = {
    "fcnet_hiddens": [256, 256],
    "fcnet_activation": "tanh",
    "conv_filters": None,
    "conv_activation": "relu",
    "post_fcnet_hiddens": [],
    "post_fcnet_activation": "relu",
    "free_log_std": False,
    "vf_share_layers": False,
    "use_lstm": False,
    "max_seq_len": 20,
    "lstm_cell_size": 256,
    "lstm_use_prev_action": False,
    "lstm_use_prev_reward": False,
    "use_attention": False,
    "attention_num_transformer_units": 1,
    "attention_dim": 64,
    "attention_num_heads": 2,
    "attention_head_dim": 32,
    "attention_memory_inference": 50,
    "attention_memory_training": 50,
    "attention_position_wise_mlp_dim": 32,
    "attention_init_gru_gate_bias": 2.0,
    "custom_model": None,
    "custom_model_config": {},
    "custom_action_dist": None,
    "dtype": None,  # None → per-model default (bf16 convs, f32 mlps)
    # decoder-style transformer torso (models/transformer.py):
    # tensor-parallel over the mesh's "model" axis when
    # AlgorithmConfig.sharding(model_parallel=...) builds a 2-D mesh
    "use_transformer": False,
    "transformer_num_layers": 2,
    "transformer_dim": 64,
    "transformer_num_heads": 4,
    "transformer_head_dim": None,  # None → dim // num_heads
    "transformer_ff_dim": None,  # None → 4 * dim
    "transformer_seq_len": 8,
    # per-leaf placement override (ordered (pattern, spec) rules —
    # sharding.specs grammar); None → the model class's own rules
    "partition_rules": None,
}

_custom_models: Dict[str, Type[RTModel]] = {}
_custom_action_dists: Dict[str, type] = {}


class ModelCatalog:
    """Static registry, mirroring reference catalog.py:195."""

    @staticmethod
    def register_custom_model(name: str, model_cls: Type[RTModel]) -> None:
        _custom_models[name] = model_cls

    @staticmethod
    def register_custom_action_dist(name: str, dist_cls: type) -> None:
        _custom_action_dists[name] = dist_cls

    @staticmethod
    def get_preprocessor_for_space(obs_space) -> Preprocessor:
        return get_preprocessor_for_space(obs_space)

    @staticmethod
    def get_action_dist(
        action_space, config: Optional[Dict] = None, dist_type: Optional[str] = None
    ) -> Tuple[type, int]:
        """→ (dist_class, required model output size).
        Reference catalog.py:212."""
        config = {**MODEL_DEFAULTS, **(config or {})}
        if config.get("custom_action_dist"):
            cls = _custom_action_dists[config["custom_action_dist"]]
            return cls, cls.required_model_output_shape(action_space)
        if isinstance(action_space, spaces.Discrete):
            return dists.Categorical, int(action_space.n)
        if isinstance(action_space, spaces.Box):
            size = int(np.prod(action_space.shape))
            if dist_type == "squashed_gaussian":
                low = float(np.min(action_space.low))
                high = float(np.max(action_space.high))
                cls = functools.partial(
                    dists.SquashedGaussian, low=low, high=high
                )
                return cls, size * 2
            if dist_type == "deterministic":
                return dists.Deterministic, size
            return dists.DiagGaussian, size * 2
        if isinstance(action_space, spaces.MultiDiscrete):
            lens = tuple(int(n) for n in action_space.nvec)
            cls = functools.partial(dists.MultiCategorical, input_lens=lens)
            return cls, int(sum(lens))
        if isinstance(action_space, spaces.MultiBinary):
            return dists.Bernoulli, int(action_space.n)
        raise NotImplementedError(
            f"Unsupported action space: {action_space}"
        )

    @staticmethod
    def get_model(
        obs_space,
        action_space,
        num_outputs: int,
        model_config: Optional[Dict] = None,
    ) -> RTModel:
        """→ flax module instance. Reference get_model_v2 (catalog.py:414)."""
        cfg = {**MODEL_DEFAULTS, **(model_config or {})}

        if cfg.get("custom_model"):
            cm = cfg["custom_model"]
            cls = _custom_models[cm] if isinstance(cm, str) else cm
            return cls(num_outputs=num_outputs, **cfg["custom_model_config"])

        obs_shape = obs_space.shape
        is_image = len(obs_shape) == 3

        if cfg["use_transformer"]:
            from ray_tpu.models.transformer import TransformerPolicyNet

            cls = TransformerPolicyNet
            if cfg.get("partition_rules"):
                cls = cls.with_logical_rules(cfg["partition_rules"])
            return cls(
                num_outputs=num_outputs,
                d_model=cfg["transformer_dim"],
                num_layers=cfg["transformer_num_layers"],
                num_heads=cfg["transformer_num_heads"],
                head_dim=cfg["transformer_head_dim"],
                ff_dim=cfg["transformer_ff_dim"],
                seq_len=cfg["transformer_seq_len"],
                dtype_=cfg["dtype"] or "float32",
            )
        if cfg["use_lstm"]:
            return LSTMWrapper(
                num_outputs=num_outputs,
                cell_size=cfg["lstm_cell_size"],
                hiddens=tuple(cfg["fcnet_hiddens"]),
                activation=cfg["fcnet_activation"],
                use_prev_action=cfg["lstm_use_prev_action"],
                use_prev_reward=cfg["lstm_use_prev_reward"],
            )
        if cfg["use_attention"]:
            return GTrXLNet(
                num_outputs=num_outputs,
                attention_dim=cfg["attention_dim"],
                num_transformer_units=cfg["attention_num_transformer_units"],
                num_heads=cfg["attention_num_heads"],
                head_dim=cfg["attention_head_dim"],
                memory_len=cfg["attention_memory_training"],
                position_wise_mlp_dim=cfg["attention_position_wise_mlp_dim"],
                init_gru_gate_bias=cfg["attention_init_gru_gate_bias"],
            )
        if is_image:
            filters = cfg["conv_filters"] or get_filter_config(obs_shape)
            return VisionNet(
                num_outputs=num_outputs,
                conv_filters=tuple(
                    (int(c), tuple(k) if isinstance(k, (list, tuple)) else (k, k),
                     tuple(s) if isinstance(s, (list, tuple)) else (s, s))
                    for c, k, s in filters
                ),
                conv_activation=cfg["conv_activation"],
                post_fcnet_hiddens=tuple(cfg["post_fcnet_hiddens"] or [512]),
                post_fcnet_activation=cfg["post_fcnet_activation"],
                vf_share_layers=True,
                dtype_=cfg["dtype"] or "bfloat16",
            )
        return FCNet(
            num_outputs=num_outputs,
            hiddens=tuple(cfg["fcnet_hiddens"]),
            activation=cfg["fcnet_activation"],
            vf_share_layers=cfg["vf_share_layers"],
            free_log_std=cfg["free_log_std"],
            dtype_=cfg["dtype"] or "float32",
        )
