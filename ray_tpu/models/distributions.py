"""Action distributions as pure-JAX classes usable inside jit.

Counterpart of the reference's ``rllib/models/torch/torch_action_dist.py`` and
``rllib/models/jax/jax_action_dist.py`` (the 298-LoC stub the reference never
finished — this module supplies the real thing). Every method is traceable:
distributions are lightweight wrappers over their ``dist_inputs`` array, so a
whole (sample, logp, entropy, kl) bundle fuses into the surrounding jitted
policy function.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

SMALL_NUMBER = 1e-6
MIN_LOG_NN_OUTPUT = -20.0
MAX_LOG_NN_OUTPUT = 2.0


class ActionDistribution:
    """Base class (reference rllib/models/action_dist.py:14)."""

    def __init__(self, inputs: jnp.ndarray):
        self.inputs = inputs

    def sample(self, rng: jax.Array) -> jnp.ndarray:
        raise NotImplementedError

    def deterministic_sample(self) -> jnp.ndarray:
        raise NotImplementedError

    def logp(self, x: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def entropy(self) -> jnp.ndarray:
        raise NotImplementedError

    def kl(self, other: "ActionDistribution") -> jnp.ndarray:
        raise NotImplementedError

    def sampled_action_logp(self, rng: jax.Array):
        a = self.sample(rng)
        return a, self.logp(a)

    @staticmethod
    def required_model_output_shape(action_space) -> int:
        raise NotImplementedError


class Categorical(ActionDistribution):
    """Discrete actions from logits."""

    def sample(self, rng):
        return jax.random.categorical(rng, self.inputs, axis=-1)

    def deterministic_sample(self):
        return jnp.argmax(self.inputs, axis=-1)

    def logp(self, x):
        logits = jax.nn.log_softmax(self.inputs, axis=-1)
        return jnp.take_along_axis(
            logits, x[..., None].astype(jnp.int32), axis=-1
        ).squeeze(-1)

    def entropy(self):
        logp = jax.nn.log_softmax(self.inputs, axis=-1)
        p = jnp.exp(logp)
        return -jnp.sum(p * logp, axis=-1)

    def kl(self, other):
        logp = jax.nn.log_softmax(self.inputs, axis=-1)
        other_logp = jax.nn.log_softmax(other.inputs, axis=-1)
        p = jnp.exp(logp)
        return jnp.sum(p * (logp - other_logp), axis=-1)

    @staticmethod
    def required_model_output_shape(action_space):
        return int(action_space.n)


class MultiCategorical(ActionDistribution):
    """Vector of discrete actions (reference MultiCategorical)."""

    def __init__(self, inputs, input_lens: Tuple[int, ...]):
        super().__init__(inputs)
        self.input_lens = tuple(int(x) for x in input_lens)
        splits = jnp.cumsum(jnp.array(self.input_lens))[:-1]
        self.cats = [
            Categorical(x) for x in jnp.split(inputs, splits, axis=-1)
        ]

    def sample(self, rng):
        rngs = jax.random.split(rng, len(self.cats))
        return jnp.stack(
            [c.sample(r) for c, r in zip(self.cats, rngs)], axis=-1
        )

    def deterministic_sample(self):
        return jnp.stack([c.deterministic_sample() for c in self.cats], -1)

    def logp(self, x):
        return sum(
            c.logp(x[..., i]) for i, c in enumerate(self.cats)
        )

    def entropy(self):
        return sum(c.entropy() for c in self.cats)

    def kl(self, other):
        return sum(c.kl(o) for c, o in zip(self.cats, other.cats))


class DiagGaussian(ActionDistribution):
    """Independent normal per dim; inputs = concat(mean, log_std)."""

    def __init__(self, inputs):
        super().__init__(inputs)
        self.mean, self.log_std = jnp.split(inputs, 2, axis=-1)
        self.std = jnp.exp(self.log_std)

    def sample(self, rng):
        return self.mean + self.std * jax.random.normal(
            rng, self.mean.shape, dtype=self.mean.dtype
        )

    def deterministic_sample(self):
        return self.mean

    def logp(self, x):
        return (
            -0.5
            * jnp.sum(jnp.square((x - self.mean) / (self.std + SMALL_NUMBER)), -1)
            - 0.5 * jnp.log(2.0 * jnp.pi) * x.shape[-1]
            - jnp.sum(self.log_std, -1)
        )

    def entropy(self):
        return jnp.sum(
            self.log_std + 0.5 * jnp.log(2.0 * jnp.pi * jnp.e), -1
        )

    def kl(self, other):
        return jnp.sum(
            other.log_std
            - self.log_std
            + (jnp.square(self.std) + jnp.square(self.mean - other.mean))
            / (2.0 * jnp.square(other.std) + SMALL_NUMBER)
            - 0.5,
            -1,
        )

    @staticmethod
    def required_model_output_shape(action_space):
        return int(jnp.prod(jnp.array(action_space.shape))) * 2


class SquashedGaussian(ActionDistribution):
    """tanh-squashed gaussian, bounded to [low, high] (SAC;
    reference rllib/models/torch/torch_action_dist.py SquashedGaussian)."""

    def __init__(self, inputs, low: float = -1.0, high: float = 1.0):
        super().__init__(inputs)
        self.mean, log_std = jnp.split(inputs, 2, axis=-1)
        self.log_std = jnp.clip(
            log_std, MIN_LOG_NN_OUTPUT, MAX_LOG_NN_OUTPUT
        )
        self.std = jnp.exp(self.log_std)
        self.low = low
        self.high = high

    def _squash(self, raw):
        return (
            (jnp.tanh(raw) + 1.0) / 2.0 * (self.high - self.low) + self.low
        )

    def _unsquash(self, a):
        a01 = (a - self.low) / (self.high - self.low) * 2.0 - 1.0
        a01 = jnp.clip(a01, -1.0 + SMALL_NUMBER, 1.0 - SMALL_NUMBER)
        return jnp.arctanh(a01)

    def sample(self, rng):
        raw = self.mean + self.std * jax.random.normal(
            rng, self.mean.shape, dtype=self.mean.dtype
        )
        return self._squash(raw)

    def deterministic_sample(self):
        return self._squash(self.mean)

    def logp(self, x):
        raw = self._unsquash(x)
        base_logp = (
            -0.5 * jnp.sum(jnp.square((raw - self.mean) / (self.std + SMALL_NUMBER)), -1)
            - 0.5 * jnp.log(2.0 * jnp.pi) * raw.shape[-1]
            - jnp.sum(self.log_std, -1)
        )
        # log det of tanh + affine jacobian
        correction = jnp.sum(
            jnp.log(1.0 - jnp.square(jnp.tanh(raw)) + SMALL_NUMBER)
            + jnp.log((self.high - self.low) / 2.0),
            axis=-1,
        )
        return base_logp - correction

    def sampled_action_logp(self, rng):
        raw = self.mean + self.std * jax.random.normal(
            rng, self.mean.shape, dtype=self.mean.dtype
        )
        a = self._squash(raw)
        base_logp = (
            -0.5 * jnp.sum(jnp.square((raw - self.mean) / (self.std + SMALL_NUMBER)), -1)
            - 0.5 * jnp.log(2.0 * jnp.pi) * raw.shape[-1]
            - jnp.sum(self.log_std, -1)
        )
        correction = jnp.sum(
            jnp.log(1.0 - jnp.square(jnp.tanh(raw)) + SMALL_NUMBER)
            + jnp.log((self.high - self.low) / 2.0),
            axis=-1,
        )
        return a, base_logp - correction

    def entropy(self):
        # No closed form post-squash; return base gaussian entropy
        # (same convention as the reference torch SquashedGaussian).
        return jnp.sum(
            self.log_std + 0.5 * jnp.log(2.0 * jnp.pi * jnp.e), -1
        )

    @staticmethod
    def required_model_output_shape(action_space):
        return int(jnp.prod(jnp.array(action_space.shape))) * 2


class Deterministic(ActionDistribution):
    """Pass-through for DDPG/TD3-style deterministic policies."""

    def sample(self, rng):
        return self.inputs

    def deterministic_sample(self):
        return self.inputs

    def logp(self, x):
        return jnp.zeros(self.inputs.shape[:-1], self.inputs.dtype)

    def entropy(self):
        return jnp.zeros(self.inputs.shape[:-1], self.inputs.dtype)

    def kl(self, other):
        return jnp.zeros(self.inputs.shape[:-1], self.inputs.dtype)


class Bernoulli(ActionDistribution):
    """Independent bernoulli per dim from logits (MultiBinary spaces)."""

    def sample(self, rng):
        p = jax.nn.sigmoid(self.inputs)
        return (
            jax.random.uniform(rng, p.shape, dtype=p.dtype) < p
        ).astype(jnp.int32)

    def deterministic_sample(self):
        return (self.inputs > 0).astype(jnp.int32)

    def logp(self, x):
        x = x.astype(self.inputs.dtype)
        return -jnp.sum(
            jnp.maximum(self.inputs, 0)
            - self.inputs * x
            + jnp.log1p(jnp.exp(-jnp.abs(self.inputs))),
            axis=-1,
        )

    def entropy(self):
        p = jax.nn.sigmoid(self.inputs)
        logp = jax.nn.log_sigmoid(self.inputs)
        log1mp = jax.nn.log_sigmoid(-self.inputs)
        return -jnp.sum(p * logp + (1 - p) * log1mp, axis=-1)

    def kl(self, other):
        p = jax.nn.sigmoid(self.inputs)
        return jnp.sum(
            p * (jax.nn.log_sigmoid(self.inputs) - jax.nn.log_sigmoid(other.inputs))
            + (1 - p) * (jax.nn.log_sigmoid(-self.inputs) - jax.nn.log_sigmoid(-other.inputs)),
            axis=-1,
        )
