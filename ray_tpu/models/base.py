"""Model interface for ray_tpu policies.

Counterpart of the reference's ``rllib/models/modelv2.py`` (ModelV2), with one
deliberate TPU-first change: instead of ``forward()`` + a separately-called,
feature-caching ``value_function()`` (reference modelv2.py), every model's
``__call__`` returns ``(logits, value, state_out)`` in a single forward pass,
so policy and value share one fused XLA computation and no host-side caching
protocol is needed.

All models are ``flax.linen`` modules with signature::

    __call__(obs, state: Sequence[jnp.ndarray], seq_lens) ->
        (logits, value, state_out)

Non-recurrent models take/return an empty state tuple and ignore seq_lens.
Recurrent models receive ``obs`` shaped (B, T, ...) and states shaped
(B, ...).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModelOutput = Tuple[jnp.ndarray, jnp.ndarray, Sequence[jnp.ndarray]]


class RTModel(nn.Module):
    """Marker base class; see module docstring for the contract."""

    # Class-level override installed by :meth:`with_logical_rules`
    # (None = ask :meth:`partition_rules`).
    _partition_rules_override = None

    def initial_state(self, batch_size: int = 1) -> Sequence[jnp.ndarray]:
        """Initial recurrent state arrays, leading dim = batch_size."""
        return ()

    def partition_rules(self):
        """Ordered ``(pattern, PartitionSpec)`` rules mapping this
        model's param-leaf paths onto the mesh's ``"model"`` axis
        (``sharding.specs.param_pspecs`` grammar). None — the default
        for every built-in except the transformer torso — keeps params
        replicated; a 2-D mesh then simply carries a size-M model axis
        nothing splits on."""
        if self._partition_rules_override is not None:
            return tuple(self._partition_rules_override)
        return None

    @classmethod
    def with_logical_rules(cls, rules):
        """Escape hatch: a subclass of this model class with the given
        partition rules baked in (``model_config["custom_model"] =
        MyNet.with_logical_rules([...])``) — for models whose default
        rules (or lack of them) don't fit the deployment."""
        return type(
            cls.__name__ + "WithRules",
            (cls,),
            {"_partition_rules_override": tuple(rules)},
        )

    @property
    def is_recurrent(self) -> bool:
        return False

    @property
    def supports_stored_train_state(self) -> bool:
        """Whether the learn-path (B, T) unroll can be fed the
        sampler's stored chunk-start states (exactly reproducing the
        rollout-time forward for mid-episode chunks). Carry-style
        models (LSTM) support this: the per-step ``resets`` mask zeroes
        the carry at genuine episode boundaries, so a stored state is
        correct wherever the chunk continues a trajectory. Models whose
        state the resets mask cannot re-zero per segment (GTrXL's
        attention memory) return False and train with zero initial
        state — a documented approximation (see models/attention.py)."""
        return False


def get_activation(name: str):
    if name in (None, "linear"):
        return lambda x: x
    return getattr(nn, name if name != "swish" else "silu")
