"""serve-lite: model serving over actor replica groups.

Counterpart of the reference's Serve core path — ``@serve.deployment``
(``serve/deployment.py:34``), controller-managed replica actors
(``serve/replica.py:218`` handle_request), round-robin routing, the
HTTP proxy (``serve/http_proxy.py:190``), queue-depth autoscaling
(``serve/autoscaling_policy.py`` BasicAutoscalingPolicy), and long-poll
config push (``serve/long_poll.py``) — scoped to one host: a
deployment is a group of replica actors behind a DeploymentHandle.

Autoscaling: each deployment may carry an ``autoscaling_config``
(min_replicas / max_replicas / target_num_ongoing_requests_per_replica
/ upscale_delay_s / downscale_delay_s); a controller thread samples the
handle's in-flight request count and adds/removes replica actors. The
new membership is pushed to handles via the long-poll host — requests
spread onto new replicas without the caller doing anything.

Config push: ``update_deployment(name, user_config=...)`` calls
``reconfigure(user_config)`` on every LIVE replica (no restart — the
reference's Deployment.user_config contract) and publishes the change.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.serve.long_poll import LongPollHost

_DEPLOYMENTS: Dict[str, "RunningDeployment"] = {}
_HTTP_SERVER = None
_LONG_POLL = LongPollHost()

DEFAULT_AUTOSCALING = {
    "min_replicas": 1,
    "max_replicas": 4,
    "target_num_ongoing_requests_per_replica": 2.0,
    "upscale_delay_s": 0.5,
    "downscale_delay_s": 2.0,
    "interval_s": 0.25,
    # queue-WAIT targeting (docs/serving.md): when set, replica stats
    # (the policy server's queue_wait_p50_s) join the inflight signal —
    # scale up when requests wait longer than this before a forward
    # starts, allow scale-down only once waits fall well under it
    "target_queue_wait_s": None,
    # probe replica stats every N seconds even without a queue-wait
    # target: dead/stopped replicas are removed from the published
    # membership and replaced (None = probe only when queue-wait
    # targeting already polls stats)
    "health_check_interval_s": None,
    "stats_timeout_s": 2.0,
    # signal source (docs/serving.md "ledger-driven autoscaling"):
    # "queue_wait" — inflight + queue-wait targeting (the default);
    # "ledger" — device-ledger targeting off replica stats: mean
    # batch-fill fraction (buckets running full = saturated compute)
    # with an HBM-headroom gate on scale-up; "both" — scale up when
    # EITHER side runs hot, down only when BOTH have cooled
    "signal": "queue_wait",
    # ledger targeting: mean fill above this = the fused buckets are
    # full and more replicas would cut real queueing; fill under half
    # of it = forwards are mostly padding, replicas can go
    "target_batch_fill": 0.85,
    # scale-up gate: never add a replica when the device reports less
    # than this fraction of HBM free — a replica that cannot fit its
    # params + activations only thrashes the allocator
    "min_hbm_headroom": 0.1,
}


@ray.remote
class _Replica:
    """Hosts one instance of the deployment class (reference
    replica.py:218)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs, user_config=None):
        if isinstance(cls_or_fn, type):
            self._obj = cls_or_fn(*init_args, **(init_kwargs or {}))
        elif init_args or init_kwargs:
            # function deployment: bind args become leading call args
            import functools

            self._obj = functools.partial(
                cls_or_fn, *init_args, **(init_kwargs or {})
            )
        else:
            self._obj = cls_or_fn
        self.num_requests = 0
        self.num_reconfigures = 0
        if user_config is not None:
            self.reconfigure(user_config)

    def handle(self, args, kwargs):
        self.num_requests += 1
        return self._obj(*args, **kwargs)

    def call_method(self, method, args, kwargs):
        self.num_requests += 1
        return getattr(self._obj, method)(*args, **kwargs)

    def reconfigure(self, user_config):
        """In-place config update, NO restart (reference
        replica.py reconfigure / user_config contract)."""
        self.num_reconfigures += 1
        if hasattr(self._obj, "reconfigure"):
            self._obj.reconfigure(user_config)

    def stats(self):
        """Replica stats, merged with the wrapped object's own
        ``stats()`` when it has one — a policy server contributes its
        queue/latency fields here, which is how the controller's
        queue-wait autoscaler sees them (docs/serving.md)."""
        out = {
            "num_requests": self.num_requests,
            "num_reconfigures": self.num_reconfigures,
        }
        obj_stats = getattr(self._obj, "stats", None)
        if callable(obj_stats):
            try:
                out.update(obj_stats() or {})
            except Exception:
                pass
        return out


class DeploymentHandle:
    """Routing client to a replica group (reference serve/handle.py):
    round-robin over the CURRENT membership, which a long-poll listener
    keeps fresh as the autoscaler adds/removes replicas. Replicas a
    completed call exposed as DEAD (actor-death errors) leave the
    rotation immediately — no more round-robining into a corpse while
    waiting for the controller to replace it."""

    def __init__(self, name: str, replicas: List):
        self.name = name
        self._replicas = list(replicas)
        self._rr = 0
        self._lock = threading.Lock()
        self._inflight = 0
        self._dead: set = set()
        # start at the key's CURRENT version: a redeploy must not
        # adopt the previous generation's (killed) membership still
        # sitting on the shared long-poll key
        self._version = _LONG_POLL.current(f"replicas:{name}")[0]
        self._stop = threading.Event()
        self._listener = threading.Thread(
            target=self._listen_loop, daemon=True,
            name=f"serve_listen_{name}",
        )
        self._listener.start()

    def _listen_loop(self):
        while not self._stop.is_set():
            out = _LONG_POLL.listen(
                f"replicas:{self.name}", self._version, timeout=1.0
            )
            if out is None:
                continue
            version, replicas = out
            with self._lock:
                self._version = version
                self._replicas = list(replicas)
                # a republished membership supersedes local dead
                # marks: removed corpses drop off, and a REUSED slot
                # (the controller only ever publishes live actors)
                # must not inherit a stale mark
                live = {self._rid(r) for r in self._replicas}
                self._dead &= live

    @staticmethod
    def _rid(replica):
        # ActorHandle identity; plain ``getattr`` with a non-underscore
        # name would synthesize an ActorMethod instead of failing
        return replica.__dict__.get("_actor_id") or id(replica)

    def mark_dead(self, replica) -> None:
        """Take a replica out of this handle's rotation (observed
        actor-death). The controller's health pass replaces it; the
        long-poll republish clears the mark."""
        with self._lock:
            self._dead.add(self._rid(replica))

    def num_dead(self) -> int:
        with self._lock:
            return len(self._dead)

    def _next(self):
        with self._lock:
            n = len(self._replicas)
            for _ in range(n):
                r = self._replicas[self._rr % n]
                self._rr += 1
                if self._rid(r) not in self._dead:
                    return r
            # every member is marked dead: fall through to plain RR
            # so the caller fails fast on the death error instead of
            # hanging on an empty rotation
            r = self._replicas[self._rr % n]
            self._rr += 1
            return r

    def _track(self, ref, replica=None):
        with self._lock:
            self._inflight += 1

        def done():
            with self._lock:
                self._inflight -= 1
            if replica is not None:
                err = ref._store.peek_error(ref.id)
                if isinstance(
                    err,
                    (
                        ray.core.object_store.RayActorError,
                        ray.core.object_store.WorkerCrashedError,
                    ),
                ):
                    self.mark_dead(replica)

        ref._store.on_ready(ref.id, done)
        return ref

    def num_inflight(self) -> int:
        with self._lock:
            return self._inflight

    def num_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)

    def remote(self, *args, **kwargs):
        r = self._next()
        return self._track(
            r.handle.remote(list(args), kwargs), r
        )

    def method(self, name: str):
        handle = self

        class _M:
            def remote(self, *args, **kwargs):
                r = handle._next()
                return handle._track(
                    r.call_method.remote(name, list(args), kwargs),
                    r,
                )

        return _M()

    def stop(self):
        self._stop.set()


def _spawn_replica_actor(spec: "Deployment", user_config):
    """Spawn one replica actor. Replicas are born knowing the ingress
    address so code INSIDE them can compose onto other deployments
    via get_deployment_handle() (the reference's model-composition
    DeploymentHandles, serve/handle.py — here routed over the HTTP
    ingress, since replica processes hold no actor handles)."""
    opts = {}
    if _HTTP_SERVER is not None:
        host, port = _HTTP_SERVER.server_address[:2]
        opts["runtime_env"] = {
            "env_vars": {"RAY_TPU_SERVE_HTTP": f"http://{host}:{port}"}
        }
    return _Replica.options(**opts).remote(
        spec._cls_or_fn,
        spec._init_args,
        spec._init_kwargs,
        user_config,
    )


class RunningDeployment:
    """Controller state for one deployment: replica membership, config
    version, and the autoscale loop (the ServeController role,
    reference serve/controller.py:55 + autoscaling_policy.py)."""

    def __init__(self, spec: "Deployment", replicas: List, handle):
        self.spec = spec
        self.name = spec.name
        self.replicas = list(replicas)
        # guards membership against the scaler thread racing
        # update_deployment / shutdown callers
        self._members_lock = threading.Lock()
        self.handle = handle
        self.user_config = spec.user_config
        self._stop = threading.Event()
        self._last_scale = time.monotonic()
        self._last_health = time.monotonic()
        self.num_replaced = 0
        self._scaler = None
        # publish the initial membership so handles listening from an
        # older generation's version converge onto THIS generation
        self._publish()
        if spec.autoscaling_config:
            cfg = {**DEFAULT_AUTOSCALING, **spec.autoscaling_config}
            # scale-to-zero is out of scope (an empty group would
            # deadlock routing: no request can complete to raise the
            # inflight signal) — the reference queues at the proxy
            cfg["min_replicas"] = max(1, cfg["min_replicas"])
            self.autoscaling = cfg
            self._scaler = threading.Thread(
                target=self._autoscale_loop, daemon=True,
                name=f"serve_scaler_{self.name}",
            )
            self._scaler.start()
        else:
            self.autoscaling = None

    def _spawn_replica(self):
        return _spawn_replica_actor(self.spec, self.user_config)

    def _publish(self):
        with self._members_lock:
            members = list(self.replicas)
        _LONG_POLL.notify(f"replicas:{self.name}", members)

    def _retire(self, victim) -> None:
        """Drain-then-kill. Membership was already republished; the
        grace sleep lets handle listener threads adopt it (the
        long-poll push is asynchronous), then the actor's ordered call
        queue means a completed stats() proves every earlier request
        finished. A handle that somehow routes to the victim after the
        drain probe still fails fast (killed actors put_error their
        pending refs) rather than hanging."""
        time.sleep(0.25)
        try:
            ray.get(victim.stats.remote(), timeout=30.0)
        except Exception:
            pass
        try:
            ray.kill(victim)
        except Exception:
            pass

    def replica_stats(
        self, timeout: Optional[float] = None
    ) -> List:
        """``[(replica, stats-dict | None | "dead"), ...]`` across the
        current membership: merged ``_Replica.stats`` (incl. any
        wrapped policy-server queue/latency fields), ``None`` for a
        replica that missed the timeout (busy, not dead), ``"dead"``
        on an actor-death error."""
        if timeout is None:
            timeout = (self.autoscaling or DEFAULT_AUTOSCALING)[
                "stats_timeout_s"
            ]
        with self._members_lock:
            members = list(self.replicas)
        refs = [(r, r.stats.remote()) for r in members]
        out = []
        for r, ref in refs:
            try:
                out.append((r, ray.get(ref, timeout=timeout)))
            except (
                ray.core.object_store.RayActorError,
                ray.core.object_store.WorkerCrashedError,
            ):
                out.append((r, "dead"))
            except Exception:
                out.append((r, None))
        return out

    def stats(self) -> Dict:
        """Aggregated deployment stats for dashboards/tests: replica
        count, inflight, and the queue/latency aggregate the
        autoscaler keys off."""
        pairs = self.replica_stats()
        replica_dicts = [s for _, s in pairs if isinstance(s, dict)]
        waits = [
            s["queue_wait_p50_s"]
            for s in replica_dicts
            if s.get("queue_wait_p50_s") is not None
        ]
        return {
            "name": self.name,
            "num_replicas": len(pairs),
            "num_replaced": self.num_replaced,
            "inflight": self.handle.num_inflight(),
            "queue_depth_total": sum(
                s.get("queue_depth", 0) or 0 for s in replica_dicts
            ),
            "queue_wait_p50_s_max": max(waits) if waits else None,
            "replicas": replica_dicts,
        }

    def _replace_dead(self, dead: List) -> None:
        """Swap confirmed-dead replicas for fresh ones at constant
        size; the republished membership also clears handle-side dead
        marks for the removed corpses."""
        if not dead:
            return
        replacements = [self._spawn_replica() for _ in dead]
        with self._members_lock:
            if self._stop.is_set():
                for r in replacements:
                    try:
                        ray.kill(r)
                    except Exception:
                        pass
                return
            dead_ids = {id(r) for r in dead}
            self.replicas = [
                r for r in self.replicas if id(r) not in dead_ids
            ] + replacements
        self.num_replaced += len(dead)
        self._publish()
        for r in dead:
            try:
                ray.kill(r)  # make sure a wedged corpse stays dead
            except Exception:
                pass

    def _autoscale_loop(self):
        cfg = self.autoscaling
        while not self._stop.wait(cfg["interval_s"]):
            ongoing = self.handle.num_inflight()
            with self._members_lock:
                n = len(self.replicas)
            per = ongoing / max(1, n)
            target = cfg["target_num_ongoing_requests_per_replica"]
            now = time.monotonic()
            # -- signal source selection (ledger autoscaling) --------
            source = cfg.get("signal") or "queue_wait"
            use_queue = source in ("queue_wait", "both")
            use_ledger = source in ("ledger", "both")
            # -- replica stats pass (queue-wait / ledger / health) ---
            wait_target = cfg.get("target_queue_wait_s")
            health_every = cfg.get("health_check_interval_s")
            wait_signal = None
            fill_signal = None
            hbm_headroom = None
            need_stats = (
                wait_target is not None
                or use_ledger
                or (
                    health_every is not None
                    and now - self._last_health >= health_every
                )
            )
            if need_stats:
                self._last_health = now
                pairs = self.replica_stats(
                    timeout=cfg["stats_timeout_s"]
                )
                self._replace_dead(
                    [r for r, s in pairs if s == "dead"]
                )
                dicts = [
                    s for _, s in pairs if isinstance(s, dict)
                ]
                waits = [
                    s["queue_wait_p50_s"]
                    for s in dicts
                    if s.get("queue_wait_p50_s") is not None
                ]
                if waits:
                    wait_signal = max(waits)
                # the device-ledger side of the same stats payload:
                # bucket occupancy + HBM headroom, reported by the
                # policy server (policy_server.stats()["device"])
                fills = [
                    s["batch_fill_fraction"]
                    for s in dicts
                    if s.get("batch_fill_fraction") is not None
                    and s.get("batches_total")
                ]
                if fills:
                    fill_signal = sum(fills) / len(fills)
                rooms = [
                    s["device"]["hbm_headroom"]
                    for s in dicts
                    if isinstance(s.get("device"), dict)
                    and s["device"].get("hbm_headroom") is not None
                ]
                if rooms:
                    hbm_headroom = min(rooms)
                with self._members_lock:
                    n = len(self.replicas)
            wait_hot = (
                wait_target is not None
                and wait_signal is not None
                and wait_signal > wait_target
            )
            # scale-down must not race a hot queue: with a wait
            # target set, waits have to be WELL under it (or unknown)
            wait_cool = wait_target is None or (
                wait_signal is None
                or wait_signal < 0.25 * wait_target
            )
            queue_hot = use_queue and (per > target or wait_hot)
            queue_cool = not use_queue or (
                per < 0.5 * target and wait_cool
            )
            fill_target = cfg.get("target_batch_fill") or 0.85
            ledger_hot = (
                use_ledger
                and fill_signal is not None
                and fill_signal > fill_target
            )
            ledger_cool = not use_ledger or (
                fill_signal is None
                or fill_signal < 0.5 * fill_target
            )
            # scale-up is gated on device headroom regardless of what
            # ran hot: no room for another replica's params means an
            # upscale only trades queueing for allocator thrash
            min_room = cfg.get("min_hbm_headroom")
            hbm_blocked = (
                use_ledger
                and min_room is not None
                and hbm_headroom is not None
                and hbm_headroom < min_room
            )
            if (
                (queue_hot or ledger_hot)
                and not hbm_blocked
                and n < cfg["max_replicas"]
                and now - self._last_scale >= cfg["upscale_delay_s"]
            ):
                replica = self._spawn_replica()
                with self._members_lock:
                    if self._stop.is_set():  # racing shutdown
                        try:
                            ray.kill(replica)
                        except Exception:
                            pass
                        return
                    self.replicas.append(replica)
                self._last_scale = now
                self._publish()
            elif (
                queue_cool
                and ledger_cool
                and n > cfg["min_replicas"]
                and now - self._last_scale >= cfg["downscale_delay_s"]
            ):
                with self._members_lock:
                    if len(self.replicas) <= cfg["min_replicas"]:
                        continue
                    victim = self.replicas.pop()
                self._last_scale = now
                self._publish()
                self._retire(victim)

    def reconfigure(self, user_config) -> None:
        """Push a new user_config to every live replica, no restart."""
        self.user_config = user_config
        with self._members_lock:
            members = list(self.replicas)
        for r in members:
            try:
                ray.get(r.reconfigure.remote(user_config))
            except Exception:
                # racing a concurrent downscale: the victim is gone,
                # and gone replicas don't need the new config
                pass
        self._publish()

    def set_num_replicas(self, n: int) -> None:
        n = max(1, n)
        victims = []
        with self._members_lock:
            while len(self.replicas) < n:
                self.replicas.append(self._spawn_replica())
            while len(self.replicas) > n:
                victims.append(self.replicas.pop())
        self._publish()
        for victim in victims:
            self._retire(victim)

    def stop(self):
        self._stop.set()
        self.handle.stop()
        with self._members_lock:
            members = list(self.replicas)
            self.replicas = []
        for r in members:
            try:
                ray.kill(r)
            except Exception:
                pass


class Deployment:
    """Bound-but-not-running deployment (reference deployment.py:34)."""

    def __init__(
        self,
        cls_or_fn,
        name: str,
        num_replicas: int = 1,
        init_args=(),
        init_kwargs=None,
        autoscaling_config: Optional[Dict] = None,
        user_config: Optional[Any] = None,
    ):
        self._cls_or_fn = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self._init_args = tuple(init_args)
        self._init_kwargs = dict(init_kwargs or {})
        self.autoscaling_config = autoscaling_config
        self.user_config = user_config

    def bind(self, *args, **kwargs) -> "Deployment":
        return Deployment(
            self._cls_or_fn,
            self.name,
            self.num_replicas,
            args,
            kwargs,
            self.autoscaling_config,
            self.user_config,
        )

    def options(
        self,
        num_replicas: Optional[int] = None,
        name: Optional[str] = None,
        autoscaling_config: Optional[Dict] = None,
        user_config: Optional[Any] = None,
    ) -> "Deployment":
        return Deployment(
            self._cls_or_fn,
            name or self.name,
            num_replicas or self.num_replicas,
            self._init_args,
            self._init_kwargs,
            (
                autoscaling_config
                if autoscaling_config is not None
                else self.autoscaling_config
            ),
            (
                user_config
                if user_config is not None
                else self.user_config
            ),
        )

    def deploy(self) -> DeploymentHandle:
        ray.init(ignore_reinit_error=True)
        n = self.num_replicas
        if self.autoscaling_config:
            n = max(
                self.autoscaling_config.get("min_replicas", 1), 1
            )
        replicas = [
            _spawn_replica_actor(self, self.user_config)
            for _ in range(n)
        ]
        old = _DEPLOYMENTS.pop(self.name, None)
        if old is not None:
            # redeploy: retire the previous generation first, or its
            # scaler thread keeps publishing stale membership onto the
            # shared long-poll key and its replicas leak
            old.stop()
        handle = DeploymentHandle(self.name, replicas)
        _DEPLOYMENTS[self.name] = RunningDeployment(
            self, replicas, handle
        )
        return handle


def deployment(
    _cls=None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    autoscaling_config: Optional[Dict] = None,
    user_config: Optional[Any] = None,
):
    """reference @serve.deployment decorator."""

    def wrap(cls):
        return Deployment(
            cls,
            name or cls.__name__,
            num_replicas,
            autoscaling_config=autoscaling_config,
            user_config=user_config,
        )

    if _cls is not None:
        return wrap(_cls)
    return wrap


def run(
    target: Deployment,
    *,
    http_host: Optional[str] = None,
    http_port: int = 0,
) -> DeploymentHandle:
    """Deploy + optionally start the HTTP ingress (reference
    serve.run + http_proxy.py). The ingress starts FIRST so the
    deployment's replicas are born knowing its address — composition
    handles inside replicas route through it."""
    if http_host is not None:
        _start_http(http_host, http_port)
    handle = target.deploy()
    return handle


class DeploymentResponse:
    """Future-shaped result of an HTTP-routed handle call (the
    reference's ``DeploymentResponse``): ``.result(timeout)`` blocks
    for the value."""

    def __init__(self, fetch):
        self._done = threading.Event()
        self._value = None
        self._error = None

        def _run():
            try:
                self._value = fetch()
            except BaseException as e:
                self._error = e
            finally:
                self._done.set()

        threading.Thread(target=_run, daemon=True).start()

    def result(self, timeout: Optional[float] = 60.0):
        if not self._done.wait(timeout):
            raise TimeoutError("deployment call did not complete")
        if self._error is not None:
            raise self._error
        return self._value


class HTTPDeploymentHandle:
    """Handle usable from INSIDE a replica (or any process that can
    reach the ingress): calls route over HTTP, so composition works
    without actor handles. Payloads and results are JSON."""

    def __init__(self, name: str, base_url: str):
        self.name = name
        self.url = f"{base_url.rstrip('/')}/{name}"

    def remote(self, payload=None) -> DeploymentResponse:
        import urllib.request

        def fetch():
            req = urllib.request.Request(
                self.url,
                data=json.dumps(payload or {}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=60.0) as resp:
                out = json.loads(resp.read())
            if "error" in out:
                raise RuntimeError(out["error"])
            return out["result"]

        return DeploymentResponse(fetch)


def get_deployment_handle(name: str):
    """Composition-safe handle lookup (reference
    ``serve.get_deployment_handle``): on the driver this is the
    actor-routing DeploymentHandle; inside a replica it is an
    HTTP-routing handle whose ``.remote()`` returns a
    DeploymentResponse (use ``.result()``)."""
    dep = _DEPLOYMENTS.get(name)
    if dep is not None:
        return dep.handle
    import os

    url = os.environ.get("RAY_TPU_SERVE_HTTP")
    if url:
        return HTTPDeploymentHandle(name, url)
    raise ValueError(
        f"no deployment {name!r} here and no ingress address "
        "(RAY_TPU_SERVE_HTTP) — was the HTTP server started before "
        "this replica spawned?"
    )


def get_deployment(name: str) -> DeploymentHandle:
    return _DEPLOYMENTS[name].handle


def get_running(name: str) -> Optional[RunningDeployment]:
    """Controller-state lookup for the ingress front door: the
    RunningDeployment owns replica membership and the autoscale loop;
    the ingress resolves policies against it (ingress/http.py)."""
    return _DEPLOYMENTS.get(name)


def membership_feed(name: str):
    """The replica-membership feed for ``name`` — the SAME long-poll
    key the controller publishes on and handles listen to, wrapped as
    a poll surface (``resilience.discovery.MembershipFeed``) for the
    ingress coalescing router."""
    from ray_tpu.resilience.discovery import MembershipFeed

    return MembershipFeed(_LONG_POLL, f"replicas:{name}")


def update_deployment(
    name: str,
    *,
    user_config: Optional[Any] = None,
    num_replicas: Optional[int] = None,
) -> None:
    """Live config update (reference controller deploy-on-update +
    long-poll broadcast): user_config reconfigures replicas in place,
    num_replicas rescales the group; both propagate to handles without
    a restart."""
    dep = _DEPLOYMENTS[name]
    if user_config is not None:
        dep.reconfigure(user_config)
    if num_replicas is not None:
        dep.set_num_replicas(num_replicas)


def autoscale(
    name: str,
    *,
    signal: Optional[str] = None,
    **overrides: Any,
) -> Dict[str, Any]:
    """Retune a RUNNING deployment's autoscaler in place — switch the
    signal source (``"queue_wait"`` / ``"ledger"`` / ``"both"``) or
    override any ``DEFAULT_AUTOSCALING`` knob (``target_batch_fill``,
    ``min_hbm_headroom``, ``target_queue_wait_s``, delays, bounds…)
    without restarting replicas: the loop reads its config dict every
    interval, so the next tick acts on the new targets. Returns the
    deployment's effective autoscaling config."""
    dep = _DEPLOYMENTS[name]
    if dep.autoscaling is None:
        raise ValueError(
            f"deployment {name!r} runs without an autoscaler; "
            "deploy with autoscaling_config= to enable one"
        )
    if signal is not None:
        if signal not in ("queue_wait", "ledger", "both"):
            raise ValueError(
                "signal must be 'queue_wait', 'ledger' or 'both', "
                f"got {signal!r}"
            )
        overrides["signal"] = signal
    unknown = set(overrides) - set(DEFAULT_AUTOSCALING)
    if unknown:
        raise ValueError(
            f"unknown autoscaling keys: {sorted(unknown)}"
        )
    dep.autoscaling.update(overrides)
    return dict(dep.autoscaling)


def _start_http(host: str, port: int):
    global _HTTP_SERVER
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if _HTTP_SERVER is not None:
        bound_host, bound_port = _HTTP_SERVER.server_address[:2]
        if (host, port) not in (
            (bound_host, bound_port),
            (bound_host, 0),
        ):
            raise RuntimeError(
                f"HTTP ingress already bound to {bound_host}:"
                f"{bound_port}; serve.shutdown() before rebinding to "
                f"{host}:{port}"
            )
        return _HTTP_SERVER

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def do_POST(self):
            name = self.path.strip("/")
            dep = _DEPLOYMENTS.get(name)
            if dep is None:
                self.send_response(404)
                self.end_headers()
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = (
                    json.loads(self.rfile.read(length))
                    if length
                    else {}
                )
                out = ray.get(dep.handle.remote(payload))
                blob = json.dumps({"result": out}).encode()
                self.send_response(200)
            except Exception as e:
                blob = json.dumps({"error": repr(e)}).encode()
                self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

    _HTTP_SERVER = ThreadingHTTPServer((host, port), Handler)
    _HTTP_SERVER.daemon_threads = True
    threading.Thread(
        target=_HTTP_SERVER.serve_forever, daemon=True
    ).start()
    return _HTTP_SERVER


def http_port() -> Optional[int]:
    return (
        _HTTP_SERVER.server_address[1] if _HTTP_SERVER else None
    )


def shutdown() -> None:
    global _HTTP_SERVER
    for dep in _DEPLOYMENTS.values():
        dep.stop()
    _DEPLOYMENTS.clear()
    if _HTTP_SERVER is not None:
        _HTTP_SERVER.shutdown()
        _HTTP_SERVER.server_close()
        _HTTP_SERVER = None
