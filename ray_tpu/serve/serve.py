"""serve-lite: model serving over actor replica groups.

Counterpart of the reference's Serve core path — ``@serve.deployment``
(``serve/deployment.py:34``), controller-managed replica actors
(``serve/replica.py:218`` handle_request), round-robin routing, and the
HTTP proxy (``serve/http_proxy.py:190``) — scoped to one host: a
deployment is a group of replica actors behind a round-robin
DeploymentHandle, optionally exposed over a stdlib HTTP ingress that
POSTs JSON to the deployment's __call__."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray

_DEPLOYMENTS: Dict[str, "RunningDeployment"] = {}
_HTTP_SERVER = None


@ray.remote
class _Replica:
    """Hosts one instance of the deployment class (reference
    replica.py:218)."""

    def __init__(self, cls_or_fn, init_args, init_kwargs):
        if isinstance(cls_or_fn, type):
            self._obj = cls_or_fn(*init_args, **(init_kwargs or {}))
        elif init_args or init_kwargs:
            # function deployment: bind args become leading call args
            import functools

            self._obj = functools.partial(
                cls_or_fn, *init_args, **(init_kwargs or {})
            )
        else:
            self._obj = cls_or_fn
        self.num_requests = 0

    def handle(self, args, kwargs):
        self.num_requests += 1
        return self._obj(*args, **kwargs)

    def call_method(self, method, args, kwargs):
        self.num_requests += 1
        return getattr(self._obj, method)(*args, **kwargs)

    def stats(self):
        return {"num_requests": self.num_requests}


class DeploymentHandle:
    """Round-robin client to a replica group (reference
    serve/handle.py)."""

    def __init__(self, name: str, replicas: List):
        self.name = name
        self._replicas = replicas
        self._rr = 0
        self._lock = threading.Lock()

    def _next(self):
        with self._lock:
            r = self._replicas[self._rr % len(self._replicas)]
            self._rr += 1
        return r

    def remote(self, *args, **kwargs):
        return self._next().handle.remote(list(args), kwargs)

    def method(self, name: str):
        handle = self

        class _M:
            def remote(self, *args, **kwargs):
                return handle._next().call_method.remote(
                    name, list(args), kwargs
                )

        return _M()


class RunningDeployment:
    def __init__(self, name, replicas, handle):
        self.name = name
        self.replicas = replicas
        self.handle = handle


class Deployment:
    """Bound-but-not-running deployment (reference deployment.py:34)."""

    def __init__(
        self,
        cls_or_fn,
        name: str,
        num_replicas: int = 1,
        init_args=(),
        init_kwargs=None,
    ):
        self._cls_or_fn = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self._init_args = tuple(init_args)
        self._init_kwargs = dict(init_kwargs or {})

    def bind(self, *args, **kwargs) -> "Deployment":
        return Deployment(
            self._cls_or_fn,
            self.name,
            self.num_replicas,
            args,
            kwargs,
        )

    def options(
        self,
        num_replicas: Optional[int] = None,
        name: Optional[str] = None,
    ) -> "Deployment":
        return Deployment(
            self._cls_or_fn,
            name or self.name,
            num_replicas or self.num_replicas,
            self._init_args,
            self._init_kwargs,
        )

    def deploy(self) -> DeploymentHandle:
        ray.init(ignore_reinit_error=True)
        replicas = [
            _Replica.remote(
                self._cls_or_fn, self._init_args, self._init_kwargs
            )
            for _ in range(self.num_replicas)
        ]
        handle = DeploymentHandle(self.name, replicas)
        _DEPLOYMENTS[self.name] = RunningDeployment(
            self.name, replicas, handle
        )
        return handle


def deployment(
    _cls=None, *, name: Optional[str] = None, num_replicas: int = 1
):
    """reference @serve.deployment decorator."""

    def wrap(cls):
        return Deployment(cls, name or cls.__name__, num_replicas)

    if _cls is not None:
        return wrap(_cls)
    return wrap


def run(
    target: Deployment,
    *,
    http_host: Optional[str] = None,
    http_port: int = 0,
) -> DeploymentHandle:
    """Deploy + optionally start the HTTP ingress (reference
    serve.run + http_proxy.py)."""
    handle = target.deploy()
    if http_host is not None:
        _start_http(http_host, http_port)
    return handle


def get_deployment(name: str) -> DeploymentHandle:
    return _DEPLOYMENTS[name].handle


def _start_http(host: str, port: int):
    global _HTTP_SERVER
    if _HTTP_SERVER is not None:
        bound_host, bound_port = _HTTP_SERVER.server_address[:2]
        if (host, port) not in (
            (bound_host, bound_port),
            (bound_host, 0),
        ):
            raise RuntimeError(
                f"HTTP ingress already bound to {bound_host}:"
                f"{bound_port}; serve.shutdown() before rebinding to "
                f"{host}:{port}"
            )
        return _HTTP_SERVER

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def do_POST(self):
            name = self.path.strip("/")
            dep = _DEPLOYMENTS.get(name)
            if dep is None:
                self.send_response(404)
                self.end_headers()
                return
            length = int(self.headers.get("Content-Length", 0))
            try:
                payload = (
                    json.loads(self.rfile.read(length))
                    if length
                    else {}
                )
                out = ray.get(dep.handle.remote(payload))
                blob = json.dumps({"result": out}).encode()
                self.send_response(200)
            except Exception as e:
                blob = json.dumps({"error": repr(e)}).encode()
                self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

    _HTTP_SERVER = ThreadingHTTPServer((host, port), Handler)
    _HTTP_SERVER.daemon_threads = True
    threading.Thread(
        target=_HTTP_SERVER.serve_forever, daemon=True
    ).start()
    return _HTTP_SERVER


def http_port() -> Optional[int]:
    return (
        _HTTP_SERVER.server_address[1] if _HTTP_SERVER else None
    )


def shutdown() -> None:
    global _HTTP_SERVER
    for dep in _DEPLOYMENTS.values():
        for r in dep.replicas:
            try:
                ray.kill(r)
            except Exception:
                pass
    _DEPLOYMENTS.clear()
    if _HTTP_SERVER is not None:
        _HTTP_SERVER.shutdown()
        _HTTP_SERVER.server_close()
        _HTTP_SERVER = None
