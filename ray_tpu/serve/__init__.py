from ray_tpu.serve.serve import (
    Deployment,
    DeploymentHandle,
    DeploymentResponse,
    HTTPDeploymentHandle,
    deployment,
    get_deployment,
    get_deployment_handle,
    run,
    shutdown,
    update_deployment,
)

__all__ = [
    "deployment",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "HTTPDeploymentHandle",
    "run",
    "get_deployment",
    "get_deployment_handle",
    "update_deployment",
    "shutdown",
]
