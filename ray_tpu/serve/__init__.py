from ray_tpu.serve.serve import (
    Deployment,
    DeploymentHandle,
    deployment,
    get_deployment,
    run,
    shutdown,
)

__all__ = [
    "deployment",
    "Deployment",
    "DeploymentHandle",
    "run",
    "get_deployment",
    "shutdown",
]
