from ray_tpu.serve.serve import (
    Deployment,
    DeploymentHandle,
    DeploymentResponse,
    HTTPDeploymentHandle,
    deployment,
    get_deployment,
    get_deployment_handle,
    run,
    shutdown,
    update_deployment,
)
from ray_tpu.serve.policy_server import (
    BatchedPolicyServer,
    CheckpointWatcher,
    PolicyDeployment,
    policy_deployment,
    restore_policy,
)

__all__ = [
    "deployment",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "HTTPDeploymentHandle",
    "run",
    "get_deployment",
    "get_deployment_handle",
    "update_deployment",
    "shutdown",
    "BatchedPolicyServer",
    "CheckpointWatcher",
    "PolicyDeployment",
    "policy_deployment",
    "restore_policy",
]
