"""Continuous-batching policy serving: the inference plane.

The serve core (``serve/serve.py``) routes ONE request per actor call —
the same per-call dispatch overhead the training superstep killed on
the learner path (docs/data_plane.md). This module applies the
identical optimization to inference (the Orca-style continuous-batching
pattern): concurrent ``compute_actions`` requests coalesce into ONE
mesh-sharded jit'd forward, so a replica's throughput scales with batch
rows instead of dispatches.

Three pieces:

- :class:`BatchedPolicyServer` — the in-process engine. A batcher
  thread drains up to ``max_batch_size`` queued requests (or
  ``batch_wait_timeout_s`` after the first, whichever first), pads the
  batch into a small set of static **bucket** shapes (powers of two →
  zero recompiles after warmup, ``compile_stats``-asserted), and runs
  one ``sharded_jit`` forward on the policy's mesh: replicated params,
  row-sharded observations, a **donated rng carry**. Results scatter
  back to per-request futures.

  **Determinism contract** (docs/serving.md): the program advances the
  rng carry exactly once per REAL request — padded rows consume no
  splits — and maps the policy's ``_action_step_body`` over
  per-request keys at batch-1 shapes (``lax.map``), so a fixed-seed
  request stream produces BIT-identical actions/extras to sequential
  ``compute_actions`` calls on a 1-shard mesh, no matter how the
  batcher happened to slice it. ``vectorized=True`` swaps the map for
  a vmap over row-sharded obs (the wide-hardware throughput mode;
  batched matmuls round the last ulp differently).

- **Checkpoint hot-reload**: :class:`CheckpointWatcher` polls a
  training run's ``checkpoint_root`` through
  ``resilience.discovery`` — the SAME newest-of stream-tail/periodic
  preference ``RecoveryManager.restore_latest`` uses — and stages the
  new policy state on the server's long-poll host. The batcher applies
  it atomically BETWEEN batches: in-flight requests finish under the
  params they started with, queued requests see the new version, and
  every response reports the ``params_version`` that computed it (no
  dropped, no blended requests). A trainer and a server pointed at the
  same root form the closed train→serve→refresh loop.

- :class:`PolicyDeployment` — the serve-core deployment wrapper:
  restores a policy from a checkpoint, owns a server + watcher, and
  surfaces queue/latency stats through ``_Replica.stats`` for the
  queue-wait autoscaler (``serve.serve.RunningDeployment``).
"""

from __future__ import annotations

import collections
import os
import pickle
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID
from ray_tpu.resilience import discovery
from ray_tpu.serve.long_poll import LongPollHost
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing


def device_ledger_summary() -> Optional[Dict[str, Any]]:
    """The device-ledger slice of ``stats()``: aggregate MFU from the
    telemetry ledger (``telemetry.device.snapshot()["totals"]``) plus
    the fraction of HBM still free on this replica's device. This is
    the serve autoscaler's SECOND signal source
    (``autoscaling_config={"signal": "ledger"}``) — batch fill says
    how hard the buckets run, this says whether another replica could
    even fit. Returns None when neither number is knowable (ledger
    disabled AND no memory stats), so stats() payloads stay honest.

    ``RAY_TPU_HBM_HEADROOM`` overrides the measured headroom (CPU
    hosts report no HBM; tests pin the gate with it)."""
    mfu = None
    try:
        from ray_tpu.telemetry import device as device_ledger

        if device_ledger.enabled():
            mfu = device_ledger.snapshot()["totals"]["mfu"]
    except Exception:
        pass
    headroom = None
    env = os.environ.get("RAY_TPU_HBM_HEADROOM")
    if env:
        try:
            headroom = float(env)
        except ValueError:
            headroom = None
    if headroom is None:
        try:
            import jax

            ms = jax.devices()[0].memory_stats()
            in_use = (ms or {}).get("bytes_in_use")
            limit = (ms or {}).get("bytes_limit")
            if in_use is not None and limit:
                headroom = max(0.0, 1.0 - in_use / limit)
        except Exception:
            pass
    if mfu is None and headroom is None:
        return None
    return {"mfu": mfu, "hbm_headroom": headroom}


def default_buckets(max_batch_size: int) -> Tuple[int, ...]:
    """Powers of two up to (and including) ``max_batch_size`` — the
    static batch shapes the server compiles. log2(B_max)+1 programs
    cover every occupancy with ≤ 2x padding waste."""
    out = []
    b = 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return tuple(out)


class TrailingWindow:
    """THE trailing-window percentile accessor of the serve plane.

    One implementation computes every queue-wait/latency signal —
    ``BatchedPolicyServer.stats()`` (what ``_Replica.stats`` forwards
    to the ``_autoscale_loop`` queue-wait targeting), the ingress
    admission controller's shedding decision, and the router's own
    wait tracking all read the SAME windowed numbers
    (regression-pinned by tests/test_ingress.py). Samples older than
    ``window_s`` decay out, so the signal relaxes once load does."""

    def __init__(self, window_s: float = 30.0, maxlen: int = 8192):
        self.window_s = float(window_s)
        self._samples: collections.deque = collections.deque(
            maxlen=maxlen
        )
        self._lock = threading.Lock()

    def observe(self, value: float, t: Optional[float] = None) -> None:
        with self._lock:
            self._samples.append(
                (time.perf_counter() if t is None else t, value)
            )

    def values(self) -> List[float]:
        cutoff = time.perf_counter() - self.window_s
        with self._lock:
            return [v for (t, v) in self._samples if t >= cutoff]

    def pct(self, q: float) -> Optional[float]:
        vals = self.values()
        if not vals:
            return None
        return float(np.percentile(np.asarray(vals), q))

    def snapshot(self) -> Dict[str, Any]:
        vals = self.values()
        arr = np.asarray(vals) if vals else None
        return {
            "p50_s": float(np.percentile(arr, 50))
            if arr is not None
            else None,
            "p99_s": float(np.percentile(arr, 99))
            if arr is not None
            else None,
            "n": len(vals),
            "window_s": self.window_s,
        }


class ServeFuture:
    """Per-request future a :meth:`BatchedPolicyServer.submit` returns.
    ``result()`` blocks for ``(action, extra)``; ``params_version``
    records which weights computed it (the hot-reload audit field)."""

    __slots__ = (
        "_event", "_value", "_error", "params_version", "latency_s",
    )

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self.params_version: Optional[int] = None
        self.latency_s: Optional[float] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = 60.0):
        if not self._event.wait(timeout):
            raise TimeoutError("policy-server request did not complete")
        if self._error is not None:
            raise self._error
        return self._value

    # -- server side ----------------------------------------------------

    def _resolve(self, value, version: int, latency_s: float) -> None:
        self._value = value
        self.params_version = version
        self.latency_s = latency_s
        self._event.set()

    def _reject(self, err: BaseException) -> None:
        self._error = err
        self._event.set()


class _Request:
    __slots__ = (
        "obs",
        "explore",
        "future",
        "t_submit",
        "flush",
        "trace",
    )

    def __init__(
        self, obs, explore, future, t_submit, flush=False, trace=None
    ):
        self.obs = obs
        self.explore = explore
        self.future = future
        self.t_submit = t_submit
        # flush hint: the tail of a router-coalesced bucket — the
        # batcher drains immediately instead of waiting out the batch
        # timeout for rows that are not coming
        self.flush = flush
        # trace context riding batch formation: the serve:batch span
        # joins the trace of its first traced request, so an ingress
        # request's spans stitch end to end
        self.trace = trace


class BatchedPolicyServer:
    """Coalesces concurrent single-observation requests into fused
    batched forwards on ``policy``'s mesh.

    The policy object is owned by the batcher thread after
    construction: param swaps, coefficient updates, and forwards all
    happen there, so no policy-level locking exists or is needed.
    """

    def __init__(
        self,
        policy,
        *,
        name: str = "policy",
        max_batch_size: int = 32,
        batch_wait_timeout_s: float = 0.002,
        explore: bool = False,
        buckets: Optional[Sequence[int]] = None,
        vectorized: bool = False,
        obs_filter=None,
        preprocessor=None,
        stats_window_s: float = 30.0,
        aot_cache=None,
        start: bool = True,
    ):
        self.policy = policy
        self.name = name
        self.max_batch_size = int(max_batch_size)
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        self.batch_wait_timeout_s = float(batch_wait_timeout_s)
        self.explore = bool(explore)
        self.buckets: Tuple[int, ...] = tuple(
            sorted(set(int(b) for b in buckets))
            if buckets
            else default_buckets(self.max_batch_size)
        )
        if self.buckets[-1] < self.max_batch_size:
            raise ValueError(
                "largest bucket must cover max_batch_size"
            )
        # exact (default): lax.map of the batch-1 action body —
        # bit-identical per row to sequential compute_actions (the
        # docs/serving.md determinism contract; batched matmuls round
        # the last ulp differently, measured on this backend).
        # vectorized: vmap + row-sharded obs — the wide-hardware
        # throughput mode, parity within ~1 ulp.
        self.vectorized = bool(vectorized)
        self.obs_filter = obs_filter
        self.preprocessor = preprocessor
        # the fused path needs a feedforward model + stateless
        # exploration; anything else serves sequentially (still
        # batched at the queue, one compute_actions per request)
        self.fused = bool(
            getattr(policy, "supports_batched_serve", False)
        )
        obs_space = policy.observation_space
        self._row_shape = tuple(obs_space.shape)
        self._row_dtype = np.dtype(obs_space.dtype)

        import jax

        from ray_tpu import sharding as sharding_lib

        self._rep = sharding_lib.replicated(policy.mesh)
        # params enter the fused forward per their live placement tree
        # (replicated for ordinary policies; per-leaf model-axis
        # shardings for partitioned ones — the supports_batched_serve
        # gate already guaranteed the placement matches the rules)
        self._param_spec = (
            getattr(policy, "param_shardings", None) or self._rep
        )
        # the rng carry CONTINUES the policy's own stream: a reference
        # policy built from the same seed makes the same splits
        # sequentially — the parity contract's anchor
        self._carry = jax.device_put(policy._rng, self._rep)
        self._fns: Dict[Tuple[int, bool], Any] = {}
        # per-bucket program specs (sharding/registry.py): warmup()
        # walks this registry, and an algorithm-owned registry can
        # absorb the same rows so the driver's AOT/coverage sweep sees
        # serve programs alongside the learn-side ones
        self.program_registry = self._build_program_registry()

        # hot-reload staging rides a long-poll host: the watcher (any
        # thread) notifies, the batcher adopts between batches
        self._swap_host = LongPollHost()
        self._applied_swap = 0
        self.params_version = 1
        self.reload_info: Optional[Dict[str, Any]] = None
        telemetry_metrics.set_serve_params_version(
            self.name, self.params_version
        )

        self._queue: "collections.deque[_Request]" = collections.deque()
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._flush_hints = 0
        self.error: Optional[BaseException] = None
        # AOT compiled-program cache (sharding/aot.py): warmup loads
        # serialized serve executables instead of compiling — the
        # cold-start path of docs/serving.md "the front door"
        from ray_tpu.sharding import aot as aot_lib

        self.aot_cache = aot_lib.resolve_cache(aot_cache)
        # a cache built HERE from a path is ours to stop; a passed-in
        # instance is fleet-shared and outlives any one server
        self._owns_aot_cache = (
            self.aot_cache is not None
            and not isinstance(aot_cache, aot_lib.AOTCompileCache)
        )

        self.requests_total = 0
        self.batches_total = 0
        self.batch_rows_total = 0
        self.padded_rows_total = 0
        # trailing-window percentile accessors — the ONE windowing
        # implementation the autoscaler (via stats()) and the ingress
        # shedding decision both read, so the signal decays once load
        # does (a lifetime p50 would pin scale-down forever)
        self.stats_window_s = float(stats_window_s)
        self._lat = TrailingWindow(self.stats_window_s)
        self._queue_wait = TrailingWindow(self.stats_window_s)

        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> None:
        """Start the batcher thread (idempotent). Deferred start lets
        a caller warm every bucket before traffic can race the carry."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"serve_batcher_{self.name}",
        )
        self._thread.start()

    # -- client side -----------------------------------------------------

    def _transform_obs(self, obs) -> np.ndarray:
        """Preprocessor + observation filter (``update=False`` —
        serving traffic must not mutate training filter statistics) +
        shape/dtype validation, shared by submit and submit_many."""
        if self.preprocessor is not None:
            obs = self.preprocessor.transform(obs)
        if self.obs_filter is not None:
            obs = self.obs_filter(obs, update=False)
        obs = np.asarray(obs, dtype=self._row_dtype)
        if obs.shape != self._row_shape:
            raise ValueError(
                f"obs shape {obs.shape} != policy row shape "
                f"{self._row_shape}"
            )
        return obs

    def submit(
        self,
        obs,
        explore: Optional[bool] = None,
        trace: Optional[Dict[str, Any]] = None,
    ) -> ServeFuture:
        """Enqueue ONE observation; returns its future. No flush hint:
        singleton submits rely on the batcher's timeout coalescing
        (the PR-9 continuous-batching contract)."""
        return self._enqueue([obs], explore, flush=False, trace=trace)[0]

    def submit_many(
        self,
        obs_rows,
        explore: Optional[bool] = None,
        trace: Optional[Dict[str, Any]] = None,
    ) -> List[ServeFuture]:
        """Enqueue a pre-coalesced run of observations ATOMICALLY (one
        lock acquisition, one batcher wakeup): the ingress router's
        dispatch path. The last request carries a flush hint, so the
        batcher drains the run immediately instead of waiting out
        ``batch_wait_timeout_s`` for rows that are not coming — a
        router-formed bucket turns into exactly one forward (plus
        whatever was already queued, which can only round UP to a
        bigger warm bucket, never retrace)."""
        return self._enqueue(obs_rows, explore, flush=True, trace=trace)

    def _enqueue(
        self, obs_rows, explore, flush: bool, trace=None
    ) -> List[ServeFuture]:
        if self._stop.is_set():
            raise RuntimeError("policy server is stopped")
        obs_rows = list(obs_rows)
        if not obs_rows:
            return []  # no rows → no flush hint to pop, don't arm one
        explore = self.explore if explore is None else bool(explore)
        now = time.perf_counter()
        reqs = []
        for i, obs in enumerate(obs_rows):
            reqs.append(
                _Request(
                    self._transform_obs(obs),
                    explore,
                    ServeFuture(),
                    now,
                    flush=flush and i == len(obs_rows) - 1,
                    trace=trace,
                )
            )
        with self._cv:
            self._queue.extend(reqs)
            depth = len(self._queue)
            self.requests_total += len(reqs)
            if flush:
                self._flush_hints += 1
            self._cv.notify_all()
        telemetry_metrics.inc_serve_requests(self.name, len(reqs))
        telemetry_metrics.set_serve_queue_depth(self.name, depth)
        return [r.future for r in reqs]

    def compute_actions(
        self, obs_batch, explore: Optional[bool] = None
    ):
        """Blocking convenience: submit every row of ``obs_batch`` and
        gather ``(actions, extras)`` numpy results in order."""
        futs = [self.submit(o, explore=explore) for o in obs_batch]
        outs = [f.result() for f in futs]
        actions = np.stack([a for a, _ in outs])
        extras = {
            k: np.stack([e[k] for _, e in outs])
            for k in (outs[0][1] if outs else {})
        }
        return actions, extras

    # -- hot reload ------------------------------------------------------

    def update_params(
        self, state, *, info: Optional[Dict[str, Any]] = None
    ) -> None:
        """Stage a new policy state (a ``Policy.get_state`` dict, a
        stream-snapshot policy entry, or a bare weights tree). The
        batcher thread adopts it atomically between batches; a fresh
        stage replaces an unadopted one (the server only ever wants
        the newest params)."""
        self._swap_host.notify("params", (state, info))
        with self._cv:
            self._cv.notify_all()

    # ray-tpu: thread=batcher
    def _maybe_apply_params(self) -> None:
        """Batcher-thread only: adopt the newest staged state, if any.
        Runs strictly between forwards, which is what makes the swap
        atomic per request."""
        ver, staged = self._swap_host.current("params")
        if ver <= self._applied_swap or staged is None:
            return
        state, info = staged
        policy = self.policy
        if isinstance(state, dict) and "weights" in state:
            policy.set_state(state)
        elif (
            isinstance(state, dict)
            and set(state.keys()) == {"state"}
        ):
            # bespoke-policy stream snapshot wrapper
            policy.set_state(state["state"])
        else:
            policy.set_weights(state)
        self._applied_swap = ver
        self.params_version += 1
        self.reload_info = info
        telemetry_metrics.set_serve_params_version(
            self.name, self.params_version
        )
        tracing.event(
            "serve:hot_reload",
            version=self.params_version,
            **{
                k: str(v)
                for k, v in (info or {}).items()
                if k in ("kind", "path")
            },
        )

    # -- fused forward ---------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _build_serve_fn(self, bucket: int, explore: bool):
        import jax
        import jax.numpy as jnp

        from ray_tpu import sharding as sharding_lib

        policy = self.policy
        rep = self._rep
        # exact mode computes replicated (every shard runs the same
        # row scan — no resharding collectives around a sequential
        # scan); vectorized mode shards rows across the mesh
        rows = rep
        if self.vectorized and (
            bucket % sharding_lib.num_shards(policy.mesh) == 0
        ):
            rows = sharding_lib.batch_sharded(policy.mesh)

        def fn(params, carry, obs, n_real, coeffs):
            # sequential per-request key stream: request i consumes
            # split i of the carry, EXACTLY like i sequential
            # compute_actions calls; padded rows (i >= n_real) leave
            # the carry untouched so occupancy never skews the stream
            def split_body(c, i):
                ks = jax.random.split(c)
                return jnp.where(i < n_real, ks[0], c), ks[1]

            carry, keys = jax.lax.scan(
                split_body, carry, jnp.arange(bucket)
            )

            def row(obs_i, key_i):
                actions, _, extra, _ = policy._action_step_body(
                    params,
                    obs_i[None],
                    key_i,
                    coeffs,
                    explore=explore,
                    expl_state=(),
                )
                return actions[0], {
                    k: v[0] for k, v in extra.items()
                }

            if self.vectorized:
                actions, extra = jax.vmap(row)(obs, keys)
            else:
                # scan of the EXACT batch-1 ops the sequential path
                # jits — the formulation that keeps per-row results
                # bitwise (vmap/batched matmuls do not, measured)
                actions, extra = jax.lax.map(
                    lambda t: row(*t), (obs, keys)
                )
            return actions, extra, carry

        return sharding_lib.sharded_jit(
            fn,
            in_specs=(self._param_spec, rep, rows, rep, rep),
            out_specs=(rows, rows, rep),
            donate_argnums=(1,),
            label=(
                f"serve[{self.name}:{bucket}"
                f":{'explore' if explore else 'greedy'}]"
            ),
        )

    # ray-tpu: thread=batcher hot-path
    def forward_padded(
        self, obs_rows: np.ndarray, explore: Optional[bool] = None
    ):
        """ONE fused forward for ``len(obs_rows)`` already-transformed
        rows, padded to the smallest covering bucket. Batcher-thread
        API (also driven directly by warmup/bench); returns
        ``(actions, extras)`` trimmed to the real rows."""
        explore = self.explore if explore is None else bool(explore)
        n = int(obs_rows.shape[0])
        bucket = self._bucket_for(n)
        padded = np.zeros(
            (bucket,) + self._row_shape, self._row_dtype
        )
        padded[:n] = obs_rows
        policy = self.policy
        policy.exploration.update_coeffs(
            policy.coeff_values, policy.global_timestep
        )
        params = policy.exploration.params_for_inference(
            policy, explore
        )
        coeffs = policy._coeff_array()
        key = (bucket, explore)
        fn = self._fns.get(key)
        if fn is None:
            fn = self._fns[key] = self._build_serve_fn(
                bucket, explore
            )
        telemetry_metrics.add_h2d_bytes("serve", padded.nbytes)
        with tracing.start_span(
            "serve:forward", bucket=bucket, rows=n
        ):
            actions, extra, self._carry = fn(
                params, self._carry, padded, np.int32(n), coeffs
            )
        # ray-tpu: allow[RTA005] the serve forward's ONE counted drain: result materialization closes the ledger interval (drain_point below)
        actions = np.asarray(actions)[:n]
        extra = {k: np.asarray(v)[:n] for k, v in extra.items()}  # ray-tpu: allow[RTA005] same counted drain
        # results materialized host-side → the serve program finished;
        # close its ledger interval (timestamps only, no extra sync)
        from ray_tpu.telemetry import device as device_ledger

        device_ledger.drain_point()
        return actions, extra

    def _build_program_registry(self):
        """One warmable :class:`~ray_tpu.sharding.registry.ProgramSpec`
        per bucket (plus the explore-variant pattern): the registry IS
        the warmup plan."""
        import functools

        from ray_tpu.sharding import registry as registry_lib

        reg = registry_lib.ProgramRegistry()
        if not self.fused:
            return reg
        for b in self.buckets:
            reg.add_program(
                rf"serve\[{re.escape(self.name)}:{b}"
                rf":(?:explore|greedy)\]",
                kind="serve",
                regex=True,
                warm=functools.partial(self._warm_bucket, b, None),
                meta={"bucket": b},
            )
        return reg

    def warmup(self, explore: Optional[bool] = None) -> int:
        """Compile every bucket for ``explore`` (default: the server's
        flag) by walking the per-bucket program registry with
        zero-occupancy forwards — ``n_real=0`` leaves the rng carry
        bitwise untouched, so warmup never perturbs the request
        stream. Returns the bucket count; after this, steady traffic
        is recompile-free (``compile_stats``-asserted)."""
        if not self.fused:
            return 0
        if explore is None:
            # the registry's warm callables carry explore=None (the
            # server flag) — the common sweep the driver also runs
            report = self.program_registry.sweep(kind="serve")
            return report["warmed"]
        for b in self.buckets:
            self._warm_bucket(b, explore)
        return len(self.buckets)

    def _warm_bucket(self, bucket, explore):
        explore = self.explore if explore is None else bool(explore)
        # force THIS bucket (forward_padded would pick the smallest)
        padded = np.zeros(
            (bucket,) + self._row_shape, self._row_dtype
        )
        policy = self.policy
        policy.exploration.update_coeffs(
            policy.coeff_values, policy.global_timestep
        )
        params = policy.exploration.params_for_inference(
            policy, explore
        )
        coeffs = policy._coeff_array()
        key = (bucket, explore)
        fn = self._fns.get(key)
        if fn is None:
            fn = self._fns[key] = self._build_serve_fn(
                bucket, explore
            )
        if self.aot_cache is not None:
            # AOT cold start (sharding/aot.py): a cache hit installs
            # the serialized executable — the warm call below then
            # executes WITHOUT any XLA compile; a miss compiles ahead
            # of time once and seeds the cache for the next replica
            fn.aot_warmup(
                self.aot_cache,
                params, self._carry, padded, np.int32(0), coeffs,
            )
        _, _, self._carry = fn(
            params, self._carry, padded, np.int32(0), coeffs
        )

    # -- batcher thread --------------------------------------------------

    # ray-tpu: thread=batcher
    def _run(self) -> None:
        try:
            while True:
                with self._cv:
                    while (
                        not self._queue
                        and not self._stop.is_set()
                        and not self._swap_pending()
                    ):
                        self._cv.wait()
                    if self._stop.is_set() and not self._queue:
                        break
                self._maybe_apply_params()
                batch = self._collect_batch()
                if batch:
                    self._process_batch(batch)
            # drain: adopt any final swap so stop() leaves a coherent
            # version, then exit
            self._maybe_apply_params()
        except BaseException as e:  # pragma: no cover - defensive
            self.error = e
            with self._cv:
                pending = list(self._queue)
                self._queue.clear()
            for req in pending:
                req.future._reject(e)

    # ray-tpu: thread=batcher
    def _swap_pending(self) -> bool:
        ver, _ = self._swap_host.current("params")
        return ver > self._applied_swap

    # ray-tpu: thread=batcher
    def _collect_batch(self) -> List[_Request]:
        """Drain up to ``max_batch_size`` same-explore requests, FIFO;
        a partial batch flushes ``batch_wait_timeout_s`` after its
        FIRST request arrived (whichever comes first — the
        timeout-flush contract)."""
        with self._cv:
            if not self._queue:
                return []
            deadline = (
                self._queue[0].t_submit + self.batch_wait_timeout_s
            )
            while (
                len(self._queue) < self.max_batch_size
                and not self._stop.is_set()
                # a flush hint means a pre-coalesced run's tail is
                # already queued — drain now, nothing more is coming
                and self._flush_hints == 0
            ):
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            batch: List[_Request] = []
            flag = self._queue[0].explore
            while (
                self._queue
                and len(batch) < self.max_batch_size
                and self._queue[0].explore == flag
            ):
                req = self._queue.popleft()
                if req.flush:
                    self._flush_hints -= 1
                batch.append(req)
            telemetry_metrics.set_serve_queue_depth(
                self.name, len(self._queue)
            )
            return batch

    # ray-tpu: thread=batcher hot-path
    def _process_batch(self, batch: List[_Request]) -> None:
        t0 = time.perf_counter()
        n = len(batch)
        explore = batch[0].explore
        version = self.params_version
        # the forward's span joins the trace of the batch's first
        # traced request (ingress→router→replica stitching); untraced
        # batches keep their own fresh span as before
        trace = next(
            (
                r.trace
                for r in batch
                if getattr(r, "trace", None) is not None
            ),
            None,
        )
        with tracing.context_span(
            trace, "serve:batch", rows=n, version=version
        ):
            try:
                if self.fused:
                    obs = np.stack([r.obs for r in batch])
                    actions, extra = self.forward_padded(
                        obs, explore=explore
                    )
                    results = [
                        (
                            actions[i],
                            {k: v[i] for k, v in extra.items()},
                        )
                        for i in range(n)
                    ]
                else:
                    # sequential fallback (recurrent / stateful
                    # exploration): correctness over coalescing
                    results = []
                    for r in batch:
                        a, _, ex = self.policy.compute_actions(
                            r.obs[None], explore=explore
                        )
                        results.append(
                            (a[0], {k: v[0] for k, v in ex.items()})
                        )
            except BaseException as e:
                for r in batch:
                    r.future._reject(e)
                raise
        t1 = time.perf_counter()
        self.batches_total += 1
        self.batch_rows_total += n
        self.padded_rows_total += self._bucket_for(n) - n
        telemetry_metrics.observe_serve_batch(self.name, n)
        # bucket occupancy of the forward that just ran: real rows /
        # executed rows (the fused path pads to a power-of-two bucket;
        # the sequential fallback runs exactly its rows)
        executed = self._bucket_for(n) if self.fused else n
        telemetry_metrics.set_serve_batch_fill(
            self.name, n / executed if executed else 0.0
        )
        for req, value in zip(batch, results):
            lat = t1 - req.t_submit
            wait = t0 - req.t_submit
            self._lat.observe(lat, t=t1)
            self._queue_wait.observe(wait, t=t1)
            telemetry_metrics.observe_serve_latency(self.name, lat)
            telemetry_metrics.observe_serve_queue_wait(
                self.name, wait
            )
            req.future._resolve(value, version, lat)

    # -- introspection ---------------------------------------------------

    def queue_wait_window(self) -> Dict[str, Any]:
        """THE queue-wait signal: trailing-window percentiles of how
        long requests sat queued before their forward launched. One
        accessor feeds BOTH consumers — ``stats()`` (whose
        ``queue_wait_p50_s`` the serve-core ``_autoscale_loop``
        targets) and the ingress admission controller's shedding
        decision — so the two planes can never act on different
        numbers (regression-pinned by tests/test_ingress.py)."""
        return self._queue_wait.snapshot()

    def latency_window(self) -> Dict[str, Any]:
        """Trailing-window end-to-end latency percentiles (same
        accessor discipline as :meth:`queue_wait_window`)."""
        return self._lat.snapshot()

    def stats(self) -> Dict[str, Any]:
        """Queue/latency surface (exact percentiles over the trailing
        ``stats_window_s``) — what ``_Replica.stats`` forwards to the
        queue-wait autoscaler and what the bench curves read."""
        with self._cv:
            depth = len(self._queue)
        lat = self.latency_window()
        qw = self.queue_wait_window()
        return {
            "queue_depth": depth,
            "requests_total": self.requests_total,
            "batches_total": self.batches_total,
            "mean_batch_rows": (
                self.batch_rows_total / self.batches_total
                if self.batches_total
                else 0.0
            ),
            "padded_rows_total": self.padded_rows_total,
            # cumulative bucket occupancy: of every row the fused
            # forwards executed, the fraction that was real work
            "batch_fill_fraction": (
                self.batch_rows_total
                / (self.batch_rows_total + self.padded_rows_total)
                if self.batch_rows_total
                else 0.0
            ),
            "latency_p50_s": lat["p50_s"],
            "latency_p99_s": lat["p99_s"],
            "queue_wait_p50_s": qw["p50_s"],
            "queue_wait_p99_s": qw["p99_s"],
            "params_version": self.params_version,
            "fused": self.fused,
            "vectorized": self.vectorized,
            # the ledger autoscale signal rides the same stats pull
            # the queue-wait targeting already makes (None when the
            # host can report neither MFU nor HBM headroom)
            "device": device_ledger_summary(),
            "buckets": list(self.buckets),
            "aot": (
                self.aot_cache.stats()
                if self.aot_cache is not None
                else None
            ),
        }

    def stop(self, join_timeout: float = 30.0) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=join_timeout)
        if self._owns_aot_cache:
            self.aot_cache.stop()


# -- checkpoint restore / hot-reload sources ----------------------------


def load_policy_state(
    kind: str, path: str, policy_id: str = DEFAULT_POLICY_ID
) -> Dict[str, Any]:
    """Policy state dict out of a restore target — a periodic
    checkpoint directory (``algorithm_state.pkl`` worker state) or a
    continuous-stream snapshot (``snapshot_*.pkl`` payload). Raises on
    torn/pruned targets; pollers retry next round."""
    if kind == "stream":
        with open(path, "rb") as f:
            payload = pickle.load(f)
        states = payload.get("policy_states", {})
    else:
        with open(
            os.path.join(path, "algorithm_state.pkl"), "rb"
        ) as f:
            state = pickle.load(f)
        states = state.get("worker", {}).get("policy_states", {})
    if policy_id not in states:
        raise KeyError(
            f"policy {policy_id!r} not in {kind} target {path!r} "
            f"(has {sorted(states)})"
        )
    return states[policy_id]


def restore_policy(
    checkpoint: str,
    *,
    policy_id: str = DEFAULT_POLICY_ID,
    config_overrides: Optional[Dict[str, Any]] = None,
    mesh=None,
):
    """Build a standalone serving policy from a periodic checkpoint.

    ``checkpoint`` is a checkpoint directory or a ``checkpoint_root``
    containing ``checkpoint_*`` ones (newest wins). The stored config
    names the algorithm (→ its default policy class) and the env (→
    observation/action spaces); the stored worker state provides
    weights and observation-filter statistics. Returns
    ``(policy, preprocessor, obs_filter, info)``.
    """
    path = checkpoint
    if not os.path.exists(
        os.path.join(path, "algorithm_state.pkl")
    ):
        latest = discovery.latest_periodic(path)
        if latest is None:
            raise ValueError(
                f"no checkpoint under {checkpoint!r} "
                "(expected algorithm_state.pkl or checkpoint_* dirs)"
            )
        path = latest
    import json

    from ray_tpu.algorithms.registry import get_algorithm_class
    from ray_tpu.core import serialization as _ser

    with open(
        os.path.join(path, "rllib_checkpoint.json")
    ) as f:
        meta = json.load(f)
    with open(
        os.path.join(path, "algorithm_config.pkl"), "rb"
    ) as f:
        config = _ser.loads(f.read())
    config = dict(config)
    config.update(config_overrides or {})
    config["num_workers"] = 0
    config.pop("_mesh", None)
    if mesh is not None:
        config["_mesh"] = mesh

    algo_cls = get_algorithm_class(meta["algorithm_name"])
    # class-level lookup only: no Algorithm (workers, telemetry, ...)
    # is built for serving
    policy_cls = algo_cls.get_default_policy_class(
        object.__new__(algo_cls), config
    )

    obs_space = config.get("observation_space")
    act_space = config.get("action_space")
    if obs_space is None or act_space is None:
        from ray_tpu.env.env_context import EnvContext
        from ray_tpu.env.registry import get_env_creator

        env = get_env_creator(config["env"])(
            EnvContext(config.get("env_config") or {}, worker_index=0)
        )
        obs_space = obs_space or env.observation_space
        act_space = act_space or env.action_space
        if hasattr(env, "close"):
            try:
                env.close()
            except Exception:
                pass

    from ray_tpu.models.catalog import ModelCatalog
    from ray_tpu.utils.filter import get_filter

    prep = ModelCatalog.get_preprocessor_for_space(obs_space)
    eff_obs_space = prep.observation_space
    policy = policy_cls(eff_obs_space, act_space, config)

    with open(
        os.path.join(path, "algorithm_state.pkl"), "rb"
    ) as f:
        worker_state = pickle.load(f).get("worker", {})
    pol_state = worker_state.get("policy_states", {}).get(policy_id)
    if pol_state is None:
        raise KeyError(
            f"policy {policy_id!r} not in checkpoint {path!r}"
        )
    policy.set_state(pol_state)

    obs_filter = get_filter(
        config.get("observation_filter", "NoFilter"),
        eff_obs_space.shape,
    )
    saved_filter = worker_state.get("filters", {}).get(policy_id)
    if saved_filter is not None:
        obs_filter.sync(saved_filter)
    info = {
        "checkpoint": path,
        "algorithm": meta["algorithm_name"],
        "policy_cls": policy_cls.__name__,
    }
    return policy, prep, obs_filter, info


class CheckpointWatcher:
    """Polls a training run's ``checkpoint_root`` and pushes every new
    restore target into ``apply_fn(state, info)``. Target selection is
    ``resilience.discovery``'s newest-of stream-tail/periodic
    preference — the same snapshot a recovering trainer would restore.
    Prune-safe: targets deleted between discovery and read are skipped
    and retried on the next poll."""

    def __init__(
        self,
        checkpoint_root: str,
        apply_fn: Callable[[Dict, Dict], None],
        *,
        policy_id: str = DEFAULT_POLICY_ID,
        poll_interval_s: float = 0.5,
        initial_version: Tuple[int, int] = (-1, -1),
        start: bool = True,
    ):
        self.checkpoint_root = checkpoint_root
        self.apply_fn = apply_fn
        self.policy_id = policy_id
        self.poll_interval_s = float(poll_interval_s)
        self.version = tuple(initial_version)
        self.num_reloads = 0
        self.last_target: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="serve_ckpt_watcher",
            )
            self._thread.start()

    def poll_once(self) -> bool:
        """One discovery round; True when a newer target was applied."""
        kind, path = discovery.discover(self.checkpoint_root)
        if path is None:
            return False
        if kind == "checkpoint" and not os.path.exists(
            os.path.join(path, "algorithm_state.pkl")
        ):
            return False  # save in progress (state lands before meta)
        try:
            ver = discovery.target_version(kind, path)
        except Exception:
            return False  # pruned/torn between listdir and read
        if tuple(ver) <= tuple(self.version):
            return False
        try:
            state = load_policy_state(kind, path, self.policy_id)
        except Exception:
            return False
        self.apply_fn(
            state,
            {"kind": kind, "path": path, "version": tuple(ver)},
        )
        self.version = tuple(ver)
        self.last_target = path
        self.num_reloads += 1
        return True

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception:
                pass  # discovery must never kill the watcher

    def stats(self) -> Dict[str, Any]:
        return {
            "version": tuple(self.version),
            "num_reloads": self.num_reloads,
            "last_target": self.last_target,
        }

    def stop(self, join_timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=join_timeout)


class PolicyDeployment:
    """The serve-core deployment class for policy serving: restore →
    batch-serve → hot-reload. Deploy via :func:`policy_deployment`
    (replica actors behind a DeploymentHandle) or instantiate directly
    for in-process serving (tests, bench, notebooks).

    Calls take ``{"obs": [...], "explore": bool?}`` (or a bare obs
    array) and return ``{"action", "params_version", "logp"?}`` with
    JSON-friendly types, so the HTTP ingress can route them as-is.
    """

    def __init__(
        self,
        checkpoint: str,
        *,
        policy_id: str = DEFAULT_POLICY_ID,
        name: str = "policy",
        max_batch_size: int = 32,
        batch_wait_timeout_s: float = 0.002,
        explore: bool = False,
        watch: bool = True,
        poll_interval_s: float = 0.5,
        warmup: bool = True,
        aot_cache=None,
        config_overrides: Optional[Dict[str, Any]] = None,
    ):
        policy, prep, obs_filter, info = restore_policy(
            checkpoint,
            policy_id=policy_id,
            config_overrides=config_overrides,
        )
        self.info = info
        self.policy_id = policy_id
        self.server = BatchedPolicyServer(
            policy,
            name=name,
            max_batch_size=max_batch_size,
            batch_wait_timeout_s=batch_wait_timeout_s,
            explore=explore,
            obs_filter=obs_filter,
            preprocessor=prep,
            # a directory path shared across the fleet: every replica
            # process resolves its own cache client over the same
            # entries, so the first replica's compiles become every
            # later replica's cold-start hits
            aot_cache=aot_cache,
            start=False,
        )
        if warmup:
            self.server.warmup()
        self.server.start()
        # the watcher follows the ROOT the checkpoint came from, so a
        # live trainer writing new checkpoints (or stream snapshots)
        # refreshes this replica continuously
        ckpt = info["checkpoint"]
        self.checkpoint_root = (
            os.path.dirname(ckpt)
            if os.path.basename(ckpt).startswith(
                discovery.PERIODIC_PREFIX
            )
            else ckpt
        )
        self.watcher = None
        if watch:
            try:
                init_ver = discovery.target_version(
                    "checkpoint", ckpt
                )
            except ValueError:
                init_ver = (-1, -1)
            self.watcher = CheckpointWatcher(
                self.checkpoint_root,
                lambda state, inf: self.server.update_params(
                    state, info=inf
                ),
                policy_id=policy_id,
                poll_interval_s=poll_interval_s,
                initial_version=init_ver,
            )

    def __call__(self, payload=None):
        if isinstance(payload, dict):
            obs = payload.get("obs")
            explore = payload.get("explore")
        else:
            obs, explore = payload, None
        fut = self.server.submit(
            np.asarray(obs), explore=explore
        )
        action, extra = fut.result()
        out = {
            "action": np.asarray(action).tolist(),
            "params_version": fut.params_version,
        }
        logp = extra.get("action_logp")
        if logp is not None:
            out["logp"] = float(np.asarray(logp))
        return out

    def compute_actions(self, obs_batch, explore=None):
        return self.server.compute_actions(
            obs_batch, explore=explore
        )

    def handle_rows(self, rows, explore=None, timeout_s: float = 60.0):
        """Batch entry point for the ingress coalescing router: one
        pre-coalesced bucket in, one JSON-friendly result row per
        request out (same fields as ``__call__``). The rows enqueue
        atomically (``submit_many``) so a router bucket becomes
        exactly one fused forward on this replica."""
        futs = self.server.submit_many(
            [np.asarray(r) for r in rows], explore=explore
        )
        out = []
        for fut in futs:
            action, extra = fut.result(timeout_s)
            row = {
                "action": np.asarray(action).tolist(),
                "params_version": fut.params_version,
            }
            logp = extra.get("action_logp")
            if logp is not None:
                row["logp"] = float(np.asarray(logp))
            out.append(row)
        return out

    def reconfigure(self, user_config) -> None:
        """Serve-core live config push: an explicit
        ``{"checkpoint": path}`` loads that target immediately (the
        push-based alternative to the polling watcher)."""
        if not user_config:
            return
        path = user_config.get("checkpoint")
        if path:
            kind = (
                "stream"
                if path.endswith(".pkl")
                else "checkpoint"
            )
            state = load_policy_state(kind, path, self.policy_id)
            self.server.update_params(
                state, info={"kind": kind, "path": path}
            )

    def preemption_notice(self):
        """Provider eviction probe — the SAME mechanism rollout
        workers poll (resilience/provider_notice.py), so one notice
        surface drains training and serving fleets alike."""
        from ray_tpu.resilience import provider_notice

        return provider_notice.probe()

    def stats(self) -> Dict[str, Any]:
        out = self.server.stats()
        if self.watcher is not None:
            out["reload"] = self.watcher.stats()
        out["checkpoint_root"] = self.checkpoint_root
        return out

    def stop(self) -> None:
        if self.watcher is not None:
            self.watcher.stop()
        self.server.stop()


def policy_deployment(
    checkpoint: str,
    *,
    name: str = "policy",
    num_replicas: int = 1,
    autoscaling_config: Optional[Dict] = None,
    **kwargs,
):
    """A ready-to-``serve.run`` Deployment serving ``checkpoint``:
    each replica actor restores the policy, batches its own requests,
    and hot-reloads from the checkpoint root independently."""
    from ray_tpu.serve.serve import Deployment

    return Deployment(
        PolicyDeployment,
        name,
        num_replicas=num_replicas,
        init_args=(checkpoint,),
        init_kwargs=dict(kwargs, name=name),
        autoscaling_config=autoscaling_config,
    )
