"""Long-poll pub/sub for serve config propagation.

Counterpart of the reference's ``serve/long_poll.py`` (LongPollHost /
LongPollClient): subscribers ask "anything newer than version v for
key k?" and block until the host publishes a change, so config updates
(replica membership, user_config) propagate promptly without polling
loops or restarts. Scoped to the single-controller host — the host is
an in-process object; handles subscribe from any thread (and could
subscribe over an actor boundary, since listen() is a plain method).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple


class LongPollHost:
    def __init__(self):
        self._values: Dict[str, Any] = {}
        self._versions: Dict[str, int] = {}
        self._cond = threading.Condition()

    def notify(self, key: str, value: Any) -> int:
        """Publish a new value for key; wakes all listeners."""
        with self._cond:
            self._versions[key] = self._versions.get(key, 0) + 1
            self._values[key] = value
            self._cond.notify_all()
            return self._versions[key]

    def listen(
        self,
        key: str,
        last_version: int = 0,
        timeout: Optional[float] = None,
    ) -> Optional[Tuple[int, Any]]:
        """Block until key's version exceeds last_version; returns
        (version, value), or None on timeout (reference
        LongPollHost.listen_for_change)."""
        import time

        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        with self._cond:
            while self._versions.get(key, 0) <= last_version:
                remaining = (
                    None
                    if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            return self._versions[key], self._values[key]

    def current(self, key: str) -> Tuple[int, Any]:
        with self._cond:
            return self._versions.get(key, 0), self._values.get(key)
