"""`python -m ray_tpu.evaluate` — rollout a trained checkpoint.

Counterpart of the reference's ``rllib/evaluate.py:282`` (`rllib evaluate`).
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def main(argv=None) -> int:
    from ray_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    parser = argparse.ArgumentParser(description="ray_tpu evaluate CLI")
    parser.add_argument("checkpoint", type=str)
    parser.add_argument("--run", type=str, required=True)
    parser.add_argument("--env", type=str, required=True)
    parser.add_argument("--episodes", type=int, default=10)
    parser.add_argument("--config", type=str, default="{}")
    parser.add_argument("--explore", action="store_true")
    args = parser.parse_args(argv)

    from ray_tpu.algorithms.registry import get_algorithm_class

    cls = get_algorithm_class(args.run)
    config = json.loads(args.config)
    config.update({"env": args.env, "num_workers": 0})
    algo = cls(config=config)
    algo.restore(args.checkpoint)

    import gymnasium as gym

    from ray_tpu.env.registry import get_env_creator

    env = get_env_creator(args.env)({})
    rewards = []
    for ep in range(args.episodes):
        obs, _ = env.reset(seed=ep)
        done = trunc = False
        total = 0.0
        state = algo.get_policy().get_initial_state() or None
        while not (done or trunc):
            if state:
                action, state, _ = algo.compute_single_action(
                    obs, state, explore=args.explore
                )
            else:
                action = algo.compute_single_action(
                    obs, explore=args.explore
                )
            obs, r, done, trunc, _ = env.step(action)
            total += float(r)
        rewards.append(total)
        print(f"episode {ep}: reward={total}")
    print(
        json.dumps(
            {
                "episodes": args.episodes,
                "mean_reward": float(np.mean(rewards)),
                "max_reward": float(np.max(rewards)),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
