"""Build the native library (g++, no external deps). Idempotent: rebuilds
only when the source is newer than the .so."""

from __future__ import annotations

import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "shm_ring.cpp")
LIB = os.path.join(_DIR, "libshm_ring.so")


def ensure_built() -> str:
    """→ path to libshm_ring.so, building if needed. Raises on failure."""
    if os.path.exists(LIB) and os.path.getmtime(LIB) >= os.path.getmtime(
        SRC
    ):
        return LIB
    cmd = [
        "g++",
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-o",
        LIB,
        SRC,
        "-lrt",
        "-pthread",
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return LIB


def available() -> bool:
    try:
        ensure_built()
        return True
    except Exception:
        return False


# -- sanitizer builds (reference: src/ray BUILD config with
# --config=tsan / --config=asan; its release tests run under them) --

STRESS_SRC = os.path.join(_DIR, "shm_ring_stress.cpp")

_SAN_FLAGS = {
    "none": ["-O2"],
    "tsan": ["-fsanitize=thread", "-O1", "-g"],
    "asan": [
        "-fsanitize=address",
        "-fsanitize=undefined",
        "-fno-omit-frame-pointer",
        "-O1",
        "-g",
    ],
}


def build_stress(kind: str) -> str:
    """Build the SPSC stress binary (``shm_ring_stress.cpp`` +
    ``shm_ring.cpp`` in one program) under a sanitizer; returns the
    binary path. A standalone instrumented binary — rather than
    LD_PRELOADing a sanitizer runtime into python — is the only
    configuration TSan reliably supports, and it exercises the
    acquire/release protocol with a real producer/consumer thread
    pair so the lock-free claims in ``shm_ring.cpp`` are CHECKED, not
    just argued (the race-detection role of SURVEY §5.2)."""
    if kind not in _SAN_FLAGS:
        raise ValueError(f"unknown sanitizer {kind!r}")
    exe = os.path.join(_DIR, f"shm_ring_stress_{kind}")
    newest = max(os.path.getmtime(SRC), os.path.getmtime(STRESS_SRC))
    if os.path.exists(exe) and os.path.getmtime(exe) >= newest:
        return exe
    cmd = (
        ["g++", "-std=c++17"]
        + _SAN_FLAGS[kind]
        + ["-o", exe, STRESS_SRC, SRC, "-lrt", "-pthread"]
    )
    subprocess.run(cmd, check=True, capture_output=True)
    return exe
