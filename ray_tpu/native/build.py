"""Build the native library (g++, no external deps). Idempotent: rebuilds
only when the source is newer than the .so."""

from __future__ import annotations

import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "shm_ring.cpp")
LIB = os.path.join(_DIR, "libshm_ring.so")


def ensure_built() -> str:
    """→ path to libshm_ring.so, building if needed. Raises on failure."""
    if os.path.exists(LIB) and os.path.getmtime(LIB) >= os.path.getmtime(
        SRC
    ):
        return LIB
    cmd = [
        "g++",
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-o",
        LIB,
        SRC,
        "-lrt",
        "-pthread",
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return LIB


def available() -> bool:
    try:
        ensure_built()
        return True
    except Exception:
        return False
