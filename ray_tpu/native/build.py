"""Build the native library (g++, no external deps). Idempotent:
rebuilds when the source content changed since the artifact was built.

Staleness is keyed on a sha256 of the sources + compile flags recorded
in a ``.stamp`` sidecar — NOT on mtimes, which are unreliable after a
fresh ``git clone`` (checkout gives every file the same mtime, so a
stale binary could win the race and be silently executed). Build
artifacts are gitignored; the first use on a new machine compiles them.
"""

from __future__ import annotations

import hashlib
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "shm_ring.cpp")
LIB = os.path.join(_DIR, "libshm_ring.so")


def _content_key(srcs, flags) -> str:
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(flags).encode())
    return h.hexdigest()


def _fresh(artifact: str, key: str) -> bool:
    if not os.path.exists(artifact):
        return False
    try:
        with open(artifact + ".stamp") as f:
            return f.read().strip() == key
    except FileNotFoundError:
        return False


def _stamp(artifact: str, key: str) -> None:
    with open(artifact + ".stamp", "w") as f:
        f.write(key)


def ensure_built() -> str:
    """→ path to libshm_ring.so, building if needed. Raises on failure."""
    cmd = [
        "g++",
        "-O2",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-o",
        LIB,
        SRC,
        "-lrt",
        "-pthread",
    ]
    key = _content_key([SRC], cmd)
    if _fresh(LIB, key):
        return LIB
    subprocess.run(cmd, check=True, capture_output=True)
    _stamp(LIB, key)
    return LIB


def available() -> bool:
    try:
        ensure_built()
        return True
    except Exception:
        return False


# -- sanitizer builds (reference: src/ray BUILD config with
# --config=tsan / --config=asan; its release tests run under them) --

STRESS_SRC = os.path.join(_DIR, "shm_ring_stress.cpp")

_SAN_FLAGS = {
    "none": ["-O2"],
    "tsan": ["-fsanitize=thread", "-O1", "-g"],
    "asan": [
        "-fsanitize=address",
        "-fsanitize=undefined",
        "-fno-omit-frame-pointer",
        "-O1",
        "-g",
    ],
}


def build_stress(kind: str) -> str:
    """Build the SPSC stress binary (``shm_ring_stress.cpp`` +
    ``shm_ring.cpp`` in one program) under a sanitizer; returns the
    binary path. A standalone instrumented binary — rather than
    LD_PRELOADing a sanitizer runtime into python — is the only
    configuration TSan reliably supports, and it exercises the
    acquire/release protocol with a real producer/consumer thread
    pair so the lock-free claims in ``shm_ring.cpp`` are CHECKED, not
    just argued (the race-detection role of SURVEY §5.2)."""
    if kind not in _SAN_FLAGS:
        raise ValueError(f"unknown sanitizer {kind!r}")
    exe = os.path.join(_DIR, f"shm_ring_stress_{kind}")
    cmd = (
        ["g++", "-std=c++17"]
        + _SAN_FLAGS[kind]
        + ["-o", exe, STRESS_SRC, SRC, "-lrt", "-pthread"]
    )
    key = _content_key([SRC, STRESS_SRC], cmd)
    if _fresh(exe, key):
        return exe
    subprocess.run(cmd, check=True, capture_output=True)
    _stamp(exe, key)
    return exe
