// SPSC stress harness for shm_ring.cpp — built under TSan/ASan by
// ray_tpu/native/build.py build_stress() and run by
// tests/test_shm_ring_sanitizers.py.
//
// One producer thread reserve/write/commits records of varying sizes
// (driving wrap-around and full-ring backoff); one consumer thread
// peeks/pops and validates length + content. Both threads operate on
// the SAME handle/mapping: TSan analyzes happens-before per address,
// so a second attach (new mmap of the same segment) would hide the
// cross-thread pairings the acquire/release protocol must order.
// Exit 0 = all records verified; any sanitizer report fails the
// harness via the sanitizer's own exit code / stderr.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* shmring_create(const char* name, uint64_t capacity);
int64_t shmring_reserve(void* ring, uint64_t len);
void shmring_commit(void* ring);
void* shmring_data(void* ring);
int64_t shmring_peek_len(void* ring);
int64_t shmring_pop(void* ring, uint8_t* buf, uint64_t maxlen);
uint64_t shmring_num_pushed(void* ring);
uint64_t shmring_num_popped(void* ring);
void shmring_mark_closed(void* ring);
int shmring_is_closed(void* ring);
void shmring_close(void* ring);
}

namespace {

constexpr int kMessages = 20000;
constexpr uint64_t kCapacity = 1 << 16;  // small: force wraps + fulls

// deterministic per-message size, 8..~6000 bytes, crossing the
// contiguous-space boundary often
uint64_t msg_len(int i) { return 8 + (uint64_t)((i * 2654435761u) % 6000); }

uint8_t msg_byte(int i, uint64_t j) {
  return (uint8_t)((i * 31 + j * 7) & 0xff);
}

}  // namespace

int main() {
  void* ring = shmring_create("/ray_tpu_stress_ring", kCapacity);
  if (!ring) {
    fprintf(stderr, "create failed\n");
    return 2;
  }
  std::atomic<int> failures{0};

  std::thread producer([&] {
    uint8_t* data = (uint8_t*)shmring_data(ring);
    for (int i = 0; i < kMessages; ++i) {
      uint64_t len = msg_len(i);
      int64_t off;
      while ((off = shmring_reserve(ring, len)) == -1)
        std::this_thread::yield();  // full: wait for the consumer
      if (off < 0) {
        fprintf(stderr, "reserve(%llu) -> %lld\n",
                (unsigned long long)len, (long long)off);
        failures.fetch_add(1);
        return;
      }
      for (uint64_t j = 0; j < len; ++j) data[off + j] = msg_byte(i, j);
      shmring_commit(ring);
    }
  });

  std::thread consumer([&] {
    std::vector<uint8_t> buf(1 << 14);
    for (int i = 0; i < kMessages; ++i) {
      int64_t len;
      while ((len = shmring_pop(ring, buf.data(), buf.size())) == -1)
        std::this_thread::yield();  // empty: wait for the producer
      if (len != (int64_t)msg_len(i)) {
        fprintf(stderr, "msg %d: len %lld != %llu\n", i, (long long)len,
                (unsigned long long)msg_len(i));
        failures.fetch_add(1);
        return;
      }
      for (uint64_t j = 0; j < (uint64_t)len; ++j) {
        if (buf[j] != msg_byte(i, j)) {
          fprintf(stderr, "msg %d: byte %llu corrupt\n", i,
                  (unsigned long long)j);
          failures.fetch_add(1);
          return;
        }
      }
    }
  });

  producer.join();
  consumer.join();
  if (shmring_num_pushed(ring) != kMessages ||
      shmring_num_popped(ring) != kMessages) {
    fprintf(stderr, "counter mismatch: pushed %llu popped %llu\n",
            (unsigned long long)shmring_num_pushed(ring),
            (unsigned long long)shmring_num_popped(ring));
    failures.fetch_add(1);
  }
  shmring_mark_closed(ring);
  if (!shmring_is_closed(ring) || shmring_reserve(ring, 8) != -3)
    failures.fetch_add(1);
  shmring_close(ring);
  if (failures.load() != 0) return 1;
  printf("ok: %d messages verified\n", kMessages);
  return 0;
}
