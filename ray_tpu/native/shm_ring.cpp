// Shared-memory SPSC ring buffer — the native data plane for
// rollout→learner batch transfer.
//
// Plays the plasma-store role of the reference
// (src/ray/object_manager/plasma/store.h:55 + the create/get protocol)
// scoped to the streaming single-producer/single-consumer pattern RL
// training actually uses: a rollout actor pushes serialized SampleBatch
// records; the learner's feeder thread pops them. Lock-free: one atomic
// head (consumer) and tail (producer) cursor in the mapped header, with
// length-prefixed records and wrap-around markers.
//
// Layout:
//   [Header | data bytes ...]
//   Header: magic, capacity, head, tail (64-byte aligned atomics)
//   Record: u64 len | len bytes (8-byte aligned). len == WRAP_MARKER
//   means "skip to buffer start".
//
// Build: g++ -O2 -shared -fPIC -o libshm_ring.so shm_ring.cpp -lrt

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52494e475450ULL;  // "RINGTP"
constexpr uint64_t kWrapMarker = ~0ULL;

struct alignas(64) Header {
  uint64_t magic;
  uint64_t capacity;  // data area size in bytes
  alignas(64) std::atomic<uint64_t> head;  // consumer cursor (abs offset)
  alignas(64) std::atomic<uint64_t> tail;  // producer cursor (abs offset)
  alignas(64) std::atomic<uint64_t> n_pushed;
  std::atomic<uint64_t> n_popped;
  std::atomic<uint64_t> closed;
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  size_t map_size;
  int owner;
  uint64_t pending_tail;  // tail to publish at next commit (producer only)
  char name[256];
};

inline uint64_t align8(uint64_t x) { return (x + 7) & ~7ULL; }

}  // namespace

extern "C" {

// Create a new ring with `capacity` data bytes. Returns NULL on error.
void* shmring_create(const char* name, uint64_t capacity) {
  capacity = align8(capacity);
  size_t total = sizeof(Header) + capacity;
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  // MAP_POPULATE pre-faults the whole mapping once so steady-state
  // pushes never pay per-page soft faults as the cursor sweeps the ring.
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Ring* r = new Ring();
  r->hdr = (Header*)mem;
  r->data = (uint8_t*)mem + sizeof(Header);
  r->map_size = total;
  r->owner = 1;
  strncpy(r->name, name, sizeof(r->name) - 1);
  r->hdr->capacity = capacity;
  r->hdr->head.store(0);
  r->hdr->tail.store(0);
  r->hdr->n_pushed.store(0);
  r->hdr->n_popped.store(0);
  r->hdr->closed.store(0);
  std::atomic_thread_fence(std::memory_order_release);
  r->hdr->magic = kMagic;
  return r;
}

// Attach to an existing ring. Returns NULL on error.
void* shmring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  // No MAP_POPULATE here: attach runs on the driver's recv thread and
  // prefaulting 64MB there would block result handling; the consumer
  // faults pages in lazily on first sweep only.
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* hdr = (Header*)mem;
  if (hdr->magic != kMagic) {
    munmap(mem, (size_t)st.st_size);
    return nullptr;
  }
  Ring* r = new Ring();
  r->hdr = hdr;
  r->data = (uint8_t*)mem + sizeof(Header);
  r->map_size = (size_t)st.st_size;
  r->owner = 0;
  strncpy(r->name, name, sizeof(r->name) - 1);
  return r;
}

// Zero-copy producer API: reserve space for a `len`-byte record and
// return the offset into the data area where the payload may be written
// directly (e.g. by the Python serializer writing into the mapped
// buffer). The record becomes visible to the consumer only at
// shmring_commit. Returns the payload offset, -1 if the ring is
// currently too full, -2 if the record can never fit, -3 if closed.
int64_t shmring_reserve(void* ring, uint64_t len) {
  Ring* r = (Ring*)ring;
  Header* h = r->hdr;
  if (h->closed.load(std::memory_order_acquire)) return -3;
  const uint64_t cap = h->capacity;
  const uint64_t need = align8(8 + len);
  if (need + 8 > cap) return -2;
  uint64_t head = h->head.load(std::memory_order_acquire);
  uint64_t tail = h->tail.load(std::memory_order_relaxed);
  uint64_t used = tail - head;
  uint64_t tpos = tail % cap;
  uint64_t contiguous = cap - tpos;
  uint64_t total_need = need;
  bool wrap = false;
  if (contiguous < need) {
    total_need = contiguous + need;
    wrap = true;
  }
  // At this cursor position the record needs total_need bytes of free
  // space; if that exceeds the capacity it can NEVER fit here no matter
  // how far the consumer drains — report -2 (permanent) rather than -1
  // (retry), or the producer would spin until timeout.
  if (total_need > cap) return -2;
  if (used + total_need > cap) return -1;  // full
  if (wrap) {
    *(uint64_t*)(r->data + tpos) = kWrapMarker;
    tail += contiguous;
    tpos = 0;
  }
  *(uint64_t*)(r->data + tpos) = len;
  r->pending_tail = tail + need;
  return (int64_t)(tpos + 8);
}

// Publish the record written after shmring_reserve.
void shmring_commit(void* ring) {
  Ring* r = (Ring*)ring;
  r->hdr->tail.store(r->pending_tail, std::memory_order_release);
  r->hdr->n_pushed.fetch_add(1, std::memory_order_relaxed);
}

// Base address of the data area (for mapping a memoryview in Python).
void* shmring_data(void* ring) { return ((Ring*)ring)->data; }

uint64_t shmring_capacity(void* ring) {
  return ((Ring*)ring)->hdr->capacity;
}

// Peek the next record's length. Returns length, -1 if empty.
int64_t shmring_peek_len(void* ring) {
  Ring* r = (Ring*)ring;
  Header* h = r->hdr;
  const uint64_t cap = h->capacity;
  uint64_t head = h->head.load(std::memory_order_relaxed);
  uint64_t tail = h->tail.load(std::memory_order_acquire);
  while (true) {
    if (head == tail) return -1;
    uint64_t hpos = head % cap;
    uint64_t len = *(uint64_t*)(r->data + hpos);
    if (len == kWrapMarker) {
      head += cap - hpos;
      h->head.store(head, std::memory_order_release);
      continue;
    }
    return (int64_t)len;
  }
}

// Pop one record into buf (size maxlen). Returns record length,
// -1 if empty, -2 if buf too small (record left in place).
int64_t shmring_pop(void* ring, uint8_t* buf, uint64_t maxlen) {
  Ring* r = (Ring*)ring;
  Header* h = r->hdr;
  int64_t len = shmring_peek_len(ring);
  if (len < 0) return len;
  if ((uint64_t)len > maxlen) return -2;
  const uint64_t cap = h->capacity;
  uint64_t head = h->head.load(std::memory_order_relaxed);
  uint64_t hpos = head % cap;
  memcpy(buf, r->data + hpos + 8, (size_t)len);
  h->head.store(head + align8(8 + (uint64_t)len),
                std::memory_order_release);
  h->n_popped.fetch_add(1, std::memory_order_relaxed);
  return len;
}

uint64_t shmring_size(void* ring) {
  Ring* r = (Ring*)ring;
  return r->hdr->tail.load(std::memory_order_acquire) -
         r->hdr->head.load(std::memory_order_acquire);
}

uint64_t shmring_num_pushed(void* ring) {
  return ((Ring*)ring)->hdr->n_pushed.load(std::memory_order_relaxed);
}

uint64_t shmring_num_popped(void* ring) {
  return ((Ring*)ring)->hdr->n_popped.load(std::memory_order_relaxed);
}

void shmring_mark_closed(void* ring) {
  ((Ring*)ring)->hdr->closed.store(1, std::memory_order_release);
}

int shmring_is_closed(void* ring) {
  return (int)((Ring*)ring)->hdr->closed.load(std::memory_order_acquire);
}

// Unmap; owner also unlinks the segment.
void shmring_close(void* ring) {
  Ring* r = (Ring*)ring;
  int owner = r->owner;
  char name[256];
  strncpy(name, r->name, sizeof(name));
  munmap((void*)r->hdr, r->map_size);
  if (owner) shm_unlink(name);
  delete r;
}

}  // extern "C"
