from ray_tpu.native.build import available, ensure_built

__all__ = ["available", "ensure_built"]
