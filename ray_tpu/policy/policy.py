"""Policy abstract base class + view requirements.

Counterpart of the reference's ``rllib/policy/policy.py:99`` (Policy ABC:
``compute_actions :356``, ``postprocess_trajectory :434``,
``learn_on_batch :487``, ``compute_gradients :598``) and
``rllib/policy/view_requirement.py:15``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch


class ViewRequirement:
    """Declares a column the policy needs at compute/train time
    (reference view_requirement.py:15).

    ``shift`` is an int (0 = this step, -1 = previous step, ...) or a
    window string ``"a:b"`` with ``a <= b <= 0`` (e.g. ``"-3:0"`` =
    the last four values including the current step, stacked on a new
    leading axis, zero-filled before the episode start). Windowed and
    negatively-shifted views are materialized by the sampler's
    :class:`~ray_tpu.evaluation.view_collector.ViewCollector` from the
    declaration alone — the policy/model never hand-wires collection.
    Positive shifts are covered by the built-in NEXT_OBS column."""

    def __init__(
        self,
        data_col: Optional[str] = None,
        shift=0,
        used_for_compute_actions: bool = True,
        used_for_training: bool = True,
        space=None,
    ):
        self.data_col = data_col
        self.shift = shift
        self.used_for_compute_actions = used_for_compute_actions
        self.used_for_training = used_for_training
        self.space = space
        if isinstance(shift, str):
            lo, hi = (int(s) for s in shift.split(":"))
            if lo > hi or hi > 0:
                raise ValueError(
                    f"window shift {shift!r} must satisfy a <= b <= 0"
                )
            self.shift_from, self.shift_to = lo, hi
        else:
            self.shift_from = self.shift_to = int(shift)

    @property
    def is_window(self) -> bool:
        return isinstance(self.shift, str)

    @property
    def lookback(self) -> int:
        """How many PAST steps this view reaches into."""
        return max(0, -self.shift_from)


class Policy:
    """Per-policy inference/learning contract (reference policy.py:99)."""

    def __init__(self, observation_space, action_space, config: Dict):
        self.observation_space = observation_space
        self.action_space = action_space
        self.config = config or {}
        self.global_timestep = 0
        self.view_requirements: Dict[str, ViewRequirement] = {
            SampleBatch.OBS: ViewRequirement(space=observation_space),
            SampleBatch.ACTIONS: ViewRequirement(
                space=action_space, used_for_compute_actions=False
            ),
            SampleBatch.REWARDS: ViewRequirement(
                used_for_compute_actions=False
            ),
            SampleBatch.TERMINATEDS: ViewRequirement(
                used_for_compute_actions=False
            ),
            SampleBatch.TRUNCATEDS: ViewRequirement(
                used_for_compute_actions=False
            ),
            SampleBatch.EPS_ID: ViewRequirement(
                used_for_compute_actions=False
            ),
        }

    # -- inference -------------------------------------------------------

    def compute_actions(
        self,
        obs_batch: np.ndarray,
        state_batches: Optional[List[np.ndarray]] = None,
        prev_action_batch: Optional[np.ndarray] = None,
        prev_reward_batch: Optional[np.ndarray] = None,
        explore: bool = True,
        timestep: Optional[int] = None,
        **kwargs,
    ) -> Tuple[np.ndarray, List[np.ndarray], Dict[str, np.ndarray]]:
        """→ (actions, state_outs, extra_fetches). Reference policy.py:356."""
        raise NotImplementedError

    def compute_single_action(
        self, obs, state=None, explore: bool = True, **kwargs
    ):
        obs_batch = np.asarray(obs)[None]
        state_batches = (
            [np.asarray(s)[None] for s in state] if state else None
        )
        actions, state_out, extra = self.compute_actions(
            obs_batch, state_batches, explore=explore, **kwargs
        )
        return (
            actions[0],
            [s[0] for s in state_out] if state_out else [],
            {k: v[0] for k, v in extra.items()},
        )

    def get_initial_state(self) -> List[np.ndarray]:
        return []

    @property
    def is_recurrent(self) -> bool:
        return bool(self.get_initial_state())

    # -- training --------------------------------------------------------

    def postprocess_trajectory(
        self,
        sample_batch: SampleBatch,
        other_agent_batches: Optional[Dict] = None,
        episode=None,
    ) -> SampleBatch:
        return sample_batch

    def learn_on_batch(self, samples: SampleBatch) -> Dict[str, Any]:
        raise NotImplementedError

    def compute_gradients(self, batch: SampleBatch):
        raise NotImplementedError

    def apply_gradients(self, gradients) -> None:
        raise NotImplementedError

    # -- state -----------------------------------------------------------

    def get_weights(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def get_inference_weights(self) -> Dict[str, np.ndarray]:
        """The subset of weights a sampling-only worker needs to act
        (e.g. SAC ships just the actor net, not critic/target towers).
        Defaults to the full tree; ``set_weights`` implementations merge
        partial trees so syncing this subset is always safe."""
        return self.get_weights()

    def set_weights(self, weights) -> None:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {
            "weights": self.get_weights(),
            "global_timestep": self.global_timestep,
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.set_weights(state["weights"])
        self.global_timestep = state.get("global_timestep", 0)

    def on_global_var_update(self, global_vars: Dict[str, Any]) -> None:
        self.global_timestep = global_vars.get(
            "timestep", self.global_timestep
        )

    def export_checkpoint(self, export_dir: str) -> None:
        import os
        import pickle

        os.makedirs(export_dir, exist_ok=True)
        with open(os.path.join(export_dir, "policy_state.pkl"), "wb") as f:
            pickle.dump(self.get_state(), f)
