"""JaxPolicy: the TPU-native Policy implementation.

This is the "missing half" the reference sketched but never built: RLlib
supports ``build_policy_class(framework="jax")`` but its parent class is
still TorchPolicy (``rllib/policy/policy_template.py:135,247``). JaxPolicy
replaces the whole TorchPolicy multi-GPU mechanism
(``rllib/policy/torch_policy.py:60``: ``learn_on_batch :467``,
``load_batch_into_buffer :498``, ``_multi_gpu_parallel_grad_calc :1049``)
with a single jitted update:

  - the entire SGD nest — ``num_sgd_iter`` epochs × minibatches, per-device
    shuffling, loss/grad, ICI gradient pmean, optimizer — compiles to ONE
    XLA program via ``jax.shard_map`` over the learner mesh, lowered
    through the ``ray_tpu.sharding`` runtime (``sharded_jit`` with
    replicated-param / row-sharded-batch NamedShardings and opt-state
    donation when ``config.sharding_backend == "mesh"``, the default;
    ``"pmap"`` keeps legacy implicit placement);
  - no loader threads, no per-device towers, no CPU gradient averaging;
  - schedule-driven scalars (lr, entropy coeff, kl coeff) enter as traced
    scalar args so schedules never trigger recompilation.

The same class serves rollout actors (CPU platform, jitted
``compute_actions``) and the learner (TPU mesh).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu import sharding as sharding_lib
from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.models.catalog import ModelCatalog
from ray_tpu.ops.framestack import FRAME_IDX as _FRAME_IDX
from ray_tpu.ops.framestack import FRAMES as _FRAMES
from ray_tpu.policy.policy import Policy
from ray_tpu.telemetry import device as device_ledger
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing
from ray_tpu.utils.metrics import timer_histogram


def _tree_to_device(tree, sharding=None):
    return jax.device_put(tree, sharding) if sharding else jax.device_put(tree)


class JaxPolicy(Policy):
    """Base JAX policy. Subclasses (or ``build_jax_policy`` templates)
    override :meth:`loss` and optionally :meth:`extra_action_out`,
    :meth:`stats_coeffs`, :meth:`postprocess_trajectory`."""

    # Names of host-side scalar coefficients fed into the loss each call
    # (e.g. PPO's adaptive kl coeff). Values live in self.coeff_values.
    coeff_names: Tuple[str, ...] = ("lr", "entropy_coeff")

    # Exploration strategy used when exploration_config gives no "type"
    # (reference Policy._create_exploration default per algorithm).
    default_exploration: str = "StochasticSampling"

    # Recurrent unroll length; instance-overridden in __init__ for
    # recurrent models. A class default so bespoke-net policies that
    # bypass JaxPolicy.__init__ (SAC/DDPG families) stay feedforward.
    _unroll_T: int = 1

    # Backend default for policies that bypass __init__ (their own
    # constructors overwrite it from config via resolve_mesh).
    sharding_backend: str = "mesh"

    # Per-leaf param placement (docs/sharding.md "2-D mesh & param
    # partitioning"). Class defaults = the replicated legacy contract,
    # so bespoke-net policies that bypass __init__ (SAC/DDPG families)
    # keep replicated trees; __init__ installs per-leaf trees when the
    # mesh carries a "model" axis and the model declares rules.
    _param_pspecs = None
    _opt_pspecs = None
    _opt_sharding = None

    @property
    def last_learn_timers(self) -> Dict[str, float]:
        """Per-stage timers of the most recent learn call (device
        transfer / compile / step), lazily created so bespoke-net
        policies that bypass __init__ report them too."""
        t = self.__dict__.get("_last_learn_timers")
        if t is None:
            t = self.__dict__["_last_learn_timers"] = {}
        return t

    def __init__(self, observation_space, action_space, config: Dict):
        super().__init__(observation_space, action_space, config)
        self.model_config = dict(config.get("model") or {})
        dist_type = config.get("dist_type")
        self.dist_class, self.num_outputs = ModelCatalog.get_action_dist(
            action_space, self.model_config, dist_type
        )
        self.model = ModelCatalog.get_model(
            observation_space, action_space, self.num_outputs,
            self.model_config,
        )
        # Recurrent learn-path unroll length (reference max_seq_len,
        # rnn_sequencing.py chop length): flat train rows are chopped
        # into fixed (B, T) unrolls with zero initial state at chunk
        # starts and a `resets` column at episode/fragment boundaries.
        self._unroll_T = (
            int(self.model_config.get("max_seq_len", 20))
            if self.model.is_recurrent
            else 1
        )

        # ---- mesh / shardings (ray_tpu.sharding runtime) ----
        self.sharding_backend = config.get("sharding_backend", "mesh")
        self.mesh = sharding_lib.resolve_mesh(config)
        self.n_shards = sharding_lib.num_shards(self.mesh)
        self._param_sharding = sharding_lib.replicated(self.mesh)
        self._data_sharding = sharding_lib.batch_sharded(self.mesh)

        # ---- params / optimizer ----
        seed = int(config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        dummy_obs = self._dummy_obs(batch=2)
        init_state = self.model.initial_state(2)
        if self.model.is_recurrent:
            init_kwargs = {}
            if getattr(self.model, "use_prev_action", False):
                init_kwargs["prev_actions"] = jnp.zeros(
                    (2, 1) + tuple(action_space.shape or ()),
                    jnp.float32,
                )
            if getattr(self.model, "use_prev_reward", False):
                init_kwargs["prev_rewards"] = jnp.zeros(
                    (2, 1), jnp.float32
                )
            self.params = self.model.init(
                init_rng, dummy_obs[:, None], init_state, **init_kwargs
            )
        else:
            self.params = self.model.init(init_rng, dummy_obs)
        # per-leaf partitioned placement: when the mesh carries a
        # "model" axis and the model declares partition rules, params
        # become first-class sharded trees (attention/MLP kernels
        # split megatron-style, the rest replicated); otherwise the
        # replicated default above stands
        self._install_param_placement()
        self.params = _tree_to_device(self.params, self._param_sharding)

        grad_clip = config.get("grad_clip")
        chain = []
        if grad_clip:
            chain.append(optax.clip_by_global_norm(grad_clip))
        chain.append(optax.scale_by_adam(eps=config.get("adam_epsilon", 1e-8)))
        self._tx = optax.chain(*chain)
        opt0 = self._tx.init(self.params)
        if self._param_pspecs is not None:
            # optimizer moments inherit each param's placement
            # (suffix-matched by path+shape); counts/scalars replicate
            self._opt_pspecs = sharding_lib.state_pspecs(
                opt0, self.params, self._param_pspecs
            )
            self._opt_sharding = sharding_lib.named_tree(
                self.mesh, self._opt_pspecs
            )
        else:
            self._opt_sharding = self._param_sharding
        self.opt_state = _tree_to_device(opt0, self._opt_sharding)

        # ---- schedules / coefficients ----
        from ray_tpu.utils.schedules import make_schedule

        self._lr_schedule = make_schedule(
            config.get("lr_schedule"), config.get("lr", 5e-5)
        )
        self._entropy_schedule = make_schedule(
            config.get("entropy_coeff_schedule"),
            config.get("entropy_coeff", 0.0),
        )
        self.coeff_values: Dict[str, float] = {
            "lr": float(self._lr_schedule(0)),
            "entropy_coeff": float(self._entropy_schedule(0)),
        }
        self._init_coeffs()

        # SGD geometry (static per compile)
        self.train_batch_size = int(config.get("train_batch_size", 4000))
        self.minibatch_size = int(
            config.get("sgd_minibatch_size")
            or config.get("train_batch_size", 4000)
        )
        self.num_sgd_iter = int(config.get("num_sgd_iter", 1))

        # (batch_size, with_frames) -> compiled SGD-nest program
        self._learn_fns: Dict[Tuple[int, bool], Any] = {}
        # AOT executable cache for the learn program (sharding/aot.py;
        # ROADMAP item 2 leftover): resolved lazily from
        # config["aot_cache_dir"] so importing the policy never touches
        # the cache machinery. The elastic joiner's warmup rides this —
        # a freshly built policy whose fleet already populated the
        # cache installs the serialized executable instead of paying
        # the XLA compile (aot_warmup in learn_on_device_batch).
        self._aot_cache = None
        self._aot_cache_resolved = False
        self._action_fn = None
        self._value_fn = None
        self.num_grad_updates = 0
        # Non-gradient state (target networks etc) — placement follows
        # the params it mirrors (suffix-matched) when partitioned.
        self.aux_state: Dict[str, Any] = self._init_aux_state()
        self._publish_params_bytes()

        # ---- exploration ----
        self._init_exploration()

        # ---- view requirements (reference view_requirement.py:15) ----
        # Shifted columns the sampler should populate for this policy.
        from ray_tpu.policy.policy import ViewRequirement

        mc = self.model_config
        if mc.get("lstm_use_prev_action") or mc.get("use_prev_action"):
            self.view_requirements[SampleBatch.PREV_ACTIONS] = (
                ViewRequirement(
                    data_col=SampleBatch.ACTIONS, shift=-1,
                    space=action_space,
                )
            )
        if mc.get("lstm_use_prev_reward") or mc.get("use_prev_reward"):
            self.view_requirements[SampleBatch.PREV_REWARDS] = (
                ViewRequirement(
                    data_col=SampleBatch.REWARDS, shift=-1
                )
            )

    # -- subclass hooks --------------------------------------------------

    def _init_exploration(self) -> None:
        """(Re)build the exploration strategy, merge its scheduled
        coefficients, and reset its carried state. Shared by __init__
        and update_config here and in the actor-critic policies (SAC,
        DDPG) that bypass the base constructor."""
        from ray_tpu.utils.exploration import exploration_from_config

        self.exploration = exploration_from_config(
            self.config,
            self.action_space,
            getattr(self, "model_config", None)
            or self.config.get("model")
            or {},
            default=self.default_exploration,
        )
        self.coeff_values.update(self.exploration.init_coeffs())
        self._expl_state: Tuple = ()
        self._expl_state_batch = -1
        self._last_obs = None  # for ParameterNoise sigma adaptation

    def _refold_exploration_config(self, new_config: Dict) -> None:
        """Hook for subclasses that mirror flat config knobs into
        exploration_config (DQN's epsilon surface)."""

    def _init_coeffs(self) -> None:
        """Subclasses add extra coefficients to self.coeff_values."""

    def _init_aux_state(self) -> Dict[str, Any]:
        """Subclasses return initial aux (non-gradient) state, e.g.
        target-network params."""
        return {}

    def loss(
        self,
        params,
        batch: Dict[str, jnp.ndarray],
        rng: jax.Array,
        coeffs: Dict[str, jnp.ndarray],
    ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        raise NotImplementedError

    def loss_with_aux(self, params, aux, batch, rng, coeffs):
        """Loss entry point inside the learn program. ``aux`` is the
        replicated non-gradient state (e.g. target-network params for
        DQN/SAC — the reference keeps these as separate torch modules);
        base policies ignore it."""
        return self.loss(params, batch, rng, coeffs)

    def extra_action_out(
        self, dist_inputs, value, dist, rng
    ) -> Dict[str, jnp.ndarray]:
        """Extra per-step fetches stored into the SampleBatch
        (reference TorchPolicy.extra_action_out)."""
        return {SampleBatch.VF_PREDS: value}

    # -- model helpers ---------------------------------------------------

    def _dummy_obs(self, batch: int = 2) -> jnp.ndarray:
        shape = self.observation_space.shape
        dtype = self.observation_space.dtype
        return jnp.zeros((batch,) + tuple(shape), dtype)

    def model_forward(
        self,
        params,
        obs,
        state=(),
        resets=None,
        prev_actions=None,
        prev_rewards=None,
    ):
        """Uniform forward: handles recurrent (B, T) vs flat (B,) models.
        Returns (dist_inputs, value, state_out) flattened over (B*T,).
        prev_actions/prev_rewards feed recurrent models configured with
        lstm_use_prev_action/_reward (view-requirement columns)."""
        if self.model.is_recurrent:
            kwargs = {}
            if resets is not None:
                kwargs["resets"] = resets
            if prev_actions is not None:
                kwargs["prev_actions"] = prev_actions
            if prev_rewards is not None:
                kwargs["prev_rewards"] = prev_rewards
            return self.model.apply(params, obs, state, **kwargs)
        return self.model.apply(params, obs)

    def get_initial_state(self) -> List[np.ndarray]:
        return [np.asarray(s[0]) for s in self.model.initial_state(1)]

    def _apply_model_for_actions(self, params, obs, rng, explore):
        """Non-recurrent inference forward inside the jitted action fn.
        Override to thread inference-time randomness into the model
        (e.g. NoisyNet weight noise in the DQN family); ``explore`` is
        static under jit. The default ignores both."""
        return self.model.apply(params, obs)

    # -- param placement (2-D data x model meshes) -----------------------

    def _model_partition_rules(self):
        """Ordered placement rules for this policy's params:
        ``model_config["partition_rules"]`` wins, then the model
        class's escape hatch / own rules (``with_logical_rules`` /
        ``partition_rules()``). None = replicate everything."""
        mc = getattr(self, "model_config", None) or {}
        if mc.get("partition_rules"):
            return tuple(mc["partition_rules"])
        model = getattr(self, "model", None)
        if model is None:
            return None
        ov = getattr(model, "_partition_rules_override", None)
        if ov is not None:
            return tuple(ov)
        fn = getattr(model, "partition_rules", None)
        if callable(fn):
            try:
                rules = fn()
            except TypeError:  # pragma: no cover - odd signatures
                rules = None
            if rules:
                return tuple(rules)
        return None

    def _install_param_placement(self) -> None:
        """Derive per-leaf param specs from the model's rules when the
        mesh has a model axis (docs/sharding.md). Runs on the HOST
        param tree right after model.init, before device placement."""
        if self.sharding_backend != "mesh":
            return
        if sharding_lib.model_axis(self.mesh) is None:
            return
        rules = self._model_partition_rules()
        if not rules:
            return
        self._param_pspecs = sharding_lib.param_pspecs(
            self.params, self.mesh, rules
        )
        self._param_sharding = sharding_lib.named_tree(
            self.mesh, self._param_pspecs
        )

    @property
    def param_shardings(self):
        """Per-leaf NamedSharding tree of the params (a single
        replicated NamedSharding on un-partitioned policies) — the
        placement serve/rollout/checkpoint call sites must use instead
        of assuming replication."""
        return self._param_sharding

    @property
    def param_pspecs(self):
        """PartitionSpec tree of the params; None = replicated."""
        return self._param_pspecs

    @property
    def is_model_sharded(self) -> bool:
        """Whether params are actually split across a model axis of
        size > 1 (a size-1 axis keeps every leaf whole — the parity
        geometry)."""
        return (
            self._param_pspecs is not None
            and sharding_lib.model_shards(self.mesh) > 1
        )

    def _params_match_active_rules(self) -> bool:
        """Do the live param arrays sit where the active rules say
        (same mesh, per-leaf placement)? False e.g. after a raw
        device_put replaced the tree — the serve plane gates its fused
        forward on this."""
        if self._param_pspecs is None:
            return True
        try:
            arrs = jax.tree_util.tree_leaves(self.params)
            wants = jax.tree_util.tree_leaves(
                self._param_sharding,
                is_leaf=lambda x: isinstance(x, NamedSharding),
            )
            if len(arrs) != len(wants):
                return False
            for arr, want in zip(arrs, wants):
                s = getattr(arr, "sharding", None)
                if s is None or not s.is_equivalent_to(want, arr.ndim):
                    return False
            return True
        except Exception:
            return False

    def _carry_pspecs(self, with_frames: bool = False):
        """(params, opt_state, aux) PartitionSpec trees for learn-
        program construction — bare ``P()`` everywhere on the
        replicated path, per-leaf trees when partitioned (aux leaves
        suffix-match the params they mirror, e.g. target networks)."""
        if self._param_pspecs is None:
            return P(), P(), P()
        p_ps = self._param_pspecs
        o_ps = (
            self._opt_pspecs
            if self._opt_pspecs is not None
            else sharding_lib.state_pspecs(
                self.opt_state, self.params, p_ps
            )
        )
        a_ps = sharding_lib.state_pspecs(
            self.aux_state, self.params, p_ps
        )
        if with_frames and isinstance(a_ps, dict):
            a_ps = {"__frames__": P(), **a_ps}
        return p_ps, o_ps, a_ps

    def _publish_params_bytes(self) -> None:
        """``ray_tpu_params_bytes{policy,placement}``: global tree
        bytes + what one device holds under the active placement."""
        try:
            total = sharding_lib.tree_nbytes(self.params)
            if self._param_pspecs is not None:
                per_shard = sharding_lib.tree_shard_nbytes(
                    self.params, self._param_pspecs, self.mesh
                )
            else:
                per_shard = total
            telemetry_metrics.set_params_bytes(
                type(self).__name__, total, per_shard
            )
        except Exception:  # telemetry must never break the policy
            pass

    # -- inference -------------------------------------------------------

    def _action_step_body(
        self, params, obs, rng, coeffs, *, explore=True, expl_state=()
    ):
        """The non-recurrent per-step action computation — model
        forward, distribution, exploration sampling, extra fetches —
        as a pure traced body: ``(actions, state_out, extra,
        expl_state)``. Shared by the jitted ``compute_actions``
        program (:meth:`_build_action_fn`) and the device rollout lane
        (``execution/jax_rollout.py``), with the SAME internal rng
        split structure, so the two rollout lanes consume identical
        key streams per step (the fixed-seed parity contract of
        docs/pipeline.md)."""
        rng_m, rng = jax.random.split(rng)
        dist_inputs, value, state_out = self._apply_model_for_actions(
            params, obs, rng_m, explore
        )
        dist = self.dist_class(dist_inputs)
        rng_x, rng = jax.random.split(rng)
        actions, logp, expl_state = self.exploration.sample_fn(
            dist, rng_x, explore, coeffs, expl_state
        )
        extra = {
            SampleBatch.ACTION_DIST_INPUTS: dist_inputs,
            SampleBatch.ACTION_LOGP: logp,
        }
        extra.update(
            self.extra_action_out(dist_inputs, value, dist, rng)
        )
        return actions, state_out, extra, expl_state

    @property
    def supports_batched_serve(self) -> bool:
        """Whether concurrent single-request inference may coalesce
        into the serve plane's fused batched forward
        (``serve/policy_server.py``): the program vmaps
        :meth:`_action_step_body` over per-request rng keys, so it
        needs a feedforward model and stateless exploration (carried
        OU/ParameterNoise state is per-stream, and a request stream
        has no stable slot identity). Ineligible policies still serve,
        one ``compute_actions`` per request."""
        return (
            not self.model.is_recurrent
            and not self.exploration.needs_last_obs
            and self.exploration.initial_state(1) == ()
            # model-sharded params may fuse only while the serve mesh
            # is the training mesh with params placed per the active
            # rules (the fused forward carries the per-leaf shardings);
            # anything else falls back to per-request compute_actions
            # through the same queue (docs/serving.md)
            and (
                not self.is_model_sharded
                or self._params_match_active_rules()
            )
        )

    @property
    def supports_jax_rollout(self) -> bool:
        """Whether this policy's act path can lower into the device
        rollout lane's scanned program (``execution/jax_rollout.py``):
        feedforward model, stateless exploration, mesh backend (the
        rollout program carries explicit shardings). Recurrent unrolls
        and stateful exploration (OU noise, ParameterNoise) stay on
        the actor lane."""
        return (
            not self.model.is_recurrent
            and self.sharding_backend == "mesh"
            and not self.exploration.needs_last_obs
            and self.exploration.initial_state(1) == ()
        )

    def _build_action_fn(self):
        model = self.model
        dist_class = self.dist_class
        recurrent = model.is_recurrent
        use_prev_a = recurrent and getattr(
            model, "use_prev_action", False
        )
        use_prev_r = recurrent and getattr(
            model, "use_prev_reward", False
        )
        exploration = self.exploration

        def fn(
            params, obs, states, rng, explore, coeffs, expl_state,
            prev_a, prev_r,
        ):
            if not recurrent:
                return self._action_step_body(
                    params, obs, rng, coeffs,
                    explore=explore, expl_state=expl_state,
                )
            kwargs = {}
            if use_prev_a:
                kwargs["prev_actions"] = prev_a[:, None]
            if use_prev_r:
                kwargs["prev_rewards"] = prev_r[:, None]
            dist_inputs, value, state_out = model.apply(
                params, obs[:, None], states, **kwargs
            )
            dist = dist_class(dist_inputs)
            rng_x, rng = jax.random.split(rng)
            actions, logp, expl_state = exploration.sample_fn(
                dist, rng_x, explore, coeffs, expl_state
            )
            extra = {
                SampleBatch.ACTION_DIST_INPUTS: dist_inputs,
                SampleBatch.ACTION_LOGP: logp,
            }
            extra.update(self.extra_action_out(dist_inputs, value, dist, rng))
            return actions, state_out, extra, expl_state

        return jax.jit(fn, static_argnames=("explore",))

    def compute_actions(
        self,
        obs_batch,
        state_batches=None,
        prev_action_batch=None,
        prev_reward_batch=None,
        explore: bool = True,
        timestep: Optional[int] = None,
        **kwargs,
    ):
        if self._action_fn is None:
            self._action_fn = self._build_action_fn()
        self.exploration.update_coeffs(
            self.coeff_values, self.global_timestep
        )
        params = self.exploration.params_for_inference(self, explore)
        self._rng, rng = jax.random.split(self._rng)
        obs = jnp.asarray(obs_batch)
        if self.exploration.needs_last_obs:
            self._last_obs = obs
        states = tuple(jnp.asarray(s) for s in (state_batches or ()))
        bsize = int(obs.shape[0])
        if self._expl_state_batch != bsize:
            self._expl_state = self.exploration.initial_state(bsize)
            self._expl_state_batch = bsize
        # prev-action/reward inputs for recurrent models that want them
        # (zeros at episode starts / when the caller passes nothing)
        if prev_action_batch is not None:
            prev_a = jnp.asarray(prev_action_batch)
        else:
            prev_a = jnp.zeros(
                (bsize,) + tuple(self.action_space.shape), jnp.float32
            ) if self.action_space.shape else jnp.zeros(
                (bsize,), jnp.int32
            )
        prev_r = (
            jnp.asarray(prev_reward_batch, jnp.float32)
            if prev_reward_batch is not None
            else jnp.zeros((bsize,), jnp.float32)
        )
        actions, state_out, extra, self._expl_state = self._action_fn(
            params, obs, states, rng, bool(explore),
            self._coeff_array(), self._expl_state, prev_a, prev_r,
        )
        return (
            np.asarray(actions),
            [np.asarray(s) for s in state_out],
            {k: np.asarray(v) for k, v in extra.items()},
        )

    def compute_log_likelihoods(
        self, actions, obs_batch, state_batches=None
    ) -> np.ndarray:
        """Log-prob of given actions under the current policy (reference
        Policy.compute_log_likelihoods :660 — used by the IS/WIS
        off-policy estimators). Deliberately NOT jitted: callers pass
        variable-length per-episode slices, and a jit cache keyed on
        every distinct episode length would recompile constantly for a
        sub-millisecond MLP forward."""
        dist_inputs, _, _ = self.model_forward(
            self.params, jnp.asarray(obs_batch)
        )
        return np.asarray(
            self.dist_class(dist_inputs).logp(jnp.asarray(actions))
        )

    def value_batch(self, obs_batch, state_batches=None) -> np.ndarray:
        """Bootstrap values for GAE (reference ppo value branch)."""
        if self._value_fn is None:
            model = self.model

            def fn(params, obs, states):
                if model.is_recurrent:
                    _, value, _ = model.apply(params, obs[:, None], states)
                else:
                    _, value, _ = model.apply(params, obs)
                return value

            self._value_fn = jax.jit(fn)
        states = tuple(jnp.asarray(s) for s in (state_batches or ()))
        return np.asarray(
            self._value_fn(self.params, jnp.asarray(obs_batch), states)
        )

    # -- learning --------------------------------------------------------

    def _coeff_array(self) -> Dict[str, jnp.ndarray]:
        # Cache device scalars; re-transfer only the coefficients whose
        # host values changed (each put is a host→device round trip).
        cache = getattr(self, "_coeff_cache", None)
        if cache is None:
            cache = self._coeff_cache = {}
        out = {}
        for k, v in self.coeff_values.items():
            ent = cache.get(k)
            if ent is None or ent[0] != v:
                ent = (v, jnp.asarray(v, jnp.float32))
                cache[k] = ent
            out[k] = ent[1]
        return out

    def _update_scheduled_coeffs(self):
        t = self.global_timestep
        self.coeff_values["lr"] = float(self._lr_schedule(t))
        self.coeff_values["entropy_coeff"] = float(self._entropy_schedule(t))

    def _nest_device_fn(self, batch_size: int, with_frames: bool = False):
        """The per-batch SGD-nest device body —
        ``(params, opt_state, aux, batch, rng, coeffs) -> (params,
        opt_state, stats)`` — shared by the per-call learn program
        (:meth:`_build_learn_fn`) and the fused superstep scan
        (:meth:`learn_superstep`): both wrap THIS body, so the fused
        chain is bit-identical to per-call dispatch. Runs inside
        ``shard_map`` (uses the mesh collectives)."""
        n_shards = self.n_shards
        stack_k = int(self.observation_space.shape[-1]) if (
            with_frames
        ) else 0
        if batch_size % n_shards:
            raise ValueError(
                f"batch size {batch_size} not divisible by "
                f"{n_shards} data shards"
            )
        b_loc = max(1, batch_size // n_shards)
        mb_loc = min(b_loc, max(1, self.minibatch_size // n_shards))
        # recurrent: shuffle/gather whole T-row sequences, never rows
        T_seq = self._unroll_T
        if T_seq > 1:
            if b_loc % T_seq:
                raise ValueError(
                    f"per-shard batch {b_loc} not a multiple of "
                    f"max_seq_len={T_seq}"
                )
            mb_loc = max(T_seq, (mb_loc // T_seq) * T_seq)
        num_mb = max(1, b_loc // mb_loc)
        num_iters = self.num_sgd_iter
        tx = self._tx
        mesh = self.mesh
        # data axis name comes from the mesh: "batch" on the sharding
        # runtime's meshes, "data" on legacy/pmap ones — the program
        # must not hard-code either
        axis = sharding_lib.data_axis(mesh)
        loss_fn = self.loss_with_aux

        rebuild_obs = self._rebuild_obs_from_frames

        def device_fn(params, opt_state, aux, batch, rng, coeffs):
            if with_frames:
                # rebuild stacked observations from the replicated
                # frame pool (ops/framestack): one gather, then the
                # nest proceeds on ordinary row columns (policies with
                # non-flat obs layouts override the hook)
                frames = aux["__frames__"]
                aux = {
                    k: v for k, v in aux.items() if k != "__frames__"
                }
                batch = rebuild_obs(frames, batch, stack_k)
            # Different shuffle stream per data shard.
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

            # uint8 row columns (pixel obs) gather 3-4x faster viewed
            # as uint32 lanes (measured: 127 -> 420 GB/s effective on
            # v5e — narrow-element gathers are element-width-bound),
            # so pack them once per nest and unpack per minibatch
            packed_shapes = {}
            batch = dict(batch)
            for k, v in list(batch.items()):
                if (
                    v.dtype == jnp.uint8
                    and v.ndim >= 2
                    and int(np.prod(v.shape[1:])) % 4 == 0
                ):
                    packed_shapes[k] = v.shape
                    batch[k] = jax.lax.bitcast_convert_type(
                        v.reshape(v.shape[0], -1, 4), jnp.uint32
                    )

            def _unpack(k, v):
                shp = packed_shapes.get(k)
                if shp is None:
                    return v
                u8 = jax.lax.bitcast_convert_type(v, jnp.uint8)
                return u8.reshape((v.shape[0],) + shp[1:])

            def mb_step(carry, mb_rng_idx):
                params, opt_state = carry
                idx, mb_rng, is_last = mb_rng_idx
                # __chunk__ columns hold one row per T-row unroll
                # (chunk-start recurrent states); gather them by the
                # unroll indices the row permutation selected
                mb = {
                    k: _unpack(
                        k,
                        (
                            v[idx.reshape(-1, T_seq)[:, 0] // T_seq]
                            if k.startswith("__chunk__")
                            else v[idx]
                        ),
                    )
                    for k, v in batch.items()
                }
                (loss, stats), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params, aux, mb, mb_rng, coeffs)
                grads = jax.lax.pmean(grads, axis)
                updates, opt_state = tx.update(grads, opt_state, params)
                lr = coeffs["lr"]
                updates = jax.tree_util.tree_map(
                    lambda u: -lr * u.astype(jnp.float32), updates
                )
                params = optax.apply_updates(params, updates)
                # grad_gnorm: FINAL minibatch only. The 12-leaf
                # reduce+sqrt chain measures ~2x the model's own
                # fwd+bwd per step on this backend (profile_nest2),
                # so running it every step nearly halves nest MFU;
                # the reference's torch learner likewise reports the
                # last batch's extra_grad_info per update.
                gnorm = jax.lax.cond(
                    is_last,
                    lambda: optax.global_norm(grads),
                    lambda: jnp.float32(0.0),
                )
                stats = dict(stats, total_loss=loss, grad_gnorm=gnorm)
                return (params, opt_state), stats

            def epoch(carry, rng_e_i):
                rng_e, ep_i = rng_e_i
                perm_rng, scan_rng = jax.random.split(rng_e)
                if T_seq > 1:
                    seq_perm = jax.random.permutation(
                        perm_rng, b_loc // T_seq
                    )
                    perm = (
                        seq_perm[:, None] * T_seq
                        + jnp.arange(T_seq)[None, :]
                    ).reshape(-1)
                else:
                    perm = jax.random.permutation(perm_rng, b_loc)
                idx = perm[: num_mb * mb_loc].reshape(num_mb, mb_loc)
                mb_rngs = jax.random.split(scan_rng, num_mb)
                is_last = (ep_i == num_iters - 1) & (
                    jnp.arange(num_mb) == num_mb - 1
                )
                carry, stats = jax.lax.scan(
                    mb_step, carry, (idx, mb_rngs, is_last)
                )
                return carry, stats

            rngs = jax.random.split(rng, num_iters)
            (params, opt_state), stats = jax.lax.scan(
                epoch,
                (params, opt_state),
                (rngs, jnp.arange(num_iters)),
            )

            # mean over epochs × minibatches, then over shards —
            # except grad_gnorm, which only the final step computed
            # (every other entry is 0, so the sum IS that value)
            def reduce_stat(name, x):
                agg = x.sum() if name == "grad_gnorm" else x.mean()
                return jax.lax.pmean(agg, axis)

            stats = {
                k: reduce_stat(k, v) for k, v in stats.items()
            }
            return params, opt_state, stats

        return device_fn

    def _build_learn_fn(self, batch_size: int, with_frames: bool = False):
        """Compile the full SGD nest for a given total batch size."""
        device_fn = self._nest_device_fn(
            batch_size, with_frames=with_frames
        )
        mesh = self.mesh
        axis = sharding_lib.data_axis(mesh)
        # per-leaf carry specs: bare P() (replicated) on the legacy
        # path, the rule-derived trees when partitioned — the body
        # then sees LOCAL param slices and the model inserts its own
        # model-axis collectives (models/transformer.py)
        p_ps, o_ps, a_ps = self._carry_pspecs(with_frames=with_frames)
        sharded = jax.shard_map(
            device_fn,
            mesh=mesh,
            in_specs=(p_ps, o_ps, a_ps, P(axis), P(), P()),
            out_specs=(p_ps, o_ps, P()),
        )
        # Donate only opt_state: params buffers must stay valid because an
        # async sampler thread may be running compute_actions with them
        # concurrently (IMPALA sync mode shares the policy object).
        label = f"learn[{type(self).__name__}:{batch_size}]"
        if self.sharding_backend == "mesh":
            # explicit placement: params/opt/aux per their spec trees
            # (all-replicated on the legacy path), rng/coeffs
            # replicated, batch row-sharded — jit broadcasts one
            # sharding over each argument's pytree leaves, and the
            # compile layer tracks retraces (compile-cache stats)
            rep = sharding_lib.replicated(mesh)
            p_sh = self._param_sharding
            o_sh = self._opt_sharding or p_sh
            a_sh = (
                sharding_lib.named_tree(mesh, a_ps)
                if self._param_pspecs is not None
                else rep
            )
            dat = self._data_sharding
            return sharding_lib.sharded_jit(
                sharded,
                in_specs=(p_sh, o_sh, a_sh, dat, rep, rep),
                out_specs=(p_sh, o_sh, rep),
                donate_argnums=(1,),
                label=label,
            )
        # pmap-era fallback: placement left to device_put, same program
        return sharding_lib.sharded_jit(
            sharded, donate_argnums=(1,), label=label
        )

    # -- superstep: K updates per dispatch (docs/data_plane.md) ----------

    # Policies whose update body can't ride the generic scan (sequence
    # replay with per-chunk state handling) set this True to opt out
    # even when they kept the base learn program.
    _superstep_opt_out = False

    @property
    def supports_superstep(self) -> bool:
        """Whether K updates of this policy may fuse into one
        ``lax.scan`` dispatch (:meth:`learn_superstep`). True only when
        the subclass kept the base learn-program composition — the
        superstep scan is built from :meth:`_device_update_fn`, so a
        policy that replaced :meth:`_build_learn_fn` wholesale
        (AlphaZero, QMIX, MADDPG, SlateQ) must chain per-call. The
        actor-critic families override this with their own identity
        checks. Requires the mesh backend (the scan program carries
        explicit shardings)."""
        return (
            not self._superstep_opt_out
            and self.sharding_backend == "mesh"
            and type(self)._build_learn_fn is JaxPolicy._build_learn_fn
            and type(self)._nest_device_fn is JaxPolicy._nest_device_fn
            and type(self)._device_update_fn
            is JaxPolicy._device_update_fn
        )

    def _device_update_fn(self, batch_size=None, with_frames=False):
        """Uniform single-update device body for the superstep scan:
        ``(params, opt_state, aux, batch, rng, coeffs) -> (params,
        opt_state, aux, stats)``. The base policy wraps the per-batch
        SGD nest (aux — target nets etc. — passes through unchanged);
        actor-critic policies (SAC/DDPG) override with bodies that
        thread their aux through the update."""
        nest = self._nest_device_fn(
            int(batch_size), with_frames=with_frames
        )

        def update_fn(params, opt_state, aux, batch, rng, coeffs):
            if with_frames:
                # per-update frame pool rides the batch tree (the
                # per-call path ships it via aux; inside a scan each
                # slot has its own pool)
                batch = dict(batch)
                frames = batch.pop(_FRAMES)
                params, opt_state, stats = nest(
                    params,
                    opt_state,
                    {"__frames__": frames, **aux},
                    batch,
                    rng,
                    coeffs,
                )
            else:
                params, opt_state, stats = nest(
                    params, opt_state, aux, batch, rng, coeffs
                )
            return params, opt_state, aux, stats

        return update_fn

    def _wrap_update_program(self, update_fn, batch_size: int):
        """shard_map + sharded_jit wrap of a 4-output single-update
        body — the one per-call learn-program shape the actor-critic
        family (SAC/DDPG/CQL/CRR) shares."""
        mesh = self.mesh
        axis = sharding_lib.data_axis(mesh)
        p_ps, o_ps, a_ps = self._carry_pspecs()
        sharded = jax.shard_map(
            update_fn,
            mesh=mesh,
            in_specs=(p_ps, o_ps, a_ps, P(axis), P(), P()),
            out_specs=(p_ps, o_ps, a_ps, P()),
        )
        label = f"learn[{type(self).__name__}:{batch_size}]"
        if self.sharding_backend == "mesh":
            rep = sharding_lib.replicated(mesh)
            p_sh = self._param_sharding
            o_sh = self._opt_sharding or p_sh
            a_sh = (
                sharding_lib.named_tree(mesh, a_ps)
                if self._param_pspecs is not None
                else rep
            )
            dat = self._data_sharding
            return sharding_lib.sharded_jit(
                sharded,
                in_specs=(p_sh, o_sh, a_sh, dat, rep, rep),
                out_specs=(p_sh, o_sh, a_sh, rep),
                donate_argnums=(1,),
                label=label,
            )
        return sharding_lib.sharded_jit(
            sharded, donate_argnums=(1,), label=label
        )

    def _learn_coeffs(self):
        """Host coefficients the learn program consumes this call —
        what the per-update path passes, so the superstep matches it.
        Frozen across a superstep's K updates (staleness contract:
        docs/data_plane.md)."""
        self._update_scheduled_coeffs()
        return self._coeff_array()

    def _updates_per_learn_call(self, batch_size: int) -> int:
        """num_grad_updates increment of ONE learn call (the base nest
        runs num_sgd_iter × minibatches; actor-critic bodies one)."""
        return self.num_sgd_iter * max(
            1, batch_size // max(1, self.minibatch_size)
        )

    # Whether the per-update PER priority refresh consumes a host rng
    # split (SAC/DDPG: always; DQN: only under NoisyNet) — the
    # superstep must replay the exact split order of the per-update
    # path for bit parity.
    @property
    def _td_refresh_uses_rng(self) -> bool:
        return False

    def _td_error_device_fn(self):
        """Per-sample TD-error device body ``(params, aux, batch, rng)
        -> (B,)`` for the in-scan prioritized-replay refresh; None for
        policies without per-sample errors (the caller falls back to
        the batch-mean scalar, like ``DQN._single_update``)."""
        return None

    def _after_superstep(self) -> None:
        """Hook: host-side cache invalidation after a fused chain
        moved the params (SAC drops its device-flattened actor
        snapshots here)."""

    # ray-tpu: hot-path
    def _active_mask(self, k: int, k_max: int) -> np.ndarray:
        """The (k_max,) float32 active mask for a k-of-k_max superstep,
        cached per (k, k_max): the mask is read-only on the device side
        so the same host array serves every dispatch (one less per-call
        allocation on the dieted path)."""
        masks = self.__dict__.setdefault("_active_masks", {})
        m = masks.get((k, k_max))
        if m is None:
            m = np.zeros(k_max, np.float32)
            m[:k] = 1.0
            masks[(k, k_max)] = m
        return m

    # ray-tpu: hot-path
    def _superstep_host_keys(self, k, k_max, refresh, td_rng):
        """The superstep's host key schedule as ONE fused program: the
        sequential split chain (learn split, then the optional td
        split, per update) unrolls inside a single jitted function
        that returns the advanced stream plus the padded (k_max, 2)
        key stacks. threefry splitting is a deterministic integer
        function of the key, so composing the chain inside one program
        yields bit-identical keys and final stream to k (or 2k)
        individual host splits — only the dispatch count changes
        (bench.py --dispatch measures exactly this collapse)."""
        fns = self.__dict__.setdefault("_split_chain_fns", {})
        sig = ("superstep", k, k_max, bool(refresh), bool(td_rng))
        fn = fns.get(sig)
        if fn is None:

            def chain(rng):
                keys, pri_keys = [], []
                for _ in range(k):
                    rng, r = jax.random.split(rng)
                    keys.append(r)
                    if refresh:
                        if td_rng:
                            rng, r2 = jax.random.split(rng)
                        else:
                            r2 = jnp.zeros_like(r)
                        pri_keys.append(r2)
                pad = jnp.zeros_like(keys[0])
                keys += [pad] * (k_max - k)
                if refresh:
                    pri_keys += [pad] * (k_max - k)
                    return rng, jnp.stack(keys), jnp.stack(pri_keys)
                return rng, jnp.stack(keys)

            fn = jax.jit(chain)
            fns[sig] = fn
        out = fn(self._rng)
        self._rng = out[0]
        return out[1], (out[2] if refresh else None)

    # ray-tpu: hot-path
    def _rollout_host_keys(self, k, k_max, T):
        """Fused host key schedule for the rollout superstep: per slot,
        T rollout splits then the learn split — k*(T+1) sequential
        splits as ONE dispatch. The T-loop runs as a lax.scan of the
        same split, which composes the identical threefry chain, so
        the stacks are bit-identical to the sequential host loop."""
        fns = self.__dict__.setdefault("_split_chain_fns", {})
        sig = ("rollout", k, k_max, T)
        fn = fns.get(sig)
        if fn is None:

            def chain(rng):
                def one_split(rng, _):
                    rng, r = jax.random.split(rng)
                    return rng, r

                learn_keys, ro_keys = [], []
                for _ in range(k):
                    rng, slot = jax.lax.scan(
                        one_split, rng, None, length=T
                    )
                    ro_keys.append(slot)
                    rng, r = jax.random.split(rng)
                    learn_keys.append(r)
                pad = jnp.zeros_like(learn_keys[0])
                pad_slot = jnp.zeros_like(ro_keys[0])
                learn_keys += [pad] * (k_max - k)
                ro_keys += [pad_slot] * (k_max - k)
                return rng, jnp.stack(learn_keys), jnp.stack(ro_keys)

            fn = jax.jit(chain)
            fns[sig] = fn
        rng, rngs, ro_rngs = fn(self._rng)
        self._rng = rng
        return rngs, ro_rngs

    def learn_superstep(
        self,
        k: int,
        batch_size: int,
        *,
        stacked=None,
        rings=None,
        k_max: Optional[int] = None,
        refresh_priorities: bool = False,
    ):
        """Run ``k`` updates as ONE compiled program (the uniform
        superstep contract — docs/data_plane.md): one dispatch, one
        stats readback, weights never bounce through the host between
        updates. Bit-identical to ``k`` sequential
        ``learn_on_device_batch(..., defer_stats=True)`` calls on the
        same batches (same device body, same host rng-split order;
        host-side ``after_learn_on_batch`` reactions lag the chain —
        callers that need them apply them to the drained stats).

        Feed (exactly one):
          - ``stacked``: ``(k_max, B, ...)`` column tree — host numpy
            (one H2D for the whole superstep) or already-resident
            device arrays (PPO's prefetched batches, zero H2D).
          - ``rings``: a :class:`~ray_tpu.execution.replay_buffer
            .DeviceReplayBuffer` feed (``buf.superstep_feed(idx,
            extra)``) — the scan gathers each update's rows from the
            device rings in place; only the ``(k_max, B)`` index array
            (and PER weights) cross the wire.

        ``k_max`` fixes the compiled scan length; any ``k <= k_max``
        runs through the same executable via the active mask (no
        per-K recompile — ``compile_stats()``-asserted in tests).
        ``refresh_priorities`` runs the per-sample TD-error body after
        each update (post-update state, per-update order) and returns
        the stacked ``|td|`` matrix in one D2H.

        Returns ``(infos, priorities, skipped)``: per-update host stat
        dicts (update order), the ``(k, B)`` priority matrix (None
        unless refreshing), and the per-update nan-guard skip flags.
        """
        import time as _time

        if (stacked is None) == (rings is None):
            raise ValueError(
                "learn_superstep needs exactly one of stacked/rings"
            )
        k = int(k)
        k_max = int(k_max or k)
        if not 1 <= k <= k_max:
            raise ValueError(f"k={k} outside [1, k_max={k_max}]")
        nan_guard = bool(self.config.get("nan_guard"))
        with_frames = stacked is not None and _FRAMES in stacked
        pri_fn = (
            self._td_error_device_fn() if refresh_priorities else None
        )
        if refresh_priorities and pri_fn is None:
            raise ValueError(
                f"{type(self).__name__} has no per-sample TD-error "
                "body; gate refresh_priorities on "
                "policy._td_error_device_fn() is not None"
            )

        from ray_tpu.sharding import superstep as superstep_lib

        if rings is not None:
            cache_mode = ("rings", rings.key, tuple(sorted(rings.extra)))
        else:
            cache_mode = ("stacked", tuple(sorted(stacked)))
        cache_key = (
            batch_size, k_max, cache_mode, refresh_priorities, nan_guard,
        )
        fns = self.__dict__.setdefault("_superstep_fns", {})
        fn = fns.get(cache_key)
        if fn is None:
            kwargs = dict(
                mesh=self.mesh,
                backend=self.sharding_backend,
                k=k_max,
                label=(
                    f"superstep[{type(self).__name__}:"
                    f"{batch_size}x{k_max}]"
                ),
                priority_fn=pri_fn,
                nan_guard=nan_guard,
                # per-leaf (params, opt, aux) placement threads
                # through the scan carry + donation unchanged
                carry_pspecs=(
                    self._carry_pspecs()
                    if self._param_pspecs is not None
                    else None
                ),
            )
            if rings is not None:
                kwargs.update(
                    gather_fn=rings.gather_fn,
                    store_shardings=rings.shardings,
                    extra_cols=tuple(sorted(rings.extra)),
                )
            else:
                kwargs.update(
                    stacked_cols=tuple(sorted(stacked)),
                    replicated_cols=(_FRAMES,) if with_frames else (),
                )
            fn = superstep_lib.build_superstep_fn(
                self._device_update_fn(
                    batch_size, with_frames=with_frames
                ),
                **kwargs,
            )
            fns[cache_key] = fn

        coeffs = self._learn_coeffs()
        # exact per-update host split order: learn split, then (iff the
        # per-update priority pass consumes one) the td split. On the
        # dieted path the whole chain runs as ONE fused program (k or
        # 2k tiny split dispatches collapse to one — the dominant
        # per-superstep host cost at K=8, bench.py --dispatch); the
        # chain composes the same threefry splits in the same order,
        # so the key stacks and the advanced self._rng are bit-
        # identical to the sequential host loop.
        td_rng = refresh_priorities and self._td_refresh_uses_rng
        if sharding_lib.dispatch_diet_enabled():
            rngs, pri = self._superstep_host_keys(
                k, k_max, refresh_priorities, td_rng
            )
            rest = (pri,) if refresh_priorities else ()
        else:
            keys, pri_keys = [], []
            for _ in range(k):
                self._rng, r = jax.random.split(self._rng)
                keys.append(r)
                if refresh_priorities:
                    if td_rng:
                        self._rng, r2 = jax.random.split(self._rng)
                    else:
                        r2 = jnp.zeros_like(r)
                    pri_keys.append(r2)
            pad_key = jnp.zeros_like(keys[0])
            while len(keys) < k_max:
                keys.append(pad_key)
            rngs = jnp.stack(keys)
            rest = ()
            if refresh_priorities:
                while len(pri_keys) < k_max:
                    pri_keys.append(pad_key)
                rest = (jnp.stack(pri_keys),)
        active = self._active_mask(k, k_max)

        if rings is not None:
            feed = (rings.store, rings.idx, rings.extra)
            # sample-path payload: the pre-drawn index matrix + stacked
            # extra columns, counted only when they actually cross
            # H2D — a device-tree draw hands device arrays here and
            # the sample path ships zero payload bytes
            telemetry_metrics.add_h2d_bytes(
                "replay_sample",
                sum(
                    v.nbytes
                    for v in (
                        rings.idx,
                        *rings.extra.values(),
                    )
                    if not isinstance(v, jax.Array)
                ),
            )
        else:
            feed = stacked
            if not any(
                isinstance(v, jax.Array) for v in stacked.values()
            ):
                telemetry_metrics.add_h2d_bytes(
                    "learn", sharding_lib.tree_nbytes(stacked)
                )

        compiles_before = getattr(fn, "traces", 0)
        t0 = _time.perf_counter()
        with tracing.start_span(
            "learn:superstep", k=k, batch_size=batch_size
        ) as _sp:
            out = fn(
                self.params,
                self.opt_state,
                self.aux_state,
                feed,
                active,
                rngs,
                *rest,
                coeffs,
            )
            if refresh_priorities:
                (
                    self.params, self.opt_state, self.aux_state,
                    stats, pri,
                ) = out
            else:
                self.params, self.opt_state, self.aux_state, stats = out
                pri = None
            _sp.set_attribute(
                "recompiles",
                getattr(fn, "traces", 0) - compiles_before,
            )
            # ONE drain for the whole chain: the stacked stats tree
            # (and the PER priority matrix) come back in a single
            # device→host readback
            if pri is not None:
                # ray-tpu: allow[RTA005] the ONE counted drain for the chain
                stats, pri = jax.device_get((stats, pri))
                pri = np.abs(np.asarray(pri)[:k])
                # the |td| pull that feeds the host alpha-power — the
                # PER path's one remaining D2H (docs/data_plane.md)
                telemetry_metrics.add_d2h_bytes(
                    "replay_priorities", pri.nbytes
                )
            else:
                # ray-tpu: allow[RTA005] the ONE counted drain for the chain
                stats = jax.device_get(stats)
            # the drain proves the superstep program finished: close
            # its device-busy interval in the ledger (timestamps only,
            # no extra sync)
            device_ledger.drain_point()
        self.num_grad_updates += k * self._updates_per_learn_call(
            batch_size
        )
        self._after_superstep()
        telemetry_metrics.counter(
            telemetry_metrics.LEARN_STEPS_TOTAL,
            "SGD-nest programs dispatched",
        ).inc(float(k))
        telemetry_metrics.inc_superstep_updates(k)
        self.last_learn_timers["learn_superstep_s"] = (
            _time.perf_counter() - t0
        )
        self.last_learn_timers["learn_recompiles"] = float(
            getattr(fn, "traces", 0) - compiles_before
        )

        skip = np.asarray(
            stats.get(superstep_lib.SKIP_KEY, np.zeros(k_max))
        )
        skipped = [bool(skip[i] > 0.5) for i in range(k)]
        infos = [
            {
                name: float(np.asarray(v)[i])
                for name, v in stats.items()
                if name != superstep_lib.SKIP_KEY
            }
            for i in range(k)
        ]
        return infos, pri, skipped

    # ray-tpu: hot-path
    def learn_rollout_superstep(
        self,
        k: int,
        batch_size: int,
        rollout,
        *,
        k_max: Optional[int] = None,
    ):
        """Fused rollout+learn superstep (docs/data_plane.md): ``k``
        iterations of [roll out T env steps on the mesh → postprocess
        → one SGD-nest update] as ONE compiled program — the device
        rollout lane's hot path. The only H2D payload is the key
        stacks and the active mask; rollout rows never exist on the
        host.

        ``rollout`` is the engine's feed descriptor
        (``execution/jax_rollout.RolloutSuperstepFeed``): ``carry``
        the device-resident env carry, ``body`` the per-shard rollout
        function the scan slot calls, ``steps`` the env steps per
        slot, ``key`` the compile-cache key.

        Host rng split order per slot — ``steps`` rollout splits, then
        the learn split — matches the actor lane's local-worker
        stream (one ``compute_actions`` split per env step, then
        ``learn_on_batch``'s), the fixed-seed parity contract.

        Returns ``(infos, carry, metrics, skipped)``: per-update host
        stat dicts, the advanced env carry (feed it back next call),
        the stacked per-slot metrics tree (host numpy), and per-update
        nan-guard skip flags.
        """
        import time as _time

        k = int(k)
        k_max = int(k_max or k)
        if not 1 <= k <= k_max:
            raise ValueError(f"k={k} outside [1, k_max={k_max}]")
        nan_guard = bool(self.config.get("nan_guard"))

        from ray_tpu.sharding import superstep as superstep_lib

        cache_key = ("rollout", batch_size, k_max, rollout.key, nan_guard)
        fns = self.__dict__.setdefault("_superstep_fns", {})
        fn = fns.get(cache_key)
        if fn is None:
            fn = superstep_lib.build_superstep_fn(
                self._device_update_fn(batch_size),
                mesh=self.mesh,
                backend=self.sharding_backend,
                k=k_max,
                label=(
                    f"rollout_superstep[{type(self).__name__}:"
                    f"{batch_size}x{k_max}]"
                ),
                rollout_fn=rollout.body,
                nan_guard=nan_guard,
                carry_pspecs=(
                    self._carry_pspecs()
                    if self._param_pspecs is not None
                    else None
                ),
            )
            fns[cache_key] = fn

        coeffs = self._learn_coeffs()
        T = int(rollout.steps)
        # host rng schedule: T rollout splits then the learn split per
        # slot. Dieted path fuses the whole k*(T+1)-split chain into
        # ONE dispatch (bit-identical keys — same threefry chain, same
        # order); see learn_superstep.
        if sharding_lib.dispatch_diet_enabled():
            rngs, ro_rngs = self._rollout_host_keys(k, k_max, T)
        else:
            learn_keys, ro_keys = [], []
            for _ in range(k):
                slot = []
                for _ in range(T):
                    self._rng, r = jax.random.split(self._rng)
                    slot.append(r)
                ro_keys.append(jnp.stack(slot))
                self._rng, r = jax.random.split(self._rng)
                learn_keys.append(r)
            pad = jnp.zeros_like(learn_keys[0])
            pad_slot = jnp.zeros_like(ro_keys[0])
            while len(learn_keys) < k_max:
                learn_keys.append(pad)
                ro_keys.append(pad_slot)
            rngs = jnp.stack(learn_keys)
            ro_rngs = jnp.stack(ro_keys)
        active = self._active_mask(k, k_max)
        # the lane's entire H2D payload: key stacks + the mask
        telemetry_metrics.add_h2d_bytes(
            "rollout",
            int(rngs.nbytes) + int(ro_rngs.nbytes) + active.nbytes,
        )

        compiles_before = getattr(fn, "traces", 0)
        t0 = _time.perf_counter()
        with tracing.start_span(
            "learn:superstep", k=k, batch_size=batch_size, rollout=True
        ) as _sp:
            (
                self.params,
                self.opt_state,
                self.aux_state,
                carry,
                stats,
                metrics,
            ) = fn(
                self.params,
                self.opt_state,
                self.aux_state,
                rollout.carry,
                active,
                rngs,
                ro_rngs,
                coeffs,
            )
            _sp.set_attribute(
                "recompiles",
                getattr(fn, "traces", 0) - compiles_before,
            )
            # ONE drain: stacked stats + episode metrics together
            # ray-tpu: allow[RTA005] the ONE counted drain for the chain
            stats, metrics = jax.device_get((stats, metrics))
            # drain done → the fused rollout+learn program is finished;
            # close its ledger interval (timestamps only)
            device_ledger.drain_point()
        self.num_grad_updates += k * self._updates_per_learn_call(
            batch_size
        )
        self._after_superstep()
        telemetry_metrics.counter(
            telemetry_metrics.LEARN_STEPS_TOTAL,
            "SGD-nest programs dispatched",
        ).inc(float(k))
        telemetry_metrics.inc_superstep_updates(k)
        self.last_learn_timers["learn_superstep_s"] = (
            _time.perf_counter() - t0
        )
        self.last_learn_timers["learn_recompiles"] = float(
            getattr(fn, "traces", 0) - compiles_before
        )

        skip = np.asarray(
            stats.get(superstep_lib.SKIP_KEY, np.zeros(k_max))
        )
        skipped = [bool(skip[i] > 0.5) for i in range(k)]
        infos = [
            {
                name: float(np.asarray(v)[i])
                for name, v in stats.items()
                if name != superstep_lib.SKIP_KEY
            }
            for i in range(k)
        ]
        return infos, carry, metrics, skipped

    def prepare_batch(self, samples) -> Tuple[Dict[str, np.ndarray], int]:
        """Public phase 1 of learning: turn a SampleBatch (or plain dict of
        arrays) into the host tree the compiled learn program consumes.

        Enforces static-shape discipline — the leading dim must be a
        multiple of the data shards; trims when possible, tiles tiny
        batches up. Returns ``(host_tree, batch_size)``; the tree is ready
        for ``jax.device_put`` onto ``self.data_sharding`` (directly or via
        a :class:`~ray_tpu.execution.device_feed.DeviceFeeder`)."""
        if isinstance(samples, SampleBatch) or not isinstance(
            samples, dict
        ):
            batch = self._batch_to_train_tree(samples)
        else:  # plain dict of arrays (benchmarks, tests)
            batch = {
                k: np.asarray(v)
                for k, v in samples.items()
                if isinstance(v, np.ndarray) and v.dtype != object
            }
        # the deduplicated frame pool is NOT a row column: it is
        # exempt from row trimming/tiling (trimmed idx rows keep
        # pointing at valid pool entries)
        frames = batch.pop(_FRAMES, None)
        bsize = int(next(iter(batch.values())).shape[0])
        # recurrent batches must also divide into whole T-row unrolls
        div = self.n_shards * self._unroll_T
        if bsize < div:
            reps = -(-div // bsize)
            orig = bsize
            batch = {
                k: np.tile(v, (reps,) + (1,) * (v.ndim - 1))[:div]
                for k, v in batch.items()
            }
            if "resets" in batch:
                # tile wrap points can land mid-unroll; the carry from
                # the end of one copy must not leak into the next
                # (stored chunk-start states only cover unroll row 0)
                resets = batch["resets"].copy()
                resets[orig::orig] = 1.0
                batch["resets"] = resets
            bsize = div
        else:
            trim = (bsize // div) * div
            if trim != bsize:
                batch = {k: v[:trim] for k, v in batch.items()}
                bsize = trim
        if self._unroll_T > 1 and "state_in_0" in batch:
            # stored-state mode ships ONE state per unroll, not per
            # row: only chunk-start states are ever read (the [:, 0]
            # in model_forward_train), so slicing here cuts the
            # host→device state transfer by T. Sliced AFTER trim/tile
            # so tiled layouts keep the state of each final chunk
            # start.
            T = self._unroll_T
            k = 0
            while f"state_in_{k}" in batch:
                batch[f"__chunk__state_in_{k}"] = batch.pop(
                    f"state_in_{k}"
                )[::T]
                k += 1
        if frames is not None:
            batch[_FRAMES] = frames
        return batch, bsize

    @property
    def data_sharding(self):
        """Sharding for train-batch leading-dim placement (public, for
        DeviceFeeder wiring)."""
        return self._data_sharding

    def batch_shardings(self, host_tree):
        """Per-column placement for a prepared train batch: row columns
        shard over the data axis; the deduplicated frame pool
        (``obs_frames``) replicates so every shard can gather stacks
        locally. Pass this method itself as a DeviceFeeder's
        ``sharding`` to get per-batch resolution. Columns whose
        leading dim doesn't divide the shard count (only possible for
        trees that bypassed ``prepare_batch``) fall back to
        replication instead of erroring (specs.leaf_sharding)."""
        if isinstance(host_tree, dict):
            return sharding_lib.sharding_tree(
                host_tree, self.mesh, replicate_keys=(_FRAMES,)
            )
        return self._data_sharding

    def learn_fn(self, batch_size: int, *, with_frames: bool = False):
        """Public accessor for the compiled SGD-nest program at a given
        (post-``prepare_batch``) batch size. Signature of the returned
        function is stable:

            ``fn(params, opt_state, aux_state, batch, rng, coeffs)
            -> (params, opt_state, stats)``

        Benchmarks and learner threads must obtain the program here (or
        use :meth:`learn_on_device_batch`) rather than via private
        attributes, so internal refactors can't silently break them.
        ``with_frames=True`` compiles the variant whose observations
        arrive as a deduplicated frame pool in ``aux['__frames__']``
        plus an ``obs_frame_idx`` row column (``ops/framestack``)."""
        key = (batch_size, with_frames)
        fn = self._learn_fns.get(key)
        if fn is None:
            # bespoke-net policies (SAC family) override
            # _build_learn_fn without the frames variant
            fn = (
                self._build_learn_fn(batch_size, with_frames=True)
                if with_frames
                else self._build_learn_fn(batch_size)
            )
            self._learn_fns[key] = fn
        return fn

    def _learn_aot_cache(self):
        """The AOT executable cache for learn programs, resolved once
        from ``config["aot_cache_dir"]`` (None when unconfigured).
        getattr-guarded: bespoke-net policies (SlateQ) run their own
        init chain past ``JaxPolicy.__init__``, so the lazy attrs may
        not exist on first touch."""
        if not getattr(self, "_aot_cache_resolved", False):
            self._aot_cache_resolved = True
            self._aot_cache = getattr(self, "_aot_cache", None)
            root = self.config.get("aot_cache_dir")
            if root:
                from ray_tpu.sharding import aot as aot_lib

                self._aot_cache = aot_lib.resolve_cache(root)
        return self._aot_cache

    # the warmup belongs to the driver thread: it installs the
    # program's dispatch path, which must not race a learn in flight
    # ray-tpu: thread=driver
    def _maybe_aot_warm(self, fn, args) -> None:
        """Elastic-joiner cold start (``ShardedFunction.aot_warmup``):
        before a freshly built learn program's FIRST dispatch, try to
        install the fleet-shared serialized executable for this exact
        signature. A hit means a joiner (or restarted driver) runs its
        first learn step with ZERO fresh compiles; a miss compiles
        ahead of time once and seeds the cache for the next joiner.
        ``aot_warmup`` only LOWERS — nothing dispatches, so the
        donated opt_state buffers in ``args`` are untouched (no
        RTA001 hazard) and the caller reuses them for the real call."""
        if getattr(fn, "_aot_warm_attempted", False):
            return  # one attempt per program (a "disabled" jax build
            # must not pay a lower() per learn call)
        fn._aot_warm_attempted = True
        if getattr(fn, "aot_source", None) is not None:
            return  # already warmed (hit, live-compiled, or fallback)
        if getattr(fn, "traces", 0) > 0 or getattr(fn, "calls", 0) > 0:
            return  # program already compiled live: nothing to save
        cache = self._learn_aot_cache()
        if cache is None:
            return
        fn.aot_warmup(cache, *args)

    # ray-tpu: thread=driver
    def _maybe_fleet_preseed(self, dev_batch, batch_size) -> None:
        """Resize-geometry AOT pre-seed (docs/fleet.md): ONCE, at the
        first learn on a mesh that spans processes with an AOT cache
        configured, compile the learn program of each ±1-host resize
        geometry into the shared cache — a later preemption-driven
        resize then restores its executable instead of compiling
        (fleet.elastic.resize_policy: zero fresh compiles). The seed
        batch is zeros at the live batch's global shapes: executables
        depend on placement and shape, never on values."""
        if getattr(self, "_fleet_preseeded", False):
            return
        self._fleet_preseeded = True
        if self._learn_aot_cache() is None:
            return
        mesh = getattr(self, "mesh", None)
        if mesh is None or not sharding_lib.mesh_spans_processes(
            mesh
        ):
            return
        from ray_tpu.fleet import elastic as elastic_lib

        if not elastic_lib.preseed_enabled():
            return
        try:
            import numpy as np

            host = {
                k: np.zeros(v.shape, v.dtype)
                for k, v in dev_batch.items()
                if hasattr(v, "shape")
            }
            for target in elastic_lib.resize_target_meshes(mesh):
                elastic_lib.preseed_resize(
                    self, target, host, batch_size
                )
        except Exception:
            pass  # the pre-seed is an optimization: a failed sweep
            # must never break the live learn path

    def learn_on_device_batch(
        self, dev_batch: Dict[str, Any], batch_size: int,
        *, defer_stats: bool = False,
    ) -> Dict[str, Any]:
        """Public phase 2 of learning: run the compiled SGD nest on an
        already-device-resident batch (e.g. transferred ahead of time by a
        DeviceFeeder so host→device copy overlapped the previous step).

        Batches in the deduplicated framestack format (``obs_frames``
        frame pool + ``obs_frame_idx`` rows — see ``ops/framestack``)
        rebuild their observations device-side: the pool rides the
        replicated aux slot (its sharding), so stacks gather locally on
        every data shard.

        ``defer_stats=True`` skips the blocking ``device_get`` of the
        stats tree and returns it as device arrays instead: dispatch
        returns as soon as XLA enqueues the program, so consecutive
        learner steps pipeline on-device and the per-dispatch latency
        (dominant on a tunneled/remote TPU backend) amortizes across the
        queue. The caller materializes stats later with
        ``jax.device_get`` — by then the program has long finished and
        the fetch is cheap. Deferring also skips
        ``after_learn_on_batch`` (host-side coefficient updates need
        host stats), so only defer for policies that don't override it."""
        import time as _time

        aux = self.aux_state
        if _FRAMES in dev_batch:
            dev_batch = dict(dev_batch)
            frames = jax.device_put(
                dev_batch.pop(_FRAMES), self._param_sharding
            )
            aux = {"__frames__": frames, **aux}
            fn = self.learn_fn(batch_size, with_frames=True)
        else:
            fn = self.learn_fn(batch_size)
        self._update_scheduled_coeffs()
        self._rng, rng = jax.random.split(self._rng)
        coeffs = self._coeff_array()
        # elastic-joiner AOT warmup at the _build_learn_fn call site:
        # install the fleet-shared executable for this signature
        # before the first dispatch (no-op without aot_cache_dir)
        self._maybe_aot_warm(
            fn,
            (self.params, self.opt_state, aux, dev_batch, rng, coeffs),
        )
        self._maybe_fleet_preseed(dev_batch, batch_size)
        compiles_before = getattr(fn, "traces", 0)
        compile_s_before = getattr(fn, "compile_time_s", 0.0)
        t0 = _time.perf_counter()
        with tracing.start_span(
            "learn:nest", batch_size=batch_size
        ) as _sp:
            self.params, self.opt_state, stats = fn(
                self.params,
                self.opt_state,
                aux,
                dev_batch,
                rng,
                coeffs,
            )
            self.num_grad_updates += self.num_sgd_iter * max(
                1, batch_size // max(1, self.minibatch_size)
            )
            _sp.set_attribute("deferred", bool(defer_stats))
            _sp.set_attribute(
                "recompiles",
                getattr(fn, "traces", 0) - compiles_before,
            )
            telemetry_metrics.counter(
                telemetry_metrics.LEARN_STEPS_TOTAL,
                "SGD-nest programs dispatched",
            ).inc()
            if defer_stats:
                return stats
            if self.config.get("deferred_stats"):
                # flag-gated one-call lag (docs/data_plane.md): hand
                # back the PREVIOUS nest's stats — that program has
                # long finished, so the fetch doesn't serialize on
                # THIS dispatch and the per-call device round trip
                # overlaps compute. The very first call has nothing
                # lagged and returns only cur_lr.
                prev = self.__dict__.get("_lagged_stats")
                self.__dict__["_lagged_stats"] = stats
                stats = (
                    jax.device_get(prev) if prev is not None else None
                )
            else:
                # One device→host transfer for all stats (individual
                # float() conversions each pay a full device round
                # trip).
                stats = jax.device_get(stats)
                # stats landed → the nest finished; close its ledger
                # interval at this (the one counted) drain
                device_ledger.drain_point()
        # per-stage timers: a call that traced pays compile; the rest
        # of this call's wall time is the step (device compute + stats
        # fetch). Exposed both as metrics series (utils.metrics) and on
        # the policy for train()-result reporting.
        total_s = _time.perf_counter() - t0
        compile_s = (
            getattr(fn, "compile_time_s", 0.0) - compile_s_before
        )
        self.last_learn_timers["learn_compile_s"] = compile_s
        self.last_learn_timers["learn_step_s"] = max(
            0.0, total_s - compile_s
        )
        self.last_learn_timers["learn_recompiles"] = float(
            getattr(fn, "traces", 0) - compiles_before
        )
        timer_histogram("ray_tpu_learner_step_seconds").observe(
            self.last_learn_timers["learn_step_s"]
        )
        if compile_s:
            timer_histogram(
                "ray_tpu_learner_compile_seconds"
            ).observe(compile_s)
        if stats is None:  # deferred first call: nothing lagged yet
            return {"cur_lr": self.coeff_values["lr"]}
        out = {k: float(v) for k, v in stats.items()}
        out.update(self.after_learn_on_batch(out))
        out["cur_lr"] = self.coeff_values["lr"]
        return out

    # ray-tpu: drain-ok
    def flush_deferred_stats(self) -> Dict[str, float]:
        """Fetch (and clear) the stats handle a ``deferred_stats``
        policy is still holding — call after the last learn step when
        the final update's numbers matter."""
        prev = self.__dict__.pop("_lagged_stats", None)
        if prev is None:
            return {}
        stats = jax.device_get(prev)
        # the lagged handle belongs to the most recent dispatch on
        # this thread — its arrival closes that ledger interval
        device_ledger.drain_point()
        return {k: float(v) for k, v in stats.items()}

    def learn_on_batch(self, samples: SampleBatch) -> Dict[str, Any]:
        """One full multi-epoch SGD update (reference
        TorchPolicy.learn_on_batch :467 + the whole train_ops stack).
        ``jax.device_put`` dispatch is asynchronous, so the transfer
        overlaps this host code until the program consumes the buffers."""
        import time as _time

        batch, bsize = self.prepare_batch(samples)
        # the frame pool is replicated, not row-sharded
        frames = batch.pop(_FRAMES, None)
        telemetry_metrics.add_h2d_bytes(
            "learn",
            sharding_lib.tree_nbytes(batch)
            + (frames.nbytes if frames is not None else 0),
        )
        t0 = _time.perf_counter()
        with tracing.start_span("learn:transfer", batch_size=bsize):
            dev = _tree_to_device(batch, self._data_sharding)
            if frames is not None:
                dev = dict(
                    dev,
                    **{
                        _FRAMES: jax.device_put(
                            frames, self._param_sharding
                        )
                    },
                )
            # block so the transfer timer is honest (the learn program
            # would wait on these buffers anyway; only the sliver of
            # host code between here and dispatch loses overlap — the
            # async path is the DeviceFeeder, which times its own
            # transfers)
            jax.block_until_ready(dev)
        transfer_s = _time.perf_counter() - t0
        self.last_learn_timers["learn_transfer_s"] = transfer_s
        timer_histogram(
            "ray_tpu_learner_transfer_seconds"
        ).observe(transfer_s)
        return self.learn_on_device_batch(dev, bsize)

    def after_learn_on_batch(self, stats: Dict[str, float]) -> Dict[str, float]:
        """Hook for host-side coefficient updates (e.g. PPO kl coeff)."""
        return {}

    def _rebuild_obs_from_frames(self, frames, batch, stack_k: int):
        """Device-side hook (runs inside the jitted learn program):
        turn the deduplicated frame pool + per-row first-frame indices
        back into the OBS column. Policies whose obs column is not a
        flat row layout (IMPALA's (B, T) unrolls) override this.
        ``build_stacks`` routes uint8 pools through the same uint32-lane
        gather trick as the per-minibatch row gather below (MFU.md
        "what would move it further" item 1)."""
        from ray_tpu.ops.framestack import build_stacks

        batch = dict(batch)
        batch[SampleBatch.OBS] = build_stacks(
            frames, batch.pop(_FRAME_IDX), stack_k
        )
        return batch

    # Losses that never read NEXT_OBS (the on-policy family) set this
    # False so the train tree doesn't ship a second full obs column to
    # the device — for pixel envs that halves learner ingest bytes.
    _ship_next_obs: bool = True

    def _td_input_tree(self, samples):
        """Batch tree for the per-sample TD-error programs: a
        device-resident replay sample is already the train tree (use
        it in place — no D2H round trip); host SampleBatches convert
        through ``_batch_to_train_tree``."""
        if getattr(samples, "is_device_resident", False):
            return samples.tree
        return self._batch_to_train_tree(samples)

    def replay_columns(self, samples: SampleBatch) -> Dict[str, np.ndarray]:
        """Host column tree a device-resident replay buffer stores for
        this policy (docs/data_plane.md): the learn program's
        train-tree columns — same key selection and dtype casts as
        ``learn_on_batch`` — WITHOUT the framestack transfer-format
        dedup. A replay buffer stores rows, and randomly sampled rows
        are not sliding windows; the pool format would be rejected by
        the ring anyway (unequal column lengths)."""
        missing = object()
        prev = self.config.get("dedup_framestack", missing)
        self.config["dedup_framestack"] = False
        try:
            return self._batch_to_train_tree(samples)
        finally:
            if prev is missing:
                self.config.pop("dedup_framestack", None)
            else:
                self.config["dedup_framestack"] = prev

    def compress_for_shipping(self, batch: SampleBatch) -> SampleBatch:
        """Worker-side, after postprocessing, right before a fragment
        ships to the driver: replace stacked framestack observations
        with the deduplicated pool + index columns
        (``ops/framestack.compress_fragment_obs``). A stacked pixel
        fragment moves ~2k single frames' worth of bytes per step
        through pickle → object ring → driver concat → TPU tunnel; the
        pool moves ~1. Applies only when the loss can train from the
        pool: on-policy flat rows (``_ship_next_obs`` False) or fixed
        unrolls (IMPALA family, which only needs the bootstrap stack —
        reconstructible at ``idx[-1]+1``). Offline output
        (``config["output"]``) keeps materialized stacks so written
        datasets stay self-describing."""
        if not self.config.get("compress_obs_shipping", True):
            return batch
        if self.config.get("output"):
            return batch
        fixed = bool(self.config.get("_fixed_unrolls"))
        if not fixed and self._ship_next_obs:
            # replay families read full NEXT_OBS — pool it too
            # (terminal stacks included) so the fragment still ships
            # ~k× smaller and the driver rebuilds both columns
            # byte-identically before replay insert
            return self._compress_replay_shipping(batch)
        model = getattr(self, "model", None)  # bespoke-net policies
        if model is None or model.is_recurrent:
            return batch
        obs = batch.get(SampleBatch.OBS)
        if (
            isinstance(obs, np.ndarray)
            and obs.ndim == 4
            and 2 <= obs.shape[-1] <= 8
            and SampleBatch.NEXT_OBS in batch
        ):
            from ray_tpu.ops.framestack import compress_fragment_obs

            dones = np.asarray(
                batch[SampleBatch.TERMINATEDS], bool
            ) | np.asarray(
                batch.get(
                    SampleBatch.TRUNCATEDS,
                    np.zeros(batch.count, bool),
                ),
                bool,
            )
            dec = compress_fragment_obs(
                obs, np.asarray(batch[SampleBatch.NEXT_OBS]), dones
            )
            if dec is not None:
                pool, idx = dec
                cols = {
                    k: v
                    for k, v in batch.items()
                    if k
                    not in (SampleBatch.OBS, SampleBatch.NEXT_OBS)
                }
                cols[_FRAMES] = pool
                cols[_FRAME_IDX] = idx
                return SampleBatch(cols)
        return batch

    def _compress_replay_shipping(self, batch: SampleBatch) -> SampleBatch:
        """Worker-side framestack dedup for the off-policy (replay)
        path: OBS and NEXT_OBS pool together via
        ``ops/framestack.compress_replay_obs`` — per-episode terminal
        stacks ride as pseudo-rows, so ``materialize_fragment`` on the
        driver rebuilds BOTH columns byte-identically (``obs[t] =
        stack(idx[t])``, ``next_obs[t] = stack(idx[t]+1)``) before
        rows enter the replay buffer."""
        model = getattr(self, "model", None)  # bespoke-net policies
        if model is None or model.is_recurrent:
            return batch
        obs = batch.get(SampleBatch.OBS)
        if not (
            isinstance(obs, np.ndarray)
            and obs.ndim == 4
            and 2 <= obs.shape[-1] <= 8
            and SampleBatch.NEXT_OBS in batch
        ):
            return batch
        from ray_tpu.ops.framestack import compress_replay_obs

        dones = np.asarray(
            batch[SampleBatch.TERMINATEDS], bool
        ) | np.asarray(
            batch.get(
                SampleBatch.TRUNCATEDS,
                np.zeros(batch.count, bool),
            ),
            bool,
        )
        dec = compress_replay_obs(
            obs, np.asarray(batch[SampleBatch.NEXT_OBS]), dones
        )
        if dec is None:
            return batch
        pool, idx = dec
        cols = {
            k: v
            for k, v in batch.items()
            if k not in (SampleBatch.OBS, SampleBatch.NEXT_OBS)
        }
        cols[_FRAMES] = pool
        cols[_FRAME_IDX] = idx
        return SampleBatch(cols)

    def _maybe_dedup_framestack(
        self, tree: Dict[str, np.ndarray]
    ) -> Dict[str, np.ndarray]:
        """Replace a stacked (N, H, W, k) OBS column with the
        deduplicated frame pool + index columns when rows really are
        sliding windows (ops/framestack) — ~k× fewer obs bytes over the
        host→device boundary, which is the e2e bottleneck on a remote/
        tunneled TPU backend. Segment boundaries (fragment starts,
        episode resets) come from the batch's bookkeeping columns; the
        decomposition verifies the sliding-window property and falls
        back to shipping stacks when it doesn't hold."""
        obs = tree.get(SampleBatch.OBS)
        if (
            obs is None
            or self.model.is_recurrent
            or not self.config.get("dedup_framestack", True)
            or obs.ndim != 4
            or not 2 <= obs.shape[-1] <= 8
            or obs.nbytes
            < self.config.get("dedup_framestack_min_bytes", 1 << 20)
        ):
            return tree
        from ray_tpu.ops.framestack import decompose_segmented_obs

        n = obs.shape[0]
        seg = np.zeros(n, bool)
        seg[0] = True
        for col in (
            SampleBatch.UNROLL_ID,
            SampleBatch.EPS_ID,
            SampleBatch.AGENT_INDEX,
        ):
            v = tree.get(col)
            if v is not None and len(v) == n:
                seg[1:] |= v[1:] != v[:-1]
        tcol = tree.get(SampleBatch.T)
        if tcol is not None and len(tcol) == n:
            seg[1:] |= tcol[1:] != tcol[:-1] + 1
        out = decompose_segmented_obs(obs, seg)
        if out is None:
            return tree
        stream, idx = out
        tree = dict(tree)
        del tree[SampleBatch.OBS]
        tree[_FRAMES] = stream
        tree[_FRAME_IDX] = idx
        return tree

    def _batch_to_train_tree(self, samples: SampleBatch) -> Dict[str, np.ndarray]:
        """Select training columns as a flat dict of arrays. For
        recurrent models, derive the per-row ``resets`` column the
        (B, T) unroll forward consumes: 1 wherever the trajectory is
        discontinuous (episode change, or a non-contiguous step counter
        marking a fragment boundary between different env slots)."""
        drop = {SampleBatch.INFOS, SampleBatch.SEQ_LENS}
        # carry-style recurrent models (LSTM) train from the sampler's
        # stored chunk-start states so the train-time forward matches
        # the rollout-time forward exactly for mid-episode chunks;
        # other models' per-row states are rollout-side plumbing (the
        # GAE bootstrap reads the last row host-side) and never ship
        # to device (R2D2 overrides this method and keeps the state
        # columns its sequence loss needs)
        stored_state = (
            self.model.is_recurrent
            and getattr(self.model, "supports_stored_train_state", False)
        )
        if not self._ship_next_obs:
            drop = drop | {SampleBatch.NEXT_OBS}
        tree = {
            k: np.asarray(v)
            for k, v in samples.items()
            if k not in drop
            and (stored_state or not k.startswith("state_in_"))
            and not k.startswith("state_out_")
            and isinstance(v, np.ndarray)
            and v.dtype != object
        }
        tree = self._maybe_dedup_framestack(tree)
        if self.model.is_recurrent and "resets" not in tree:
            n = len(next(iter(tree.values())))
            resets = np.zeros(n, np.float32)
            eps = tree.get(SampleBatch.EPS_ID)
            tcol = tree.get(SampleBatch.T)
            if not stored_state:
                # row 0 is always treated as a trajectory start (also
                # makes tiled copies in prepare_batch reset at each
                # wrap point); with stored state the chunk-start state
                # column is itself correct at row 0 and at every tiled
                # copy, so row 0 is a reset only when it genuinely
                # starts an episode (step counter 0)
                resets[0] = 1.0
            elif tcol is None or tcol[0] == 0:
                resets[0] = 1.0
            if eps is not None:
                resets[1:] = np.maximum(
                    resets[1:], (eps[1:] != eps[:-1]).astype(np.float32)
                )
            if tcol is not None:
                resets[1:] = np.maximum(
                    resets[1:],
                    (tcol[1:] != tcol[:-1] + 1).astype(np.float32),
                )
            tree["resets"] = resets
        return tree

    def model_forward_train(self, params, batch):
        """Learn-path forward over a flat training batch. Feedforward
        models pass through; recurrent models reshape the N flat rows
        into (N/T, T) unrolls — chunk starts use the sampler's stored
        states when the model supports it (LSTM; exact rollout replay)
        and zero states otherwise (GTrXL; documented approximation in
        models/attention.py), with the ``resets`` column zeroing the
        carry at trajectory boundaries — and return flattened (N,)
        outputs, so losses written against flat rows work unchanged
        (the reference's rnn_sequencing role, fixed-shape style)."""
        obs = batch[SampleBatch.OBS]
        if not self.model.is_recurrent:
            return self.model.apply(params, obs)
        T = self._unroll_T
        N = obs.shape[0]
        if N % T:
            raise ValueError(
                f"recurrent train batch of {N} rows is not a multiple "
                f"of the unroll length max_seq_len={T}"
            )
        B = N // T
        kwargs = {}
        resets = batch.get("resets")
        if resets is not None:
            kwargs["resets"] = resets.reshape(B, T)
        if getattr(self.model, "use_prev_action", False):
            pa = batch.get(SampleBatch.PREV_ACTIONS)
            if pa is not None:
                kwargs["prev_actions"] = pa.reshape(
                    (B, T) + pa.shape[1:]
                )
        if getattr(self.model, "use_prev_reward", False):
            pr = batch.get(SampleBatch.PREV_REWARDS)
            if pr is not None:
                kwargs["prev_rewards"] = pr.reshape(B, T)
        stored = getattr(self.model, "supports_stored_train_state", False)
        if stored and "__chunk__state_in_0" in batch:
            # prepare_batch already sliced to one state per unroll
            state0 = []
            k = 0
            while f"__chunk__state_in_{k}" in batch:
                state0.append(batch[f"__chunk__state_in_{k}"])
                k += 1
            state0 = tuple(state0)
        elif stored and "state_in_0" in batch:
            # per-row columns (compute_gradients path, which bypasses
            # prepare_batch): each unroll starts from the state the
            # sampler recorded at its first row (exact rollout replay
            # for mid-episode chunks; resets re-zero the carry at any
            # in-chunk episode boundary)
            state0 = []
            k = 0
            while f"state_in_{k}" in batch:
                s = batch[f"state_in_{k}"]
                state0.append(
                    s.reshape((B, T) + s.shape[1:])[:, 0]
                )
                k += 1
            state0 = tuple(state0)
        else:
            state0 = self._zero_initial_state(obs, B)
        return self.model.apply(
            params, obs.reshape((B, T) + obs.shape[1:]), state0,
            **kwargs,
        )

    def _zero_initial_state(self, obs, B: int):
        """Zero recurrent state for B unrolls, derived from the batch
        (0 * anchor) so the scan carry is device-varying under
        shard_map — plain jnp.zeros is axis-unvarying and trips the
        lax.scan vma check inside the sharded learn program."""
        anchor = obs.reshape(B, -1)[:, 0].astype(jnp.float32)
        return tuple(
            s + 0.0 * anchor.reshape((B,) + (1,) * (s.ndim - 1))
            for s in self.model.initial_state(B)
        )

    # -- gradients API (A3C-style parity) --------------------------------

    def compute_gradients(self, samples: SampleBatch):
        if not hasattr(self, "_grad_fn"):
            loss_fn = self.loss_with_aux

            def gfn(params, aux, batch, rng, coeffs):
                (loss, stats), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params, aux, batch, rng, coeffs)
                return grads, dict(stats, total_loss=loss)

            self._grad_fn = sharding_lib.sharded_jit(
                gfn, label=f"grads[{type(self).__name__}]"
            )
        batch = self._batch_to_train_tree(samples)
        if self._unroll_T > 1:
            # async-gradient batches bypass prepare_batch: trim to
            # whole unrolls so model_forward_train's reshape holds
            n = len(next(iter(batch.values())))
            trim = (n // self._unroll_T) * self._unroll_T
            if trim == 0:
                raise ValueError(
                    f"compute_gradients batch of {n} rows is shorter "
                    f"than one max_seq_len={self._unroll_T} unroll"
                )
            if trim != n:
                batch = {k: v[:trim] for k, v in batch.items()}
        self._rng, rng = jax.random.split(self._rng)
        grads, stats = self._grad_fn(
            self.params, self.aux_state, batch, rng, self._coeff_array()
        )
        return jax.device_get(grads), {k: float(v) for k, v in stats.items()}

    def apply_gradients(self, gradients) -> None:
        if not hasattr(self, "_apply_fn"):
            tx = self._tx

            def afn(params, opt_state, grads, lr):
                updates, opt_state = tx.update(grads, opt_state, params)
                updates = jax.tree_util.tree_map(
                    lambda u: -lr * u.astype(jnp.float32), updates
                )
                return optax.apply_updates(params, updates), opt_state

            self._apply_fn = sharding_lib.sharded_jit(
                afn,
                donate_argnums=(0, 1),
                label=f"apply_grads[{type(self).__name__}]",
            )
        self.params, self.opt_state = self._apply_fn(
            self.params,
            self.opt_state,
            gradients,
            jnp.asarray(self.coeff_values["lr"], jnp.float32),
        )

    # -- weights ---------------------------------------------------------

    def update_config(self, new_config: Dict) -> None:
        """Apply mutated hyperparameters at runtime (PBT explore,
        reference tune/schedulers/pbt.py does this via checkpoint+restart
        of the whole trial). Loss constants (clip_param, vf_loss_coeff,
        ...) are baked into the compiled learn programs, so those are
        dropped for re-trace; lr/entropy schedules are rebuilt from the
        new config; subclass coefficients re-derived."""
        self.config.update(new_config)
        from ray_tpu.utils.schedules import make_schedule

        self._lr_schedule = make_schedule(
            self.config.get("lr_schedule"), self.config.get("lr", 5e-5)
        )
        self._entropy_schedule = make_schedule(
            self.config.get("entropy_coeff_schedule"),
            self.config.get("entropy_coeff", 0.0),
        )
        # Re-derive loss coefficients from the mutated config, but keep
        # adaptive state (e.g. PPO's kl_coeff) for keys NOT explicitly
        # mutated — exploit just restored the donor's adapted values.
        adapted = {
            k: v
            for k, v in self.coeff_values.items()
            if k not in new_config
        }
        self._init_coeffs()
        self.coeff_values.update(
            {k: v for k, v in adapted.items() if k in self.coeff_values}
        )
        self._update_scheduled_coeffs()
        # SGD geometry is cached at init and baked into the compiled
        # nest; refresh it so mutations of these knobs take effect.
        self.train_batch_size = int(
            self.config.get("train_batch_size", self.train_batch_size)
        )
        self.minibatch_size = int(
            self.config.get("sgd_minibatch_size")
            or self.config.get("train_batch_size", self.minibatch_size)
        )
        self.num_sgd_iter = int(
            self.config.get("num_sgd_iter", self.num_sgd_iter)
        )
        self._learn_fns.clear()
        self.__dict__.pop("_superstep_fns", None)
        if hasattr(self, "_grad_fn"):
            del self._grad_fn
        # Rebuild exploration (type/knobs may have mutated) and drop the
        # compiled action program — its closure captured the old
        # strategy object.
        self._refold_exploration_config(new_config)
        self._init_exploration()
        self._action_fn = None

    # When set (a tuple of top-level param keys), only those subtrees
    # ship to sampling-only workers on sync_weights(inference_only=True)
    # — e.g. SAC's actor without its critic/target towers. None = full.
    inference_weight_keys: Optional[Tuple[str, ...]] = None

    def get_weights(self):
        return jax.device_get(self.params)

    def get_inference_weights(self):
        keys = self.inference_weight_keys
        if keys is None or not isinstance(self.params, dict):
            return self.get_weights()
        return jax.device_get(
            {k: self.params[k] for k in keys if k in self.params}
        )

    def _weights_sharding(self, weights):
        """Placement for an incoming (possibly partial) host weight
        tree: the per-leaf tree sliced to the given top-level keys
        when partitioned, the single replicated sharding otherwise.
        This is the reshard-on-restore half of the checkpoint
        contract: gather-on-save stays the format, and a tree saved
        under any mesh geometry re-places per the ACTIVE rules here."""
        ps = self._param_sharding
        if (
            isinstance(ps, dict)
            and isinstance(weights, dict)
            and all(k in ps for k in weights)
        ):
            return {k: ps[k] for k in weights}
        return ps

    def set_weights(self, weights) -> None:
        if (
            isinstance(weights, dict)
            and isinstance(self.params, dict)
            and set(weights) < set(self.params)
        ):
            # partial tree (inference-only sync): merge over the
            # existing params instead of dropping the absent subtrees
            merged = dict(self.params)
            merged.update(
                _tree_to_device(
                    weights, self._weights_sharding(weights)
                )
            )
            self.params = merged
        else:
            self.params = _tree_to_device(
                weights, self._weights_sharding(weights)
            )
        self._publish_params_bytes()
        self.exploration.on_weights_updated(self)

    def get_state(self) -> Dict[str, Any]:
        return {
            "weights": self.get_weights(),
            "opt_state": jax.device_get(self.opt_state),
            "coeff_values": dict(self.coeff_values),
            "global_timestep": self.global_timestep,
            "num_grad_updates": self.num_grad_updates,
            "exploration_state": self.exploration.get_state(),
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.set_weights(state["weights"])
        if "opt_state" in state:
            self.opt_state = _tree_to_device(
                state["opt_state"],
                self._opt_sharding or self._param_sharding,
            )
        self.coeff_values.update(state.get("coeff_values", {}))
        self.global_timestep = state.get("global_timestep", 0)
        self.num_grad_updates = state.get("num_grad_updates", 0)
        self.exploration.set_state(state.get("exploration_state", {}))


def build_jax_policy(
    name: str,
    *,
    loss_fn,
    extra_action_out_fn=None,
    postprocess_fn=None,
    init_coeffs_fn=None,
    after_learn_fn=None,
    stats_fn=None,
):
    """Runtime policy-class builder, the JAX counterpart of the
    reference's ``build_policy_class`` (``rllib/policy/policy_template.py:38``
    — whose framework="jax" mode still inherited TorchPolicy; here the
    parent is the real JaxPolicy).

    ``loss_fn(policy, params, batch, rng, coeffs) -> (loss, stats)``
    """

    class _Built(JaxPolicy):
        def loss(self, params, batch, rng, coeffs):
            return loss_fn(self, params, batch, rng, coeffs)

        def _init_coeffs(self):
            if init_coeffs_fn:
                self.coeff_values.update(init_coeffs_fn(self))

        def extra_action_out(self, dist_inputs, value, dist, rng):
            if extra_action_out_fn:
                return extra_action_out_fn(
                    self, dist_inputs, value, dist, rng
                )
            return super().extra_action_out(dist_inputs, value, dist, rng)

        def postprocess_trajectory(
            self, sample_batch, other_agent_batches=None, episode=None
        ):
            if postprocess_fn:
                return postprocess_fn(
                    self, sample_batch, other_agent_batches, episode
                )
            return sample_batch

        def after_learn_on_batch(self, stats):
            if after_learn_fn:
                return after_learn_fn(self, stats)
            return {}

    _Built.__name__ = name
    _Built.__qualname__ = name
    return _Built
