from ray_tpu.policy.policy import Policy, ViewRequirement
from ray_tpu.policy.jax_policy import JaxPolicy, build_jax_policy

__all__ = ["Policy", "ViewRequirement", "JaxPolicy", "build_jax_policy"]
