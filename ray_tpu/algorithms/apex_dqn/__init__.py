from ray_tpu.algorithms.apex_dqn.apex_dqn import (
    ApexDQN,
    ApexDQNConfig,
    ReplayActor,
)

__all__ = ["ApexDQN", "ApexDQNConfig", "ReplayActor"]
