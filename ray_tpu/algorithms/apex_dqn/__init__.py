from ray_tpu.algorithms.apex_dqn.apex_dqn import (
    ApexDDPG,
    ApexDDPGConfig,
    ApexDQN,
    ApexDQNConfig,
    ReplayActor,
)

__all__ = [
    "ApexDQN",
    "ApexDQNConfig",
    "ApexDDPG",
    "ApexDDPGConfig",
    "ReplayActor",
]
