"""Ape-X DQN: distributed prioritized experience replay.

Counterpart of the reference's ``rllib/algorithms/apex_dqn/apex_dqn.py``
(Horgan et al. 2018): many rollout workers with a per-worker epsilon
ladder feed sharded replay buffers; the learner continuously draws
prioritized samples from the shards, trains, and pushes per-sample
priority updates back; weights broadcast to workers periodically.

Two replay-shard planes (docs/data_plane.md "device sum tree &
sharded Ape-X"):

- **object plane** (the reference's shape): shards are
  ``ReplayActor``s on the CPU fleet; every insert/sample/priority
  round-trip crosses the object store, and every sampled batch
  re-crosses H2D at learn time. Sampling, insertion, learning, and
  priority updates overlap through in-flight futures.
- **mesh plane** (``replay_device_resident`` resolves on): shards are
  :class:`DevicePrioritizedReplayBuffer` rings placed on the learner
  mesh. A fragment crosses H2D exactly once — the insert upload also
  feeds the initial-priority TD program (the shared
  ``_td_error_device_fn`` via ``compute_td_error``, not a second
  transfer) — and the learn loop is distributed-insert →
  in-program gather → ``learn_superstep`` per shard, with the PER
  refresh landing back in each shard's (optionally device-resident)
  sum tree. No object plane, no host copy between sample and update.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import ray_tpu as ray
from ray_tpu.algorithms.algorithm import NUM_ENV_STEPS_SAMPLED
from ray_tpu.algorithms.dqn.dqn import (
    DQN,
    DQNConfig,
    DQNJaxPolicy,
    adjust_nstep,
)
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.execution.replay_buffer import (
    DevicePrioritizedReplayBuffer,
    DeviceTrainBatch,
    PrioritizedReplayBuffer,
    resolve_device_resident,
    resolve_device_tree,
)
from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED


@ray.remote
class ReplayActor:
    """One prioritized replay shard (reference apex ReplayActor)."""

    def __init__(
        self,
        capacity: int,
        alpha: float,
        beta: float,
        seed: Optional[int] = None,
    ):
        self.buffer = PrioritizedReplayBuffer(
            capacity=capacity, alpha=alpha, seed=seed
        )
        self.beta = beta

    def add(self, batch: SampleBatch, priorities=None):
        if priorities is not None:
            self.buffer.add_with_priorities(batch, priorities)
        else:
            self.buffer.add(batch)
        return self.buffer.num_added

    def sample(self, num_items: int) -> Optional[SampleBatch]:
        if len(self.buffer) < num_items:
            return None
        return self.buffer.sample(num_items, beta=self.beta)

    def update_priorities(self, batch_indexes, priorities):
        self.buffer.update_priorities(batch_indexes, priorities)

    def size(self) -> int:
        return len(self.buffer)

    def stats(self) -> Dict:
        return self.buffer.stats()


class ApexDQNConfig(DQNConfig):
    """reference apex_dqn.py ApexDQNConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDQN)
        self.num_workers = 4
        self.num_replay_buffer_shards = 2
        self.per_worker_exploration = True
        self.worker_side_prioritization = False
        self.n_step = 3
        self.train_batch_size = 512
        self.rollout_fragment_length = 50
        self.target_network_update_freq = 2500
        self.num_steps_sampled_before_learning_starts = 1000
        self.max_sample_requests_in_flight_per_worker = 2
        self.broadcast_interval = 1
        self.replay_buffer_config = {
            "capacity": 100000,
            "prioritized_replay": True,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
        }

    def training(
        self,
        *,
        num_replay_buffer_shards: Optional[int] = None,
        per_worker_exploration: Optional[bool] = None,
        **kwargs,
    ) -> "ApexDQNConfig":
        super().training(**kwargs)
        if num_replay_buffer_shards is not None:
            self.num_replay_buffer_shards = num_replay_buffer_shards
        if per_worker_exploration is not None:
            self.per_worker_exploration = per_worker_exploration
        return self


class ApexDQN(DQN):
    _default_policy_class = DQNJaxPolicy

    @classmethod
    def get_default_config(cls) -> ApexDQNConfig:
        return ApexDQNConfig(cls)

    def setup(self, config: Dict) -> None:
        super().setup(config)  # DQN.setup builds the local buffer (unused)
        self.local_replay_buffer = None
        rb = config.get("replay_buffer_config") or {}
        n_shards = max(1, int(config.get("num_replay_buffer_shards", 2)))
        per_shard = max(
            1, int(rb.get("capacity", 100000)) // n_shards
        )
        seed = config.get("seed")
        self._replay_beta = rb.get("prioritized_replay_beta", 0.4)
        mesh = config.get("_mesh")
        # mesh plane: shards become device rings on the learner mesh —
        # same per-shard seeds and round-robin routing as the actor
        # plane, so the per-shard generator streams are identical
        self._apex_device = resolve_device_resident(config, mesh)
        self.replay_shards: List = []
        self.replay_actors: List = []
        if self._apex_device:
            device_tree = resolve_device_tree(config, mesh)
            self.replay_shards = [
                DevicePrioritizedReplayBuffer(
                    per_shard,
                    rb.get("prioritized_replay_alpha", 0.6),
                    None if seed is None else seed + 100 + i,
                    mesh=mesh,
                    memory_cap_bytes=config.get(
                        "replay_memory_cap_bytes"
                    ),
                    label=f"apex_shard_{i}",
                    device_tree=device_tree,
                )
                for i in range(n_shards)
            ]
        else:
            self.replay_actors = [
                ReplayActor.remote(
                    per_shard,
                    rb.get("prioritized_replay_alpha", 0.6),
                    self._replay_beta,
                    None if seed is None else seed + 100 + i,
                )
                for i in range(n_shards)
            ]
        self._sample_in_flight: Dict = {}  # ref -> worker
        self._replay_in_flight: Dict = {}  # ref -> replay actor
        self._shard_rr = 0
        self._last_target_update = 0
        self._batches_since_broadcast: Dict = {}

    def _route_to_replay(self, batch: SampleBatch) -> None:
        """n-step fold, optional initial priorities, round-robin shard
        insert. By default new samples insert at max priority (standard
        prioritized-replay behavior); worker_side_prioritization=True
        computes real initial TD errors through the policy's SHARED
        per-sample TD program (``_td_error_device_fn``, the same body
        the loss and the PER refresh run) — an extra jitted forward
        per fragment on the learning critical path, so it is opt-in.

        Mesh plane: the fragment's train columns cross H2D exactly
        once — the SAME uploaded tree feeds the initial-TD program
        and the donated insert scatter — then enter the round-robin
        shard ring with the computed (or max) priorities."""
        config = self.config
        from ray_tpu.ops.framestack import (
            FRAMES as _FRAMES,
            materialize_fragment,
        )

        if _FRAMES in batch:
            # worker-compressed framestack fragment (byte-exact
            # replay-pool format): rebuild OBS/NEXT_OBS before the
            # n-step fold reads them and rows enter the replay shard
            k = int(self.get_policy().observation_space.shape[-1])
            batch = SampleBatch(materialize_fragment(dict(batch), k))
        n_step = config.get("n_step", 1)
        if n_step > 1:
            adjust_nstep(n_step, config["gamma"], batch)

        if self._apex_device:
            self._route_to_device_shard(batch)
            return
        prios = None
        if config.get("worker_side_prioritization"):
            try:
                prios = (
                    self.get_policy().compute_td_error(batch) + 1e-6
                )
            except Exception:
                prios = None
        shard = self.replay_actors[
            self._shard_rr % len(self.replay_actors)
        ]
        self._shard_rr += 1
        shard.add.remote(batch, prios)

    def _route_to_device_shard(self, batch: SampleBatch) -> None:
        """One H2D crossing per fragment: upload the policy's replay
        columns, (optionally) run the shared TD program on that SAME
        device tree for initial priorities, and hand the resident rows
        to the round-robin shard's donated insert scatter."""
        import jax

        from ray_tpu import sharding as sharding_lib
        from ray_tpu.telemetry import metrics as telemetry_metrics

        policy = self.get_policy()
        shard = self.replay_shards[
            self._shard_rr % len(self.replay_shards)
        ]
        self._shard_rr += 1
        if shard.spilled:
            # spilled shards keep the host protocol (placement
            # changed, sampling didn't)
            prios = None
            if self.config.get("worker_side_prioritization"):
                prios = policy.compute_td_error(batch) + 1e-6
            shard.add_tree(policy.replay_columns(batch), prios)
            return
        cols = policy.replay_columns(batch)
        telemetry_metrics.add_h2d_bytes(
            "replay_insert", sharding_lib.tree_nbytes(cols)
        )
        dev_tree = jax.device_put(cols, policy.batch_shardings(cols))
        prios = None
        if self.config.get("worker_side_prioritization"):
            # the SHARED per-sample TD body (compute_td_error jits
            # policy._td_error_device_fn) on the already-uploaded
            # rows — bit-identical to the host-batch route, zero
            # extra transfer (regression-pinned in tests)
            n = int(next(iter(dev_tree.values())).shape[0])
            prios = (
                policy.compute_td_error(DeviceTrainBatch(dev_tree, n))
                + 1e-6
            )
        shard.add_device_tree(dev_tree, priorities=prios)

    def training_step(self) -> Dict:
        """reference apex_dqn.py training_step: overlap sampling,
        replay insertion, learning, and priority updates."""
        config = self.config
        workers = self.workers.remote_workers()
        policy = self.get_policy()
        train_info: Dict = {}

        # ---- keep rollout workers saturated ----
        if workers:
            max_inflight = config.get(
                "max_sample_requests_in_flight_per_worker", 2
            )
            counts: Dict = {}
            for ref, w in self._sample_in_flight.items():
                counts[id(w)] = counts.get(id(w), 0) + 1
            for w in workers:
                while counts.get(id(w), 0) < max_inflight:
                    self._sample_in_flight[w.sample.remote()] = w
                    counts[id(w)] = counts.get(id(w), 0) + 1
            ready, _ = ray.wait(
                list(self._sample_in_flight.keys()),
                num_returns=1,
                timeout=1.0,
            )
            weights_ref = None
            for ref in ready:
                w = self._sample_in_flight.pop(ref)
                try:
                    batch = ray.get(ref)
                except (
                    ray.core.object_store.RayActorError,
                    ray.core.object_store.WorkerCrashedError,
                ):
                    continue
                finally:
                    ray.free([ref])
                self._counters[NUM_ENV_STEPS_SAMPLED] += (
                    batch.env_steps()
                )
                if hasattr(batch, "policy_batches"):
                    batch = batch.policy_batches[DEFAULT_POLICY_ID]
                self._route_to_replay(batch)
                # periodic weight broadcast to the producing worker
                k = id(w)
                self._batches_since_broadcast[k] = (
                    self._batches_since_broadcast.get(k, 0) + 1
                )
                if self._batches_since_broadcast[k] >= config.get(
                    "broadcast_interval", 1
                ):
                    if weights_ref is None:
                        weights_ref = ray.put(
                            self.workers.local_worker().get_weights()
                        )
                    w.set_weights.remote(
                        weights_ref,
                        {
                            "timestep": self._counters[
                                NUM_ENV_STEPS_SAMPLED
                            ]
                        },
                    )
                    self._batches_since_broadcast[k] = 0
        else:
            # degenerate single-process mode (tests)
            batch = self.workers.local_worker().sample()
            self._counters[NUM_ENV_STEPS_SAMPLED] += batch.env_steps()
            if hasattr(batch, "policy_batches"):
                batch = batch.policy_batches[DEFAULT_POLICY_ID]
            self._route_to_replay(batch)

        # ---- learn from replay shards ----
        if (
            self._counters[NUM_ENV_STEPS_SAMPLED]
            >= config.get("num_steps_sampled_before_learning_starts", 0)
        ) and self._apex_device:
            info = self._learn_from_device_shards(policy)
            if info:
                train_info = info
        elif (
            self._counters[NUM_ENV_STEPS_SAMPLED]
            >= config.get("num_steps_sampled_before_learning_starts", 0)
        ):
            # top up replay sample requests (one per shard in flight)
            shards_busy = set(
                id(a) for a in self._replay_in_flight.values()
            )
            for actor in self.replay_actors:
                if id(actor) not in shards_busy:
                    self._replay_in_flight[
                        actor.sample.remote(config["train_batch_size"])
                    ] = actor
            ready, _ = ray.wait(
                list(self._replay_in_flight.keys()),
                num_returns=1,
                timeout=1.0,
            )
            for ref in ready:
                actor = self._replay_in_flight.pop(ref)
                try:
                    train_batch = ray.get(ref)
                finally:
                    ray.free([ref])
                if train_batch is None:
                    continue
                info = policy.learn_on_batch(train_batch)
                train_info = {DEFAULT_POLICY_ID: info}
                self._counters[NUM_ENV_STEPS_TRAINED] += (
                    train_batch.count
                )
                # push per-sample priority refresh back to the shard
                td = policy.compute_td_error(train_batch)
                actor.update_priorities.remote(
                    np.asarray(train_batch["batch_indexes"]),
                    td + 1e-6,
                )
                # target network sync
                if (
                    self._counters[NUM_ENV_STEPS_TRAINED]
                    - self._last_target_update
                    >= config.get("target_network_update_freq", 2500)
                ):
                    policy.update_target()
                    self._last_target_update = self._counters[
                        NUM_ENV_STEPS_TRAINED
                    ]
                    self._counters["num_target_updates"] += 1

        if not workers:
            self.workers.sync_weights(
                global_vars={
                    "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
                }
            )
        return train_info

    def _maybe_update_target(self, policy) -> None:
        if (
            self._counters[NUM_ENV_STEPS_TRAINED]
            - self._last_target_update
            >= self.config.get("target_network_update_freq", 2500)
        ):
            policy.update_target()
            self._last_target_update = self._counters[
                NUM_ENV_STEPS_TRAINED
            ]
            self._counters["num_target_updates"] += 1

    def _learn_from_device_shards(self, policy) -> Dict:
        """The mesh plane's learn round: every full-enough shard gets
        one learn pass — a fused superstep of K prioritized updates
        when the contract resolves on (in-program gather from the
        shard's rings, in-scan PER refresh back into the shard's
        tree), a single sample → learn → refresh otherwise. No host
        copy between sample and update either way."""
        from ray_tpu.execution.train_ops import superstep_train_replay

        config = self.config
        bs = int(config["train_batch_size"])
        K = self._resolve_superstep_k()
        train_info: Dict = {}
        for shard in self.replay_shards:
            if len(shard) < bs:
                continue
            fused = (
                K > 1
                and getattr(policy, "supports_superstep", False)
                and bs % max(1, getattr(policy, "n_shards", 1)) == 0
                and not shard.spilled
            )
            if fused:
                info = superstep_train_replay(
                    self,
                    policy,
                    shard,
                    K,
                    K,
                    bs,
                    prioritized=True,
                    beta=self._replay_beta,
                )
                if info is None:
                    # frame-pool/ragged batches can't ride the scan
                    self._superstep_k = 1
                    fused = False
                else:
                    train_info[DEFAULT_POLICY_ID] = info
                    self._counters[NUM_ENV_STEPS_TRAINED] += K * bs
            if not fused:
                batch = shard.sample(bs, beta=self._replay_beta)
                if getattr(batch, "is_device_resident", False):
                    info = policy.learn_on_device_batch(
                        dict(batch.tree), batch.count
                    )
                    idx = batch.indices
                else:  # spilled shard: host SampleBatch
                    info = policy.learn_on_batch(batch)
                    idx = np.asarray(batch["batch_indexes"])
                train_info[DEFAULT_POLICY_ID] = info
                self._counters[NUM_ENV_STEPS_TRAINED] += batch.count
                td = policy.compute_td_error(batch)
                shard.update_priorities(idx, td + 1e-6)
            self._maybe_update_target(policy)
        return train_info

    def cleanup(self) -> None:
        for a in getattr(self, "replay_actors", []):
            try:
                ray.kill(a)
            except Exception:
                pass
        super().cleanup()


class ApexDDPGConfig(ApexDQNConfig):
    """reference rllib/algorithms/apex_ddpg/apex_ddpg.py: the Ape-X
    distributed-replay loop around DDPG's continuous-control policy."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDDPG)
        from ray_tpu.algorithms.ddpg.ddpg import DDPGConfig
        from ray_tpu.algorithms.dqn.dqn import DQNConfig

        # Pull in every DDPG policy-side knob on top of the Ape-X loop
        # settings: any attribute DDPGConfig adds or changes vs the
        # shared DQNConfig base is DDPG policy surface — derived by
        # diff so new DDPG knobs can't silently drift out of sync.
        ddpg, base = DDPGConfig(), DQNConfig()
        loop_keys = {
            "algo_class",
            "num_workers",
            "train_batch_size",
            "rollout_fragment_length",
            "n_step",
            "num_steps_sampled_before_learning_starts",
            "replay_buffer_config",
            "target_network_update_freq",
        }
        for key, val in vars(ddpg).items():
            if key in loop_keys:
                continue
            if (
                key not in vars(base)
                or vars(base)[key] != val
            ):
                setattr(self, key, val)
        self.n_step = 3
        self.per_worker_exploration = False
        self.train_batch_size = 256


class ApexDDPG(ApexDQN):
    @classmethod
    def get_default_config(cls) -> "ApexDDPGConfig":
        return ApexDDPGConfig(cls)

    def get_default_policy_class(self, config):
        from ray_tpu.algorithms.ddpg.ddpg import DDPGJaxPolicy

        return DDPGJaxPolicy
