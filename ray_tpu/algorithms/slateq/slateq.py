"""SlateQ: Q-learning over recommendation slates.

Counterpart of the reference's ``rllib/algorithms/slateq/slateq.py``
(Ie et al. 2019) and ``slateq_torch_policy.py``: per-item Q values
decomposed over a slate with a multinomial-proportional user-choice
model — Q(s, slate) = Σ_i score_i·Q_i / (Σ_i score_i + no_click), the
greedy slate maximizes that over ALL candidate slates, and the TD
target bootstraps the max next-slate value (``build_slateq_losses``,
``get_per_slate_q_values``, ``score_documents``).

The user-choice model is LEARNED, like the reference's UserChoiceModel:
a multinomial-logit with learnable affinity scale (beta) and no-click
score, fit by cross-entropy on the observed click/no-click events with
its own learning rate (``lr_choice_model``), and its probabilities
drive both the slate decomposition and the TD targets (stop-gradient:
the TD loss never reshapes the choice model). Slates are ordered
S-permutations enumerated at init (same as the reference's precomputed
``policy.slates``). The whole step — choice NLL, per-item Q net, slate
enumeration via gather, choice-weighted decomposition, target max —
is ONE jitted program; slate enumeration is a static (A, S) index
table so XLA sees fixed shapes.

Because the stock samplers stack flat observation arrays, observations
are the FLAT RecSim layout ``[user(E) | docs(C*E) | response(2S)]``
where response carries the PREVIOUS step's click indicator and watch
times (the RecSim convention the reference consumes); the policy slices
it. ``SyntheticSlateEnv`` below provides the interest-evolution-style
test env (the image has no RecSim)."""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import flax.linen as nn
import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu import sharding as sharding_lib
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig  # noqa: F401
from ray_tpu.algorithms.dqn.dqn import DQN, DQNConfig
from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.models.base import get_activation
from ray_tpu.policy.jax_policy import JaxPolicy, _tree_to_device
from ray_tpu.policy.policy import Policy


class SyntheticSlateEnv(gym.Env):
    """Interest-evolution-style slate env: the user clicks candidates
    proportionally to interest (dot product), watch time rewards
    interest, and interest slowly drifts toward watched content."""

    def __init__(self, config=None):
        config = config or {}
        self.E = int(config.get("embedding_dim", 4))
        self.C = int(config.get("num_candidates", 8))
        self.S = int(config.get("slate_size", 2))
        self.horizon = int(config.get("horizon", 20))
        self._rng = np.random.default_rng(config.get("seed", 0))
        self.observation_space = gym.spaces.Box(
            -np.inf,
            np.inf,
            (self.E + self.C * self.E + 2 * self.S,),
            np.float32,
        )
        self.action_space = gym.spaces.MultiDiscrete(
            [self.C] * self.S
        )

    def _sample_docs(self):
        docs = self._rng.standard_normal((self.C, self.E))
        return (docs / np.linalg.norm(docs, axis=1, keepdims=True)).astype(
            np.float32
        )

    def _obs(self):
        return np.concatenate(
            [
                self.user,
                self.docs.reshape(-1),
                self.last_response.reshape(-1),
            ]
        ).astype(np.float32)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        user = self._rng.standard_normal(self.E)
        self.user = (user / np.linalg.norm(user)).astype(np.float32)
        self.docs = self._sample_docs()
        self.last_response = np.zeros((2, self.S), np.float32)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        slate = np.asarray(action, np.int64).reshape(-1)[: self.S]
        scores = self.docs[slate] @ self.user  # (S,)
        # multinomial proportional choice with no-click mass
        probs = np.maximum(scores + 1.0, 0.0)
        all_mass = np.concatenate([probs, [1.0]])  # no-click last
        all_mass = all_mass / all_mass.sum()
        choice = self._rng.choice(self.S + 1, p=all_mass)
        click = np.zeros(self.S, np.float32)
        watch = np.zeros(self.S, np.float32)
        reward = 0.0
        if choice < self.S:
            click[choice] = 1.0
            watch[choice] = max(0.0, float(scores[choice])) + 0.1
            reward = float(watch[choice])
            # interest drifts toward watched content
            doc = self.docs[slate[choice]]
            self.user = (0.95 * self.user + 0.05 * doc).astype(
                np.float32
            )
            self.user /= np.linalg.norm(self.user)
        self.last_response = np.stack([click, watch])
        self.docs = self._sample_docs()
        self._t += 1
        truncated = self._t >= self.horizon
        return self._obs(), reward, False, truncated, {}


class _ItemQNet(nn.Module):
    """Q(user, doc) per candidate (reference QValueModel)."""

    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, user, docs):
        # user: (B, E); docs: (B, C, E) → (B, C)
        B, C, E = docs.shape
        act = get_activation("relu")
        x = jnp.concatenate(
            [jnp.repeat(user[:, None], C, axis=1), docs], axis=-1
        ).reshape(B * C, 2 * E)
        for i, h in enumerate(self.hiddens):
            x = act(nn.Dense(h, name=f"fc_{i}")(x))
        return nn.Dense(1, name="q")(x).reshape(B, C)


class SlateQConfig(DQNConfig):
    """reference slateq.py SlateQConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or SlateQ)
        self.slate_size = 2
        self.num_candidates = 8
        self.embedding_dim = 4
        self.hiddens = [64, 64]
        self.lr = 1e-3
        self.train_batch_size = 64
        self.rollout_fragment_length = 20
        self.n_step = 1
        self.target_network_update_freq = 500
        self.num_steps_sampled_before_learning_starts = 500
        self.replay_buffer_config = {
            "capacity": 20000,
            "prioritized_replay": False,
        }

    def training(
        self,
        *,
        slate_size: Optional[int] = None,
        num_candidates: Optional[int] = None,
        embedding_dim: Optional[int] = None,
        **kwargs,
    ) -> "SlateQConfig":
        super().training(**kwargs)
        if slate_size is not None:
            self.slate_size = slate_size
        if num_candidates is not None:
            self.num_candidates = num_candidates
        if embedding_dim is not None:
            self.embedding_dim = embedding_dim
        return self


def _score_documents(user, docs, no_click_score=1.0, min_normalizer=-1.0):
    """reference score_documents: proportional choice scores (the
    FIXED scorer; kept for choice_model="proportional")."""
    scores = jnp.sum(user[:, None, :] * docs, axis=-1)  # (B, C)
    scores = scores - min_normalizer
    no_click = jnp.full((user.shape[0],), no_click_score - min_normalizer)
    return scores, no_click


class _ChoiceModel(nn.Module):
    """LEARNED multinomial-logit user-choice model (reference
    slateq_torch_policy.py UserChoiceModel: learnable ``beta`` scaling
    the user·doc affinity and a learnable no-click score, fit by
    cross-entropy on observed clicks with its own learning rate,
    ``lr_choice_model``)."""

    @nn.compact
    def __call__(self, user, docs):
        # beta starts at 0 (uniform choice, matching the reference's
        # UserChoiceModel init): the model must LEARN the affinity
        # scale from observed clicks, so its NLL has genuine headroom
        # below the untrained value — a beta=1 init happens to sit
        # near this env's optimum (exp(s) ≈ 1+s on unit-normalized
        # docs) and leaves the fit nothing to do
        beta = self.param(
            "beta", lambda k: jnp.asarray(0.0, jnp.float32)
        )
        score_no_click = self.param(
            "score_no_click", lambda k: jnp.asarray(0.0, jnp.float32)
        )
        dots = jnp.sum(user[:, None, :] * docs, axis=-1)  # (B, C)
        scores = jnp.clip(beta * dots, -15.0, 15.0)
        no_click = jnp.broadcast_to(
            jnp.clip(score_no_click, -15.0, 15.0), (user.shape[0],)
        )
        return scores, no_click


def _choice_masses(scores, no_click):
    """Multinomial-logit masses: exp(score) per doc, exp(no_click)
    abstention mass — the v_i the slate decomposition normalizes."""
    return jnp.exp(scores), jnp.exp(no_click)


class SlateQJaxPolicy(JaxPolicy):
    """reference slateq_torch_policy.py (decomposed slate Q)."""

    default_exploration = "EpsilonGreedy"

    def __init__(self, observation_space, action_space, config):
        from ray_tpu.algorithms.dqn.dqn import (
            _epsilon_exploration_config,
        )

        config = dict(config)
        config["exploration_config"] = _epsilon_exploration_config(
            config
        )
        Policy.__init__(self, observation_space, action_space, config)
        self.E = int(config.get("embedding_dim", 4))
        self.C = int(config.get("num_candidates", 8))
        self.S = int(config.get("slate_size", 2))
        # all ordered slates (reference precomputes policy.slates)
        self.slates = np.array(
            list(itertools.permutations(range(self.C), self.S)),
            np.int32,
        )  # (A, S)

        self.sharding_backend = config.get("sharding_backend", "mesh")
        self.mesh = sharding_lib.resolve_mesh(config)
        self.n_shards = sharding_lib.num_shards(self.mesh)
        self._param_sharding = sharding_lib.replicated(self.mesh)
        self._data_sharding = sharding_lib.batch_sharded(self.mesh)

        self.qnet = _ItemQNet(tuple(config.get("hiddens", (64, 64))))
        self.choice_model = _ChoiceModel()
        seed = int(config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._rng, r1, r2 = jax.random.split(self._rng, 3)
        dummy_u = jnp.zeros((2, self.E), jnp.float32)
        dummy_d = jnp.zeros((2, self.C, self.E), jnp.float32)
        self.params = _tree_to_device(
            {
                "q": self.qnet.init(r1, dummy_u, dummy_d),
                "choice": self.choice_model.init(
                    r2, dummy_u, dummy_d
                ),
            },
            self._param_sharding,
        )
        self.aux_state = _tree_to_device(
            {"target_params": self.params["q"]}, self._param_sharding
        )
        # separate learning rates: TD net vs the choice model's NLL
        # (reference lr_choice_model vs lr_q_model)
        self._tx = optax.multi_transform(
            {
                "q": optax.adam(float(config.get("lr", 1e-3))),
                "choice": optax.adam(
                    float(config.get("lr_choice_model", 1e-2))
                ),
            },
            lambda params: {
                k: jax.tree_util.tree_map(lambda _: k, sub)
                for k, sub in params.items()
            },
        )
        self.opt_state = _tree_to_device(
            self._tx.init(self.params), self._param_sharding
        )
        self.gamma = float(config.get("gamma", 0.99))
        # base learn_on_device_batch plumbing (schedules feed the
        # traced coeffs dict; the adam tx already embeds the lr, so the
        # scheduled value is informational here)
        from ray_tpu.utils.schedules import make_schedule

        self._lr_schedule = make_schedule(
            config.get("lr_schedule"), config.get("lr", 1e-3)
        )
        self._entropy_schedule = make_schedule(None, 0.0)
        self.coeff_values: Dict[str, float] = {
            "lr": float(self._lr_schedule(0)),
            "entropy_coeff": 0.0,
        }
        self.train_batch_size = int(config.get("train_batch_size", 64))
        self.minibatch_size = self.train_batch_size
        self.num_sgd_iter = 1
        self._learn_fns: Dict = {}
        self._action_fn = None
        self.num_grad_updates = 0
        self._init_exploration()

    # -- obs slicing -------------------------------------------------------

    def _split_obs(self, obs):
        user = obs[:, : self.E]
        docs = obs[
            :, self.E : self.E + self.C * self.E
        ].reshape(-1, self.C, self.E)
        response = obs[:, self.E + self.C * self.E :].reshape(
            -1, 2, self.S
        )
        return user, docs, response

    def _slate_values(self, q_values, scores, no_click):
        """Q(s, slate) for every slate (reference
        get_per_slate_q_values)."""
        slates = jnp.asarray(self.slates)  # (A, S)
        q_slate = q_values[:, slates]  # (B, A, S)
        s_slate = scores[:, slates]  # (B, A, S)
        denom = s_slate.sum(-1) + no_click[:, None]  # (B, A)
        return (q_slate * s_slate).sum(-1) / denom  # (B, A)

    # -- inference ---------------------------------------------------------

    def _build_action_fn(self):
        def fn(params, obs, rng, explore, epsilon):
            user, docs, _ = self._split_obs(obs)
            q = self.qnet.apply(params["q"], user, docs)
            scores, no_click = _choice_masses(
                *self.choice_model.apply(params["choice"], user, docs)
            )
            slate_vals = self._slate_values(q, scores, no_click)
            greedy = jnp.argmax(slate_vals, axis=-1)  # (B,)
            if explore:
                rng_u, rng_a = jax.random.split(rng)
                rand = jax.random.randint(
                    rng_a, greedy.shape, 0, self.slates.shape[0]
                )
                use_rand = (
                    jax.random.uniform(rng_u, greedy.shape) < epsilon
                )
                idx = jnp.where(use_rand, rand, greedy)
            else:
                idx = greedy
            return jnp.asarray(self.slates)[idx]  # (B, S)

        return jax.jit(fn, static_argnames=("explore",))

    def compute_actions(
        self, obs_batch, state_batches=None, explore=True, **kwargs
    ):
        if self._action_fn is None:
            self._action_fn = self._build_action_fn()
        self.exploration.update_coeffs(
            self.coeff_values, self.global_timestep
        )
        self._rng, rng = jax.random.split(self._rng)
        actions = self._action_fn(
            self.params,
            jnp.asarray(obs_batch, jnp.float32),
            rng,
            bool(explore),
            jnp.asarray(
                self.coeff_values.get("epsilon", 0.0), jnp.float32
            ),
        )
        return np.asarray(actions), [], {}

    # -- learning ----------------------------------------------------------

    def _build_learn_fn(self, batch_size: int):
        from jax.sharding import PartitionSpec as P

        gamma = self.gamma
        tx = self._tx
        axis = sharding_lib.data_axis(self.mesh)

        def device_fn(params, opt_state, aux, batch, rng, coeffs):
            obs = batch[SampleBatch.OBS]
            next_obs = batch[SampleBatch.NEXT_OBS]
            actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
            done = batch[SampleBatch.TERMINATEDS].astype(jnp.float32)
            user, docs, _ = self._split_obs(obs)
            # NEXT_OBS response slot carries THIS transition's clicks
            next_user, next_docs, next_resp = self._split_obs(next_obs)
            click = next_resp[:, 0, :]  # (B, S)
            watch = next_resp[:, 1, :]
            reward = jnp.sum(watch * click, axis=1)

            # target: max over next slates of the decomposed value.
            # Target Qs evaluate the NEXT observation's user/docs — the
            # reference evaluates its target model on current obs with
            # a "TODO: find out whether obs or next_obs is correct"
            # (slateq_torch_policy.py:137); with per-step candidate
            # resampling only the next-obs pairing is coherent. Choice
            # probabilities come from the CURRENT learned choice model
            # (stop-gradient: the TD loss must not reshape it).
            tq = self.qnet.apply(
                aux["target_params"], next_user, next_docs
            )
            n_scores, n_no_click = _choice_masses(
                *self.choice_model.apply(
                    params["choice"], next_user, next_docs
                )
            )
            n_scores = jax.lax.stop_gradient(n_scores)
            n_no_click = jax.lax.stop_gradient(n_no_click)
            target_slate_vals = self._slate_values(
                tq, n_scores, n_no_click
            )
            next_max = jnp.max(target_slate_vals, axis=-1)
            y = jax.lax.stop_gradient(
                reward + gamma * (1.0 - done) * next_max
            )

            is_weights = batch.get(
                "weights", jnp.ones_like(done)
            )  # PER importance correction

            def loss_fn(p):
                q = self.qnet.apply(p["q"], user, docs)  # (B, C)
                slate_q = jnp.take_along_axis(
                    q, actions, axis=1
                )  # (B, S)
                clicked_q = jnp.sum(slate_q * click, axis=1)  # (B,)
                clicked = click.sum(axis=1)  # 0/1
                td = (clicked_q - y) * clicked  # only clicked rows
                # normalize by the GLOBAL clicked count so gradient
                # weight per sample doesn't depend on how clicks land
                # across shards (pmean of grads follows)
                n = jnp.maximum(
                    jax.lax.psum(clicked.sum(), axis), 1.0
                )
                shards = jax.lax.psum(1.0, axis)
                td_loss = (
                    shards * jnp.sum(is_weights * jnp.square(td)) / n
                )
                # choice-model NLL on the OBSERVED event: which of the
                # S shown docs was clicked, or no-click (class S) —
                # reference slateq_torch_policy.py choice_loss with
                # lr_choice_model
                c_scores, c_no_click = self.choice_model.apply(
                    p["choice"], user, docs
                )
                shown = jnp.take_along_axis(
                    c_scores, actions, axis=1
                )  # (B, S)
                logits = jnp.concatenate(
                    [shown, c_no_click[:, None]], axis=1
                )  # (B, S+1)
                label = jnp.where(
                    clicked > 0,
                    jnp.argmax(click, axis=1),
                    jnp.full_like(actions[:, 0], self.S),
                )
                logp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(
                    logp, label[:, None], axis=1
                ).squeeze(1)
                choice_loss = jnp.mean(nll)
                return (
                    td_loss + choice_loss,
                    (clicked_q, td, n, choice_loss),
                )

            (
                (loss, (clicked_q, td, n, choice_loss)),
                grads,
            ) = jax.value_and_grad(loss_fn, has_aux=True)(params)
            grads = jax.lax.pmean(grads, axis)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            stats = {
                "total_loss": loss,
                "choice_loss": choice_loss,
                "choice_beta": params["choice"]["params"]["beta"],
                "mean_q_clicked": jnp.sum(clicked_q) / n,
                "mean_td_error": jnp.sum(td) / n,
                "click_fraction": jnp.mean(click.sum(axis=1)),
            }
            stats = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, axis), stats
            )
            return params, opt_state, stats

        sharded = jax.shard_map(
            device_fn,
            mesh=self.mesh,
            in_specs=(P(), P(), P(), P(axis), P(), P()),
            out_specs=(P(), P(), P()),
        )
        label = f"learn[{type(self).__name__}:{batch_size}]"
        if self.sharding_backend == "mesh":
            rep = self._param_sharding
            dat = self._data_sharding
            return sharding_lib.sharded_jit(
                sharded,
                in_specs=(rep, rep, rep, dat, rep, rep),
                out_specs=(rep, rep, rep),
                donate_argnums=(1,),
                label=label,
            )
        return sharding_lib.sharded_jit(
            sharded, donate_argnums=(1,), label=label
        )

    def _refold_exploration_config(self, new_config):
        from ray_tpu.algorithms.dqn.dqn import (
            _epsilon_exploration_config,
        )

        self.config["exploration_config"] = _epsilon_exploration_config(
            self.config, force_keys=new_config
        )

    def update_target(self) -> None:
        # the choice model has no target copy: TD targets always use
        # the freshest learned choice probabilities
        self.aux_state = {"target_params": self.params["q"]}

    def _batch_to_train_tree(self, samples: SampleBatch):
        keys = [
            SampleBatch.OBS,
            SampleBatch.NEXT_OBS,
            SampleBatch.ACTIONS,
            SampleBatch.TERMINATEDS,
            "weights",  # PER importance correction
        ]
        return {
            k: np.asarray(samples[k]) for k in keys if k in samples
        }

    def compute_td_error(self, samples) -> np.ndarray:
        """Per-sample |TD| for prioritized-replay refresh (unclicked
        rows report 0 — they contribute no TD signal)."""
        if not hasattr(self, "_td_error_fn"):

            def fn(params, aux, batch):
                obs = batch[SampleBatch.OBS]
                next_obs = batch[SampleBatch.NEXT_OBS]
                actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
                done = batch[SampleBatch.TERMINATEDS].astype(
                    jnp.float32
                )
                user, docs, _ = self._split_obs(obs)
                next_user, next_docs, next_resp = self._split_obs(
                    next_obs
                )
                click = next_resp[:, 0, :]
                watch = next_resp[:, 1, :]
                reward = jnp.sum(watch * click, axis=1)
                tq = self.qnet.apply(
                    aux["target_params"], next_user, next_docs
                )
                n_scores, n_no_click = _choice_masses(
                    *self.choice_model.apply(
                        params["choice"], next_user, next_docs
                    )
                )
                next_max = jnp.max(
                    self._slate_values(tq, n_scores, n_no_click),
                    axis=-1,
                )
                y = reward + self.gamma * (1.0 - done) * next_max
                q = self.qnet.apply(params["q"], user, docs)
                clicked_q = jnp.sum(
                    jnp.take_along_axis(q, actions, axis=1) * click,
                    axis=1,
                )
                return (clicked_q - y) * click.sum(axis=1)

            self._td_error_fn = jax.jit(fn)
        batch = self._td_input_tree(samples)
        td = self._td_error_fn(self.params, self.aux_state, batch)
        return np.abs(np.asarray(td))

    def get_initial_state(self):
        return []


class SlateQ(DQN):
    _default_policy_class = SlateQJaxPolicy

    @classmethod
    def get_default_config(cls) -> SlateQConfig:
        return SlateQConfig(cls)

    def setup(self, config) -> None:
        if int(config.get("n_step", 1)) != 1:
            raise ValueError(
                "SlateQ derives rewards from the slate response in "
                "NEXT_OBS; n-step folding would pair them wrongly — "
                "n_step must be 1"
            )
        if config.get("lr_schedule"):
            raise ValueError(
                "SlateQ's compiled step embeds a fixed adam lr; "
                "lr_schedule is not supported yet"
            )
        super().setup(config)


# default example-env registration so tuned_examples yamls resolve it
from ray_tpu.env.registry import register_env  # noqa: E402

register_env("SyntheticSlate-v0", lambda cfg: SyntheticSlateEnv(cfg))
