from ray_tpu.algorithms.slateq.slateq import (
    SlateQ,
    SlateQConfig,
    SlateQJaxPolicy,
    SyntheticSlateEnv,
)

__all__ = [
    "SlateQ",
    "SlateQConfig",
    "SlateQJaxPolicy",
    "SyntheticSlateEnv",
]
