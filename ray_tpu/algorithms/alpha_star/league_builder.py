"""League builder: self-play league management for AlphaStar-style
training.

Counterpart of the reference's
``rllib/algorithms/alpha_star/league_builder.py`` (AlphaStar league of
main agents + frozen snapshots with prioritized fictitious self-play
matchmaking), scoped to the single-main-agent league: the trainable
"main" policy plays against frozen snapshots of itself; when its league
win rate crosses ``win_rate_threshold`` a new snapshot joins; opponents
are sampled PFSP-style — harder opponents (lower main win rate) drawn
more often."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

MAIN_POLICY_ID = "main"


class LeagueBuilder:
    """reference league_builder.py AlphaStarLeagueBuilder (scoped)."""

    def __init__(
        self,
        win_rate_threshold: float = 0.7,
        window: int = 50,
        pfsp_power: float = 2.0,
        max_league_size: int = 8,
        seed: Optional[int] = None,
    ):
        self.win_rate_threshold = win_rate_threshold
        self.window = window
        self.pfsp_power = pfsp_power
        self.max_league_size = max_league_size
        self._rng = np.random.default_rng(seed)
        self.members: List[str] = []  # frozen snapshot policy ids
        # per-opponent recent outcomes from main's perspective (1 win,
        # 0.5 draw, 0 loss)
        self._outcomes: Dict[str, List[float]] = {}
        self.num_snapshots = 0

    # -- matchmaking ------------------------------------------------------

    def sample_opponent(self) -> str:
        """PFSP: weight opponents by (1 - winrate)^p so the hardest
        get played most (reference pfsp weighting)."""
        if not self.members:
            raise RuntimeError("league has no members yet")
        weights = []
        for m in self.members:
            wr = self.win_rate(m)
            weights.append(max(1e-3, (1.0 - wr)) ** self.pfsp_power)
        w = np.asarray(weights)
        return str(
            self._rng.choice(self.members, p=w / w.sum())
        )

    # -- bookkeeping ------------------------------------------------------

    def record_outcome(self, opponent: str, outcome: float) -> None:
        buf = self._outcomes.setdefault(opponent, [])
        buf.append(float(outcome))
        del buf[: -self.window]

    def win_rate(self, opponent: Optional[str] = None) -> float:
        if opponent is not None:
            buf = self._outcomes.get(opponent, [])
            return float(np.mean(buf)) if buf else 0.5
        rates = [self.win_rate(m) for m in self.members]
        return float(np.mean(rates)) if rates else 0.5

    def games_played(self) -> int:
        return sum(len(v) for v in self._outcomes.values())

    # -- league growth ----------------------------------------------------

    def should_snapshot(self) -> bool:
        """Main dominates the current league → freeze a copy of it as
        a new member (reference build() snapshot condition)."""
        if len(self.members) >= self.max_league_size:
            return False
        if self.games_played() < self.window:
            return False
        return self.win_rate() >= self.win_rate_threshold

    def register_member(self, policy_id: str) -> None:
        self.members.append(policy_id)
        self.num_snapshots += 1
        # fresh evaluation window vs the NEW league composition — the
        # old outcomes would keep should_snapshot() true and fill the
        # league with near-identical duplicates
        self._outcomes = {m: [] for m in self.members}

    def next_member_id(self) -> str:
        return f"league_{self.num_snapshots}"

    def state(self) -> Dict:
        return {
            "members": list(self.members),
            "win_rates": {
                m: self.win_rate(m) for m in self.members
            },
            "games_played": self.games_played(),
        }
