"""AlphaStar-style league self-play training.

Counterpart of the reference's ``rllib/algorithms/alpha_star/
alpha_star.py:2,102`` (league-based asynchronous multi-agent training
with DISTRIBUTED PER-POLICY LEARNERS) and ``league_builder.py``:

- TWO trainable league roles — "main" (PFSP against frozen league
  snapshots, prioritized fictitious self-play) and "main_exploiter"
  (trains exclusively against the current main, the reference's
  exploiter role) — plus frozen snapshots that join the league when
  main dominates.
- Per-policy learner sharding, the TPU way: the reference places each
  trainable policy's learner on its own GPU shard
  (``alpha_star.py:102`` distributed learner actors); here each
  trainable policy compiles its SGD nest over its OWN SUBMESH of the
  device mesh (mesh split across trainables when enough devices
  exist), so the per-policy updates are independent XLA programs on
  disjoint devices — dispatched asynchronously from one controller,
  they run concurrently like the reference's learner shards.

Env contract: exactly two agents per game; agent ids are arbitrary but
sorted order decides sides — sorted[0] plays the first role of the
current matchup, sorted[1] the second. Matchups alternate between
(main vs PFSP-sampled snapshot) and (main_exploiter vs main).
Zero-sum outcome is read from per-agent episode rewards."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.ppo.ppo import PPO, PPOConfig, PPOJaxPolicy
from ray_tpu.algorithms.alpha_star.league_builder import (
    MAIN_POLICY_ID,
    LeagueBuilder,
)
from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.execution.rollout_ops import synchronous_parallel_sample
from ray_tpu.execution.train_ops import train_one_step


class AlphaStarConfig(PPOConfig):
    """reference alpha_star.py AlphaStarConfig (league knobs)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or AlphaStar)
        self.win_rate_threshold = 0.7
        self.league_window = 50
        self.max_league_size = 8
        self.pfsp_power = 2.0
        self.num_workers = 0  # league matchmaking is driver-side
        # the exploiter role (reference league_builder main exploiters);
        # False = single-main league
        self.train_exploiter = True

    def training(
        self,
        *,
        win_rate_threshold: Optional[float] = None,
        league_window: Optional[int] = None,
        max_league_size: Optional[int] = None,
        train_exploiter: Optional[bool] = None,
        **kwargs,
    ) -> "AlphaStarConfig":
        super().training(**kwargs)
        if win_rate_threshold is not None:
            self.win_rate_threshold = win_rate_threshold
        if league_window is not None:
            self.league_window = league_window
        if max_league_size is not None:
            self.max_league_size = max_league_size
        if train_exploiter is not None:
            self.train_exploiter = train_exploiter
        return self


EXPLOITER_POLICY_ID = "main_exploiter"


class AlphaStar(Algorithm):
    _default_policy_class = PPOJaxPolicy

    @classmethod
    def get_default_config(cls) -> AlphaStarConfig:
        return AlphaStarConfig(cls)

    def setup(self, config: Dict) -> None:
        if int(config.get("num_workers", 0)) != 0:
            raise ValueError(
                "league matchmaking runs driver-side: num_workers=0 "
                "(the reference shards league actors instead)"
            )
        # main + first frozen snapshot share spaces from the env
        from ray_tpu.env.registry import get_env_creator

        probe = get_env_creator(config["env"])(
            config.get("env_config") or {}
        )
        obs_space = probe.observation_space
        act_space = probe.action_space
        try:
            probe.close()
        except Exception:
            pass
        self.league = LeagueBuilder(
            win_rate_threshold=config.get("win_rate_threshold", 0.7),
            window=config.get("league_window", 50),
            pfsp_power=config.get("pfsp_power", 2.0),
            max_league_size=config.get("max_league_size", 8),
            seed=config.get("seed"),
        )
        first = self.league.next_member_id()
        self._train_exploiter = bool(
            config.get("train_exploiter", True)
        )
        trainable = [MAIN_POLICY_ID] + (
            [EXPLOITER_POLICY_ID] if self._train_exploiter else []
        )
        # per-policy learner shards: split the mesh across trainable
        # policies when enough devices exist (the reference's
        # distributed per-policy learner actors, alpha_star.py:102);
        # fewer devices than trainables → everyone shares the full mesh
        import jax

        from ray_tpu.parallel import mesh as mesh_lib

        devices = list(jax.devices())
        per = len(devices) // len(trainable)
        submeshes = {}
        if per >= 1 and len(trainable) > 1 and len(devices) > 1:
            for i, pid in enumerate(trainable):
                submeshes[pid] = mesh_lib.make_mesh(
                    devices=devices[i * per : (i + 1) * per]
                )
        self._learner_submeshes = submeshes
        config["policies"] = {
            pid: (
                None,
                obs_space,
                act_space,
                (
                    {"_mesh": submeshes[pid]}
                    if pid in submeshes
                    else {}
                ),
            )
            for pid in trainable
        }
        config["policies"][first] = (None, obs_space, act_space, {})
        config["policies_to_train"] = trainable
        self._current_opponent = first
        self._obs_space, self._act_space = obs_space, act_space
        self._mapping_calls = 0
        self._matchup_idx = 0
        self._side_order = [MAIN_POLICY_ID, first]

        # The sampler re-consults the mapping fn for every agent at
        # each episode reset (exactly two agents per game), so every
        # even-numbered call starts a fresh matchup: the first
        # consulted agent plays the matchup's first role, the second
        # its opponent.
        def mapping_fn(agent_id, **kw):
            if self._mapping_calls % 2 == 0:
                self._new_matchup()
            role = self._side_order[self._mapping_calls % 2]
            self._mapping_calls += 1
            return role

        config["policy_mapping_fn"] = mapping_fn
        super().setup(config)
        self.league.register_member(first)

    def _new_matchup(self) -> None:
        """Per-episode matchmaking: alternate (main vs PFSP snapshot)
        with (main_exploiter vs main) — the reference's main-exploiter
        games train the exploiter against the CURRENT main while main
        keeps learning from the same episodes."""
        self._matchup_idx += 1
        if self._train_exploiter and self._matchup_idx % 2 == 0:
            self._side_order = [EXPLOITER_POLICY_ID, MAIN_POLICY_ID]
            return
        if self.league.members:
            self._current_opponent = self.league.sample_opponent()
        self._side_order = [MAIN_POLICY_ID, self._current_opponent]

    def training_step(self) -> Dict:
        train_batch = synchronous_parallel_sample(
            worker_set=self.workers,
            max_env_steps=self.config["train_batch_size"],
        )
        self._counters[NUM_ENV_STEPS_SAMPLED] += train_batch.env_steps()
        self._counters[NUM_AGENT_STEPS_SAMPLED] += (
            train_batch.agent_steps()
            if hasattr(train_batch, "agent_steps")
            else train_batch.env_steps()
        )
        # standardize every trainable policy's advantages (PPO
        # semantics, per learner shard)
        pb = getattr(train_batch, "policy_batches", {})
        for pid in self.config.get("policies_to_train") or []:
            if pid in pb:
                b = pb[pid]
                adv = np.asarray(
                    b[SampleBatch.ADVANTAGES], np.float32
                )
                b[SampleBatch.ADVANTAGES] = (
                    (adv - adv.mean()) / max(1e-4, adv.std())
                ).astype(np.float32)
        info = train_one_step(self, train_batch)

        # league bookkeeping from finished episodes' per-agent rewards
        lw = self.workers.local_worker()
        for m in lw.get_metrics():
            self._episode_history.append(m)
            self._episodes_total += 1
            by_pid: Dict[str, float] = {}
            for (aid, pid), r in m.agent_rewards.items():
                by_pid[pid] = by_pid.get(pid, 0.0) + r
            if MAIN_POLICY_ID in by_pid and len(by_pid) == 2:
                opp = next(
                    p for p in by_pid if p != MAIN_POLICY_ID
                )
                # PFSP stats track league snapshots only; exploiter
                # games don't count toward snapshot win rates
                if opp not in self.league.members:
                    continue
                diff = by_pid[MAIN_POLICY_ID] - by_pid[opp]
                outcome = (
                    1.0 if diff > 0 else (0.0 if diff < 0 else 0.5)
                )
                self.league.record_outcome(opp, outcome)

        # schedules (lr/entropy) read global_timestep
        lw.set_global_vars(
            {"timestep": self._counters[NUM_ENV_STEPS_SAMPLED]}
        )

        # snapshot main into the league when it dominates
        if self.league.should_snapshot():
            new_id = self.league.next_member_id()
            weights = lw.policy_map[MAIN_POLICY_ID].get_weights()
            self._add_league_policy(new_id, weights)
            self.league.register_member(new_id)
            self._counters["league_size"] = len(self.league.members)

        out = dict(info)
        out["league"] = self.league.state()
        return out

    def _add_league_policy(self, new_id: str, weights) -> None:
        """Add a frozen snapshot everywhere the mapping fn can route a
        game — including evaluation workers, whose policy_map was built
        before the league grew."""
        lw = self.workers.local_worker()
        cls = type(lw.policy_map[MAIN_POLICY_ID])
        lw.add_policy(
            new_id, cls, self._obs_space, self._act_space,
            weights=weights,
        )
        if self.evaluation_workers is not None:
            ev = self.evaluation_workers.local_worker()
            if ev is not None:
                ev.add_policy(
                    new_id, cls, self._obs_space, self._act_space,
                    weights=weights,
                )

    # -- checkpoint state: league snapshots + matchmaking stats ----------

    def __getstate__(self) -> Dict:
        state = super().__getstate__()
        lw = self.workers.local_worker()
        state["league"] = {
            "members": list(self.league.members),
            "num_snapshots": self.league.num_snapshots,
            "outcomes": {
                k: list(v) for k, v in self.league._outcomes.items()
            },
            "snapshot_weights": {
                m: lw.policy_map[m].get_weights()
                for m in self.league.members
                if m in lw.policy_map
            },
        }
        return state

    def __setstate__(self, state: Dict) -> None:
        league = state.pop("league", None)
        super().__setstate__(state)
        if league:
            lw = self.workers.local_worker()
            for m in league["members"]:
                if m not in lw.policy_map:
                    self._add_league_policy(
                        m, league["snapshot_weights"][m]
                    )
                elif m in league["snapshot_weights"]:
                    lw.policy_map[m].set_weights(
                        league["snapshot_weights"][m]
                    )
            self.league.members = list(league["members"])
            self.league.num_snapshots = league["num_snapshots"]
            self.league._outcomes = {
                k: list(v) for k, v in league["outcomes"].items()
            }
