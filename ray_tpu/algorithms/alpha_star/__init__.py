from ray_tpu.algorithms.alpha_star.alpha_star import (
    AlphaStar,
    AlphaStarConfig,
)
from ray_tpu.algorithms.alpha_star.league_builder import (
    MAIN_POLICY_ID,
    LeagueBuilder,
)

__all__ = [
    "AlphaStar",
    "AlphaStarConfig",
    "LeagueBuilder",
    "MAIN_POLICY_ID",
]
