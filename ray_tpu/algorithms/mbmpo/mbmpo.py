"""MBMPO: model-based meta-policy optimization.

Counterpart of the reference's ``rllib/algorithms/mbmpo/`` (Clavera et
al. 2018): learn an ENSEMBLE of transition-dynamics (TD) models from
real experience, then run MAML where each ensemble member plays the role
of a task — the policy meta-learns an initialization that adapts in one
inner PG step to any member's dynamics, which makes it robust to model
bias when deployed on the real env.

Reference structure (``mbmpo.py``, ``model_ensemble.py``):
- ``DynamicsEnsembleCustomModel``: E MLPs predicting Δobs from
  (obs, action), normalized data, train/validation split, early stop on
  a moving-average validation loss;
- ``model_vector_env``: imagined episodes sampled from a random member;
- the MAML inner/outer loop with ``maml_optimizer_steps`` PPO-surrogate
  meta-updates per batch of imagined data.

TPU-first shape:
- the ensemble is ONE set of stacked parameters; a training epoch is a
  single jitted program — ``lax.scan`` over minibatches, ``vmap`` over
  members (each with its own shuffling) — so E models train in one XLA
  dispatch instead of E python loops;
- imagined rollouts are a ``lax.scan`` over the horizon, ``vmap``-ed
  over members, so the whole [E, rollouts, T] data tensor is produced
  device-side in one call (the reference steps a python VectorEnv);
- the meta-objective differentiates straight through the inner PG step
  (see ``ray_tpu/algorithms/maml/maml.py``), vmapped over members.

Env contract: like the reference (``mbmpo.py model_vector_env``), the
env must expose ``reward(obs, action, next_obs)``; it must be written
with array operators so it traces under jit (numpy ufuncs on jnp arrays
are fine).  Box action spaces only (the reference's published configs
are all continuous-control).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID
from ray_tpu.env.registry import get_env_creator
from ray_tpu.evaluation.metrics import RolloutMetrics
from ray_tpu.algorithms.maml.maml import (
    build_act_fn,
    build_meta_objective,
)
from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED
from ray_tpu.ops.gae import discount_cumsum
from ray_tpu.models.catalog import ModelCatalog
from ray_tpu.models.distributions import DiagGaussian


class PointMassEnv(gym.Env):
    """1D double-integrator: obs = [pos, vel], action = accel; reward =
    -(pos² + 0.1 vel²). ``reward`` is written with array operators so it
    traces inside the jitted imagined rollout (the MBMPO env contract;
    the reference's counterpart task suite is ``rllib/env/wrappers/
    model_vector_env``-compatible mujoco envs)."""

    def __init__(self, config=None):
        config = config or {}
        self.horizon = int(config.get("horizon", 30))
        self.observation_space = gym.spaces.Box(
            -np.inf, np.inf, (2,), np.float32
        )
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._rng = np.random.default_rng(config.get("seed", 0))

    def reward(self, obs, action, next_obs):
        return -(next_obs[..., 0] ** 2 + 0.1 * next_obs[..., 1] ** 2)

    def reset(self, *, seed=None, options=None):
        self.x = self._rng.normal(0, 1.0, 2).astype(np.float32)
        self._t = 0
        return self.x.copy(), {}

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1)[0], -1, 1))
        pos, vel = float(self.x[0]), float(self.x[1])
        vel = vel + 0.2 * a
        pos = pos + 0.2 * vel
        self.x = np.array([pos, vel], np.float32)
        self._t += 1
        r = float(self.reward(None, None, self.x))
        return self.x.copy(), r, False, self._t >= self.horizon, {}


class TDModel(nn.Module):
    """One transition-dynamics model: (obs, action) → Δobs
    (reference ``model_ensemble.py:53`` TDModel)."""

    obs_dim: int
    hiddens: Tuple[int, ...] = (512, 512, 512)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for h in self.hiddens:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.obs_dim)(x)


class DynamicsEnsemble:
    """E TD models with stacked params, trained in one jitted program
    (reference ``model_ensemble.py:117`` DynamicsEnsembleCustomModel).

    Normalization statistics (mean/std of inputs and of Δobs targets)
    are recomputed at every ``fit`` like the reference; early stopping
    watches a 5-epoch moving average of the mean validation loss
    (scoped: the reference stops each member independently)."""

    def __init__(self, obs_dim, act_dim, config, seed=0):
        self.obs_dim, self.act_dim = obs_dim, act_dim
        self.ensemble_size = int(config.get("ensemble_size", 5))
        self.model = TDModel(
            obs_dim=obs_dim,
            hiddens=tuple(config.get("fcnet_hiddens", [512, 512, 512])),
        )
        self.lr = float(config.get("lr", 1e-3))
        self.train_epochs = int(config.get("train_epochs", 500))
        self.batch_size = int(config.get("batch_size", 500))
        self.valid_split = float(config.get("valid_split_ratio", 0.2))
        self.normalize_data = bool(config.get("normalize_data", True))
        keys = jax.random.split(jax.random.PRNGKey(seed), self.ensemble_size)
        dummy = jnp.zeros((1, obs_dim + act_dim), jnp.float32)
        self.params = jax.vmap(self.model.init, in_axes=(0, None))(
            keys, dummy
        )
        self._tx = optax.adam(self.lr)
        self.opt_state = jax.vmap(self._tx.init)(self.params)
        self.norm = {
            "x_mean": jnp.zeros(obs_dim + act_dim),
            "x_std": jnp.ones(obs_dim + act_dim),
            "y_mean": jnp.zeros(obs_dim),
            "y_std": jnp.ones(obs_dim),
        }
        self._np_rng = np.random.default_rng(seed)
        self._epoch_fn = None
        self._val_fn = None

    # -- jitted programs ---------------------------------------------------

    def _build(self):
        model, tx = self.model, self._tx

        def mse(p, x, y):
            return jnp.mean(jnp.square(model.apply(p, x) - y))

        def per_model_epoch(p, opt, x, y, perm):
            """perm: (n_mb, mb) minibatch index matrix for this member."""

            def mb_step(carry, idx):
                p, opt = carry
                loss, grads = jax.value_and_grad(mse)(p, x[idx], y[idx])
                upd, opt = tx.update(grads, opt, p)
                return (optax.apply_updates(p, upd), opt), loss

            (p, opt), losses = jax.lax.scan(mb_step, (p, opt), perm)
            return p, opt, jnp.mean(losses)

        self._epoch_fn = jax.jit(
            jax.vmap(per_model_epoch, in_axes=(0, 0, None, None, 0))
        )
        self._val_fn = jax.jit(
            jax.vmap(mse, in_axes=(0, None, None))
        )

    def fit(self, obs, actions, next_obs) -> Dict[str, float]:
        """Fit all members on (obs, action) → Δobs; returns loss stats."""
        if self._epoch_fn is None:
            self._build()
        X = np.concatenate([obs, actions], -1).astype(np.float32)
        Y = (next_obs - obs).astype(np.float32)
        if self.normalize_data:
            self.norm = {
                "x_mean": jnp.asarray(X.mean(0)),
                "x_std": jnp.asarray(X.std(0) + 1e-6),
                "y_mean": jnp.asarray(Y.mean(0)),
                "y_std": jnp.asarray(Y.std(0) + 1e-6),
            }
        Xn = (jnp.asarray(X) - self.norm["x_mean"]) / self.norm["x_std"]
        Yn = (jnp.asarray(Y) - self.norm["y_mean"]) / self.norm["y_std"]
        n = len(X)
        split = max(1, int(n * (1 - self.valid_split)))
        order = self._np_rng.permutation(n)
        tr_idx, va_idx = order[:split], order[split:]
        Xtr, Ytr = Xn[tr_idx], Yn[tr_idx]
        Xva, Yva = Xn[va_idx], Yn[va_idx]
        mb = min(self.batch_size, len(tr_idx))
        n_mb = max(1, len(tr_idx) // mb)

        best, patience, train_loss, val_loss = np.inf, 0, np.nan, np.nan
        history = []
        for _ in range(self.train_epochs):
            perms = np.stack(
                [
                    self._np_rng.permutation(len(tr_idx))[: n_mb * mb]
                    .reshape(n_mb, mb)
                    for _ in range(self.ensemble_size)
                ]
            )
            self.params, self.opt_state, tr_losses = self._epoch_fn(
                self.params, self.opt_state, Xtr, Ytr,
                jnp.asarray(perms),
            )
            train_loss = float(jnp.mean(tr_losses))
            if len(va_idx):
                val_loss = float(
                    jnp.mean(self._val_fn(self.params, Xva, Yva))
                )
            else:
                val_loss = train_loss
            history.append(val_loss)
            avg = float(np.mean(history[-5:]))
            if avg < best - 1e-5:
                best, patience = avg, 0
            else:
                patience += 1
                if patience >= 5:
                    break
        return {
            "dyn_train_loss": train_loss,
            "dyn_val_loss": val_loss,
            "dyn_epochs": len(history),
        }

    def predict_fn(self):
        """Pure (member_params, norm, obs, action) → next_obs for use
        inside jitted rollouts. ``norm`` is a runtime argument so the
        rollout program compiles once and survives refits."""
        model = self.model

        def predict(member_params, norm, obs, action):
            x = jnp.concatenate([obs, action], -1)
            xn = (x - norm["x_mean"]) / norm["x_std"]
            dn = model.apply(member_params, xn)
            return obs + dn * norm["y_std"] + norm["y_mean"]

        return predict


class MBMPOConfig(AlgorithmConfig):
    """reference ``mbmpo.py:70`` MBMPOConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or MBMPO)
        self.inner_lr = 1e-3
        self.clip_param = 0.5
        self.inner_adaptation_steps = 1
        self.maml_optimizer_steps = 8
        self.num_maml_steps = 10
        self.horizon = 200
        self.rollouts_per_model = 20
        self.real_episodes_per_iteration = 2
        self.lr = 1e-3
        self.dynamics_model = {
            "ensemble_size": 5,
            "fcnet_hiddens": [512, 512, 512],
            "lr": 1e-3,
            "train_epochs": 500,
            "batch_size": 500,
            "valid_split_ratio": 0.2,
            "normalize_data": True,
        }
        self.model = {"fcnet_hiddens": [64, 64]}

    def training(
        self,
        *,
        inner_lr: Optional[float] = None,
        clip_param: Optional[float] = None,
        inner_adaptation_steps: Optional[int] = None,
        maml_optimizer_steps: Optional[int] = None,
        num_maml_steps: Optional[int] = None,
        horizon: Optional[int] = None,
        rollouts_per_model: Optional[int] = None,
        real_episodes_per_iteration: Optional[int] = None,
        dynamics_model: Optional[dict] = None,
        **kwargs,
    ) -> "MBMPOConfig":
        super().training(**kwargs)
        for name, val in (
            ("inner_lr", inner_lr),
            ("clip_param", clip_param),
            ("inner_adaptation_steps", inner_adaptation_steps),
            ("maml_optimizer_steps", maml_optimizer_steps),
            ("num_maml_steps", num_maml_steps),
            ("horizon", horizon),
            ("rollouts_per_model", rollouts_per_model),
            ("real_episodes_per_iteration", real_episodes_per_iteration),
        ):
            if val is not None:
                setattr(self, name, val)
        if dynamics_model is not None:
            self.dynamics_model = {
                **self.dynamics_model, **dynamics_model
            }
        return self


class MBMPO(Algorithm):
    @classmethod
    def get_default_config(cls) -> MBMPOConfig:
        return MBMPOConfig(cls)

    def setup(self, config: Dict) -> None:
        env_spec = config.get("env")
        super().setup(dict(config, env=None))
        self.env = get_env_creator(env_spec)(
            config.get("env_config") or {}
        )
        assert hasattr(self.env, "reward"), (
            "MBMPO needs env.reward(obs, action, next_obs) for imagined "
            "rollouts (reference mbmpo.py model_vector_env)"
        )
        obs_space = self.env.observation_space
        act_space = self.env.action_space
        assert isinstance(act_space, gym.spaces.Box)
        self.obs_dim = int(np.prod(obs_space.shape))
        self.act_dim = int(np.prod(act_space.shape))
        self._act_low = np.asarray(act_space.low, np.float32)
        self._act_high = np.asarray(act_space.high, np.float32)

        self.dist_cls = DiagGaussian
        self.model = ModelCatalog.get_model(
            obs_space, act_space, 2 * self.act_dim,
            dict(config.get("model") or {}),
        )
        seed = int(config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._np_rng = np.random.default_rng(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        dummy = jnp.zeros((2, self.obs_dim), jnp.float32)
        self.params = self.model.init(init_rng, dummy)
        self._tx = optax.adam(float(config.get("lr", 1e-3)))
        self.opt_state = self._tx.init(self.params)

        self.dynamics = DynamicsEnsemble(
            self.obs_dim,
            self.act_dim,
            dict(
                MBMPOConfig().dynamics_model,
                **(config.get("dynamics_model") or {}),
            ),
            seed=seed,
        )
        # real-experience dataset for model fitting
        self._real = {"obs": [], "actions": [], "next_obs": []}
        self._start_obs: list = []
        self._meta_fn = None
        self._rollout_fn = None
        self._rollout_member_fn = None
        self._act_fn = None

    # -- real-env interaction ---------------------------------------------

    def _real_episode(self, params) -> float:
        if self._act_fn is None:
            self._act_fn = build_act_fn(self.model, self.dist_cls)
        horizon = int(self.config.get("horizon", 200))
        obs, _ = self.env.reset()
        self._start_obs.append(np.asarray(obs, np.float32))
        ep_reward, steps = 0.0, 0
        for _ in range(horizon):
            self._rng, sub = jax.random.split(self._rng)
            a, _ = self._act_fn(
                params, jnp.asarray(obs, jnp.float32)[None], sub
            )
            a = np.clip(
                np.asarray(a[0]), self._act_low, self._act_high
            )
            next_obs, r, term, trunc, _ = self.env.step(a)
            self._real["obs"].append(np.asarray(obs, np.float32))
            self._real["actions"].append(a.astype(np.float32))
            self._real["next_obs"].append(
                np.asarray(next_obs, np.float32)
            )
            ep_reward += float(r)
            steps += 1
            obs = next_obs
            if term or trunc:
                break
        self._counters[NUM_ENV_STEPS_SAMPLED] += steps
        self._counters[NUM_AGENT_STEPS_SAMPLED] += steps
        self._episode_history.append(RolloutMetrics(steps, ep_reward))
        self._episodes_total += 1
        return ep_reward

    # -- imagined rollouts (one jitted program) ----------------------------

    def _build_rollout_fn(self):
        predict = self.dynamics.predict_fn()
        model, dist_cls = self.model, self.dist_cls
        horizon = int(self.config.get("imagine_horizon") or 0) or int(
            self.config.get("horizon", 200)
        )
        gamma = float(self.config.get("gamma", 0.99))
        reward_fn = self.env.reward
        lo = jnp.asarray(self._act_low.reshape(-1))
        hi = jnp.asarray(self._act_high.reshape(-1))

        def one_member(member_params, norm, params, obs0, rng):
            """obs0: (n, obs_dim) start states for this member."""

            def step(obs, rng_t):
                dist_inputs, _, _ = model.apply(params, obs)
                a, logp = dist_cls(dist_inputs).sampled_action_logp(
                    rng_t
                )
                # store the UNCLIPPED sample so (action, logp) stay a
                # consistent pair for the PPO ratio; clip only at the
                # dynamics/reward boundary (like env-side clipping)
                a_env = jnp.clip(a, lo, hi)
                next_obs = predict(member_params, norm, obs, a_env)
                r = reward_fn(obs, a_env, next_obs)
                return next_obs, (obs, a, logp, r)

            _, (o, a, logp, r) = jax.lax.scan(
                step, obs0, jax.random.split(rng, horizon)
            )
            # log-depth reverse discounted cumsum (works on last axis)
            rets = jnp.moveaxis(
                discount_cumsum(jnp.moveaxis(r, 0, -1), gamma), -1, 0
            )
            return o, a, logp, rets, r

        def make(params_axis):
            """params_axis=None: one shared policy tree for every
            member (pre-adaptation data). params_axis=0: a stacked tree
            of per-member adapted policies θ'_m — each member's post
            data is rolled out under its own θ'_m, so the PPO surrogate
            in the meta-loss evaluates genuinely on-policy post data."""

            def sample_all(ens_params, norm, params, obs0, rng):
                """obs0: (E, n, obs_dim) → (E, n*T) flat task batches."""
                E = obs0.shape[0]
                rngs = jax.random.split(rng, E)
                o, a, logp, rets, r = jax.vmap(
                    one_member, in_axes=(0, None, params_axis, 0, 0)
                )(ens_params, norm, params, obs0, rngs)
                # (E, T, n, ...) → (E, n*T, ...)
                def flat(x):
                    x = jnp.moveaxis(x, 1, 2)
                    return x.reshape((E, -1) + x.shape[3:])

                adv = flat(rets)
                adv = (adv - adv.mean()) / (adv.std() + 1e-4)
                return {
                    "obs": flat(o),
                    "actions": flat(a),
                    "logp": flat(logp),
                    "advantages": adv,
                    "mean_reward": jnp.mean(r),
                }

            return jax.jit(sample_all)

        return make(None), make(0)

    # -- meta objective (shared shape with MAML) ---------------------------

    def _build_meta_fn(self):
        self._adapted_jit, meta_step = build_meta_objective(
            self.model,
            self.dist_cls,
            self._tx,
            inner_lr=float(self.config.get("inner_lr", 1e-3)),
            clip=float(self.config.get("clip_param", 0.5)),
            inner_steps=int(
                self.config.get("inner_adaptation_steps", 1)
            ),
        )
        # θ'_m per ensemble member: vmap the inner adaptation over the
        # member axis of the pre batches (one stacked params tree out)
        self._adapt_members = jax.jit(
            jax.vmap(self._adapted_jit, in_axes=(None, 0))
        )
        return meta_step


    # -- training ----------------------------------------------------------

    def _sample_start_obs(self, rng) -> jnp.ndarray:
        E = self.dynamics.ensemble_size
        n = int(self.config.get("rollouts_per_model", 20))
        pool = np.stack(self._start_obs)
        idx = rng.integers(len(pool), size=E * n)
        return jnp.asarray(
            pool[idx].reshape(E, n, self.obs_dim), jnp.float32
        )

    def training_step(self) -> Dict:
        config = self.config
        if self._meta_fn is None:
            self._meta_fn = self._build_meta_fn()

        # 1. real experience with the current (post-adapted) policy
        n_real = int(config.get("real_episodes_per_iteration", 2))
        rewards = [self._real_episode(self.params) for _ in range(n_real)]

        # 2. refit the dynamics ensemble on everything seen so far
        dyn_stats = self.dynamics.fit(
            np.stack(self._real["obs"]),
            np.stack(self._real["actions"]),
            np.stack(self._real["next_obs"]),
        )
        if self._rollout_fn is None:
            self._rollout_fn, self._rollout_member_fn = (
                self._build_rollout_fn()
            )

        # 3. MAML over ensemble members as tasks
        meta_losses, imag_rewards = [], []
        n_steps = int(config.get("num_maml_steps", 10))
        opt_steps = int(config.get("maml_optimizer_steps", 8))
        loss = float("nan")
        for _ in range(n_steps):
            obs0 = self._sample_start_obs(self._np_rng)
            self._rng, r1, r2 = jax.random.split(self._rng, 3)
            pre = self._rollout_fn(
                self.dynamics.params, self.dynamics.norm,
                self.params, obs0, r1,
            )
            pre.pop("mean_reward")
            # post-adaptation data: imagined rollouts under θ'_m,
            # adapted PER MEMBER on that member's pre batch (vmap) and
            # rolled out under that member's own adapted policy — the
            # same per-task adaptation build_meta_objective's meta-loss
            # performs, so the PPO surrogate's clipped ratios are
            # evaluated on on-policy post data (reference: per-worker
            # adapted policy copies in mbmpo.py's inner loop).
            post_obs0 = self._sample_start_obs(self._np_rng)
            adapted_stack = self._adapt_members(self.params, pre)
            post = self._rollout_member_fn(
                self.dynamics.params, self.dynamics.norm,
                adapted_stack, post_obs0, r2,
            )
            # imagined post-adaptation reward: the standard MBMPO
            # model-rollout diagnostic
            imag_rewards.append(float(post.pop("mean_reward")))
            for _ in range(opt_steps):
                self.params, self.opt_state, loss = self._meta_fn(
                    self.params, self.opt_state, pre, post
                )
            meta_losses.append(float(loss))
            self._counters[NUM_ENV_STEPS_TRAINED] += int(
                pre["obs"].shape[0] * pre["obs"].shape[1]
            )

        return {
            DEFAULT_POLICY_ID: {
                "meta_loss": float(np.mean(meta_losses)),
                "real_episode_reward": float(np.mean(rewards)),
                "imagined_reward_mean": float(np.mean(imag_rewards)),
                **{k: float(v) for k, v in dyn_stats.items()},
            }
        }

    def __getstate__(self) -> Dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "dyn_params": jax.device_get(self.dynamics.params),
            "dyn_norm": jax.device_get(self.dynamics.norm),
            "counters": dict(self._counters),
            "episodes_total": self._episodes_total,
        }

    def __setstate__(self, state: Dict) -> None:
        import collections

        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])
        if hasattr(self, "dynamics"):
            self.dynamics.params = jax.device_put(state["dyn_params"])
            self.dynamics.norm = jax.device_put(state["dyn_norm"])
        self._counters = collections.defaultdict(
            int, state.get("counters", {})
        )
        self._episodes_total = state.get("episodes_total", 0)

    def cleanup(self) -> None:
        try:
            self.env.close()
        except Exception:
            pass
        super().cleanup()


# default example-env registration so tuned_examples yamls resolve it
from ray_tpu.env.registry import register_env  # noqa: E402

register_env("PointMass-v0", lambda cfg: PointMassEnv(cfg))
