from ray_tpu.algorithms.mbmpo.mbmpo import (  # noqa: F401
    MBMPO,
    DynamicsEnsemble,
    MBMPOConfig,
    TDModel,
)
