"""AlgorithmConfig: typed fluent builder → plain dict.

Counterpart of the reference's ``rllib/algorithms/algorithm_config.py:33``
(``resources :339``, ``framework :408``, ``environment :453``,
``rollouts :533``, ``training :717``, ``evaluation :800``,
``multi_agent :1027``, ``to_dict :241``). The framework is always "jax"
here; the knob kept for API parity.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Type


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class

        # environment (reference :453)
        self.env = None
        self.env_config: Dict = {}
        self.observation_space = None
        self.action_space = None
        self.clip_actions = False
        self.normalize_actions = True
        self.horizon = None
        # rollout lane (docs/pipeline.md "two rollout lanes"):
        # "actor" (default) samples on CPU Ray-actor workers through
        # the SyncSampler; "jax" runs act → env.step → postprocess as
        # ONE jit'd program on the learner mesh (JaxVectorEnv envs
        # only — zero rollout bytes over H2D). The two lanes share
        # SampleBatch semantics and a fixed-seed parity contract
        # (tests/test_jax_env.py).
        self.env_backend = "actor"
        # "jax" lane only: fuse rollout+learn into one dispatched
        # superstep program (False keeps rollout and learn as
        # separate dispatches — the benchmark A/B's middle lane)
        self.jax_fused_rollout = True

        # framework (reference :408)
        # ray-tpu: allow[RTA012] API-parity stub: the framework is always jax here; the knob exists so reference configs round-trip
        self.framework_str = "jax"

        # rollouts (reference :533)
        self.num_workers = 0
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 200
        self.batch_mode = "truncate_episodes"
        self.observation_filter = "NoFilter"
        # ray-tpu: allow[RTA012] API-parity stub: in-process transport never serializes observations, so there is nothing to compress
        self.compress_observations = False
        self.ignore_worker_failures = False
        self.recreate_failed_workers = False
        # Pipelined sampling (docs/pipeline.md): >0 overlaps rollout
        # collection + host concat + device transfer of batch k+1 with
        # the SGD nest of batch k, at a bounded staleness of
        # `sample_prefetch` updates. 0 (default) keeps the fully
        # synchronous loop — bit-identical to the classic path.
        self.sample_prefetch = 0
        # Outstanding sample requests per rollout worker for the async
        # paths (reference max_requests_in_flight_per_rollout_worker).
        self.max_requests_in_flight_per_rollout_worker = 2

        # fault tolerance (docs/resilience.md)
        # recovery-action budget for Algorithm.train(): worker
        # recreations + checkpoint restores. < 0 = unlimited (the
        # pre-existing semantics of the two rollout flags above).
        self.max_failures = -1
        # every N iterations, save into checkpoint_root (default
        # <logdir>/resilience) and keep it as the auto-restore target;
        # 0 = off
        self.checkpoint_frequency = 0
        self.checkpoint_root = None
        # prune periodic checkpoints down to the newest N (None = keep
        # everything)
        self.keep_checkpoints_num = None
        # restartable driver-side failure (learner crash, anything
        # non-actor-death) → restore the latest checkpoint + continue
        self.restore_on_failure = False
        # skip non-finite learn batches instead of corrupting params
        self.nan_guard = False
        # single wall-clock budget for the parallel health sweep
        self.worker_health_probe_timeout_s = 10.0
        # the uniform RetryPolicy (resilience/retry.py) every
        # driver-side remote interaction draws from
        self.retry_max_attempts = 3
        self.retry_timeout_s = 60.0
        self.retry_backoff_s = 0.05
        self.retry_backoff_mult = 2.0
        self.retry_max_backoff_s = 2.0
        self.retry_jitter = 0.1
        # deterministic chaos spec (resilience/faults.py); {} = inert,
        # None additionally allows the RAY_TPU_FAULTS env fallback
        self.fault_injection: Optional[Dict] = None
        # elastic fleet (docs/resilience.md "elastic fleets &
        # preemption"): True starts a FleetController at
        # Algorithm.setup — the rollout fleet grows/shrinks at runtime
        # within [min_workers, max_workers]: preemption notices drain
        # workers gracefully (zero recovery budget), learner
        # starvation (empty sampler queues) scales up, long-idle
        # workers reap down. Batch accounting is fleet-size
        # independent, so a stable-fleet phase is bit-identical to a
        # non-elastic run on a fixed seed.
        self.elastic = False
        self.min_workers = None  # None → 1
        self.max_workers = None  # None → 2 × num_workers
        # drain budget: how long a noticed/reaped worker gets to ship
        # its final sample results + filter state before being dropped
        self.drain_grace_s = 15.0
        self.fleet_interval_s = 1.0  # monitor-thread poll period
        self.fleet_idle_timeout_s = 30.0  # reap after this long idle
        self.fleet_starvation_patience = 3  # polls before scale-up
        self.scale_up_step = 1
        # continuous checkpoint streaming (resilience/streamer.py):
        # True snapshots params/opt-state every
        # checkpoint_stream_interval supersteps on a background thread
        # (atomic write + fsync, off the critical path), bounding
        # work-lost-on-driver-crash to ~1 superstep; the recovery
        # layer restores from the stream tail when it is newer than
        # the latest periodic checkpoint.
        self.checkpoint_streaming = False
        self.checkpoint_stream_interval = 1

        # training (reference :717)
        self.gamma = 0.99
        self.lr = 0.001
        self.lr_schedule = None
        self.train_batch_size = 4000
        self.model: Dict = {}
        self.optimizer: Dict = {}
        self.grad_clip = None
        self.seed = None

        # device-resident data plane (docs/data_plane.md)
        # "auto" (default): off-policy replay rows live as device
        # arrays on the learner mesh — each transition crosses H2D
        # once at insert, never per learn step — spilling back to the
        # host ring when the projected buffer exceeds
        # replay_memory_cap_bytes (default 60% of the device's
        # reported budget). Auto engages only behind a real
        # accelerator boundary (on the CPU client "device" arrays
        # share host RAM — nothing to diet); True forces device
        # placement anywhere (still spills on the memory projection),
        # False keeps the host ring. Fixed-seed results are
        # bit-identical either way.
        self.replay_device_resident = "auto"
        self.replay_memory_cap_bytes = None
        # Device sum tree (docs/data_plane.md "device sum tree"):
        # prioritized-replay priorities live as f64 mesh arrays and a
        # sample is ONE fused draw→gather program — zero payload bytes
        # cross H2D on the sample path, and index draws reproduce the
        # host sum tree bit-exactly (the generator's raw uniform
        # stream stays host-fed). Requires device-resident rows.
        # "auto" engages behind a real accelerator; True forces it
        # (tests/benches); False keeps the host numpy tree walk.
        self.replay_device_tree = "auto"
        # Learn-while-rollout interleave for the off-policy family on
        # the fused jax rollout lane (env_backend="jax"): dispatch the
        # round's rollout-fill program asynchronously, run the replay
        # superstep against the PREVIOUS round's buffer contents while
        # the fill executes, then insert — acting and fused updates
        # overlap in one cadence (one-round insert staleness, same
        # spirit as sample_async's weight lag; docs/data_plane.md).
        self.learn_while_rollout = False
        # On-device training superstep (docs/data_plane.md): one
        # driver dispatch = K learner updates, uniformly across the
        # learner path (DQN-family chained updates incl. prioritized
        # replay, PPO's prefetch loop, IMPALA's learner thread). The
        # whole K-update chain — weights threaded through a lax.scan
        # carry, device-replay batches gathered in place, stats (and
        # PER priorities) drained as one stacked readback — runs as
        # ONE compiled program, so per-dispatch overhead amortizes
        # 1/K. "auto" (default) resolves to K=8 behind a real
        # accelerator boundary and off on the CPU client (mirroring
        # replay_device_resident); an int forces that K anywhere.
        # Fixed-seed results are bit-identical to K individual learn
        # calls (host-side stat reactions lag the chain — staleness
        # semantics in docs/data_plane.md).
        self.superstep = "auto"
        # Defer the learner's stats readback by one call: learn
        # returns right after the SGD nest is dispatched and fetches
        # the PREVIOUS call's stats (long finished) instead of
        # blocking on this one — amortizes per-dispatch latency
        # (dominant on a tunneled/remote TPU). train() results lag
        # one learn step; host-side stat hooks (PPO kl adaptation)
        # see the lagged values.
        self.deferred_stats = False

        # learner placement (TPU-specific)
        self.learner_devices = None  # None → all visible devices
        # learner sharding runtime (docs/sharding.md): "mesh" lowers
        # the learn program through ray_tpu.sharding's sharded_jit with
        # explicit NamedShardings on a ("batch",) mesh; "pmap" keeps
        # the legacy ("data",)-mesh path with implicit placement.
        # Fixed-seed results are bit-identical between the two.
        self.sharding_backend = "mesh"
        # tensor parallelism (docs/sharding.md "2-D mesh & param
        # partitioning"): None (default) keeps the 1-D data mesh; an
        # int M (or "auto") builds the 2-D [("batch", D//M),
        # ("model", M)] mesh and places params per the model's
        # partition rules — attention/MLP kernels split across M
        # shards, so a policy too large to replicate per device still
        # trains/serves on the same mesh runtime. "auto" resolves to 1
        # on the CPU client, 2 behind an even-count accelerator.
        # model_parallel=1 is the parity geometry: per-leaf specs flow
        # but every leaf stays whole — bit-identical to replicated.
        self.model_parallel = None
        # multi-host learner fleet (docs/fleet.md): None (default)
        # keeps the single-process mesh; an int N (or "auto") builds
        # the learner mesh over the GLOBAL device view of an N-process
        # jax.distributed runtime — the batch axis spans hosts, XLA
        # routes collectives over ICI within a host and DCN across.
        # Requires dist.initialize() to have joined N processes
        # (RAY_TPU_COORDINATOR et al.; Algorithm.setup validates).
        self.hosts = None
        # AOT compiled-program cache directory (sharding/aot.py,
        # docs/serving.md "the front door"): when set, the policy's
        # learn program warms through the fleet-shared executable
        # cache at its first build — an elastic joiner (or a restarted
        # driver) whose predecessor populated the cache compiles
        # NOTHING on the learn path. None = live jit (the default).
        self.aot_cache_dir = None

        # exploration
        self.explore = True
        self.exploration_config: Dict = {}

        # offline data (reference :offline_data)
        self.input_ = None  # "sampler" | path/glob of JSON shards
        self.output = None  # path to write sampled batches to
        self.output_max_file_size = 64 * 1024 * 1024
        self.off_policy_estimation_methods: list = []

        # evaluation (reference :800)
        self.evaluation_interval = None
        self.evaluation_duration = 10
        # ray-tpu: allow[RTA012] API-parity stub: evaluation counts episodes only; the timesteps unit is unimplemented and documented as such
        self.evaluation_duration_unit = "episodes"
        self.evaluation_num_workers = 0
        self.evaluation_config: Dict = {}

        # multi-agent (reference :1027)
        self.policies: Dict = {}
        self.policy_mapping_fn = None
        self.policies_to_train = None

        # reporting
        self.min_time_s_per_iteration = None
        self.min_sample_timesteps_per_iteration = 0
        self.metrics_num_episodes_for_smoothing = 100

        # telemetry (docs/observability.md): empty dict = off (the
        # default hot path sees only null-spans). Keys: metrics_port
        # (int, 0 = ephemeral → Prometheus /metrics scrape target),
        # trace (bool → span tracing + per-iteration overlap rollup).
        self.telemetry_config: Dict = {}

        # debugging / resources — API-parity stubs: this runtime
        # schedules TPU meshes + CPU actors, not per-trial GPUs, and
        # logging rides the host config
        # ray-tpu: allow[RTA012] API-parity stub (see block comment)
        self.log_level = "WARN"
        # ray-tpu: allow[RTA012] API-parity stub (see block comment)
        self.num_gpus = 0
        # ray-tpu: allow[RTA012] API-parity stub (see block comment)
        self.num_cpus_per_worker = 1

        # callbacks
        self.callbacks_class = None

    # -- fluent sections -------------------------------------------------

    def environment(
        self,
        env=None,
        *,
        env_config: Optional[Dict] = None,
        observation_space=None,
        action_space=None,
        clip_actions: Optional[bool] = None,
        normalize_actions: Optional[bool] = None,
        horizon: Optional[int] = None,
        env_backend: Optional[str] = None,
        jax_fused_rollout: Optional[bool] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        """``env_backend``: which rollout lane produces samples —
        ``"actor"`` (CPU Ray-actor workers, any env) or ``"jax"``
        (JaxVectorEnv rollouts jit'd onto the learner mesh, zero
        rollout H2D — docs/pipeline.md). ``jax_fused_rollout``
        additionally fuses rollout+learn into one dispatch on the jax
        lane (default True)."""
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = env_config
        if env_backend is not None:
            if env_backend not in ("actor", "jax"):
                raise ValueError(
                    "env_backend must be 'actor' or 'jax', got "
                    f"{env_backend!r}"
                )
            self.env_backend = env_backend
        if jax_fused_rollout is not None:
            self.jax_fused_rollout = bool(jax_fused_rollout)
        if observation_space is not None:
            self.observation_space = observation_space
        if action_space is not None:
            self.action_space = action_space
        if clip_actions is not None:
            self.clip_actions = clip_actions
        if normalize_actions is not None:
            self.normalize_actions = normalize_actions
        if horizon is not None:
            self.horizon = horizon
        return self

    def framework(self, framework: str = "jax", **kwargs) -> "AlgorithmConfig":
        self.framework_str = framework
        return self

    def rollouts(
        self,
        *,
        num_rollout_workers: Optional[int] = None,
        num_envs_per_worker: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
        batch_mode: Optional[str] = None,
        observation_filter: Optional[str] = None,
        ignore_worker_failures: Optional[bool] = None,
        recreate_failed_workers: Optional[bool] = None,
        sample_prefetch: Optional[int] = None,
        max_requests_in_flight_per_rollout_worker: Optional[int] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if batch_mode is not None:
            self.batch_mode = batch_mode
        if observation_filter is not None:
            self.observation_filter = observation_filter
        if ignore_worker_failures is not None:
            self.ignore_worker_failures = ignore_worker_failures
        if recreate_failed_workers is not None:
            self.recreate_failed_workers = recreate_failed_workers
        if sample_prefetch is not None:
            self.sample_prefetch = sample_prefetch
        if max_requests_in_flight_per_rollout_worker is not None:
            self.max_requests_in_flight_per_rollout_worker = (
                max_requests_in_flight_per_rollout_worker
            )
        return self

    def training(
        self,
        *,
        gamma: Optional[float] = None,
        lr: Optional[float] = None,
        lr_schedule=None,
        train_batch_size: Optional[int] = None,
        model: Optional[Dict] = None,
        optimizer: Optional[Dict] = None,
        grad_clip: Optional[float] = None,
        replay_device_resident=None,
        replay_memory_cap_bytes: Optional[int] = None,
        deferred_stats: Optional[bool] = None,
        superstep=None,
        replay_device_tree=None,
        learn_while_rollout: Optional[bool] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        """``replay_device_resident`` / ``replay_memory_cap_bytes`` /
        ``deferred_stats`` / ``superstep`` / ``replay_device_tree`` /
        ``learn_while_rollout``: the device-resident data-plane knobs
        (docs/data_plane.md) — see the attribute comments in
        ``__init__``."""
        if gamma is not None:
            self.gamma = gamma
        if lr is not None:
            self.lr = lr
        if lr_schedule is not None:
            self.lr_schedule = lr_schedule
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if model is not None:
            self.model = model
        if optimizer is not None:
            self.optimizer = optimizer
        if grad_clip is not None:
            self.grad_clip = grad_clip
        if replay_device_resident is not None:
            self.replay_device_resident = replay_device_resident
        if replay_memory_cap_bytes is not None:
            self.replay_memory_cap_bytes = int(replay_memory_cap_bytes)
        if deferred_stats is not None:
            self.deferred_stats = bool(deferred_stats)
        if superstep is not None:
            self.superstep = superstep
        if replay_device_tree is not None:
            self.replay_device_tree = replay_device_tree
        if learn_while_rollout is not None:
            self.learn_while_rollout = bool(learn_while_rollout)
        for k, v in kwargs.items():
            setattr(self, k, v)
        return self

    def resources(
        self,
        *,
        num_gpus: Optional[int] = None,
        num_cpus_per_worker: Optional[int] = None,
        learner_devices: Optional[int] = None,
        sharding_backend: Optional[str] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        if num_gpus is not None:
            self.num_gpus = num_gpus
        if num_cpus_per_worker is not None:
            self.num_cpus_per_worker = num_cpus_per_worker
        if learner_devices is not None:
            self.learner_devices = learner_devices
        if sharding_backend is not None:
            if sharding_backend not in ("mesh", "pmap"):
                raise ValueError(
                    "sharding_backend must be 'mesh' or 'pmap', got "
                    f"{sharding_backend!r}"
                )
            self.sharding_backend = sharding_backend
        return self

    def sharding(
        self,
        *,
        sharding_backend: Optional[str] = None,
        model_parallel=None,
        hosts=None,
        aot_cache_dir: Optional[str] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        """Learner-plane placement (docs/sharding.md).
        ``sharding_backend``: "mesh" (default) | "pmap" — same knob as
        :meth:`resources`. ``model_parallel``: "auto" | int M — build
        the 2-D (data x model) mesh and partition params per the
        model's rules; see the attribute comment in ``__init__``.
        ``hosts``: "auto" | int N — span the learner mesh over the N
        processes of the jax.distributed runtime (the multi-host
        fleet, docs/fleet.md). ``aot_cache_dir``: fleet-shared AOT
        executable cache the learn program warms through (zero fresh
        compiles for elastic joiners on a warm cache)."""
        if aot_cache_dir is not None:
            self.aot_cache_dir = str(aot_cache_dir)
        if hosts is not None:
            if hosts != "auto":
                h = int(hosts)
                if h < 1:
                    raise ValueError(
                        "hosts must be 'auto' or an int >= 1, got "
                        f"{hosts!r}"
                    )
                hosts = h
            self.hosts = hosts
        if sharding_backend is not None:
            if sharding_backend not in ("mesh", "pmap"):
                raise ValueError(
                    "sharding_backend must be 'mesh' or 'pmap', got "
                    f"{sharding_backend!r}"
                )
            self.sharding_backend = sharding_backend
        if model_parallel is not None:
            if model_parallel != "auto":
                m = int(model_parallel)
                if m < 1:
                    raise ValueError(
                        "model_parallel must be 'auto' or an int "
                        f">= 1, got {model_parallel!r}"
                    )
                model_parallel = m
            self.model_parallel = model_parallel
        return self

    def offline_data(
        self,
        *,
        input_=None,
        output: Optional[str] = None,
        output_max_file_size: Optional[int] = None,
        off_policy_estimation_methods=None,
        **kwargs,
    ) -> "AlgorithmConfig":
        """reference algorithm_config.py offline_data()."""
        if input_ is not None:
            self.input_ = input_
        if output is not None:
            self.output = output
        if output_max_file_size is not None:
            self.output_max_file_size = output_max_file_size
        if off_policy_estimation_methods is not None:
            self.off_policy_estimation_methods = (
                off_policy_estimation_methods
            )
        return self

    def exploration(
        self, *, explore: Optional[bool] = None,
        exploration_config: Optional[Dict] = None, **kwargs,
    ) -> "AlgorithmConfig":
        if explore is not None:
            self.explore = explore
        if exploration_config is not None:
            self.exploration_config = exploration_config
        return self

    def evaluation(
        self,
        *,
        evaluation_interval: Optional[int] = None,
        evaluation_duration: Optional[int] = None,
        evaluation_duration_unit: Optional[str] = None,
        evaluation_num_workers: Optional[int] = None,
        evaluation_config: Optional[Dict] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        if evaluation_duration_unit is not None:
            self.evaluation_duration_unit = evaluation_duration_unit
        if evaluation_num_workers is not None:
            self.evaluation_num_workers = evaluation_num_workers
        if evaluation_config is not None:
            self.evaluation_config = evaluation_config
        return self

    def multi_agent(
        self,
        *,
        policies: Optional[Dict] = None,
        policy_mapping_fn: Optional[Callable] = None,
        policies_to_train=None,
        **kwargs,
    ) -> "AlgorithmConfig":
        if policies is not None:
            self.policies = policies
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        if policies_to_train is not None:
            self.policies_to_train = policies_to_train
        return self

    def reporting(
        self,
        *,
        min_time_s_per_iteration: Optional[float] = None,
        min_sample_timesteps_per_iteration: Optional[int] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        if min_time_s_per_iteration is not None:
            self.min_time_s_per_iteration = min_time_s_per_iteration
        if min_sample_timesteps_per_iteration is not None:
            self.min_sample_timesteps_per_iteration = (
                min_sample_timesteps_per_iteration
            )
        return self

    def debugging(
        self, *, log_level: Optional[str] = None,
        seed: Optional[int] = None, **kwargs,
    ) -> "AlgorithmConfig":
        if log_level is not None:
            self.log_level = log_level
        if seed is not None:
            self.seed = seed
        return self

    def callbacks(self, callbacks_class) -> "AlgorithmConfig":
        self.callbacks_class = callbacks_class
        return self

    def fault_tolerance(
        self,
        *,
        ignore_worker_failures: Optional[bool] = None,
        recreate_failed_workers: Optional[bool] = None,
        max_failures: Optional[int] = None,
        checkpoint_frequency: Optional[int] = None,
        checkpoint_root: Optional[str] = None,
        keep_checkpoints_num: Optional[int] = None,
        restore_on_failure: Optional[bool] = None,
        nan_guard: Optional[bool] = None,
        worker_health_probe_timeout_s: Optional[float] = None,
        retry_max_attempts: Optional[int] = None,
        retry_timeout_s: Optional[float] = None,
        retry_backoff_s: Optional[float] = None,
        retry_backoff_mult: Optional[float] = None,
        retry_max_backoff_s: Optional[float] = None,
        retry_jitter: Optional[float] = None,
        fault_injection: Optional[Dict] = None,
        elastic: Optional[bool] = None,
        min_workers: Optional[int] = None,
        max_workers: Optional[int] = None,
        drain_grace_s: Optional[float] = None,
        fleet_interval_s: Optional[float] = None,
        fleet_idle_timeout_s: Optional[float] = None,
        fleet_starvation_patience: Optional[int] = None,
        scale_up_step: Optional[int] = None,
        checkpoint_streaming: Optional[bool] = None,
        checkpoint_stream_interval: Optional[int] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        """Fault-tolerance knobs (docs/resilience.md).

        ``recreate_failed_workers``: on an observed rollout-worker
        death, probe the fleet (bounded by
        ``worker_health_probe_timeout_s``), spawn weight-synced
        replacements, and continue in degraded mode meanwhile.
        ``checkpoint_frequency`` + ``restore_on_failure``: periodic
        checkpoints become the auto-restore target for restartable
        driver-side failures; prune to ``keep_checkpoints_num``.
        ``nan_guard``: skip non-finite learn batches instead of
        corrupting params. ``max_failures`` caps total recovery
        actions (< 0 = unlimited). ``retry_*``: the uniform
        RetryPolicy behind every driver-side remote interaction.
        ``fault_injection``: deterministic chaos spec for tests and
        ``bench.py --chaos`` (resilience/faults.py).
        ``elastic`` + ``min_workers``/``max_workers``: run the rollout
        fleet under a FleetController — preemption notices drain
        workers gracefully, learner starvation scales up, idle workers
        reap down (docs/resilience.md "elastic fleets & preemption").
        ``checkpoint_streaming`` + ``checkpoint_stream_interval``:
        continuous background param/opt-state snapshots bounding
        work-lost-on-driver-crash to ~1 superstep."""
        if ignore_worker_failures is not None:
            self.ignore_worker_failures = ignore_worker_failures
        if recreate_failed_workers is not None:
            self.recreate_failed_workers = recreate_failed_workers
        if max_failures is not None:
            self.max_failures = int(max_failures)
        if checkpoint_frequency is not None:
            self.checkpoint_frequency = int(checkpoint_frequency)
        if checkpoint_root is not None:
            self.checkpoint_root = checkpoint_root
        if keep_checkpoints_num is not None:
            self.keep_checkpoints_num = int(keep_checkpoints_num)
        if restore_on_failure is not None:
            self.restore_on_failure = bool(restore_on_failure)
        if nan_guard is not None:
            self.nan_guard = bool(nan_guard)
        if worker_health_probe_timeout_s is not None:
            self.worker_health_probe_timeout_s = float(
                worker_health_probe_timeout_s
            )
        if retry_max_attempts is not None:
            self.retry_max_attempts = int(retry_max_attempts)
        if retry_timeout_s is not None:
            self.retry_timeout_s = retry_timeout_s
        if retry_backoff_s is not None:
            self.retry_backoff_s = float(retry_backoff_s)
        if retry_backoff_mult is not None:
            self.retry_backoff_mult = float(retry_backoff_mult)
        if retry_max_backoff_s is not None:
            self.retry_max_backoff_s = float(retry_max_backoff_s)
        if retry_jitter is not None:
            self.retry_jitter = float(retry_jitter)
        if fault_injection is not None:
            self.fault_injection = fault_injection
        if elastic is not None:
            self.elastic = bool(elastic)
        if min_workers is not None:
            self.min_workers = int(min_workers)
        if max_workers is not None:
            self.max_workers = int(max_workers)
        if drain_grace_s is not None:
            self.drain_grace_s = float(drain_grace_s)
        if fleet_interval_s is not None:
            self.fleet_interval_s = float(fleet_interval_s)
        if fleet_idle_timeout_s is not None:
            self.fleet_idle_timeout_s = float(fleet_idle_timeout_s)
        if fleet_starvation_patience is not None:
            self.fleet_starvation_patience = int(
                fleet_starvation_patience
            )
        if scale_up_step is not None:
            self.scale_up_step = int(scale_up_step)
        if checkpoint_streaming is not None:
            self.checkpoint_streaming = bool(checkpoint_streaming)
        if checkpoint_stream_interval is not None:
            self.checkpoint_stream_interval = int(
                checkpoint_stream_interval
            )
        return self

    def telemetry(
        self,
        *,
        metrics_port: Optional[int] = None,
        trace: Optional[bool] = None,
        device_ledger=None,
        profile_iters: Optional[int] = None,
        peak_flops: Optional[float] = None,
        **kwargs,
    ) -> "AlgorithmConfig":
        """Run-telemetry activation (docs/observability.md).

        ``metrics_port``: start a Prometheus ``MetricsServer`` on this
        port at ``Algorithm.setup`` (0 = pick an ephemeral port; read
        it back from ``algo._telemetry.metrics_port``).
        ``trace``: enable span tracing end to end — remote submissions
        carry trace context, every ``train()`` result gains
        ``info/telemetry`` (stage wall-times + rollout/learn overlap
        fraction), and ``Algorithm.export_timeline(path)`` writes the
        chrome trace (with the device program lanes when the ledger
        runs).
        ``device_ledger``: the compiled-program ledger
        (docs/observability.md "device ledger") — per-program FLOPs /
        HBM bytes / MFU / recompile causes under
        ``info/device_ledger``. Defaults on whenever telemetry is
        active; ``"light"`` skips the cost/memory analysis (and its
        one extra AOT compile per traced signature), ``False``
        disables.
        ``profile_iters``: capture ``jax.profiler`` traces of the
        first N train iterations into ``<logdir>/jax_profile`` (no-op
        where the profiler is unavailable; numerics untouched —
        bit-parity-tested).
        ``peak_flops``: per-device peak FLOPs/s the MFU accounting
        divides by — overrides the built-in device-kind table (the
        CPU-container knob; ``peak_hbm_bytes_per_s`` rides along in
        kwargs)."""
        tc = dict(self.telemetry_config)
        if metrics_port is not None:
            tc["metrics_port"] = int(metrics_port)
        if trace is not None:
            tc["trace"] = bool(trace)
        if device_ledger is not None:
            tc["device_ledger"] = device_ledger
        if profile_iters is not None:
            tc["profile_iters"] = int(profile_iters)
        if peak_flops is not None:
            tc["peak_flops"] = float(peak_flops)
        tc.update(kwargs)
        self.telemetry_config = tc
        return self

    # -- conversion ------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        """reference algorithm_config.py:241."""
        out = {}
        for k, v in vars(self).items():
            if k == "algo_class":
                continue
            if k == "framework_str":
                out["framework"] = v
                continue
            if k == "input_":
                out["input"] = v
                continue
            out[k] = v
        return copy.deepcopy(
            {k: v for k, v in out.items()}
        ) if False else dict(out)

    def update_from_dict(self, d: Dict) -> "AlgorithmConfig":
        for k, v in d.items():
            if k == "framework":
                self.framework_str = v
            elif k == "num_rollout_workers":
                self.num_workers = v
            elif k == "input":
                self.input_ = v
            else:
                setattr(self, k, v)
        return self

    def copy(self) -> "AlgorithmConfig":
        new = self.__class__()
        new.__dict__.update(copy.deepcopy(self.__dict__))
        return new

    def build(self, env=None, logger_creator=None):
        if env is not None:
            self.env = env
        cls = self.algo_class
        if cls is None:
            raise ValueError("No algo_class bound to this config")
        return cls(config=self.to_dict(), env=self.env)

    def validate(self) -> None:
        pass
