from ray_tpu.algorithms.dreamer.dreamer import (  # noqa: F401
    Dreamer,
    DreamerConfig,
    EpisodicBuffer,
)
