"""Dreamer: world-model RL (learning behaviors by latent imagination).

Counterpart of the reference's ``rllib/algorithms/dreamer/`` (DreamerV1,
Hafner et al. 2020). Three components, three optimizers
(``dreamer_torch_policy.py:50-160``):

1. **World model** (PlaNET): RSSM latent dynamics (deterministic GRU path
   + stochastic state), observation encoder/decoder and reward head,
   trained by reconstruction + reward log-likelihood + KL(posterior ‖
   prior) clipped at ``free_nats``.
2. **Actor**: a tanh-Normal policy over latent features, trained by
   backpropagating lambda-returns THROUGH the learned dynamics over an
   ``imagine_horizon``-step imagined rollout (pure reparameterization —
   no score function).
3. **Critic**: value head on latent features regressed onto the
   lambda-returns.

TPU-first shape: the reference threads python loops and explicit
``FreezeParameters`` scopes through torch autograd; here

- ``observe`` (posterior filtering over a [B, T] batch) and ``imagine``
  (the H-step latent rollout) are ``lax.scan`` programs, so XLA sees one
  fused graph with static shapes rather than T (resp. H) python steps;
- the entire update — world-model grads, actor grads through the
  imagined rollout, critic grads, three clipped-Adam applies — is ONE
  jitted ``train_step``; parameter freezing falls out of differentiating
  each loss only w.r.t. its own parameter tree (no freeze scopes needed);
- acting is a jitted recurrent ``policy_step`` carrying (stoch, deter,
  prev_action) across env steps.

The conv encoder/decoder path (DMC-style 64x64 images) and the vector
MLP path are both supported; tests exercise the vector path on CPU.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import flax.linen as nn
import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID
from ray_tpu.env.registry import get_env_creator
from ray_tpu.evaluation.metrics import RolloutMetrics
from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED

# ---------------------------------------------------------------------------
# Models
# ---------------------------------------------------------------------------


class WorldModel(nn.Module):
    """Encoder + RSSM dynamics + decoder + reward head (the PlaNET
    model; reference ``dreamer_model.py`` ConvEncoder/ConvDecoder/
    RSSM/DenseDecoder)."""

    obs_shape: Tuple[int, ...]
    action_size: int
    stoch_size: int = 30
    deter_size: int = 200
    hidden_size: int = 400
    depth_size: int = 32
    min_std: float = 0.1

    @property
    def _image_obs(self) -> bool:
        return len(self.obs_shape) == 3

    def setup(self):
        act = nn.elu
        self._act = act
        d = self.depth_size
        if self._image_obs:
            # DreamerV1 conv stack (64x64): depths d,2d,4d,8d, k4 s2
            self.enc_convs = [
                nn.Conv(d * m, (4, 4), (2, 2), padding="VALID")
                for m in (1, 2, 4, 8)
            ]
            self.dec_in = nn.Dense(32 * d)
            self.dec_convs = [
                nn.ConvTranspose(d * 4, (5, 5), (2, 2), padding="VALID"),
                nn.ConvTranspose(d * 2, (5, 5), (2, 2), padding="VALID"),
                nn.ConvTranspose(d, (6, 6), (2, 2), padding="VALID"),
                nn.ConvTranspose(
                    self.obs_shape[-1], (6, 6), (2, 2), padding="VALID"
                ),
            ]
        else:
            self.enc1 = nn.Dense(self.hidden_size)
            self.enc2 = nn.Dense(self.hidden_size)
            self.dec1 = nn.Dense(self.hidden_size)
            self.dec2 = nn.Dense(self.hidden_size)
            self.dec_out = nn.Dense(int(np.prod(self.obs_shape)))
        # RSSM (reference RSSM.img_step / obs_step)
        self.gru = nn.GRUCell(features=self.deter_size)
        self.img1 = nn.Dense(self.hidden_size)
        self.img2 = nn.Dense(self.hidden_size)
        self.img3 = nn.Dense(2 * self.stoch_size)
        self.obs1 = nn.Dense(self.hidden_size)
        self.obs2 = nn.Dense(2 * self.stoch_size)
        # reward head (2-layer dense decoder)
        self.rew1 = nn.Dense(self.hidden_size)
        self.rew2 = nn.Dense(self.hidden_size)
        self.rew_out = nn.Dense(1)

    # -- encoder / decoder -------------------------------------------------

    def preprocess(self, obs: jnp.ndarray) -> jnp.ndarray:
        """Model-space observations: pixels map to [-0.5, 0.5] (the
        standard Dreamer obs/255 - 0.5); vector obs pass through.
        Reconstruction targets use the same space."""
        if self._image_obs:
            return obs.astype(jnp.float32) / 255.0 - 0.5
        return obs.astype(jnp.float32)

    def encode(self, obs: jnp.ndarray) -> jnp.ndarray:
        x = self.preprocess(obs)
        if self._image_obs:
            for conv in self.enc_convs:
                x = self._act(conv(x))
            return x.reshape((x.shape[0], -1))
        x = self._act(self.enc1(x))
        return self._act(self.enc2(x))

    def decode(self, feat: jnp.ndarray) -> jnp.ndarray:
        """Mean of the (unit-std Gaussian) observation reconstruction."""
        if self._image_obs:
            x = self.dec_in(feat)
            x = x.reshape((-1, 1, 1, 32 * self.depth_size))
            for conv in self.dec_convs[:-1]:
                x = self._act(conv(x))
            x = self.dec_convs[-1](x)
            return x.reshape((feat.shape[0],) + self.obs_shape)
        x = self._act(self.dec1(feat))
        x = self._act(self.dec2(x))
        return self.dec_out(x).reshape((feat.shape[0],) + self.obs_shape)

    def reward(self, feat: jnp.ndarray) -> jnp.ndarray:
        x = self._act(self.rew1(feat))
        x = self._act(self.rew2(x))
        return self.rew_out(x)[..., 0]

    # -- RSSM --------------------------------------------------------------

    def img_step(self, state: Dict, prev_action: jnp.ndarray, rng) -> Dict:
        """One prior (imagination) step: p(s_t | s_{t-1}, a_{t-1})."""
        x = jnp.concatenate([state["stoch"], prev_action], -1)
        x = self._act(self.img1(x))
        deter, _ = self.gru(state["deter"], x)
        y = self._act(self.img2(deter))
        mean, std = jnp.split(self.img3(y), 2, -1)
        std = jax.nn.softplus(std) + self.min_std
        stoch = mean + std * jax.random.normal(rng, mean.shape)
        return {"mean": mean, "std": std, "stoch": stoch, "deter": deter}

    def obs_step(
        self, state: Dict, prev_action: jnp.ndarray, embed: jnp.ndarray, rng
    ) -> Tuple[Dict, Dict]:
        """One posterior (filtering) step: q(s_t | s_{t-1}, a_{t-1}, o_t).
        Returns (post, prior)."""
        rng_p, rng_q = jax.random.split(rng)
        prior = self.img_step(state, prev_action, rng_p)
        x = jnp.concatenate([prior["deter"], embed], -1)
        x = self._act(self.obs1(x))
        mean, std = jnp.split(self.obs2(x), 2, -1)
        std = jax.nn.softplus(std) + self.min_std
        stoch = mean + std * jax.random.normal(rng_q, mean.shape)
        post = {
            "mean": mean,
            "std": std,
            "stoch": stoch,
            "deter": prior["deter"],
        }
        return post, prior

    def __call__(self, obs, prev_action, rng):
        """Init-only path touching every submodule once."""
        embed = self.encode(obs)
        state = init_state(obs.shape[0], self.stoch_size, self.deter_size)
        post, prior = self.obs_step(state, prev_action, embed, rng)
        feat = get_feat(post)
        return self.decode(feat), self.reward(feat), post, prior


class Actor(nn.Module):
    """Tanh-Normal policy head over latent features (reference
    ``dreamer_model.py:185`` ActionDecoder, dist="tanh_normal")."""

    action_size: int
    hidden_size: int = 400
    layers: int = 4
    min_std: float = 1e-4
    init_std: float = 5.0
    mean_scale: float = 5.0

    @nn.compact
    def __call__(self, feat: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = feat
        for _ in range(self.layers):
            x = nn.elu(nn.Dense(self.hidden_size)(x))
        out = nn.Dense(2 * self.action_size)(x)
        mean, std = jnp.split(out, 2, -1)
        raw_init_std = float(np.log(np.exp(self.init_std) - 1.0))
        mean = self.mean_scale * jnp.tanh(mean / self.mean_scale)
        std = jax.nn.softplus(std + raw_init_std) + self.min_std
        return mean, std


class Critic(nn.Module):
    """Value head on latent features (reference DenseDecoder value)."""

    hidden_size: int = 400
    layers: int = 3

    @nn.compact
    def __call__(self, feat: jnp.ndarray) -> jnp.ndarray:
        x = feat
        for _ in range(self.layers):
            x = nn.elu(nn.Dense(self.hidden_size)(x))
        return nn.Dense(1)(x)[..., 0]


def init_state(batch: int, stoch: int, deter: int) -> Dict:
    z = jnp.zeros((batch, stoch), jnp.float32)
    return {
        "mean": z,
        "std": jnp.ones_like(z),
        "stoch": z,
        "deter": jnp.zeros((batch, deter), jnp.float32),
    }


def get_feat(state: Dict) -> jnp.ndarray:
    return jnp.concatenate([state["stoch"], state["deter"]], -1)


def _kl_diag_gaussian(post: Dict, prior: Dict) -> jnp.ndarray:
    """KL(post ‖ prior) for diagonal Gaussians, summed over stoch dims."""
    var_ratio = jnp.square(post["std"] / prior["std"])
    mean_term = jnp.square((post["mean"] - prior["mean"]) / prior["std"])
    return 0.5 * jnp.sum(
        var_ratio + mean_term - 1.0 - jnp.log(var_ratio), -1
    )


def _neg_logp_unit_normal(pred: jnp.ndarray, target: jnp.ndarray):
    """-log N(target; pred, 1), summed over trailing feature dims."""
    err = 0.5 * jnp.square(pred - target) + 0.5 * np.log(2.0 * np.pi)
    reduce_axes = tuple(range(2, pred.ndim))
    return jnp.sum(err, reduce_axes) if reduce_axes else err


# ---------------------------------------------------------------------------
# Episodic replay
# ---------------------------------------------------------------------------


class EpisodicBuffer:
    """Stores complete episodes, samples [batch_size, length] chunks
    (reference ``dreamer.py:204`` EpisodicBuffer). Rows follow the
    reference's (s_t, a_{t-1}, r_{t-1}) convention: row 0 pairs the
    reset obs with zero action/reward (``dreamer_torch_policy.py``
    postprocess_trajectory)."""

    def __init__(self, max_length: int = 1000, length: int = 50, seed: int = 0):
        self.episodes: List[Dict[str, np.ndarray]] = []
        self.max_length = max_length
        self.length = length
        self.timesteps = 0
        self._rng = np.random.default_rng(seed)

    def add(self, episode: Dict[str, np.ndarray]) -> None:
        self.timesteps += len(episode["obs"]) - 1
        self.episodes.append(episode)
        if len(self.episodes) > self.max_length:
            del self.episodes[: len(self.episodes) - self.max_length]

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        eligible = [
            e for e in self.episodes if len(e["obs"]) >= self.length
        ]
        if not eligible:
            raise ValueError(
                f"no stored episode is >= batch_length={self.length} "
                "steps; lower batch_length or raise the env horizon"
            )
        out = {k: [] for k in ("obs", "actions", "rewards")}
        for _ in range(batch_size):
            ep = eligible[self._rng.integers(len(eligible))]
            start = self._rng.integers(len(ep["obs"]) - self.length + 1)
            for k in out:
                out[k].append(ep[k][start : start + self.length])
        return {k: np.stack(v) for k, v in out.items()}


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------


class DreamerConfig(AlgorithmConfig):
    """reference ``dreamer.py:46`` DreamerConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or Dreamer)
        self.td_model_lr = 6e-4
        self.actor_lr = 8e-5
        self.critic_lr = 8e-5
        self.grad_clip = 100.0
        self.lambda_ = 0.95
        self.dreamer_train_iters = 100
        self.batch_size = 50
        self.batch_length = 50
        self.imagine_horizon = 15
        self.free_nats = 3.0
        self.kl_coeff = 1.0
        self.prefill_timesteps = 5000
        self.explore_noise = 0.3
        self.action_repeat = 2
        self.max_episodes_in_buffer = 1000
        self.dreamer_model = {
            "deter_size": 200,
            "stoch_size": 30,
            "depth_size": 32,
            "hidden_size": 400,
            "action_init_std": 5.0,
        }
        self.gamma = 0.99

    def training(
        self,
        *,
        td_model_lr: Optional[float] = None,
        actor_lr: Optional[float] = None,
        critic_lr: Optional[float] = None,
        lambda_: Optional[float] = None,
        dreamer_train_iters: Optional[int] = None,
        batch_size: Optional[int] = None,
        batch_length: Optional[int] = None,
        imagine_horizon: Optional[int] = None,
        free_nats: Optional[float] = None,
        kl_coeff: Optional[float] = None,
        prefill_timesteps: Optional[int] = None,
        explore_noise: Optional[float] = None,
        action_repeat: Optional[int] = None,
        dreamer_model: Optional[dict] = None,
        **kwargs,
    ) -> "DreamerConfig":
        super().training(**kwargs)
        for name, val in (
            ("td_model_lr", td_model_lr),
            ("actor_lr", actor_lr),
            ("critic_lr", critic_lr),
            ("lambda_", lambda_),
            ("dreamer_train_iters", dreamer_train_iters),
            ("batch_size", batch_size),
            ("batch_length", batch_length),
            ("imagine_horizon", imagine_horizon),
            ("free_nats", free_nats),
            ("kl_coeff", kl_coeff),
            ("prefill_timesteps", prefill_timesteps),
            ("explore_noise", explore_noise),
            ("action_repeat", action_repeat),
        ):
            if val is not None:
                setattr(self, name, val)
        if dreamer_model is not None:
            self.dreamer_model = {**self.dreamer_model, **dreamer_model}
        return self


# ---------------------------------------------------------------------------
# Algorithm
# ---------------------------------------------------------------------------


class Dreamer(Algorithm):
    """Single-worker world-model trainer (the reference pins
    ``num_workers=0`` — ``dreamer.py:330`` validate_config)."""

    @classmethod
    def get_default_config(cls) -> DreamerConfig:
        return DreamerConfig(cls)

    def setup(self, config: Dict) -> None:
        env_spec = config.get("env")
        super().setup(dict(config, env=None))
        self.env = get_env_creator(env_spec)(
            config.get("env_config") or {}
        )
        obs_space = self.env.observation_space
        act_space = self.env.action_space
        assert isinstance(act_space, gym.spaces.Box), (
            "Dreamer's tanh-Normal actor needs a continuous Box action "
            f"space, got {act_space} (reference dreamer_model.py:252)"
        )
        self.obs_shape = tuple(obs_space.shape)
        self.act_dim = int(np.prod(act_space.shape))
        self._act_low = np.asarray(act_space.low, np.float32)
        self._act_high = np.asarray(act_space.high, np.float32)

        m = dict(
            DreamerConfig().dreamer_model, **(config.get("dreamer_model") or {})
        )
        self.wm = WorldModel(
            obs_shape=self.obs_shape,
            action_size=self.act_dim,
            stoch_size=int(m["stoch_size"]),
            deter_size=int(m["deter_size"]),
            hidden_size=int(m["hidden_size"]),
            depth_size=int(m["depth_size"]),
        )
        self.actor = Actor(
            action_size=self.act_dim,
            hidden_size=int(m["hidden_size"]),
            init_std=float(m.get("action_init_std", 5.0)),
        )
        self.critic = Critic(hidden_size=int(m["hidden_size"]))
        self._stoch = int(m["stoch_size"])
        self._deter = int(m["deter_size"])

        seed = int(config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._np_rng = np.random.default_rng(seed)
        self._rng, k1, k2, k3 = jax.random.split(self._rng, 4)
        dummy_obs = jnp.zeros((2,) + self.obs_shape, jnp.float32)
        dummy_act = jnp.zeros((2, self.act_dim), jnp.float32)
        self.wm_params = self.wm.init(k1, dummy_obs, dummy_act, k1)
        feat_dim = self._stoch + self._deter
        dummy_feat = jnp.zeros((2, feat_dim), jnp.float32)
        self.actor_params = self.actor.init(k2, dummy_feat)
        self.critic_params = self.critic.init(k3, dummy_feat)

        clip = config.get("grad_clip", 100.0)

        def make_tx(lr):
            if not clip:  # None/0 → unclipped
                return optax.adam(lr)
            return optax.chain(
                optax.clip_by_global_norm(float(clip)), optax.adam(lr)
            )

        self._tx_model = make_tx(float(config.get("td_model_lr", 6e-4)))
        self._tx_actor = make_tx(float(config.get("actor_lr", 8e-5)))
        self._tx_critic = make_tx(float(config.get("critic_lr", 8e-5)))
        self.opt_model = self._tx_model.init(self.wm_params)
        self.opt_actor = self._tx_actor.init(self.actor_params)
        self.opt_critic = self._tx_critic.init(self.critic_params)

        self.buffer = EpisodicBuffer(
            max_length=int(config.get("max_episodes_in_buffer", 1000)),
            length=int(config.get("batch_length", 50)),
            seed=seed,
        )
        self._train_fn = None
        self._policy_fn = None
        self._prefilled = False

    # -- pure programs -----------------------------------------------------

    def _observe(self, wm_params, obs, actions, rng):
        """Posterior filtering over a [B, T] batch as one lax.scan.
        Returns (posts, priors) as dicts of (T, B, ...) arrays."""
        wm = self.wm
        B, T = actions.shape[:2]
        embed = wm.apply(
            wm_params,
            obs.reshape((B * T,) + self.obs_shape),
            method=WorldModel.encode,
        ).reshape((B, T, -1))

        def step(state, inp):
            emb_t, act_t, rng_t = inp
            post, prior = wm.apply(
                wm_params, state, act_t, emb_t, rng_t,
                method=WorldModel.obs_step,
            )
            return post, (post, prior)

        init = init_state(B, self._stoch, self._deter)
        xs = (
            jnp.moveaxis(embed, 1, 0),
            jnp.moveaxis(actions, 1, 0),
            jax.random.split(rng, T),
        )
        _, (posts, priors) = jax.lax.scan(step, init, xs)
        return posts, priors

    def _imagine(self, wm_params, actor_params, start, horizon, rng):
        """H-step latent rollout under the actor, one lax.scan; actions
        are reparameterized samples so actor gradients flow through the
        dynamics chain (reference imagine_ahead, dreamer_model.py:525)."""
        wm, actor = self.wm, self.actor

        def step(state, rng_t):
            a_rng, s_rng = jax.random.split(rng_t)
            mean, std = actor.apply(actor_params, get_feat(state))
            pre = mean + std * jax.random.normal(a_rng, mean.shape)
            action = jnp.tanh(pre)
            prior = wm.apply(
                wm_params, state, action, s_rng,
                method=WorldModel.img_step,
            )
            return prior, get_feat(prior)

        _, feats = jax.lax.scan(
            step, start, jax.random.split(rng, horizon)
        )
        return feats  # (H, N, feat)

    def _build_train_fn(self):
        config = self.config
        wm, critic = self.wm, self.critic
        kl_coeff = float(config.get("kl_coeff", 1.0))
        free_nats = float(config.get("free_nats", 3.0))
        horizon = int(config.get("imagine_horizon", 15))
        gamma = float(config.get("gamma", 0.99))
        lambda_ = float(config.get("lambda_", 0.95))
        tx_m, tx_a, tx_c = self._tx_model, self._tx_actor, self._tx_critic

        def model_loss(wm_params, batch, rng):
            posts, priors = self._observe(
                wm_params, batch["obs"], batch["actions"], rng
            )
            feat = get_feat(posts)  # (T, B, F)
            T, B = feat.shape[:2]
            flat = feat.reshape((T * B, -1))
            recon = wm.apply(
                wm_params, flat, method=WorldModel.decode
            ).reshape((T, B) + self.obs_shape)
            rew = wm.apply(
                wm_params, flat, method=WorldModel.reward
            ).reshape((T, B))
            obs_t = wm.apply(
                wm_params,
                jnp.moveaxis(batch["obs"], 1, 0),
                method=WorldModel.preprocess,
            )
            rew_t = jnp.moveaxis(batch["rewards"], 1, 0)
            image_loss = jnp.mean(_neg_logp_unit_normal(recon, obs_t))
            reward_loss = jnp.mean(_neg_logp_unit_normal(rew, rew_t))
            div = jnp.maximum(
                jnp.mean(_kl_diag_gaussian(posts, priors)), free_nats
            )
            loss = kl_coeff * div + reward_loss + image_loss
            aux = {
                "posts": posts,
                "image_loss": image_loss,
                "reward_loss": reward_loss,
                "divergence": div,
                "prior_ent": jnp.mean(
                    jnp.sum(
                        0.5 * jnp.log(2 * np.pi * np.e)
                        + jnp.log(priors["std"]),
                        -1,
                    )
                ),
                "post_ent": jnp.mean(
                    jnp.sum(
                        0.5 * jnp.log(2 * np.pi * np.e)
                        + jnp.log(posts["std"]),
                        -1,
                    )
                ),
            }
            return loss, aux

        def lambda_returns(reward, value):
            """GAE-flavoured lambda-returns over the imagined rollout
            (reference dreamer_torch_policy.py:100-118)."""
            inputs = reward[:-1] + gamma * value[1:] * (1 - lambda_)

            def step(last, inp):
                last = inp + gamma * lambda_ * last
                return last, last

            _, rets = jax.lax.scan(
                step, value[-1], inputs, reverse=True
            )
            return rets  # (H-1, N)

        def actor_loss(actor_params, wm_params, critic_params, start, rng):
            feats = self._imagine(
                wm_params, actor_params, start, horizon, rng
            )
            rew = wm.apply(wm_params, feats, method=WorldModel.reward)
            value = critic.apply(critic_params, feats)
            returns = lambda_returns(rew, value)
            ones = jnp.ones_like(rew[:1])
            discount = jnp.cumprod(
                jnp.concatenate([ones, gamma * jnp.ones_like(rew[:-2])], 0),
                0,
            )
            loss = -jnp.mean(discount * returns)
            return loss, (feats, returns, discount)

        def critic_loss(critic_params, feats, returns, discount):
            pred = critic.apply(critic_params, feats[:-1])
            nll = 0.5 * jnp.square(pred - returns) + 0.5 * np.log(
                2.0 * np.pi
            )
            return jnp.mean(discount * nll)

        def train_step(
            wm_params, actor_params, critic_params,
            opt_m, opt_a, opt_c, batch, rng,
        ):
            rng_m, rng_i = jax.random.split(rng)
            (m_loss, aux), m_grads = jax.value_and_grad(
                model_loss, has_aux=True
            )(wm_params, batch, rng_m)
            upd, opt_m = tx_m.update(m_grads, opt_m, wm_params)
            wm_params = optax.apply_updates(wm_params, upd)

            # imagination starts from every detached posterior state
            posts = jax.lax.stop_gradient(aux["posts"])
            T, B = posts["stoch"].shape[:2]
            start = {
                "mean": posts["mean"].reshape((T * B, -1)),
                "std": posts["std"].reshape((T * B, -1)),
                "stoch": posts["stoch"].reshape((T * B, -1)),
                "deter": posts["deter"].reshape((T * B, -1)),
            }
            (a_loss, (feats, returns, discount)), a_grads = (
                jax.value_and_grad(actor_loss, has_aux=True)(
                    actor_params, wm_params, critic_params, start, rng_i
                )
            )
            upd, opt_a = tx_a.update(a_grads, opt_a, actor_params)
            actor_params = optax.apply_updates(actor_params, upd)

            feats = jax.lax.stop_gradient(feats)
            returns = jax.lax.stop_gradient(returns)
            discount = jax.lax.stop_gradient(discount)
            c_loss, c_grads = jax.value_and_grad(critic_loss)(
                critic_params, feats, returns, discount
            )
            upd, opt_c = tx_c.update(c_grads, opt_c, critic_params)
            critic_params = optax.apply_updates(critic_params, upd)

            stats = {
                "model_loss": m_loss,
                "image_loss": aux["image_loss"],
                "reward_loss": aux["reward_loss"],
                "divergence": aux["divergence"],
                "prior_ent": aux["prior_ent"],
                "post_ent": aux["post_ent"],
                "actor_loss": a_loss,
                "critic_loss": c_loss,
            }
            return (
                wm_params, actor_params, critic_params,
                opt_m, opt_a, opt_c, stats,
            )

        return jax.jit(train_step)

    def _build_policy_fn(self):
        wm, actor = self.wm, self.actor
        noise = float(self.config.get("explore_noise", 0.3))

        def policy_step(
            wm_params, actor_params, state, prev_action, obs, rng, explore
        ):
            e_rng, a_rng, s_rng = jax.random.split(rng, 3)
            embed = wm.apply(
                wm_params, obs[None], method=WorldModel.encode
            )
            post, _ = wm.apply(
                wm_params, state, prev_action, embed, s_rng,
                method=WorldModel.obs_step,
            )
            mean, std = actor.apply(actor_params, get_feat(post))
            pre = jnp.where(
                explore,
                mean + std * jax.random.normal(a_rng, mean.shape),
                mean,
            )
            action = jnp.tanh(pre)
            action = jnp.where(
                explore,
                jnp.clip(
                    action
                    + noise * jax.random.normal(e_rng, action.shape),
                    -1.0,
                    1.0,
                ),
                action,
            )
            return post, action

        return jax.jit(policy_step)

    # -- acting ------------------------------------------------------------

    def _scale_action(self, tanh_a: np.ndarray) -> np.ndarray:
        return self._act_low + (tanh_a + 1.0) / 2.0 * (
            self._act_high - self._act_low
        )

    def _collect_episode(self, explore: bool = True, random: bool = False):
        """One env episode with the recurrent latent policy; returns the
        buffer-format episode dict and the (real-env) episode reward."""
        if self._policy_fn is None:
            self._policy_fn = self._build_policy_fn()
        repeat = max(1, int(self.config.get("action_repeat", 1)))
        obs, _ = self.env.reset()
        state = init_state(1, self._stoch, self._deter)
        prev_action = jnp.zeros((1, self.act_dim), jnp.float32)
        rows_obs = [np.asarray(obs, np.float32)]
        rows_act = [np.zeros(self.act_dim, np.float32)]
        rows_rew = [0.0]
        ep_reward, done, env_steps = 0.0, False, 0
        while not done:
            if random:
                # prefill: uniform actions, no latent filtering needed
                # ray-tpu: allow[RTA011] the episode-length predicate only reaches device data through the NON-random branch's actions; when random=True every action in the trajectory came from this host generator, so the draw count is host-deterministic
                tanh_a = self._np_rng.uniform(
                    -1.0, 1.0, self.act_dim
                ).astype(np.float32)
            else:
                self._rng, sub = jax.random.split(self._rng)
                state, a = self._policy_fn(
                    self.wm_params, self.actor_params, state,
                    prev_action, jnp.asarray(obs, jnp.float32), sub,
                    explore,
                )
                tanh_a = np.asarray(a[0])
            prev_action = jnp.asarray(tanh_a, jnp.float32)[None]
            env_a = self._scale_action(tanh_a).reshape(
                self.env.action_space.shape
            )
            r_sum = 0.0
            for _ in range(repeat):
                obs, r, term, trunc, _ = self.env.step(env_a)
                r_sum += float(r)
                env_steps += 1
                done = term or trunc
                if done:
                    break
            rows_obs.append(np.asarray(obs, np.float32))
            rows_act.append(tanh_a)
            rows_rew.append(r_sum)
            ep_reward += r_sum
        episode = {
            "obs": np.stack(rows_obs),
            "actions": np.stack(rows_act),
            "rewards": np.asarray(rows_rew, np.float32),
        }
        self._counters[NUM_ENV_STEPS_SAMPLED] += env_steps
        self._counters[NUM_AGENT_STEPS_SAMPLED] += env_steps
        return episode, ep_reward, env_steps

    # -- training ----------------------------------------------------------

    def _prefill(self) -> None:
        target = int(self.config.get("prefill_timesteps", 5000))
        repeat = max(1, int(self.config.get("action_repeat", 1)))
        while self.buffer.timesteps * repeat < target:
            episode, _, _ = self._collect_episode(random=True)
            self.buffer.add(episode)
        self._prefilled = True

    def training_step(self) -> Dict:
        if self._train_fn is None:
            self._train_fn = self._build_train_fn()
        if not self._prefilled:
            self._prefill()

        episode, ep_reward, _ = self._collect_episode(explore=True)
        self.buffer.add(episode)
        self._episode_history.append(
            RolloutMetrics(len(episode["obs"]) - 1, ep_reward)
        )
        self._episodes_total += 1
        # a restored run starts with an empty (non-checkpointed) buffer:
        # refill with on-policy episodes until one is long enough to
        # sample batch_length windows from
        for _ in range(100):
            if any(
                len(e["obs"]) >= self.buffer.length
                for e in self.buffer.episodes
            ):
                break
            episode, ep_reward, _ = self._collect_episode(explore=True)
            self.buffer.add(episode)
            self._episode_history.append(
                RolloutMetrics(len(episode["obs"]) - 1, ep_reward)
            )
            self._episodes_total += 1

        batch_size = int(self.config.get("batch_size", 50))
        iters = int(self.config.get("dreamer_train_iters", 100))
        stats = {}
        for _ in range(iters):
            host = self.buffer.sample(batch_size)
            batch = {k: jnp.asarray(v) for k, v in host.items()}
            self._rng, sub = jax.random.split(self._rng)
            (
                self.wm_params, self.actor_params, self.critic_params,
                self.opt_model, self.opt_actor, self.opt_critic, stats,
            ) = self._train_fn(
                self.wm_params, self.actor_params, self.critic_params,
                self.opt_model, self.opt_actor, self.opt_critic,
                batch, sub,
            )
            self._counters[NUM_ENV_STEPS_TRAINED] += (
                batch_size * int(self.config.get("batch_length", 50))
            )
        return {
            DEFAULT_POLICY_ID: {
                k: float(v) for k, v in stats.items()
            }
        }

    def __getstate__(self) -> Dict:
        return {
            "wm_params": jax.device_get(self.wm_params),
            "actor_params": jax.device_get(self.actor_params),
            "critic_params": jax.device_get(self.critic_params),
            "opt_model": jax.device_get(self.opt_model),
            "opt_actor": jax.device_get(self.opt_actor),
            "opt_critic": jax.device_get(self.opt_critic),
            "counters": dict(self._counters),
            "episodes_total": self._episodes_total,
            # restore must not re-run the random-action prefill on top
            # of trained params (restarting training on a buffer
            # dominated by random data); rng continues the stream
            "prefilled": self._prefilled,
            "rng": jax.device_get(self._rng),
        }

    def __setstate__(self, state: Dict) -> None:
        import collections

        for k in (
            "wm_params", "actor_params", "critic_params",
            "opt_model", "opt_actor", "opt_critic",
        ):
            setattr(self, k, jax.device_put(state[k]))
        self._counters = collections.defaultdict(
            int, state.get("counters", {})
        )
        self._episodes_total = state.get("episodes_total", 0)
        # the episodic buffer itself is not checkpointed (matches the
        # reference's default store_buffer_in_checkpoints=False), but a
        # restored run refills it with on-policy episodes, not the
        # random prefill
        self._prefilled = bool(state.get("prefilled", False))
        if "rng" in state:
            self._rng = jax.device_put(state["rng"])

    def cleanup(self) -> None:
        try:
            self.env.close()
        except Exception:
            pass
        super().cleanup()
