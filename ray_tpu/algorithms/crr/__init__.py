from ray_tpu.algorithms.crr.crr import CRR, CRRConfig, CRRJaxPolicy

__all__ = ["CRR", "CRRConfig", "CRRJaxPolicy"]
