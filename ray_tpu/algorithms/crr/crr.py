"""CRR: Critic-Regularized Regression for offline RL.

Counterpart of the reference's ``rllib/algorithms/crr/crr.py``
(CRRConfig: weight_type bin|exp, temperature, max_weight,
n_action_sample, twin_q, target_update_grad_intervals) and
``crr_torch_policy.py`` (actor = advantage-weighted behavior cloning
with weights from the critic's advantage estimate; critic = TD
regression against target nets with policy next-actions).

One jitted shard_map program per step: critic step, advantage estimate
via n sampled policy actions, weighted-BC actor step, periodic hard
target sync via a traced step-counter select (no recompiles)."""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_tpu import sharding as sharding_lib

from ray_tpu.algorithms.sac.sac import SAC, SACConfig, SACJaxPolicy
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.models.distributions import SquashedGaussian
from ray_tpu.policy.jax_policy import _tree_to_device


class CRRConfig(SACConfig):
    """reference crr.py CRRConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or CRR)
        self.weight_type = "bin"  # "bin" | "exp"
        self.temperature = 1.0
        self.max_weight = 20.0
        self.n_action_sample = 4
        self.twin_q = True
        self.target_update_grad_intervals = 100
        self.num_steps_sampled_before_learning_starts = 0
        self.off_policy_estimation_methods = []

    def training(
        self,
        *,
        weight_type: Optional[str] = None,
        temperature: Optional[float] = None,
        max_weight: Optional[float] = None,
        n_action_sample: Optional[int] = None,
        target_update_grad_intervals: Optional[int] = None,
        **kwargs,
    ) -> "CRRConfig":
        super().training(**kwargs)
        if weight_type is not None:
            self.weight_type = weight_type
        if temperature is not None:
            self.temperature = temperature
        if max_weight is not None:
            self.max_weight = max_weight
        if n_action_sample is not None:
            self.n_action_sample = n_action_sample
        if target_update_grad_intervals is not None:
            self.target_update_grad_intervals = (
                target_update_grad_intervals
            )
        return self


class CRRJaxPolicy(SACJaxPolicy):
    """reference crr_torch_policy.py losses."""

    def __init__(self, observation_space, action_space, config):
        super().__init__(observation_space, action_space, config)
        # CRR targets both nets; hard-sync on a traced interval
        import jax.numpy as _jnp

        actor_params = jax.device_get(self.params["actor"])
        self.aux_state = _tree_to_device(
            {
                "target_actor": actor_params,
                "target_critic": jax.device_get(
                    self.params["critic"]
                ),
                "step": _jnp.zeros((), _jnp.int32),
            },
            self._param_sharding,
        )

    def _device_update_fn(self, batch_size=None, with_frames=False):
        """CRR's own single-update body: the generic superstep scans
        THIS (weighted-regression actor loss included), so chained CRR
        updates fuse correctly."""
        actor, critic = self.actor, self.critic
        tx_a, tx_c = self._tx_actor, self._tx_critic
        gamma = self.gamma**self.n_step
        low, high = self.low, self.high
        mesh = self.mesh
        axis = sharding_lib.data_axis(mesh)
        cfg = self.config
        weight_type = cfg.get("weight_type", "bin")
        temperature = float(cfg.get("temperature", 1.0))
        max_weight = float(cfg.get("max_weight", 20.0))
        n_sample = int(cfg.get("n_action_sample", 4))
        sync_interval = int(cfg.get("target_update_grad_intervals", 100))
        act_dim = self.action_dim

        def mean_policy_q(cp, ap, obs, rng):
            """E_{a~pi}[Q(s,a)] via n sampled actions."""
            B = obs.shape[0]
            dist = SquashedGaussian(
                actor.apply(ap, obs), low=low, high=high
            )
            rngs = jax.random.split(rng, n_sample)
            acts, _ = jax.vmap(lambda r: dist.sampled_action_logp(r))(
                rngs
            )  # (n, B, act_dim)
            acts = jnp.swapaxes(acts, 0, 1).reshape(
                B * n_sample, act_dim
            )
            obs_rep = jnp.repeat(obs, n_sample, axis=0)
            q1, q2 = critic.apply(cp, obs_rep, acts)
            q = jnp.minimum(q1, q2).reshape(B, n_sample)
            return q.mean(axis=1)

        def device_fn(params, opt_state, aux, batch, rng, coeffs):
            obs = batch[SampleBatch.OBS].astype(jnp.float32)
            next_obs = batch[SampleBatch.NEXT_OBS].astype(jnp.float32)
            rewards = batch[SampleBatch.REWARDS].astype(jnp.float32)
            not_done = 1.0 - batch[SampleBatch.TERMINATEDS].astype(
                jnp.float32
            )
            actions = batch[SampleBatch.ACTIONS].astype(jnp.float32)
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
            rng_t, rng_adv = jax.random.split(rng)

            # ---- critic TD step: next action from the TARGET actor ----
            next_dist = SquashedGaussian(
                actor.apply(aux["target_actor"], next_obs),
                low=low,
                high=high,
            )
            next_a, _ = next_dist.sampled_action_logp(rng_t)
            tq1, tq2 = critic.apply(
                aux["target_critic"], next_obs, next_a
            )
            td_target = jax.lax.stop_gradient(
                rewards + gamma * not_done * jnp.minimum(tq1, tq2)
            )

            def critic_loss(cp):
                q1, q2 = critic.apply(cp, obs, actions)
                return (
                    jnp.mean(jnp.square(q1 - td_target))
                    + jnp.mean(jnp.square(q2 - td_target))
                ), q1

            (c_loss, q1), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True
            )(params["critic"])
            c_grads = jax.lax.pmean(c_grads, axis)
            c_upd, c_opt = tx_c.update(
                c_grads, opt_state["critic"], params["critic"]
            )
            new_critic = optax.apply_updates(params["critic"], c_upd)

            # ---- advantage-weighted BC actor step ----
            qa1, qa2 = critic.apply(new_critic, obs, actions)
            q_data = jnp.minimum(qa1, qa2)
            v_est = mean_policy_q(
                new_critic, params["actor"], obs, rng_adv
            )
            advantage = jax.lax.stop_gradient(q_data - v_est)
            if weight_type == "exp":
                weights = jnp.clip(
                    jnp.exp(advantage / temperature), 0.0, max_weight
                )
            else:  # "bin"
                weights = (advantage > 0.0).astype(jnp.float32)

            def actor_loss(ap):
                dist = SquashedGaussian(
                    actor.apply(ap, obs), low=low, high=high
                )
                bc_logp = dist.logp(actions)
                return -jnp.mean(weights * bc_logp)

            a_loss, a_grads = jax.value_and_grad(actor_loss)(
                params["actor"]
            )
            a_grads = jax.lax.pmean(a_grads, axis)
            a_upd, a_opt = tx_a.update(
                a_grads, opt_state["actor"], params["actor"]
            )
            new_actor = optax.apply_updates(params["actor"], a_upd)

            # ---- periodic hard target sync (traced select) ----
            step = aux["step"] + 1
            do_sync = (step % sync_interval) == 0
            new_target_actor = jax.tree_util.tree_map(
                lambda t, o: jnp.where(do_sync, o, t),
                aux["target_actor"],
                new_actor,
            )
            new_target_critic = jax.tree_util.tree_map(
                lambda t, o: jnp.where(do_sync, o, t),
                aux["target_critic"],
                new_critic,
            )

            new_params = dict(
                params, actor=new_actor, critic=new_critic
            )
            new_opt = dict(opt_state, actor=a_opt, critic=c_opt)
            new_aux = {
                "target_actor": new_target_actor,
                "target_critic": new_target_critic,
                "step": step,
            }
            stats = {
                "actor_loss": a_loss,
                "critic_loss": c_loss,
                "mean_q": jnp.mean(q1),
                "mean_advantage": jnp.mean(advantage),
                "mean_weight": jnp.mean(weights),
                "total_loss": a_loss + c_loss,
            }
            stats = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, axis), stats
            )
            return new_params, new_opt, new_aux, stats

        return device_fn


class CRR(SAC):
    """Offline training loop over JsonReader data (reference crr.py
    trains from offline input with SAC-style machinery)."""

    _default_policy_class = CRRJaxPolicy

    @classmethod
    def get_default_config(cls) -> CRRConfig:
        return CRRConfig(cls)

    def setup(self, config: Dict) -> None:
        if config.get("twin_q") is False:
            raise NotImplementedError(
                "CRR always trains twin critics (the nets are a "
                "TwinQNet); twin_q=False is not supported"
            )
        super().setup(config)
        from ray_tpu.offline.offline_ops import setup_offline_reader

        self._reader = setup_offline_reader(config)

    def training_step(self) -> Dict:
        if self._reader is None:
            return super().training_step()
        from ray_tpu.offline.offline_ops import offline_training_step

        return offline_training_step(self)
