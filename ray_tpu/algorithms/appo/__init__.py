from ray_tpu.algorithms.appo.appo import APPO, APPOConfig, APPOJaxPolicy

__all__ = ["APPO", "APPOConfig", "APPOJaxPolicy"]
