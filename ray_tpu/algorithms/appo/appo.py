"""APPO: asynchronous PPO on the IMPALA actor-learner machinery.

Counterpart of the reference's ``rllib/algorithms/appo/appo.py``
(APPOConfig extends ImpalaConfig; ``after_train_step`` updates the
target net + adapts the KL coeff) and ``appo_torch_policy.py`` (V-trace
weighted PPO-clip surrogate against a periodically-frozen "old policy"
target network). Worker polling rides IMPALA's shared
``AsyncRequestsManager`` (execution/parallel_requests.py): per-worker
in-flight caps, ``ray.wait`` harvest, dead workers dropped and reported
(recreated when ``recreate_failed_workers`` is set).

Loss semantics (appo_torch_policy.py:160-270): V-trace advantages are
computed against the TARGET policy's logits; the surrogate ratio is
``clamp(exp(behaviour_logp - old_logp), 0, 2) * exp(cur_logp -
behaviour_logp)`` — a doubly-corrected IS ratio that keeps the clipping
anchor at the slow-moving old policy while samples come from slightly
stale behaviour policies. The target params live in the policy's
replicated aux_state like DQN's target net."""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.algorithms.impala.impala import (
    IMPALA,
    IMPALAConfig,
    ImpalaJaxPolicy,
)
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED
from ray_tpu.ops.vtrace import vtrace_from_logits


class APPOConfig(IMPALAConfig):
    """reference appo.py APPOConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.vtrace = True
        self.use_critic = True
        self.use_gae = True
        self.lambda_ = 1.0
        self.clip_param = 0.4
        self.use_kl_loss = False
        self.kl_coeff = 1.0
        self.kl_target = 0.01
        # learner steps between old-policy refreshes (the reference
        # derives this from num_sgd_iter * minibatch_buffer_size, i.e.
        # effectively every train step).
        self.target_update_frequency = 1

    def training(
        self,
        *,
        clip_param: Optional[float] = None,
        use_kl_loss: Optional[bool] = None,
        kl_coeff: Optional[float] = None,
        kl_target: Optional[float] = None,
        lambda_: Optional[float] = None,
        target_update_frequency: Optional[int] = None,
        **kwargs,
    ) -> "APPOConfig":
        super().training(**kwargs)
        if clip_param is not None:
            self.clip_param = clip_param
        if use_kl_loss is not None:
            self.use_kl_loss = use_kl_loss
        if kl_coeff is not None:
            self.kl_coeff = kl_coeff
        if kl_target is not None:
            self.kl_target = kl_target
        if lambda_ is not None:
            self.lambda_ = lambda_
        if target_update_frequency is not None:
            self.target_update_frequency = target_update_frequency
        return self


class APPOJaxPolicy(ImpalaJaxPolicy):
    """V-trace weighted PPO-clip surrogate vs a frozen old policy
    (reference appo_torch_policy.py)."""

    def _init_coeffs(self):
        self.coeff_values["kl_coeff"] = float(
            self.config.get("kl_coeff", 1.0)
        )

    def _init_aux_state(self):
        return {"target_params": self.params}

    def update_target(self) -> None:
        """Refresh the frozen old policy (reference
        appo.py after_train_step → p.update_target())."""
        self.aux_state = {"target_params": self.params}

    def loss_with_aux(self, params, aux, batch, rng, coeffs):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        clip_param = cfg.get("clip_param", 0.4)
        use_kl = cfg.get("use_kl_loss", False)
        obs = batch[SampleBatch.OBS]
        B, T = obs.shape[0], obs.shape[1]

        dist_inputs, values, bootstrap_value = self._forward_unrolls(
            params, batch
        )
        old_inputs, _, _ = self._forward_unrolls(
            aux["target_params"], batch
        )
        old_inputs = jax.lax.stop_gradient(old_inputs)
        dist = self.dist_class(dist_inputs)
        old_dist = self.dist_class(old_inputs)

        actions = batch[SampleBatch.ACTIONS]
        flat_actions = actions.reshape((B * T,) + actions.shape[2:])
        cur_logp = dist.logp(flat_actions)
        old_logp = old_dist.logp(flat_actions)
        behaviour_logp = batch[SampleBatch.ACTION_LOGP].reshape(B * T)

        # V-trace against the OLD policy (its logp as target).
        vtr = vtrace_from_logits(
            behaviour_action_log_probs=batch[SampleBatch.ACTION_LOGP],
            target_action_log_probs=old_logp.reshape(B, T),
            discounts=gamma * (1.0 - batch["dones"]),
            rewards=batch[SampleBatch.REWARDS],
            values=values.reshape(B, T),
            bootstrap_value=bootstrap_value,
            clip_rho_threshold=cfg.get("vtrace_clip_rho_threshold", 1.0),
            clip_pg_rho_threshold=cfg.get(
                "vtrace_clip_pg_rho_threshold", 1.0
            ),
        )
        advantages = vtr.pg_advantages.reshape(B * T)

        # Doubly-corrected IS ratio (appo_torch_policy.py:236-239).
        is_ratio = jnp.clip(
            jnp.exp(behaviour_logp - old_logp), 0.0, 2.0
        )
        logp_ratio = is_ratio * jnp.exp(cur_logp - behaviour_logp)

        surrogate = jnp.minimum(
            advantages * logp_ratio,
            advantages
            * jnp.clip(logp_ratio, 1.0 - clip_param, 1.0 + clip_param),
        )
        pi_loss = -jnp.mean(surrogate)
        vf_loss = 0.5 * jnp.mean(
            jnp.square(vtr.vs - values.reshape(B, T))
        )
        entropy_mean = jnp.mean(dist.entropy())
        action_kl = jnp.mean(old_dist.kl(dist))

        total = (
            pi_loss
            + cfg.get("vf_loss_coeff", 0.5) * vf_loss
            - coeffs["entropy_coeff"] * entropy_mean
        )
        if use_kl:
            total = total + coeffs["kl_coeff"] * action_kl
        stats = {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy_mean,
            "kl": action_kl,
            "mean_is_ratio": jnp.mean(is_ratio),
        }
        return total, stats


class APPO(IMPALA):
    _default_policy_class = APPOJaxPolicy

    @classmethod
    def get_default_config(cls) -> APPOConfig:
        return APPOConfig(cls)

    def setup(self, config: Dict) -> None:
        super().setup(config)
        self._last_target_refresh = 0

    def training_step(self) -> Dict:
        results = super().training_step()
        # Target refresh + KL adaptation (reference appo.py
        # after_train_step).
        trained = self._counters[NUM_ENV_STEPS_TRAINED]
        freq = self.config.get("target_update_frequency", 1)
        batch_size = max(1, self.config.get("train_batch_size", 500))
        if trained - self._last_target_refresh >= freq * batch_size:
            self._last_target_refresh = trained
            self._counters["num_target_updates"] += 1
            policy = self.get_policy()
            policy.update_target()
            if self.config.get("use_kl_loss"):
                kl = results.get(DEFAULT_POLICY_ID, {}).get("kl")
                target = self.config.get("kl_target", 0.01)
                if kl is not None:
                    if kl > 2.0 * target:
                        policy.coeff_values["kl_coeff"] *= 1.5
                    elif kl < 0.5 * target:
                        policy.coeff_values["kl_coeff"] *= 0.5
        return results
