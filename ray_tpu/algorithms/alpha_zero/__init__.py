from ray_tpu.algorithms.alpha_zero.alpha_zero import (
    AlphaZero,
    AlphaZeroConfig,
    MCTS,
)

__all__ = ["AlphaZero", "AlphaZeroConfig", "MCTS"]
