"""AlphaZero: MCTS-guided policy iteration (Silver et al. 2017).

Counterpart of the reference's ``rllib/algorithms/alpha_zero/``
(``alpha_zero.py``, ``mcts.py``): self-play with PUCT tree search over a
clonable env (``get_state``/``set_state``), visit-count policy targets,
and a joint policy+value network trained on (obs, pi_mcts, z) tuples.

TPU-first split: the tree search is inherently sequential host logic
(numpy PUCT with batched-leaf evaluation would be the next step), while
ALL network math — the prior/value evaluation inside the search and the
cross-entropy+MSE training step — is jitted. Ranked rewards (the
reference's single-player r2 wrapper) is replaced by discounted
return-to-go value targets, which fits the same CartPole-style
single-player setting the reference ships tests for."""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID
from ray_tpu.env.registry import get_env_creator
from ray_tpu.evaluation.metrics import RolloutMetrics
from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED
from ray_tpu.models.catalog import ModelCatalog


class _Node:
    """PUCT search node (reference mcts.py Node, vectorized over
    children with numpy)."""

    __slots__ = (
        "state",
        "obs",
        "done",
        "reward",
        "priors",
        "net_value",
        "child_n",
        "child_w",
        "children",
        "num_actions",
    )

    def __init__(
        self, state, obs, done, reward, priors, net_value, num_actions
    ):
        self.state = state
        self.obs = obs
        self.done = done
        self.reward = reward
        self.priors = priors
        self.net_value = net_value  # value from the SAME forward pass
        self.num_actions = num_actions
        self.child_n = np.zeros(num_actions, np.float32)
        self.child_w = np.zeros(num_actions, np.float32)
        self.children: Dict[int, "_Node"] = {}

    def puct_action(self, c: float) -> int:
        q = self.child_w / np.maximum(self.child_n, 1.0)
        # Min-max normalize Q over visited children (MuZero App. B):
        # raw discounted returns are unbounded, and an unnormalized Q
        # swamps the prior term — the reference instead squashes returns
        # into [-1, 1] with its ranked-rewards wrapper.
        visited = self.child_n > 0
        if visited.any():
            lo, hi = q[visited].min(), q[visited].max()
            # all-equal (e.g. a single visited child) normalizes to 0 so
            # the prior term drives exploration, as MuZero does
            q = np.where(
                visited, (q - lo) / max(hi - lo, 1e-8), 0.0
            )
        u = (
            c
            * self.priors
            * math.sqrt(max(1.0, self.child_n.sum()))
            / (1.0 + self.child_n)
        )
        return int(np.argmax(q + u))


class MCTS:
    """reference mcts.py MCTS."""

    def __init__(self, eval_fn, config: Dict, num_actions: int, rng):
        self.eval_fn = eval_fn  # obs -> (priors, value)
        self.num_sims = int(config.get("num_simulations", 30))
        self.c_puct = float(config.get("puct_coefficient", 1.4))
        self.dir_eps = float(config.get("dirichlet_epsilon", 0.25))
        self.dir_alpha = float(config.get("dirichlet_noise", 0.3))
        self.temperature = float(config.get("temperature", 1.0))
        self.gamma = float(config.get("gamma", 0.99))
        self.num_actions = num_actions
        self.rng = rng

    def _make_node(self, env, state, obs, done, reward) -> _Node:
        priors, value = self.eval_fn(obs)
        return _Node(
            state, obs, done, reward, priors, float(value),
            self.num_actions,
        )

    def search(self, env, obs) -> np.ndarray:
        """→ visit-count policy over actions at the current env state."""
        root_state = env.get_state()
        root = self._make_node(env, root_state, obs, False, 0.0)
        # Dirichlet exploration noise at the root (AlphaZero eq. in
        # Methods; reference mcts.py dir_epsilon/dir_noise)
        noise = self.rng.dirichlet(
            [self.dir_alpha] * self.num_actions
        )
        root.priors = (
            (1 - self.dir_eps) * root.priors + self.dir_eps * noise
        ).astype(np.float32)

        for _ in range(self.num_sims):
            node = root
            path: List[tuple] = []
            # select down to a leaf
            while True:
                a = node.puct_action(self.c_puct)
                path.append((node, a))
                child = node.children.get(a)
                if child is None:
                    break
                node = child
                if node.done:
                    break
            # expand
            if child is None and not node.done:
                env.set_state(node.state)
                step_obs, r, term, trunc, _ = env.step(a)
                done = bool(term or trunc)
                child = self._make_node(
                    env, env.get_state(), step_obs, done, float(r)
                )
                node.children[a] = child
                node = child
            # evaluate: reuse the value from the expansion forward
            # pass (one network call per simulation, not two)
            value = 0.0 if node.done else node.net_value
            # backup with per-edge rewards (single-player discounted)
            for parent, a in reversed(path):
                child = parent.children.get(a)
                r = child.reward if child is not None else 0.0
                value = r + self.gamma * value
                parent.child_n[a] += 1.0
                parent.child_w[a] += value
        env.set_state(root_state)
        visits = root.child_n
        if self.temperature <= 1e-6:
            pi = np.zeros_like(visits)
            pi[int(np.argmax(visits))] = 1.0
            return pi
        scaled = visits ** (1.0 / self.temperature)
        return (scaled / max(scaled.sum(), 1e-8)).astype(np.float32)


class AlphaZeroConfig(AlgorithmConfig):
    """reference alpha_zero.py AlphaZeroConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or AlphaZero)
        self.mcts_config = {
            "num_simulations": 30,
            "puct_coefficient": 1.4,
            "dirichlet_epsilon": 0.25,
            "dirichlet_noise": 0.3,
            "temperature": 1.0,
        }
        self.lr = 1e-3
        self.train_batch_size = 128
        self.rollout_fragment_length = 64
        self.buffer_size = 5000
        self.num_sgd_iter = 1
        self.vf_loss_coeff = 1.0

    def training(
        self,
        *,
        mcts_config: Optional[Dict] = None,
        vf_loss_coeff: Optional[float] = None,
        buffer_size: Optional[int] = None,
        **kwargs,
    ) -> "AlphaZeroConfig":
        super().training(**kwargs)
        if mcts_config is not None:
            self.mcts_config.update(mcts_config)
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if buffer_size is not None:
            self.buffer_size = buffer_size
        return self


class AlphaZero(Algorithm):
    @classmethod
    def get_default_config(cls) -> AlphaZeroConfig:
        return AlphaZeroConfig(cls)

    def setup(self, config: Dict) -> None:
        env_spec = config.get("env")
        super().setup(dict(config, env=None))
        self.env = get_env_creator(env_spec)(
            config.get("env_config") or {}
        )
        assert hasattr(self.env, "get_state") and hasattr(
            self.env, "set_state"
        ), "AlphaZero requires a clonable env (get_state/set_state)"
        obs_space = self.env.observation_space
        act_space = self.env.action_space
        assert isinstance(act_space, gym.spaces.Discrete)
        self.num_actions = int(act_space.n)

        seed = int(config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._np_rng = np.random.default_rng(seed)
        self.model = ModelCatalog.get_model(
            obs_space,
            act_space,
            self.num_actions,
            dict(config.get("model") or {}),
        )
        self._rng, init_rng = jax.random.split(self._rng)
        dummy = jnp.zeros(
            (2,) + tuple(obs_space.shape), jnp.float32
        )
        self.params = self.model.init(init_rng, dummy)
        self._tx = optax.adam(float(config.get("lr", 1e-3)))
        self.opt_state = self._tx.init(self.params)

        # The value head learns the NORMALIZED return (1-gamma)*V (the
        # reference squashes returns with its ranked-rewards wrapper
        # instead): keeps the MSE term commensurate with the policy CE
        # and the PUCT Q scale stable. MCTS unscales at leaf evaluation.
        gamma = float(config.get("gamma", 0.99))
        self._value_scale = max(1e-6, 1.0 - gamma)

        def eval_one(params, obs):
            logits, value, _ = self.model.apply(params, obs[None])
            return jax.nn.softmax(logits[0]), value[0]

        self._eval_jit = jax.jit(eval_one)

        def scaled_eval(obs):
            priors, v = self._eval_jit(
                self.params, jnp.asarray(obs, jnp.float32)
            )
            return (
                np.asarray(priors),
                np.float32(v) / self._value_scale,
            )
        self.mcts = MCTS(
            scaled_eval,
            {**config.get("mcts_config", {}), "gamma": gamma},
            self.num_actions,
            self._np_rng,
        )
        self._buffer: List[Dict] = []
        self._buffer_idx = 0
        self._learn_fn = None
        self._cur_obs, _ = self.env.reset(seed=seed)
        self._episode: List[Dict] = []
        self._episode_reward = 0.0

    # -- self-play --------------------------------------------------------

    def _self_play(self, num_steps: int) -> None:
        cap = int(self.config.get("buffer_size", 5000))
        gamma = float(self.config.get("gamma", 0.99))
        for _ in range(num_steps):
            pi = self.mcts.search(self.env, self._cur_obs)
            action = int(self._np_rng.choice(self.num_actions, p=pi))
            next_obs, reward, term, trunc, _ = self.env.step(action)
            self._episode.append(
                {
                    "obs": np.asarray(self._cur_obs, np.float32),
                    "pi": pi,
                    "reward": float(reward),
                }
            )
            self._episode_reward += float(reward)
            self._counters[NUM_ENV_STEPS_SAMPLED] += 1
            self._counters[NUM_AGENT_STEPS_SAMPLED] += 1
            self._cur_obs = next_obs
            if term or trunc:
                # backfill discounted returns as value targets
                z = 0.0
                for row in reversed(self._episode):
                    z = row["reward"] + gamma * z
                    row["z"] = z
                for row in self._episode:
                    entry = {
                        "obs": row["obs"],
                        "pi": row["pi"],
                        "z": np.float32(
                            row["z"] * self._value_scale
                        ),
                    }
                    if len(self._buffer) < cap:
                        self._buffer.append(entry)
                    else:
                        self._buffer[self._buffer_idx] = entry
                    self._buffer_idx = (self._buffer_idx + 1) % cap
                self._episode_history.append(
                    RolloutMetrics(
                        len(self._episode), self._episode_reward
                    )
                )
                self._episodes_total += 1
                self._episode = []
                self._episode_reward = 0.0
                self._cur_obs, _ = self.env.reset()

    # -- learning ---------------------------------------------------------

    def _build_learn_fn(self):
        vf_coeff = float(self.config.get("vf_loss_coeff", 1.0))
        model, tx = self.model, self._tx

        def fn(params, opt_state, obs, pi, z):
            def loss_fn(p):
                logits, value, _ = model.apply(p, obs)
                logp = jax.nn.log_softmax(logits)
                policy_loss = -jnp.mean(jnp.sum(pi * logp, axis=-1))
                value_loss = jnp.mean(jnp.square(value - z))
                return policy_loss + vf_coeff * value_loss, (
                    policy_loss,
                    value_loss,
                )

            (loss, (pl, vl)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {
                "total_loss": loss,
                "policy_loss": pl,
                "vf_loss": vl,
            }

        return jax.jit(fn)

    def training_step(self) -> Dict:
        config = self.config
        self._self_play(int(config.get("rollout_fragment_length", 64)))
        train_info: Dict = {}
        if len(self._buffer) >= config["train_batch_size"]:
            if self._learn_fn is None:
                self._learn_fn = self._build_learn_fn()
            idx = self._np_rng.integers(
                0, len(self._buffer), config["train_batch_size"]
            )
            rows = [self._buffer[i] for i in idx]
            obs = jnp.asarray(np.stack([r["obs"] for r in rows]))
            pi = jnp.asarray(np.stack([r["pi"] for r in rows]))
            z = jnp.asarray(np.stack([r["z"] for r in rows]))
            self.params, self.opt_state, stats = self._learn_fn(
                self.params, self.opt_state, obs, pi, z
            )
            train_info = {
                DEFAULT_POLICY_ID: {
                    k: float(v)
                    for k, v in jax.device_get(stats).items()
                }
            }
            self._counters[NUM_ENV_STEPS_TRAINED] += int(
                config["train_batch_size"]
            )
        return train_info

    def __getstate__(self) -> Dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "counters": dict(self._counters),
            "episodes_total": self._episodes_total,
        }

    def __setstate__(self, state: Dict) -> None:
        import collections

        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])
        self._counters = collections.defaultdict(
            int, state.get("counters", {})
        )
        self._episodes_total = state.get("episodes_total", 0)

    def cleanup(self) -> None:
        try:
            self.env.close()
        except Exception:
            pass
        super().cleanup()
