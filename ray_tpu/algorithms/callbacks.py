"""User callback hooks into the sampling/training loop.

Counterpart of the reference's ``rllib/algorithms/callbacks.py``
DefaultCallbacks: subclass, override the hooks you need, and pass the
CLASS via ``config["callbacks_class"]`` (fluent:
``.callbacks(MyCallbacks)``). The episode object exposes
``user_data`` (scratch space across a whole episode) and
``custom_metrics`` (scalars aggregated into the training result as
``custom_metrics/<name>_mean|min|max``, exactly like the reference).
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class DefaultCallbacks:
    """All hooks are no-ops; override freely. Signatures follow the
    reference's keyword-only style so overrides stay source-portable
    (extra kwargs are always passed, so accept ``**kwargs``)."""

    def on_episode_start(
        self, *, worker=None, base_env=None, policies=None,
        episode=None, env_index: Optional[int] = None, **kwargs,
    ) -> None:
        pass

    def on_episode_step(
        self, *, worker=None, base_env=None, policies=None,
        episode=None, env_index: Optional[int] = None, **kwargs,
    ) -> None:
        pass

    def on_episode_end(
        self, *, worker=None, base_env=None, policies=None,
        episode=None, env_index: Optional[int] = None, **kwargs,
    ) -> None:
        pass

    def on_sample_end(
        self, *, worker=None, samples=None, **kwargs
    ) -> None:
        pass

    def on_postprocess_trajectory(
        self, *, worker=None, episode=None, agent_id=None,
        policy_id=None, policies=None, postprocessed_batch=None,
        original_batches=None, **kwargs,
    ) -> None:
        pass

    def on_train_result(
        self, *, algorithm=None, result: Optional[Dict] = None,
        **kwargs,
    ) -> None:
        pass


class MultiCallbacks(DefaultCallbacks):
    """Fan one hook call out to several callback objects (reference
    MultiCallbacks)."""

    def __init__(self, callbacks_classes):
        self._callbacks = [c() for c in callbacks_classes]

    def __getattribute__(self, name: str) -> Any:
        if name.startswith("on_"):
            cbs = object.__getattribute__(self, "_callbacks")

            def fan_out(**kwargs):
                for cb in cbs:
                    getattr(cb, name)(**kwargs)

            return fan_out
        return object.__getattribute__(self, name)
