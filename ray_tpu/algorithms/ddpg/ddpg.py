"""DDPG and TD3: deterministic-policy actor-critic with target networks.

Counterpart of the reference's ``rllib/algorithms/ddpg/ddpg.py`` (config;
DDPG extends SimpleQ's off-policy loop) and ``ddpg_torch_policy.py``
(actor/critic losses, target smoothing, delayed policy updates for TD3
via ``policy_delay``; ``rllib/algorithms/td3/td3.py`` is DDPG with twin
critics + smoothed targets + Gaussian exploration).

TPU-first: the whole update — critic step, (delayed) actor step, polyak
target blends for both nets — is ONE jitted shard_map program; the
delayed actor update is a ``lax.cond`` on a traced step counter carried
in aux_state, so the program never recompiles across steps."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_tpu import sharding as sharding_lib

from ray_tpu.algorithms.algorithm_config import AlgorithmConfig  # noqa: F401
from ray_tpu.algorithms.dqn.dqn import DQN, DQNConfig
from ray_tpu.algorithms.sac.sac import _TwinQNet
from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.models.base import get_activation
from ray_tpu.models.distributions import Deterministic
from ray_tpu.policy.jax_policy import JaxPolicy, _tree_to_device


class _DetActorNet(nn.Module):
    """MLP -> tanh -> affine to [low, high] (reference
    ddpg_torch_model.py policy network)."""

    action_dim: int
    low: float
    high: float
    hiddens: Sequence[int] = (400, 300)
    activation: str = "relu"

    @nn.compact
    def __call__(self, obs):
        act = get_activation(self.activation)
        x = obs.astype(jnp.float32).reshape(obs.shape[0], -1)
        for i, h in enumerate(self.hiddens):
            x = act(nn.Dense(h, name=f"fc_{i}")(x))
        raw = nn.Dense(self.action_dim, name="out")(x)
        squashed = jnp.tanh(raw)
        mid = (self.high + self.low) / 2.0
        half = (self.high - self.low) / 2.0
        return mid + half * squashed


class DDPGConfig(DQNConfig):
    """reference ddpg.py DDPGConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self.twin_q = False
        self.policy_delay = 1
        self.smooth_target_policy = False
        self.target_noise = 0.2
        self.target_noise_clip = 0.5
        self.actor_hiddens = [400, 300]
        self.actor_hidden_activation = "relu"
        self.critic_hiddens = [400, 300]
        self.critic_hidden_activation = "relu"
        self.tau = 0.002
        self.use_huber = False
        self.huber_threshold = 1.0
        self.l2_reg = 1e-6
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.train_batch_size = 256
        self.rollout_fragment_length = 1
        self.num_steps_sampled_before_learning_starts = 1500
        self.target_network_update_freq = 0
        self.n_step = 1
        self.grad_clip = None
        self.exploration_config = {
            "type": "OrnsteinUhlenbeckNoise",
            "scale_timesteps": 10000,
            "initial_scale": 1.0,
            "final_scale": 0.02,
            "ou_base_scale": 0.1,
            "ou_theta": 0.15,
            "ou_sigma": 0.2,
        }
        self.replay_buffer_config = {
            "capacity": 50000,
            "prioritized_replay": False,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
        }

    def training(
        self,
        *,
        twin_q: Optional[bool] = None,
        policy_delay: Optional[int] = None,
        smooth_target_policy: Optional[bool] = None,
        target_noise: Optional[float] = None,
        target_noise_clip: Optional[float] = None,
        actor_hiddens: Optional[Sequence[int]] = None,
        critic_hiddens: Optional[Sequence[int]] = None,
        tau: Optional[float] = None,
        use_huber: Optional[bool] = None,
        actor_lr: Optional[float] = None,
        critic_lr: Optional[float] = None,
        l2_reg: Optional[float] = None,
        **kwargs,
    ) -> "DDPGConfig":
        super().training(**kwargs)
        if twin_q is not None:
            self.twin_q = twin_q
        if policy_delay is not None:
            self.policy_delay = policy_delay
        if smooth_target_policy is not None:
            self.smooth_target_policy = smooth_target_policy
        if target_noise is not None:
            self.target_noise = target_noise
        if target_noise_clip is not None:
            self.target_noise_clip = target_noise_clip
        if actor_hiddens is not None:
            self.actor_hiddens = list(actor_hiddens)
        if critic_hiddens is not None:
            self.critic_hiddens = list(critic_hiddens)
        if tau is not None:
            self.tau = tau
        if use_huber is not None:
            self.use_huber = use_huber
        if actor_lr is not None:
            self.actor_lr = actor_lr
        if critic_lr is not None:
            self.critic_lr = critic_lr
        if l2_reg is not None:
            self.l2_reg = l2_reg
        return self


class TD3Config(DDPGConfig):
    """reference td3.py TD3Config: twin critics, delayed + smoothed
    target policy, Gaussian exploration."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or TD3)
        self.twin_q = True
        self.policy_delay = 2
        self.smooth_target_policy = True
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.exploration_config = {
            "type": "GaussianNoise",
            "stddev": 0.1,
            "initial_scale": 1.0,
            "final_scale": 1.0,
            "scale_timesteps": 1,
        }
        self.num_steps_sampled_before_learning_starts = 10000


class DDPGJaxPolicy(JaxPolicy):
    """Deterministic actor + (twin) critic with target nets (reference
    ddpg_torch_policy.py ddpg_actor_critic_loss)."""

    default_exploration = "OrnsteinUhlenbeckNoise"

    def __init__(self, observation_space, action_space, config):
        from ray_tpu.policy.policy import Policy

        Policy.__init__(self, observation_space, action_space, config)
        self.action_dim = int(np.prod(action_space.shape))
        self.low = float(np.min(action_space.low))
        self.high = float(np.max(action_space.high))

        self.sharding_backend = config.get("sharding_backend", "mesh")
        self.mesh = sharding_lib.resolve_mesh(config)
        self.n_shards = sharding_lib.num_shards(self.mesh)
        self._param_sharding = sharding_lib.replicated(self.mesh)
        self._data_sharding = sharding_lib.batch_sharded(self.mesh)

        self.actor = _DetActorNet(
            self.action_dim,
            self.low,
            self.high,
            tuple(config.get("actor_hiddens", (400, 300))),
            config.get("actor_hidden_activation", "relu"),
        )
        self.critic = _TwinQNet(
            tuple(config.get("critic_hiddens", (400, 300))),
            config.get("critic_hidden_activation", "relu"),
        )

        seed = int(config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._rng, r1, r2 = jax.random.split(self._rng, 3)
        dummy_obs = jnp.zeros(
            (2,) + tuple(observation_space.shape), jnp.float32
        )
        dummy_act = jnp.zeros((2, self.action_dim), jnp.float32)
        actor_params = self.actor.init(r1, dummy_obs)
        critic_params = self.critic.init(r2, dummy_obs, dummy_act)
        self.params = _tree_to_device(
            {"actor": actor_params, "critic": critic_params},
            self._param_sharding,
        )
        self.aux_state = _tree_to_device(
            {
                "target_actor": actor_params,
                "target_critic": critic_params,
                "step": jnp.zeros((), jnp.int32),
            },
            self._param_sharding,
        )

        self._tx_actor = optax.adam(config.get("actor_lr", 1e-3))
        self._tx_critic = optax.adam(config.get("critic_lr", 1e-3))
        self.opt_state = _tree_to_device(
            {
                "actor": self._tx_actor.init(actor_params),
                "critic": self._tx_critic.init(critic_params),
            },
            self._param_sharding,
        )

        self.tau = float(config.get("tau", 0.002))
        self.gamma = float(config.get("gamma", 0.99))
        self.n_step = int(config.get("n_step", 1))
        self.twin_q = bool(config.get("twin_q", False))
        self.policy_delay = int(config.get("policy_delay", 1))

        self.coeff_values: Dict[str, float] = {}
        self._learn_fns: Dict = {}
        self._action_fn = None
        self.num_grad_updates = 0
        self._init_exploration()

    def get_initial_state(self):
        return []

    # -- inference -------------------------------------------------------

    def _build_action_fn(self):
        actor = self.actor
        exploration = self.exploration

        def fn(params, obs, rng, explore, coeffs, expl_state):
            det = actor.apply(params["actor"], obs)
            dist = Deterministic(det)
            actions, logp, expl_state = exploration.sample_fn(
                dist, rng, explore, coeffs, expl_state
            )
            return actions, expl_state

        return jax.jit(fn, static_argnames=("explore",))

    def compute_actions(
        self, obs_batch, state_batches=None, explore=True, **kwargs
    ):
        if self._action_fn is None:
            self._action_fn = self._build_action_fn()
        self.exploration.update_coeffs(
            self.coeff_values, self.global_timestep
        )
        params = self.exploration.params_for_inference(self, explore)
        self._rng, rng = jax.random.split(self._rng)
        obs = jnp.asarray(obs_batch)
        if self.exploration.needs_last_obs:
            self._last_obs = obs
        bsize = int(obs.shape[0])
        if self._expl_state_batch != bsize:
            self._expl_state = self.exploration.initial_state(bsize)
            self._expl_state_batch = bsize
        actions, self._expl_state = self._action_fn(
            params, obs, rng, bool(explore),
            self._coeff_array(), self._expl_state,
        )
        return np.asarray(actions), [], {}

    # -- learning --------------------------------------------------------

    def _td_targets(self, params, aux, batch, rng):
        """Target-Q computation shared by the loss and compute_td_error."""
        cfg = self.config
        next_obs = batch[SampleBatch.NEXT_OBS].astype(jnp.float32)
        rewards = batch[SampleBatch.REWARDS].astype(jnp.float32)
        not_done = 1.0 - batch[SampleBatch.TERMINATEDS].astype(
            jnp.float32
        )
        # per-row fold counts from adjust_nstep: fragment tails fold
        # fewer than n_step rewards, so their bootstrap discounts by
        # gamma**k, not a uniform gamma**n_step (dqn.py does the same)
        if "n_steps" in batch:
            gamma_n = self.gamma ** batch["n_steps"].astype(
                jnp.float32
            )
        else:
            gamma_n = self.gamma**self.n_step
        next_a = self.actor.apply(aux["target_actor"], next_obs)
        if cfg.get("smooth_target_policy"):
            noise = jnp.clip(
                cfg.get("target_noise", 0.2)
                * jax.random.normal(rng, next_a.shape),
                -cfg.get("target_noise_clip", 0.5),
                cfg.get("target_noise_clip", 0.5),
            )
            next_a = jnp.clip(next_a + noise, self.low, self.high)
        tq1, tq2 = self.critic.apply(
            aux["target_critic"], next_obs, next_a
        )
        target_q = jnp.minimum(tq1, tq2) if self.twin_q else tq1
        return jax.lax.stop_gradient(
            rewards + gamma_n * not_done * target_q
        )

    def _device_update_fn(self, batch_size=None, with_frames=False):
        """Single-update device body (shard_map), shared by the
        per-call learn program and the generic superstep scan
        (``JaxPolicy.learn_superstep``)."""
        actor, critic = self.actor, self.critic
        tx_a, tx_c = self._tx_actor, self._tx_critic
        tau = self.tau
        twin_q = self.twin_q
        policy_delay = self.policy_delay
        use_huber = bool(self.config.get("use_huber", False))
        huber_d = float(self.config.get("huber_threshold", 1.0))
        l2_reg = float(self.config.get("l2_reg", 0.0) or 0.0)
        mesh = self.mesh
        axis = sharding_lib.data_axis(mesh)

        def device_fn(params, opt_state, aux, batch, rng, coeffs):
            obs = batch[SampleBatch.OBS].astype(jnp.float32)
            actions = batch[SampleBatch.ACTIONS].astype(jnp.float32)
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
            td_target = self._td_targets(params, aux, batch, rng)

            # ---- critic step ----
            # prioritized-replay importance weights (Ape-X DDPG path);
            # absent column -> uniform
            is_weights = batch.get(
                "weights", jnp.ones_like(td_target)
            )

            def critic_loss(cp):
                q1, q2 = critic.apply(cp, obs, actions)
                err1 = q1 - td_target
                err2 = q2 - td_target

                def base_loss(err):
                    if use_huber:
                        a = jnp.abs(err)
                        return jnp.where(
                            a < huber_d,
                            0.5 * jnp.square(err),
                            huber_d * (a - 0.5 * huber_d),
                        )
                    return jnp.square(err)

                loss = jnp.mean(is_weights * base_loss(err1))
                if twin_q:
                    loss = loss + jnp.mean(
                        is_weights * base_loss(err2)
                    )
                if l2_reg:
                    loss = loss + l2_reg * optax.global_norm(cp) ** 2
                return loss, (q1, err1)

            (c_loss, (q1, td_err)), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True
            )(params["critic"])
            c_grads = jax.lax.pmean(c_grads, axis)
            c_upd, c_opt = tx_c.update(
                c_grads, opt_state["critic"], params["critic"]
            )
            new_critic = optax.apply_updates(params["critic"], c_upd)

            # ---- delayed actor step (TD3 policy_delay) ----
            def actor_loss(ap):
                a = actor.apply(ap, obs)
                aq1, _ = critic.apply(new_critic, obs, a)
                loss = -jnp.mean(aq1)
                if l2_reg:
                    loss = loss + l2_reg * optax.global_norm(ap) ** 2
                return loss

            a_loss, a_grads = jax.value_and_grad(actor_loss)(
                params["actor"]
            )
            a_grads = jax.lax.pmean(a_grads, axis)
            a_upd, a_opt = tx_a.update(
                a_grads, opt_state["actor"], params["actor"]
            )
            updated_actor = optax.apply_updates(params["actor"], a_upd)

            step = aux["step"]
            do_update = (step % policy_delay) == 0
            new_actor = jax.tree_util.tree_map(
                lambda new, old: jnp.where(do_update, new, old),
                updated_actor,
                params["actor"],
            )
            new_a_opt = jax.tree_util.tree_map(
                lambda new, old: jnp.where(do_update, new, old),
                a_opt,
                opt_state["actor"],
            )

            # ---- polyak blends (actor target only on actor updates) ----
            new_target_critic = jax.tree_util.tree_map(
                lambda t, o: (1.0 - tau) * t + tau * o,
                aux["target_critic"],
                new_critic,
            )
            new_target_actor = jax.tree_util.tree_map(
                lambda t, o: jnp.where(
                    do_update, (1.0 - tau) * t + tau * o, t
                ),
                aux["target_actor"],
                new_actor,
            )

            new_params = {"actor": new_actor, "critic": new_critic}
            new_opt = {"actor": new_a_opt, "critic": c_opt}
            new_aux = {
                "target_actor": new_target_actor,
                "target_critic": new_target_critic,
                "step": step + 1,
            }
            stats = {
                "actor_loss": a_loss,
                "critic_loss": c_loss,
                "mean_q": jnp.mean(q1),
                "mean_td_error": jnp.mean(td_err),
                "total_loss": a_loss + c_loss,
            }
            stats = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, axis), stats
            )
            return new_params, new_opt, new_aux, stats

        return device_fn

    def _build_learn_fn(self, batch_size: int):
        return self._wrap_update_program(
            self._device_update_fn(batch_size), batch_size
        )

    # -- superstep contract (JaxPolicy.learn_superstep) ------------------

    @property
    def supports_superstep(self) -> bool:
        return (
            not self._superstep_opt_out
            and self.sharding_backend == "mesh"
            and type(self)._build_learn_fn
            is DDPGJaxPolicy._build_learn_fn
        )

    def _learn_coeffs(self):
        return {}

    def _updates_per_learn_call(self, batch_size: int) -> int:
        return 1

    @property
    def _td_refresh_uses_rng(self) -> bool:
        return True  # target-policy smoothing noise

    def learn_on_device_batch(
        self, dev_batch, batch_size: int, *, defer_stats: bool = False
    ) -> Dict:
        fn = self.learn_fn(batch_size)
        self._rng, rng = jax.random.split(self._rng)
        self.params, self.opt_state, self.aux_state, stats = fn(
            self.params, self.opt_state, self.aux_state, dev_batch,
            rng, {},
        )
        self.num_grad_updates += 1
        if defer_stats:
            return stats
        if self.config.get("deferred_stats"):
            # one-call lag, same contract as the JaxPolicy base
            # (docs/data_plane.md)
            prev = self.__dict__.get("_lagged_stats")
            self.__dict__["_lagged_stats"] = stats
            if prev is None:
                return {}
            stats = jax.device_get(prev)
        else:
            stats = jax.device_get(stats)
        return {k: float(v) for k, v in stats.items()}

    def _td_error_device_fn(self):
        """Signed per-sample TD error — shared by ``compute_td_error``
        and the superstep's in-scan prioritized refresh."""

        def fn(params, aux, batch, rng):
            td_target = self._td_targets(params, aux, batch, rng)
            q1, _ = self.critic.apply(
                params["critic"],
                batch[SampleBatch.OBS].astype(jnp.float32),
                batch[SampleBatch.ACTIONS].astype(jnp.float32),
            )
            return q1 - td_target

        return fn

    def compute_td_error(self, samples) -> np.ndarray:
        """Per-sample |TD error| for prioritized replay."""
        if not hasattr(self, "_td_error_fn"):
            self._td_error_fn = jax.jit(self._td_error_device_fn())
        batch = self._td_input_tree(samples)
        self._rng, rng = jax.random.split(self._rng)
        td = self._td_error_fn(self.params, self.aux_state, batch, rng)
        return np.abs(np.asarray(td))

    def update_target(self) -> None:
        """No-op: polyak blending happens inside the learn program."""

    def _batch_to_train_tree(self, samples: SampleBatch):
        keys = [
            SampleBatch.OBS,
            SampleBatch.NEXT_OBS,
            SampleBatch.ACTIONS,
            SampleBatch.REWARDS,
            SampleBatch.TERMINATEDS,
            "weights",  # PER importance correction (Ape-X)
            "n_steps",  # per-row n-step fold counts
        ]
        return {
            k: np.asarray(samples[k]) for k in keys if k in samples
        }

    def get_state(self) -> Dict:
        return {
            "weights": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "aux_state": jax.device_get(self.aux_state),
            "global_timestep": self.global_timestep,
            "num_grad_updates": self.num_grad_updates,
            "exploration_state": self.exploration.get_state(),
        }

    def set_state(self, state: Dict) -> None:
        self.set_weights(state["weights"])
        if "opt_state" in state:
            self.opt_state = _tree_to_device(
                state["opt_state"], self._param_sharding
            )
        if "aux_state" in state:
            self.aux_state = _tree_to_device(
                state["aux_state"], self._param_sharding
            )
        self.global_timestep = state.get("global_timestep", 0)
        self.num_grad_updates = state.get("num_grad_updates", 0)
        self.exploration.set_state(state.get("exploration_state", {}))


class DDPG(DQN):
    _default_policy_class = DDPGJaxPolicy

    @classmethod
    def get_default_config(cls) -> DDPGConfig:
        return DDPGConfig(cls)


class TD3(DDPG):
    @classmethod
    def get_default_config(cls) -> TD3Config:
        return TD3Config(cls)
