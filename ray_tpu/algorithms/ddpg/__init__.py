from ray_tpu.algorithms.ddpg.ddpg import (
    DDPG,
    DDPGConfig,
    DDPGJaxPolicy,
    TD3,
    TD3Config,
)

__all__ = ["DDPG", "DDPGConfig", "DDPGJaxPolicy", "TD3", "TD3Config"]
