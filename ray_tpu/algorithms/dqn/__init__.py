from ray_tpu.algorithms.dqn.dqn import (
    DQN,
    DQNConfig,
    DQNJaxPolicy,
    SimpleQ,
    SimpleQConfig,
)

__all__ = ["DQN", "DQNConfig", "DQNJaxPolicy", "SimpleQ", "SimpleQConfig"]
