"""Dueling / distributional / noisy Q-network for the DQN family.

Counterpart of the reference's ``rllib/algorithms/dqn/dqn_torch_model.py``
(DQNTorchModel: advantage/value streams, C51 support heads, NoisyLayer).
One flax module owns the trunk (MLP, or Nature-CNN for image obs) and the
Q heads; ``q_dist`` exposes the per-action support logits the C51 loss
needs, while ``__call__`` returns expected Q values so the generic
epsilon-greedy action path works unchanged (argmax over expected Q is
correct for both dueling and distributional heads).

NoisyNet weight noise (Fortunato et al. 2018) is driven by an explicit
``noise_key`` argument rather than a flax rng collection, so the same
program works deterministically (``noise_key=None`` → mean weights) and
stochastically under jit without rng-collection plumbing.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.base import RTModel, get_activation
from ray_tpu.models.cnn import get_filter_config


class NoisyDense(nn.Module):
    """Factorized-Gaussian noisy linear layer (reference
    ``rllib/models/torch/modules/noisy_layer.py``): w = μ_w + σ_w·(f(ε_in)
    f(ε_out)ᵀ), f(x) = sign(x)·√|x|; σ initialized to sigma0/√fan_in.
    ``noise_key=None`` uses the mean weights (evaluation mode)."""

    features: int
    sigma0: float = 0.5

    @nn.compact
    def __call__(self, x, noise_key=None):
        in_dim = x.shape[-1]
        sigma_init = self.sigma0 / np.sqrt(in_dim)
        w_mu = self.param(
            "w_mu",
            nn.initializers.variance_scaling(
                1.0 / 3.0, "fan_in", "uniform"
            ),
            (in_dim, self.features),
        )
        w_sigma = self.param(
            "w_sigma",
            nn.initializers.constant(sigma_init),
            (in_dim, self.features),
        )
        b_mu = self.param(
            "b_mu", nn.initializers.zeros, (self.features,)
        )
        b_sigma = self.param(
            "b_sigma",
            nn.initializers.constant(sigma_init),
            (self.features,),
        )
        if noise_key is None:
            return x @ w_mu + b_mu
        k_in, k_out = jax.random.split(noise_key)

        def f(eps):
            return jnp.sign(eps) * jnp.sqrt(jnp.abs(eps))

        eps_in = f(jax.random.normal(k_in, (in_dim, 1)))
        eps_out = f(jax.random.normal(k_out, (1, self.features)))
        w = w_mu + w_sigma * (eps_in @ eps_out)
        b = b_mu + b_sigma * eps_out[0]
        return x @ w + b


class DQNModel(RTModel):
    """Trunk + dueling/distributional Q heads. ``num_outputs`` is the
    number of discrete actions (catalog custom-model calling
    convention)."""

    num_outputs: int
    hiddens: Sequence[int] = (256, 256)
    activation: str = "tanh"
    use_conv: bool = False
    conv_filters: Optional[Tuple] = None
    conv_activation: str = "relu"
    # convs run in bf16 like VisionNet (MXU-native); heads stay float32
    conv_dtype: str = "bfloat16"
    num_atoms: int = 1
    v_min: float = -10.0
    v_max: float = 10.0
    dueling: bool = True
    noisy: bool = False
    sigma0: float = 0.5

    def setup(self):
        if self.use_conv:
            filters = self.conv_filters or get_filter_config((84, 84, 4))
            dtype = jnp.dtype(self.conv_dtype)
            self._convs = [
                nn.Conv(
                    out_ch, kernel, stride, padding="VALID", dtype=dtype
                )
                for out_ch, kernel, stride in filters
            ]
        self._fcs = [nn.Dense(h) for h in self.hiddens]
        head = (
            (lambda n: NoisyDense(n, sigma0=self.sigma0))
            if self.noisy
            else nn.Dense
        )
        self._adv_head = head(self.num_outputs * self.num_atoms)
        if self.dueling:
            self._value_head = head(self.num_atoms)

    def _head(self, layer, x, noise_key):
        if self.noisy:
            return layer(x, noise_key=noise_key)
        return layer(x)

    def features(self, obs: jnp.ndarray) -> jnp.ndarray:
        if self.use_conv:
            x = obs.astype(jnp.dtype(self.conv_dtype))
            if obs.dtype == jnp.uint8:  # raw pixels only (VisionNet)
                x = x / 255.0
            act = get_activation(self.conv_activation)
            for conv in self._convs:
                x = act(conv(x))
            x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        else:
            x = obs.astype(jnp.float32).reshape((obs.shape[0], -1))
        act = get_activation(self.activation)
        for fc in self._fcs:
            x = act(fc(x))
        return x

    def q_dist(self, obs, noise_key=None):
        """→ (q_values (B, A), support_logits (B, A, atoms),
        support_probs (B, A, atoms) or None when num_atoms == 1).
        Dueling combine happens per atom: support = V + A - mean_a(A)
        (reference dqn_torch_model.py get_q_value_distributions +
        get_state_value)."""
        k_a = k_v = None
        if noise_key is not None:
            k_a, k_v = jax.random.split(noise_key)
        feat = self.features(obs)
        adv = self._head(self._adv_head, feat, k_a).reshape(
            (-1, self.num_outputs, self.num_atoms)
        )
        if self.dueling:
            value = self._head(self._value_head, feat, k_v).reshape(
                (-1, 1, self.num_atoms)
            )
            support = value + adv - jnp.mean(adv, axis=1, keepdims=True)
        else:
            support = adv
        if self.num_atoms > 1:
            probs = jax.nn.softmax(support, axis=-1)
            z = jnp.linspace(
                self.v_min, self.v_max, self.num_atoms
            )
            q = jnp.sum(probs * z, axis=-1)
            return q, support, probs
        q = support[..., 0]
        return q, support, None

    def __call__(self, obs, state=(), seq_lens=None, noise_key=None):
        q, _, _ = self.q_dist(obs, noise_key=noise_key)
        return q, jnp.max(q, axis=-1), ()


def categorical_projection(
    next_probs: jnp.ndarray,
    rewards: jnp.ndarray,
    bootstrap_discount: jnp.ndarray,
    not_done: jnp.ndarray,
    v_min: float,
    v_max: float,
) -> jnp.ndarray:
    """C51 Bellman projection (Bellemare et al. 2017; reference
    ``dqn_torch_policy.py`` QLoss distributional branch): shift the atom
    support by the n-step Bellman operator and redistribute probability
    mass onto the fixed grid. Fully vectorized — the scatter-add over
    floor/ceil bins is two one-hot contractions, so XLA sees dense
    (B, atoms, atoms) matmuls instead of per-sample scatters.

    next_probs: (B, atoms) target-net probs of the chosen next action.
    Returns the projected target distribution m: (B, atoms).
    """
    num_atoms = next_probs.shape[-1]
    z = jnp.linspace(v_min, v_max, num_atoms)
    dz = (v_max - v_min) / (num_atoms - 1)
    tz = (
        rewards[:, None]
        + (bootstrap_discount * not_done)[:, None] * z[None, :]
    )
    tz = jnp.clip(tz, v_min, v_max)
    b = (tz - v_min) / dz  # (B, atoms), in [0, atoms-1]
    low = jnp.floor(b)
    high = jnp.ceil(b)
    # mass to the lower bin; when b lands exactly on a bin (low == high)
    # all of it goes there
    w_low = (high - b) + (low == high).astype(b.dtype)
    w_high = b - low
    onehot_low = jax.nn.one_hot(low.astype(jnp.int32), num_atoms)
    onehot_high = jax.nn.one_hot(high.astype(jnp.int32), num_atoms)
    m = jnp.einsum("ba,bax->bx", next_probs * w_low, onehot_low)
    m = m + jnp.einsum("ba,bax->bx", next_probs * w_high, onehot_high)
    return m
