"""DQN family: double/dueling DQN with (prioritized) replay.

Counterpart of the reference's ``rllib/algorithms/dqn/dqn.py`` (config,
``training_step :336`` — shared by all off-policy algos) and
``rllib/algorithms/simple_q/simple_q.py:256``. The TD-loss/optimizer runs as
one jitted program; the target network lives in the policy's replicated
``aux_state`` (the reference keeps a second torch module) and is refreshed
by a host-side copy every ``target_network_update_freq`` trained steps.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.execution.replay_buffer import (
    DevicePrioritizedReplayBuffer,
    DeviceReplayBuffer,
    MultiAgentReplayBuffer,
    PrioritizedReplayBuffer,
    resolve_device_resident,
    resolve_device_tree,
)
from ray_tpu.execution.rollout_ops import synchronous_parallel_sample
from ray_tpu.execution.train_ops import (
    NUM_AGENT_STEPS_TRAINED,
    NUM_ENV_STEPS_TRAINED,
)
from ray_tpu.algorithms.dqn.dqn_model import (
    DQNModel,
    categorical_projection,
)
from ray_tpu.policy.jax_policy import JaxPolicy


class DQNConfig(AlgorithmConfig):
    """reference dqn.py DQNConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.lr = 5e-4
        self.train_batch_size = 32
        self.rollout_fragment_length = 4
        self.gamma = 0.99
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500
        self.double_q = True
        self.dueling = True
        self.n_step = 1
        # Rainbow knobs (reference dqn.py: num_atoms/v_min/v_max for
        # C51 distributional Q, noisy/sigma0 for NoisyNet exploration)
        self.num_atoms = 1
        self.v_min = -10.0
        self.v_max = 10.0
        self.noisy = False
        self.sigma0 = 0.5
        self.replay_buffer_config = {
            "capacity": 50000,
            "prioritized_replay": False,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
        }
        self.epsilon_timesteps = 10000
        self.final_epsilon = 0.02
        self.initial_epsilon = 1.0
        self.training_intensity = None
        self.grad_clip = 40.0

    def training(
        self,
        *,
        target_network_update_freq: Optional[int] = None,
        double_q: Optional[bool] = None,
        dueling: Optional[bool] = None,
        n_step: Optional[int] = None,
        num_atoms: Optional[int] = None,
        v_min: Optional[float] = None,
        v_max: Optional[float] = None,
        noisy: Optional[bool] = None,
        sigma0: Optional[float] = None,
        replay_buffer_config: Optional[Dict] = None,
        num_steps_sampled_before_learning_starts: Optional[int] = None,
        epsilon_timesteps: Optional[int] = None,
        final_epsilon: Optional[float] = None,
        **kwargs,
    ) -> "DQNConfig":
        super().training(**kwargs)
        if target_network_update_freq is not None:
            self.target_network_update_freq = target_network_update_freq
        if double_q is not None:
            self.double_q = double_q
        if dueling is not None:
            self.dueling = dueling
        if n_step is not None:
            self.n_step = n_step
        for name, val in (
            ("num_atoms", num_atoms),
            ("v_min", v_min),
            ("v_max", v_max),
            ("noisy", noisy),
            ("sigma0", sigma0),
        ):
            if val is not None:
                setattr(self, name, val)
        if replay_buffer_config is not None:
            self.replay_buffer_config.update(replay_buffer_config)
        if num_steps_sampled_before_learning_starts is not None:
            self.num_steps_sampled_before_learning_starts = (
                num_steps_sampled_before_learning_starts
            )
        if epsilon_timesteps is not None:
            self.epsilon_timesteps = epsilon_timesteps
        if final_epsilon is not None:
            self.final_epsilon = final_epsilon
        return self


def adjust_nstep(n_step: int, gamma: float, batch: SampleBatch) -> None:
    """In-place n-step reward folding (reference
    ``rllib/utils/replay_buffers/utils.py`` / dqn postprocessing):
    rewards[t] ← sum_{k<n} gamma^k r[t+k], new_obs[t] ← obs[t+n] with
    termination-aware truncation.

    Records the actual number of folded steps per row in an ``n_steps``
    column so the TD target can discount the bootstrap by gamma**k rather
    than a uniform gamma**n_step — fragment tails fold fewer than n_step
    rewards (the reference sidesteps this by only applying n-step to
    episode-sliced trajectories)."""
    n = batch.count
    rewards = np.asarray(batch[SampleBatch.REWARDS], np.float32)
    dones = np.asarray(batch[SampleBatch.TERMINATEDS], bool)
    next_obs = np.asarray(batch[SampleBatch.NEXT_OBS])
    new_rewards = rewards.copy()
    new_next = next_obs.copy()
    new_dones = dones.copy()
    n_steps = np.ones(n, np.float32)
    for t in range(n):
        acc = rewards[t]
        last = t
        for k in range(1, n_step):
            if t + k >= n or dones[last]:
                break
            acc += (gamma**k) * rewards[t + k]
            last = t + k
        new_rewards[t] = acc
        new_next[t] = next_obs[last]
        new_dones[t] = dones[last]
        n_steps[t] = last - t + 1
    batch[SampleBatch.REWARDS] = new_rewards
    batch[SampleBatch.NEXT_OBS] = new_next
    batch[SampleBatch.TERMINATEDS] = new_dones
    batch["n_steps"] = n_steps


_EPSILON_KEYS = ("initial_epsilon", "final_epsilon", "epsilon_timesteps")


def _epsilon_exploration_config(config: Dict, force_keys=()) -> Dict:
    """Fold DQN's flat epsilon knobs into exploration_config so the
    pluggable EpsilonGreedy strategy picks them up. A user-supplied
    exploration_config wins over the flat DQNConfig defaults (which
    always exist), EXCEPT for keys in ``force_keys`` — the explicitly
    mutated knobs of an update_config/PBT call, which must override
    stale fold-ins from init time."""
    ec = dict(config.get("exploration_config") or {})
    for key in _EPSILON_KEYS:
        if key in config and (key not in ec or key in force_keys):
            ec[key] = config[key]
    # Ape-X per-worker epsilon ladder (reference apex_dqn.py /
    # rllib per_worker_exploration): worker i (1-based) of n explores
    # with the constant eps_i = 0.4 ** (1 + 7*(i-1)/(n-1)).
    if config.get("per_worker_exploration"):
        i = int(config.get("worker_index", 0))
        n = max(1, int(config.get("num_workers", 1)))
        if i > 0:
            exponent = 1.0 + 7.0 * (i - 1) / max(1, n - 1)
            eps = 0.4**exponent
            ec.update(
                initial_epsilon=eps,
                final_epsilon=eps,
                epsilon_timesteps=1,
            )
    return ec


class DQNJaxPolicy(JaxPolicy):
    """Double/dueling TD loss (reference dqn_torch_policy.py). Action
    selection is epsilon-greedy via the pluggable exploration framework
    (reference rllib/utils/exploration/epsilon_greedy.py)."""

    default_exploration = "EpsilonGreedy"
    # recurrent Q needs sequence replay with burn-in — that's R2D2's
    # machinery (which sets this True); plain DQN's uniform/PER row
    # replay cannot train an LSTM correctly
    _supports_recurrent = False

    def __init__(self, observation_space, action_space, config):
        config = dict(config)
        config["exploration_config"] = _epsilon_exploration_config(config)
        # Non-recurrent configs get the dedicated dueling/C51/noisy
        # Q-model (reference dqn_torch_model.py DQNTorchModel); the
        # recurrent path (R2D2's use_lstm) keeps the catalog LSTM whose
        # logits head IS the Q head.
        model_cfg = dict(config.get("model") or {})
        if (
            model_cfg.get("use_lstm") or model_cfg.get("use_attention")
        ) and not self._supports_recurrent:
            raise ValueError(
                "DQN with a recurrent model (use_lstm/use_attention) "
                "requires sequence replay — use the R2D2 algorithm "
                "(reference r2d2.py) instead"
            )
        self._uses_dqn_model = not any(
            model_cfg.get(k)
            for k in (
                "use_lstm",
                "use_attention",
                "custom_model",
                "use_transformer",
            )
        )
        if not self._uses_dqn_model:
            # the fallback treats the model's logits head as Q values —
            # atom-level outputs and weight noise need the built-in model
            if int(config.get("num_atoms", 1)) > 1:
                raise ValueError(
                    "distributional Q (num_atoms > 1) requires the "
                    "built-in DQNModel; it is unavailable with "
                    "use_lstm/use_attention/use_transformer/"
                    "custom_model"
                )
            if config.get("noisy"):
                raise ValueError(
                    "noisy nets require the built-in DQNModel; "
                    "unavailable with use_lstm/use_attention/"
                    "use_transformer/custom_model"
                )
        if self._uses_dqn_model:
            from ray_tpu.models.catalog import MODEL_DEFAULTS
            from ray_tpu.models.cnn import get_filter_config

            cfg = {**MODEL_DEFAULTS, **model_cfg}
            is_image = len(observation_space.shape) == 3
            if is_image:
                # VisionNet conventions: post-conv widths/activation
                # from post_fcnet_*, empty coerces to [512]
                hiddens = tuple(cfg["post_fcnet_hiddens"] or [512])
                activation = cfg["post_fcnet_activation"]
                filters = cfg["conv_filters"] or get_filter_config(
                    observation_space.shape
                )
                conv_filters = tuple(
                    (
                        int(c),
                        tuple(k) if isinstance(k, (list, tuple)) else (k, k),
                        tuple(s) if isinstance(s, (list, tuple)) else (s, s),
                    )
                    for c, k, s in filters
                )
            else:
                hiddens = tuple(cfg["fcnet_hiddens"])
                activation = cfg["fcnet_activation"]
                conv_filters = None
            config["model"] = {
                **model_cfg,
                "custom_model": DQNModel,
                "custom_model_config": {
                    "hiddens": hiddens,
                    "activation": activation,
                    "use_conv": is_image,
                    "conv_filters": conv_filters,
                    "conv_activation": cfg["conv_activation"],
                    "num_atoms": int(config.get("num_atoms", 1)),
                    "v_min": float(config.get("v_min", -10.0)),
                    "v_max": float(config.get("v_max", 10.0)),
                    "dueling": bool(config.get("dueling", True)),
                    "noisy": bool(config.get("noisy", False)),
                    "sigma0": float(config.get("sigma0", 0.5)),
                },
            }
        super().__init__(observation_space, action_space, config)
        if self.model.is_recurrent and not self._supports_recurrent:
            raise ValueError(
                "DQN cannot train a recurrent custom model with "
                "row replay — use R2D2 (reference r2d2.py)"
            )
        self._steps_since_target_update = 0

    def _init_aux_state(self):
        return {"target_params": self.params}

    def _refold_exploration_config(self, new_config: Dict) -> None:
        self.config["exploration_config"] = _epsilon_exploration_config(
            self.config, force_keys=new_config
        )

    # knobs baked into the built model's architecture/support grid: the
    # loss would retrace but the model cannot change post-init
    _ARCH_KEYS = ("num_atoms", "noisy", "dueling", "v_min", "v_max", "sigma0")

    def update_config(self, new_config: Dict) -> None:
        for key in self._ARCH_KEYS:
            if key in new_config and new_config[key] != self.config.get(
                key
            ):
                raise ValueError(
                    f"DQN architecture knob {key!r} is baked into the "
                    "built Q-model and cannot be mutated via "
                    "update_config; rebuild the policy instead"
                )
        super().update_config(new_config)
        if hasattr(self, "_td_error_fn"):
            del self._td_error_fn

    def update_target(self) -> None:
        """Copy online → target (reference update_target in
        dqn_torch_policy)."""
        self.aux_state = {"target_params": self.params}

    def _apply_model_for_actions(self, params, obs, rng, explore):
        """NoisyNet exploration: resample weight noise per action call
        while exploring (the reference's NoisyLayer resamples every
        training-mode forward); evaluation uses the mean weights."""
        if explore and self._uses_dqn_model and self.config.get("noisy"):
            return self.model.apply(params, obs, noise_key=rng)
        return super()._apply_model_for_actions(params, obs, rng, explore)

    def extra_action_out(self, dist_inputs, value, dist, rng):
        # The per-action Q values already ride ACTION_DIST_INPUTS (the
        # model head IS the Q head); don't duplicate them as a second
        # replay-buffer column.
        return {}

    # -- loss ------------------------------------------------------------

    def _q_dist(self, params, obs, noise_key=None):
        """→ (q_values, support_logits (B, A, atoms), support_probs or
        None). The DQNModel path exposes atom-level outputs; the
        recurrent/custom fallback treats the logits head as Q values."""
        if self._uses_dqn_model:
            return self.model.apply(
                params, obs, noise_key=noise_key,
                method=DQNModel.q_dist,
            )
        q, _, _ = self.model_forward(params, obs)
        return q, q[..., None], None

    def _td_error(self, params, aux, batch, rng=None):
        """Per-sample TD error (shared by the loss and the PER priority
        refresh; reference dqn_torch_policy computes it inside QLoss and
        exposes policy.compute_td_error). For distributional Q
        (num_atoms > 1) the "TD error" is the per-sample softmax
        cross-entropy to the projected target distribution, exactly the
        quantity the reference feeds PER in the C51 case."""
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        n_step = cfg.get("n_step", 1)
        num_atoms = int(cfg.get("num_atoms", 1))
        target_params = aux["target_params"]
        # independent weight noise for online / target / selection nets
        # (NoisyNet training regime); None → mean weights
        k1 = k2 = k3 = None
        if rng is not None and cfg.get("noisy"):
            k1, k2, k3 = jax.random.split(rng, 3)

        q_all, logits_all, _ = self._q_dist(
            params, batch[SampleBatch.OBS], k1
        )
        q_next_target, _, probs_next_target = self._q_dist(
            target_params, batch[SampleBatch.NEXT_OBS], k2
        )
        actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
        q_sel = jnp.take_along_axis(
            q_all, actions[:, None], axis=-1
        ).squeeze(-1)

        if cfg.get("double_q", True):
            q_next_online, _, _ = self._q_dist(
                params, batch[SampleBatch.NEXT_OBS], k3
            )
            next_actions = jnp.argmax(q_next_online, axis=-1)
        else:
            next_actions = jnp.argmax(q_next_target, axis=-1)

        not_done = 1.0 - batch[SampleBatch.TERMINATEDS].astype(
            jnp.float32
        )
        # Per-row bootstrap exponent: fragment tails fold fewer than
        # n_step rewards (recorded by adjust_nstep in "n_steps").
        steps = batch.get("n_steps")
        bootstrap_discount = (
            gamma ** steps if steps is not None else gamma**n_step
        )
        if isinstance(bootstrap_discount, float):
            bootstrap_discount = jnp.full_like(q_sel, bootstrap_discount)

        if num_atoms > 1:
            # C51: cross-entropy to the projected target distribution
            p_next = jnp.take_along_axis(
                probs_next_target,
                next_actions[:, None, None],
                axis=1,
            ).squeeze(1)  # (B, atoms)
            m = categorical_projection(
                p_next,
                batch[SampleBatch.REWARDS],
                bootstrap_discount,
                not_done,
                float(cfg.get("v_min", -10.0)),
                float(cfg.get("v_max", 10.0)),
            )
            m = jax.lax.stop_gradient(m)
            logits_sel = jnp.take_along_axis(
                logits_all, actions[:, None, None], axis=1
            ).squeeze(1)  # (B, atoms)
            td_error = -jnp.sum(
                m * jax.nn.log_softmax(logits_sel, axis=-1), axis=-1
            )
            return td_error, q_sel, q_all

        q_next = jnp.take_along_axis(
            q_next_target, next_actions[:, None], axis=-1
        ).squeeze(-1)
        td_target = (
            batch[SampleBatch.REWARDS]
            + bootstrap_discount
            * not_done
            * jax.lax.stop_gradient(q_next)
        )
        td_error = q_sel - jax.lax.stop_gradient(td_target)
        return td_error, q_sel, q_all

    def loss_with_aux(self, params, aux, batch, rng, coeffs):
        td_error, q_sel, q_all = self._td_error(params, aux, batch, rng)
        if int(self.config.get("num_atoms", 1)) > 1:
            # td_error is already the per-sample cross-entropy loss
            per_sample = td_error
        else:
            # Huber loss (reference huber_loss, delta=1)
            abs_err = jnp.abs(td_error)
            per_sample = jnp.where(
                abs_err < 1.0,
                0.5 * jnp.square(td_error),
                abs_err - 0.5,
            )
        weights = batch.get("weights", jnp.ones_like(per_sample))
        loss = jnp.mean(weights * per_sample)
        stats = {
            "mean_q": jnp.mean(q_sel),
            "mean_td_error": jnp.mean(td_error),
            "max_q": jnp.max(q_all),
        }
        return loss, stats

    @property
    def _td_refresh_uses_rng(self) -> bool:
        # the priority pass consumes a host rng split only under
        # NoisyNet (compute_td_error's split discipline)
        return bool(self.config.get("noisy"))

    def _td_error_device_fn(self):
        """Signed per-sample TD error — shared by ``compute_td_error``
        and the superstep's in-scan prioritized refresh. Non-noisy
        configs ignore the rng argument (the per-update path passes
        None there; the in-scan caller a dummy key)."""
        noisy = bool(self.config.get("noisy"))

        def fn(params, aux, batch, rng):
            td, _, _ = self._td_error(
                params, aux, batch, rng if noisy else None
            )
            return td

        return fn

    def compute_td_error(self, samples) -> np.ndarray:
        """Per-sample |TD error| for prioritized-replay updates, aligned
        with the rows of ``samples`` (pre-tiling/trim: uses a plain jit
        forward, not the sharded nest)."""
        if not hasattr(self, "_td_error_fn"):
            self._td_error_fn = jax.jit(self._td_error_device_fn())
        batch = self._td_input_tree(samples)
        # NoisyNet: sample weight noise for the priority pass too, so
        # priorities are computed under the same training-mode network
        # family the loss minimizes (mean weights would decorrelate PER
        # priorities from the actual training TD errors).
        rng = None
        if self.config.get("noisy"):
            self._rng, rng = jax.random.split(self._rng)
        td = self._td_error_fn(self.params, self.aux_state, batch, rng)
        return np.abs(np.asarray(td))

    def after_learn_on_batch(self, stats):
        self._steps_since_target_update += 1
        return {}


class DQN(Algorithm):
    _default_policy_class = DQNJaxPolicy

    @classmethod
    def get_default_config(cls) -> DQNConfig:
        return DQNConfig(cls)

    def setup(self, config: Dict) -> None:
        super().setup(config)
        rb_cfg = config.get("replay_buffer_config") or {}
        self.local_replay_buffer = MultiAgentReplayBuffer(
            capacity=rb_cfg.get("capacity", 50000),
            prioritized=rb_cfg.get("prioritized_replay", False),
            alpha=rb_cfg.get("prioritized_replay_alpha", 0.6),
            seed=config.get("seed"),
            device_resident=resolve_device_resident(
                config, config.get("_mesh")
            ),
            device_tree=resolve_device_tree(
                config, config.get("_mesh")
            ),
            mesh=config.get("_mesh"),
            memory_cap_bytes=config.get("replay_memory_cap_bytes"),
            # columns convert to the policy's train tree ONCE, at
            # insert — the single H2D crossing of the device plane
            replay_columns_fn=lambda pid, sb: self.get_policy(
                pid
            ).replay_columns(sb),
        )
        self._last_target_update = 0

    def on_fleet_change(self, added, removed) -> None:
        """Elastic fleet: the synchronous sampling path re-reads
        ``workers.remote_workers()`` every round and needs nothing;
        the ``sample_async`` path holds one pending ref per worker of
        LAST round's fleet — drop them so the next round re-issues
        against the current fleet instead of ray.get-ing a drained
        worker's ref."""
        super().on_fleet_change(added, removed)
        if removed and getattr(self, "_pending_sample_refs", None):
            import ray_tpu as _ray

            try:
                _ray.free(self._pending_sample_refs)
            except Exception:
                pass
            self._pending_sample_refs = None

    def _single_update(self, prioritized: bool, kwargs: Dict) -> Dict:
        """One replay sample + learn round (the classic path), with
        per-sample PER priority refresh."""
        config = self.config
        train_info: Dict = {}
        train_batch = self.local_replay_buffer.sample(
            config["train_batch_size"], **kwargs
        )
        for pid, b in train_batch.policy_batches.items():
            policy = self.get_policy(pid)
            if getattr(b, "is_device_resident", False):
                # device plane: rows are already resident on the
                # learner mesh — learn without any H2D transfer
                info = policy.learn_on_device_batch(
                    dict(b.tree), b.count
                )
            else:
                info = policy.learn_on_batch(b)
            train_info[pid] = info
            if prioritized:
                buf = self.local_replay_buffer.buffers[pid]
                if isinstance(
                    buf,
                    (
                        PrioritizedReplayBuffer,
                        DevicePrioritizedReplayBuffer,
                    ),
                ):
                    # Per-sample |TD error| refresh (reference
                    # dqn.py training_step → update_priorities):
                    # a batch-mean scalar would cancel +/- errors
                    # and collapse PER to uniform sampling.
                    # Policies without per-sample errors (e.g.
                    # continuous-action subclasses) fall back to
                    # the batch-mean scalar.
                    idx = (
                        b.indices
                        if getattr(b, "is_device_resident", False)
                        else b["batch_indexes"]
                    )
                    if hasattr(policy, "compute_td_error"):
                        td = policy.compute_td_error(b)
                    else:
                        td = np.full(
                            len(idx),
                            abs(info.get("mean_td_error", 0.0)),
                        )
                    buf.update_priorities(idx, td + 1e-6)
            self._counters[NUM_ENV_STEPS_TRAINED] += b.count
        return train_info

    def _resolve_superstep_k(self) -> int:
        """K of the fused superstep contract for this run
        (sharding.superstep.resolve_superstep, cached)."""
        k = self.__dict__.get("_superstep_k")
        if k is None:
            from ray_tpu.sharding.superstep import resolve_superstep

            k = self._superstep_k = resolve_superstep(
                self.config, self.config.get("_mesh")
            )
        return k

    def _chained_updates(
        self,
        updates: int,
        prioritized: bool = False,
        beta: float = 0.4,
    ) -> Dict:
        """``updates`` replay SGD rounds back to back.

        With the superstep contract resolved on (``config.superstep``,
        docs/data_plane.md), full windows of K updates run as ONE
        compiled program per policy: one dispatch, one stats readback,
        device-replay rows gathered in place by the scan — the uniform
        generalization of what used to be a SAC-only stacked path.
        Prioritized replay chains here too (per-update ``|td|``
        refresh ships back as one stacked D2H, applied in update
        order; draws within a window see priorities as of window
        start — the documented staleness). The remainder (and policies
        whose programs can't ride the scan) falls back to per-update
        dispatch with deferred stats, so the programs still queue
        on-device and the per-dispatch latency amortizes across the
        chain; bounded lag keeps device memory in check. Others loop
        learn_on_batch."""
        import jax

        from ray_tpu import sharding as sharding_lib
        from ray_tpu.policy.jax_policy import JaxPolicy
        from ray_tpu.telemetry import metrics as telemetry_metrics

        config = self.config
        train_info: Dict = {}

        pols = {
            pid: self.get_policy(pid)
            for pid in self.workers.local_worker().policy_map
        }
        bs = int(config["train_batch_size"])
        K = self._resolve_superstep_k()
        left = updates
        if K > 1 and all(
            getattr(p, "supports_superstep", False)
            # the superstep skips prepare_batch's trim/tile, so the
            # per-update batch must already divide the data shards
            and bs % max(1, getattr(p, "n_shards", 1)) == 0
            for p in pols.values()
        ):
            from ray_tpu.execution.train_ops import (
                superstep_train_replay,
            )

            while left >= K:
                fused = False
                for pid, policy in pols.items():
                    buf = self.local_replay_buffer.buffers.get(pid)
                    if buf is None or len(buf) < bs:
                        continue
                    info = superstep_train_replay(
                        self,
                        policy,
                        buf,
                        K,
                        K,
                        bs,
                        prioritized=prioritized,
                        beta=beta,
                    )
                    if info is None:
                        # frame-pool/ragged batches: this run can't
                        # ride the scan — per-update path from here on
                        self._superstep_k = 1
                        break
                    fused = True
                    train_info[pid] = info
                    self._counters[NUM_ENV_STEPS_TRAINED] += K * bs
                if not fused or self._superstep_k == 1:
                    if fused:
                        left -= K
                    break
                left -= K
        if left <= 0:
            return train_info
        if prioritized:
            # leftover prioritized updates keep the classic
            # sample → learn → refresh cadence
            for _ in range(left):
                info = self._single_update(True, {"beta": beta})
                train_info.update(info)
            return train_info

        for _ in range(left):
            train_batch = self.local_replay_buffer.sample(
                config["train_batch_size"]
            )
            for pid, b in train_batch.policy_batches.items():
                policy = self.get_policy(pid)
                device_res = getattr(b, "is_device_resident", False)
                deferable = device_res or (
                    isinstance(policy, JaxPolicy)
                    and (
                        type(policy).learn_on_batch
                        is JaxPolicy.learn_on_batch
                    )
                    and (
                        type(policy).after_learn_on_batch
                        is JaxPolicy.after_learn_on_batch
                    )
                )
                if deferable:
                    if device_res:
                        dev, bsize = dict(b.tree), b.count
                    else:
                        tree, bsize = policy.prepare_batch(b)
                        telemetry_metrics.add_h2d_bytes(
                            "learn", sharding_lib.tree_nbytes(tree)
                        )
                        dev = jax.device_put(
                            tree, policy.batch_shardings(tree)
                        )
                    lazy = policy.learn_on_device_batch(
                        dev, bsize, defer_stats=True
                    )
                    pend = self._pending_stats = getattr(
                        self, "_pending_stats", []
                    )
                    pend.append((pid, lazy))
                    while len(pend) > 3:  # bounded on-device queue
                        old_pid, old = pend.pop(0)
                        stats = jax.device_get(old)
                        train_info[old_pid] = {
                            k: float(v) for k, v in stats.items()
                        }
                else:
                    train_info[pid] = policy.learn_on_batch(b)
                self._counters[NUM_ENV_STEPS_TRAINED] += b.count
        pend = getattr(self, "_pending_stats", None)
        while pend:
            pid, lazy = pend.pop(0)
            stats = jax.device_get(lazy)
            train_info[pid] = {
                k: float(v) for k, v in stats.items()
            }
        return train_info

    def _materialize_compressed(self, batch):
        """Rebuild stacked observation columns from worker-compressed
        frame pools (``ops/framestack.compress_replay_obs`` format:
        the pool covers OBS and NEXT_OBS exactly, terminal stacks
        included, so ``materialize_fragment`` is byte-exact here)."""
        from ray_tpu.data.sample_batch import MultiAgentBatch
        from ray_tpu.ops.framestack import (
            FRAMES as _FRAMES,
            materialize_fragment,
        )

        def mat(pid, sb):
            if _FRAMES not in sb:
                return sb
            k = int(
                self.get_policy(pid).observation_space.shape[-1]
            )
            return SampleBatch(materialize_fragment(dict(sb), k))

        if isinstance(batch, MultiAgentBatch):
            batch.policy_batches = {
                pid: mat(pid, sb)
                for pid, sb in batch.policy_batches.items()
            }
            return batch
        return mat(DEFAULT_POLICY_ID, batch)

    def __getstate__(self) -> Dict:
        """Checkpoint the replay buffer alongside the policy state
        (device rings pull back to host numpy; restore re-uploads) —
        an off-policy restore without its buffer replays the warmup
        from scratch."""
        state = super().__getstate__()
        buf = getattr(self, "local_replay_buffer", None)
        if buf is not None:
            state["replay_buffer"] = buf.get_state()
        return state

    def __setstate__(self, state: Dict) -> None:
        super().__setstate__(state)
        buf = getattr(self, "local_replay_buffer", None)
        if buf is not None and "replay_buffer" in state:
            buf.set_state(state["replay_buffer"])

    def _jax_rollout_engine_get(self):
        """Build (once) and return the fused-rollout engine
        (config.env_backend == "jax", docs/pipeline.md)."""
        eng = self.__dict__.get("_jax_rollout_engine")
        if eng is None:
            from ray_tpu.execution.jax_rollout import (
                JaxRolloutEngine,
                supports_jax_rollout_lane,
            )

            if int(self.config.get("n_step", 1)) > 1:
                raise ValueError(
                    "env_backend='jax' supports n_step=1 only (n-step "
                    "folding is a host-side postprocess)"
                )
            if self.config.get("policies"):
                raise ValueError(
                    "env_backend='jax' is single-policy"
                )
            policy = self.get_policy()
            env = self.workers.local_worker().env
            ok, reason = supports_jax_rollout_lane(policy, env)
            if not ok:
                raise ValueError(
                    "config.env_backend='jax' but the device rollout "
                    f"lane is unavailable: {reason}"
                )
            N = int(self.config.get("num_envs_per_worker", 1)) * max(
                1, int(self.config.get("num_workers", 0))
            )
            T = int(self.config.get("rollout_fragment_length", 4))
            eng = JaxRolloutEngine(
                policy,
                env,
                N,
                T,
                seed=self.config.get("seed"),
                postprocess="none",
            )
            self._jax_rollout_engine = eng
            self._extra_metric_sources = [eng.get_metrics]
        return eng

    def _insert_rollout_tree(self, tree) -> None:
        """Absorb one dispatched rollout's device rows: the
        device-insert path for resident buffers (same donated scatter,
        zero H2D), one pull-back for host rings."""
        buf = self.local_replay_buffer._buffer(DEFAULT_POLICY_ID)
        if isinstance(buf, DeviceReplayBuffer):
            buf.add_device_tree(tree)
        else:
            import jax

            self.local_replay_buffer.add(
                SampleBatch(jax.device_get(tree))
            )

    def _jax_rollout_fill(self) -> int:
        """Device rollout lane for the off-policy family
        (config.env_backend == "jax", docs/pipeline.md): one dispatched
        rollout produces transition rows ON the learner mesh, and a
        device-resident replay buffer absorbs them via
        ``add_device_tree`` — rollout rows never touch the host (a
        host-ring buffer pulls them back once, which still deletes the
        actor lane's sampling cost). Returns env steps taken."""
        tree, count = self._jax_rollout_engine_get().rollout()
        self._insert_rollout_tree(tree)
        return count

    def _interleave_ready(self) -> bool:
        """The learn-while-rollout cadence (``learn_while_rollout``,
        docs/data_plane.md) engages once the lane is warm: engine
        built, learning started, and the buffer already holds a full
        batch of PREVIOUS rounds' rows for the updates to draw from —
        until then the serial fill→learn order runs."""
        config = self.config
        if not config.get("learn_while_rollout"):
            return False
        if self.__dict__.get("_jax_rollout_engine") is None:
            return False
        buf = self.local_replay_buffer.buffers.get(DEFAULT_POLICY_ID)
        if buf is None or len(buf) < int(config["train_batch_size"]):
            return False
        return self._counters[NUM_ENV_STEPS_SAMPLED] >= config.get(
            "num_steps_sampled_before_learning_starts", 0
        )

    def _replay_update_phase(self, sampled_steps: int) -> Dict:
        """The learn half of the shared off-policy training_step:
        training-intensity debt → chained/fused replay updates (or the
        single classic round), then the target-network sync.
        ``sampled_steps`` is this round's env-step count (the debt
        accrual basis)."""
        config = self.config
        train_info: Dict = {}
        if not (
            self._counters[NUM_ENV_STEPS_SAMPLED]
            >= config.get("num_steps_sampled_before_learning_starts", 0)
            and len(self.local_replay_buffer) > 0
        ):
            return train_info
        rb_cfg = config.get("replay_buffer_config") or {}
        prioritized = rb_cfg.get("prioritized_replay", False)
        kwargs = (
            {"beta": rb_cfg.get("prioritized_replay_beta", 0.4)}
            if prioritized
            else {}
        )
        # training_intensity (reference dqn.py calculate_rr_weights
        # role): desired trained-steps : sampled-steps ratio. The
        # natural ratio of one update per round is
        # train_batch/rollout; a higher intensity runs MULTIPLE
        # replay updates per round — fused K-per-dispatch under
        # the superstep contract, per-update with deferred stats
        # otherwise, so either way consecutive SGD programs
        # pipeline on-device and the per-dispatch latency
        # (dominant on a tunneled TPU) amortizes. PER joins the
        # chain only under a superstep (its stacked priority
        # refresh keeps the update-order tree writes); without
        # one, priorities must refresh between samples, so PER
        # keeps the one-update path.
        updates = 1
        ti = config.get("training_intensity")
        if ti and (
            not prioritized or self._resolve_superstep_k() > 1
        ):
            self._training_debt = (
                getattr(self, "_training_debt", 0.0)
                + sampled_steps * float(ti)
            )
            updates = int(
                self._training_debt // config["train_batch_size"]
            )
            self._training_debt -= (
                updates * config["train_batch_size"]
            )
        if updates > 1:
            train_info = self._chained_updates(
                updates,
                prioritized=prioritized,
                beta=kwargs.get("beta", 0.4),
            )
        elif updates == 1:
            train_info = self._single_update(prioritized, kwargs)
        # updates == 0: debt still accruing — sample-only round
        # target network sync
        if (
            self._counters[NUM_ENV_STEPS_TRAINED]
            - self._last_target_update
            >= config.get("target_network_update_freq", 500)
        ):
            for pid in self.workers.local_worker().policy_map:
                self.get_policy(pid).update_target()
            self._last_target_update = self._counters[
                NUM_ENV_STEPS_TRAINED
            ]
            self._counters["num_target_updates"] += 1
        return train_info

    def training_step(self) -> Dict:
        """reference dqn.py:336 (shared off-policy training_step).

        With ``learn_while_rollout`` on the jax lane
        (docs/data_plane.md): the round's rollout-fill program is
        DISPATCHED (async), the replay superstep runs against the
        previous rounds' buffer contents while the fill executes on
        the mesh, and the fill's rows insert afterwards — acting and
        fused updates overlap in one cadence, at a one-round insert
        staleness (the draws simply cannot see rows that are still
        being produced)."""
        config = self.config
        batch = None
        interleaved = False
        jax_sampled = 0
        train_info: Dict = {}
        if config.get("env_backend") == "jax":
            if self._interleave_ready():
                tree, count = self._jax_rollout_engine_get().rollout()
                self._counters[NUM_ENV_STEPS_SAMPLED] += count
                # jax dispatch is asynchronous: the fill program is
                # queued, not finished — the superstep below neither
                # waits on it nor depends on its rows
                train_info = self._replay_update_phase(count)
                self._insert_rollout_tree(tree)
                interleaved = True
            else:
                jax_sampled = self._jax_rollout_fill()
                self._counters[NUM_ENV_STEPS_SAMPLED] += jax_sampled
        elif config.get("sample_async") and self.workers.remote_workers():
            # Overlap rollout with learning (reference's sample_async /
            # Ape-X decoupling): collect the fragment requested LAST
            # round, then immediately kick off the next one so the
            # workers sample while the driver replays + updates below.
            # Behavior weights lag the learner by exactly one round —
            # standard off-policy staleness.
            import ray_tpu as _ray

            refs = getattr(self, "_pending_sample_refs", None)
            if refs is None:
                refs = [
                    w.sample.remote()
                    for w in self.workers.remote_workers()
                ]
            batches = _ray.get(refs)
            self._pending_sample_refs = [
                w.sample.remote()
                for w in self.workers.remote_workers()
            ]
            from ray_tpu.data.sample_batch import concat_samples

            batch = concat_samples(batches)
        else:
            batch = synchronous_parallel_sample(
                worker_set=self.workers,
                max_env_steps=config.get("rollout_fragment_length", 4)
                * max(1, config.get("num_envs_per_worker", 1)),
            )
        if batch is not None:  # actor lane (jax lane inserted above)
            # worker-compressed framestack fragments
            # (compress_replay_obs pools) rebuild OBS/NEXT_OBS
            # byte-identically here, before n-step folding reads
            # NEXT_OBS and rows enter the replay ring
            batch = self._materialize_compressed(batch)
            n_step = config.get("n_step", 1)
            if n_step > 1:
                from ray_tpu.data.sample_batch import MultiAgentBatch

                if isinstance(batch, MultiAgentBatch):
                    for b in batch.policy_batches.values():
                        adjust_nstep(n_step, config["gamma"], b)
                else:
                    adjust_nstep(n_step, config["gamma"], batch)
            self._counters[NUM_ENV_STEPS_SAMPLED] += batch.env_steps()
            self.local_replay_buffer.add(batch)

        if not interleaved:
            sampled = (
                batch.env_steps() if batch is not None else jax_sampled
            )
            train_info = self._replay_update_phase(sampled)

        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            },
            # workers only act: ship the acting subset (SAC: actor
            # net alone — the full tree pull off a tunneled TPU
            # otherwise dominates the round)
            inference_only=True,
        )
        return train_info


class SimpleQConfig(DQNConfig):
    """reference simple_q.py:256 — DQN without double/dueling/n-step."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or SimpleQ)
        self.double_q = False
        self.dueling = False
        self.n_step = 1


class SimpleQ(DQN):
    @classmethod
    def get_default_config(cls) -> SimpleQConfig:
        return SimpleQConfig(cls)
