"""Algorithm registry (reference ``rllib/algorithms/registry.py``)."""

from __future__ import annotations

import importlib
from typing import Callable, Dict, Tuple

_ALGORITHMS: Dict[str, Callable] = {}

# name -> (module, class attr); imports resolve lazily on first lookup.
_BUILTINS: Dict[str, Tuple[str, str]] = {
    "PPO": ("ray_tpu.algorithms.ppo.ppo", "PPO"),
    "APPO": ("ray_tpu.algorithms.appo.appo", "APPO"),
    "DDPPO": ("ray_tpu.algorithms.ddppo.ddppo", "DDPPO"),
    "IMPALA": ("ray_tpu.algorithms.impala.impala", "IMPALA"),
    "SAC": ("ray_tpu.algorithms.sac.sac", "SAC"),
    "RNNSAC": ("ray_tpu.algorithms.sac.rnnsac", "RNNSAC"),
    "DQN": ("ray_tpu.algorithms.dqn.dqn", "DQN"),
    "SimpleQ": ("ray_tpu.algorithms.dqn.dqn", "SimpleQ"),
    "A2C": ("ray_tpu.algorithms.a2c.a2c", "A2C"),
    "A3C": ("ray_tpu.algorithms.a2c.a2c", "A3C"),
    "PG": ("ray_tpu.algorithms.pg.pg", "PG"),
    "DDPG": ("ray_tpu.algorithms.ddpg.ddpg", "DDPG"),
    "TD3": ("ray_tpu.algorithms.ddpg.ddpg", "TD3"),
    "ES": ("ray_tpu.algorithms.es.es", "ES"),
    "ARS": ("ray_tpu.algorithms.es.es", "ARS"),
    "MARWIL": ("ray_tpu.algorithms.marwil.marwil", "MARWIL"),
    "BC": ("ray_tpu.algorithms.marwil.marwil", "BC"),
    "CQL": ("ray_tpu.algorithms.cql.cql", "CQL"),
    "CRR": ("ray_tpu.algorithms.crr.crr", "CRR"),
    "APEX": ("ray_tpu.algorithms.apex_dqn.apex_dqn", "ApexDQN"),
    "ApexDQN": ("ray_tpu.algorithms.apex_dqn.apex_dqn", "ApexDQN"),
    "R2D2": ("ray_tpu.algorithms.r2d2.r2d2", "R2D2"),
    "ApexDDPG": ("ray_tpu.algorithms.apex_dqn.apex_dqn", "ApexDDPG"),
    "APEX_DDPG": ("ray_tpu.algorithms.apex_dqn.apex_dqn", "ApexDDPG"),
    "SlateQ": ("ray_tpu.algorithms.slateq.slateq", "SlateQ"),
    "AlphaStar": ("ray_tpu.algorithms.alpha_star.alpha_star", "AlphaStar"),
    "MAML": ("ray_tpu.algorithms.maml.maml", "MAML"),
    "BanditLinUCB": ("ray_tpu.algorithms.bandit.bandit", "BanditLinUCB"),
    "BanditLinTS": ("ray_tpu.algorithms.bandit.bandit", "BanditLinTS"),
    "QMIX": ("ray_tpu.algorithms.qmix.qmix", "QMIX"),
    "MADDPG": ("ray_tpu.algorithms.maddpg.maddpg", "MADDPG"),
    "AlphaZero": ("ray_tpu.algorithms.alpha_zero.alpha_zero", "AlphaZero"),
    "Dreamer": ("ray_tpu.algorithms.dreamer.dreamer", "Dreamer"),
    "MBMPO": ("ray_tpu.algorithms.mbmpo.mbmpo", "MBMPO"),
}


def register_algorithm(name: str, loader: Callable) -> None:
    _ALGORITHMS[name] = loader


def get_algorithm_class(name: str):
    if name in _ALGORITHMS:
        cls = _ALGORITHMS[name]()
    elif name in _BUILTINS:
        module, attr = _BUILTINS[name]
        cls = getattr(importlib.import_module(module), attr)
    else:
        raise ValueError(
            f"Unknown algorithm {name!r}; known: "
            f"{sorted(set(_ALGORITHMS) | set(_BUILTINS))}"
        )
    # checkpoints record this so Algorithm.from_checkpoint can find
    # the class again without the caller naming it
    cls._registry_name = name
    return cls
