"""Algorithm registry (reference ``rllib/algorithms/registry.py``)."""

from __future__ import annotations

from typing import Callable, Dict

_ALGORITHMS: Dict[str, Callable] = {}


def register_algorithm(name: str, loader: Callable) -> None:
    _ALGORITHMS[name] = loader


def get_algorithm_class(name: str):
    if name not in _ALGORITHMS:
        _register_builtins()
    if name not in _ALGORITHMS:
        raise ValueError(
            f"Unknown algorithm {name!r}; known: {sorted(_ALGORITHMS)}"
        )
    return _ALGORITHMS[name]()


def _register_builtins() -> None:
    def _ppo():
        from ray_tpu.algorithms.ppo.ppo import PPO

        return PPO

    _ALGORITHMS.setdefault("PPO", _ppo)
    try:
        def _impala():
            from ray_tpu.algorithms.impala.impala import IMPALA

            return IMPALA

        _ALGORITHMS.setdefault("IMPALA", _impala)
    except ImportError:
        pass
    try:
        def _sac():
            from ray_tpu.algorithms.sac.sac import SAC

            return SAC

        _ALGORITHMS.setdefault("SAC", _sac)
    except ImportError:
        pass
    try:
        def _dqn():
            from ray_tpu.algorithms.dqn.dqn import DQN

            return DQN

        _ALGORITHMS.setdefault("DQN", _dqn)
    except ImportError:
        pass
    try:
        def _a2c():
            from ray_tpu.algorithms.a2c.a2c import A2C

            return A2C

        _ALGORITHMS.setdefault("A2C", _a2c)
    except ImportError:
        pass
