from ray_tpu.algorithms.ppo.ppo import PPO, PPOConfig, PPOJaxPolicy

__all__ = ["PPO", "PPOConfig", "PPOJaxPolicy"]
