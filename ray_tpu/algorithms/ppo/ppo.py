"""PPO: config, JAX policy (loss), and algorithm.

Counterpart of the reference's ``rllib/algorithms/ppo/ppo.py`` (PPOConfig
``:47``, ``training_step :400``, adaptive-KL update ``:433-447``) and the
torch loss ``rllib/algorithms/ppo/ppo_torch_policy.py:69``. The learner side
— advantage standardization, the clipped surrogate/vf/entropy loss, and the
``num_sgd_iter × minibatches`` SGD nest — runs as one jitted shard_map
program on the TPU mesh (see JaxPolicy).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.evaluation.postprocessing import compute_gae_for_sample_batch
from ray_tpu.execution.rollout_ops import synchronous_parallel_sample
from ray_tpu.execution.train_ops import train_one_step
from ray_tpu.policy.jax_policy import JaxPolicy


class PPOConfig(AlgorithmConfig):
    """reference ppo.py:47."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lr = 5e-5
        self.train_batch_size = 4000
        self.sgd_minibatch_size = 128
        self.num_sgd_iter = 30
        self.lambda_ = 1.0
        self.use_gae = True
        self.use_critic = True
        self.kl_coeff = 0.2
        self.kl_target = 0.01
        self.vf_loss_coeff = 1.0
        self.entropy_coeff = 0.0
        self.entropy_coeff_schedule = None
        self.clip_param = 0.3
        self.vf_clip_param = 10.0
        self.shuffle_sequences = True

    def training(
        self,
        *,
        lambda_: Optional[float] = None,
        use_gae: Optional[bool] = None,
        use_critic: Optional[bool] = None,
        kl_coeff: Optional[float] = None,
        kl_target: Optional[float] = None,
        sgd_minibatch_size: Optional[int] = None,
        num_sgd_iter: Optional[int] = None,
        vf_loss_coeff: Optional[float] = None,
        entropy_coeff: Optional[float] = None,
        entropy_coeff_schedule=None,
        clip_param: Optional[float] = None,
        vf_clip_param: Optional[float] = None,
        **kwargs,
    ) -> "PPOConfig":
        super().training(**kwargs)
        if lambda_ is not None:
            self.lambda_ = lambda_
        if use_gae is not None:
            self.use_gae = use_gae
        if use_critic is not None:
            self.use_critic = use_critic
        if kl_coeff is not None:
            self.kl_coeff = kl_coeff
        if kl_target is not None:
            self.kl_target = kl_target
        if sgd_minibatch_size is not None:
            self.sgd_minibatch_size = sgd_minibatch_size
        if num_sgd_iter is not None:
            self.num_sgd_iter = num_sgd_iter
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        if entropy_coeff_schedule is not None:
            self.entropy_coeff_schedule = entropy_coeff_schedule
        if clip_param is not None:
            self.clip_param = clip_param
        if vf_clip_param is not None:
            self.vf_clip_param = vf_clip_param
        return self

    def to_dict(self) -> Dict:
        d = super().to_dict()
        d["lambda"] = d.pop("lambda_", 1.0)
        return d


class PPOJaxPolicy(JaxPolicy):
    """Clipped-surrogate PPO loss (reference ppo_torch_policy.py:69),
    with KL penalty adapted on host between train calls."""

    # loss never reads NEXT_OBS; don't ship a second obs column
    _ship_next_obs = False

    def _init_coeffs(self):
        self.coeff_values["kl_coeff"] = float(
            self.config.get("kl_coeff", 0.2)
        )

    def loss(self, params, batch, rng, coeffs):
        cfg = self.config
        clip_param = cfg.get("clip_param", 0.3)
        vf_clip = cfg.get("vf_clip_param", 10.0)
        vf_coeff = cfg.get("vf_loss_coeff", 1.0)

        dist_inputs, value, _ = self.model_forward_train(params, batch)
        dist = self.dist_class(dist_inputs)
        prev_dist = self.dist_class(
            batch[SampleBatch.ACTION_DIST_INPUTS]
        )

        logp = dist.logp(batch[SampleBatch.ACTIONS])
        logp_ratio = jnp.exp(logp - batch[SampleBatch.ACTION_LOGP])
        advantages = batch[SampleBatch.ADVANTAGES]

        surrogate = jnp.minimum(
            advantages * logp_ratio,
            advantages
            * jnp.clip(logp_ratio, 1.0 - clip_param, 1.0 + clip_param),
        )
        action_kl = prev_dist.kl(dist)
        entropy = dist.entropy()

        value_targets = batch[SampleBatch.VALUE_TARGETS]
        vf_loss = jnp.square(value - value_targets)
        vf_loss_clipped = jnp.clip(vf_loss, 0.0, vf_clip)

        total = jnp.mean(
            -surrogate
            + coeffs["kl_coeff"] * action_kl
            + vf_coeff * vf_loss_clipped
            - coeffs["entropy_coeff"] * entropy
        )
        stats = {
            "policy_loss": jnp.mean(-surrogate),
            "vf_loss": jnp.mean(vf_loss_clipped),
            "kl": jnp.mean(action_kl),
            "entropy": jnp.mean(entropy),
            "vf_explained_var": _explained_variance(
                value_targets, value
            ),
        }
        return total, stats

    def after_learn_on_batch(self, stats: Dict[str, float]) -> Dict:
        """Adaptive KL coefficient (reference ppo.py:433-447 /
        ppo_torch_policy KLCoeffMixin.update_kl)."""
        kl = stats.get("kl", 0.0)
        target = self.config.get("kl_target", 0.01)
        if self.coeff_values["kl_coeff"] > 0.0:
            if kl > 2.0 * target:
                self.coeff_values["kl_coeff"] *= 1.5
            elif kl < 0.5 * target:
                self.coeff_values["kl_coeff"] *= 0.5
        return {"cur_kl_coeff": self.coeff_values["kl_coeff"]}

    def postprocess_trajectory(
        self, sample_batch, other_agent_batches=None, episode=None
    ):
        return compute_gae_for_sample_batch(
            self, sample_batch, other_agent_batches, episode
        )


def _explained_variance(y, pred):
    y_var = jnp.var(y)
    diff_var = jnp.var(y - pred)
    return jnp.maximum(-1.0, 1.0 - diff_var / (y_var + 1e-8))


class PPO(Algorithm):
    _default_policy_class = PPOJaxPolicy

    @classmethod
    def get_default_config(cls) -> PPOConfig:
        return PPOConfig(cls)

    def training_step(self) -> Dict:
        """reference ppo.py:400."""
        train_batch = synchronous_parallel_sample(
            worker_set=self.workers,
            max_env_steps=self.config["train_batch_size"],
        )
        self._counters[NUM_ENV_STEPS_SAMPLED] += train_batch.env_steps()
        self._counters[NUM_AGENT_STEPS_SAMPLED] += (
            train_batch.env_steps()
        )

        # standardize advantages across the full train batch
        # (reference ppo.py:415 standardize_fields)
        from ray_tpu.data.sample_batch import MultiAgentBatch

        def _standardize(b):
            adv = np.asarray(b[SampleBatch.ADVANTAGES], np.float32)
            b[SampleBatch.ADVANTAGES] = (
                (adv - adv.mean()) / max(1e-4, adv.std())
            ).astype(np.float32)

        if isinstance(train_batch, MultiAgentBatch):
            for b in train_batch.policy_batches.values():
                _standardize(b)
        else:
            _standardize(train_batch)

        train_info = train_one_step(self, train_batch)

        # broadcast new weights + timestep to rollout workers
        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            }
        )
        if self.config.get("observation_filter") not in (
            None,
            "NoFilter",
        ):
            self.workers.sync_filters()
        return train_info
