"""PPO: config, JAX policy (loss), and algorithm.

Counterpart of the reference's ``rllib/algorithms/ppo/ppo.py`` (PPOConfig
``:47``, ``training_step :400``, adaptive-KL update ``:433-447``) and the
torch loss ``rllib/algorithms/ppo/ppo_torch_policy.py:69``. The learner side
— advantage standardization, the clipped surrogate/vf/entropy loss, and the
``num_sgd_iter × minibatches`` SGD nest — runs as one jitted shard_map
program on the TPU mesh (see JaxPolicy).

``config.sample_prefetch > 0`` switches ``training_step`` to the
pipelined loop (docs/pipeline.md): a SamplePrefetcher thread collects,
concatenates and ``prepare_batch``-es batch k+1 and a DeviceFeeder
transfers it while the TPU runs the SGD nest for batch k. Off by
default: the synchronous path below stays bit-identical to the classic
loop on a fixed seed.
"""

from __future__ import annotations

import queue
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

import ray_tpu as ray
from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.evaluation.postprocessing import compute_gae_for_sample_batch
from ray_tpu.execution.rollout_ops import synchronous_parallel_sample
from ray_tpu.execution.train_ops import train_one_step
from ray_tpu.policy.jax_policy import JaxPolicy


class PPOConfig(AlgorithmConfig):
    """reference ppo.py:47."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lr = 5e-5
        self.train_batch_size = 4000
        self.sgd_minibatch_size = 128
        self.num_sgd_iter = 30
        self.lambda_ = 1.0
        self.use_gae = True
        self.use_critic = True
        self.kl_coeff = 0.2
        self.kl_target = 0.01
        self.vf_loss_coeff = 1.0
        self.entropy_coeff = 0.0
        self.entropy_coeff_schedule = None
        self.clip_param = 0.3
        self.vf_clip_param = 10.0
        self.shuffle_sequences = True

    def training(
        self,
        *,
        lambda_: Optional[float] = None,
        use_gae: Optional[bool] = None,
        use_critic: Optional[bool] = None,
        kl_coeff: Optional[float] = None,
        kl_target: Optional[float] = None,
        sgd_minibatch_size: Optional[int] = None,
        num_sgd_iter: Optional[int] = None,
        vf_loss_coeff: Optional[float] = None,
        entropy_coeff: Optional[float] = None,
        entropy_coeff_schedule=None,
        clip_param: Optional[float] = None,
        vf_clip_param: Optional[float] = None,
        **kwargs,
    ) -> "PPOConfig":
        super().training(**kwargs)
        if lambda_ is not None:
            self.lambda_ = lambda_
        if use_gae is not None:
            self.use_gae = use_gae
        if use_critic is not None:
            self.use_critic = use_critic
        if kl_coeff is not None:
            self.kl_coeff = kl_coeff
        if kl_target is not None:
            self.kl_target = kl_target
        if sgd_minibatch_size is not None:
            self.sgd_minibatch_size = sgd_minibatch_size
        if num_sgd_iter is not None:
            self.num_sgd_iter = num_sgd_iter
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        if entropy_coeff_schedule is not None:
            self.entropy_coeff_schedule = entropy_coeff_schedule
        if clip_param is not None:
            self.clip_param = clip_param
        if vf_clip_param is not None:
            self.vf_clip_param = vf_clip_param
        return self

    def to_dict(self) -> Dict:
        d = super().to_dict()
        d["lambda"] = d.pop("lambda_", 1.0)
        return d


class PPOJaxPolicy(JaxPolicy):
    """Clipped-surrogate PPO loss (reference ppo_torch_policy.py:69),
    with KL penalty adapted on host between train calls."""

    # loss never reads NEXT_OBS; don't ship a second obs column
    _ship_next_obs = False

    def _init_coeffs(self):
        self.coeff_values["kl_coeff"] = float(
            self.config.get("kl_coeff", 0.2)
        )

    def loss(self, params, batch, rng, coeffs):
        cfg = self.config
        clip_param = cfg.get("clip_param", 0.3)
        vf_clip = cfg.get("vf_clip_param", 10.0)
        vf_coeff = cfg.get("vf_loss_coeff", 1.0)

        dist_inputs, value, _ = self.model_forward_train(params, batch)
        dist = self.dist_class(dist_inputs)
        prev_dist = self.dist_class(
            batch[SampleBatch.ACTION_DIST_INPUTS]
        )

        logp = dist.logp(batch[SampleBatch.ACTIONS])
        logp_ratio = jnp.exp(logp - batch[SampleBatch.ACTION_LOGP])
        advantages = batch[SampleBatch.ADVANTAGES]

        surrogate = jnp.minimum(
            advantages * logp_ratio,
            advantages
            * jnp.clip(logp_ratio, 1.0 - clip_param, 1.0 + clip_param),
        )
        action_kl = prev_dist.kl(dist)
        entropy = dist.entropy()

        value_targets = batch[SampleBatch.VALUE_TARGETS]
        vf_loss = jnp.square(value - value_targets)
        vf_loss_clipped = jnp.clip(vf_loss, 0.0, vf_clip)

        total = jnp.mean(
            -surrogate
            + coeffs["kl_coeff"] * action_kl
            + vf_coeff * vf_loss_clipped
            - coeffs["entropy_coeff"] * entropy
        )
        stats = {
            "policy_loss": jnp.mean(-surrogate),
            "vf_loss": jnp.mean(vf_loss_clipped),
            "kl": jnp.mean(action_kl),
            "entropy": jnp.mean(entropy),
            "vf_explained_var": _explained_variance(
                value_targets, value
            ),
        }
        return total, stats

    def after_learn_on_batch(self, stats: Dict[str, float]) -> Dict:
        """Adaptive KL coefficient (reference ppo.py:433-447 /
        ppo_torch_policy KLCoeffMixin.update_kl)."""
        kl = stats.get("kl", 0.0)
        target = self.config.get("kl_target", 0.01)
        if self.coeff_values["kl_coeff"] > 0.0:
            if kl > 2.0 * target:
                self.coeff_values["kl_coeff"] *= 1.5
            elif kl < 0.5 * target:
                self.coeff_values["kl_coeff"] *= 0.5
        return {"cur_kl_coeff": self.coeff_values["kl_coeff"]}

    def postprocess_trajectory(
        self, sample_batch, other_agent_batches=None, episode=None
    ):
        return compute_gae_for_sample_batch(
            self, sample_batch, other_agent_batches, episode
        )


def _explained_variance(y, pred):
    y_var = jnp.var(y)
    diff_var = jnp.var(y - pred)
    return jnp.maximum(-1.0, 1.0 - diff_var / (y_var + 1e-8))


def _standardize_advantages(b) -> None:
    """reference ppo.py:415 standardize_fields."""
    adv = np.asarray(b[SampleBatch.ADVANTAGES], np.float32)
    b[SampleBatch.ADVANTAGES] = (
        (adv - adv.mean()) / max(1e-4, adv.std())
    ).astype(np.float32)


class PPO(Algorithm):
    _default_policy_class = PPOJaxPolicy

    @classmethod
    def get_default_config(cls) -> PPOConfig:
        return PPOConfig(cls)

    def setup(self, config: Dict) -> None:
        super().setup(config)
        self._sample_pipeline = None
        self._prefetch_feeder = None

    def training_step(self) -> Dict:
        """reference ppo.py:400."""
        if self.config.get("env_backend") == "jax":
            return self._training_step_jax_rollout()
        if self._use_sample_prefetch():
            return self._training_step_prefetch()
        train_batch = synchronous_parallel_sample(
            worker_set=self.workers,
            max_env_steps=self.config["train_batch_size"],
        )
        self._counters[NUM_ENV_STEPS_SAMPLED] += train_batch.env_steps()
        self._counters[NUM_AGENT_STEPS_SAMPLED] += (
            train_batch.env_steps()
        )

        # standardize advantages across the full train batch
        # (reference ppo.py:415 standardize_fields)
        from ray_tpu.data.sample_batch import MultiAgentBatch

        if isinstance(train_batch, MultiAgentBatch):
            for b in train_batch.policy_batches.values():
                _standardize_advantages(b)
        else:
            _standardize_advantages(train_batch)

        train_info = train_one_step(self, train_batch)

        # broadcast new weights + timestep to rollout workers
        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            }
        )
        if self.config.get("observation_filter") not in (
            None,
            "NoFilter",
        ):
            self.workers.sync_filters()
        return train_info

    # -- device rollout lane (config.env_backend == "jax") ---------------

    def _jax_engine(self):
        """Lazily build the device rollout engine (docs/pipeline.md):
        N = num_envs_per_worker × max(1, num_workers) env slots on the
        learner mesh, T = rollout_fragment_length — one rollout is
        exactly one train batch, so the lane's geometry contract is
        ``train_batch_size == N·T`` (fail fast otherwise)."""
        eng = self.__dict__.get("_jax_rollout_engine")
        if eng is None:
            from ray_tpu.execution.jax_rollout import (
                JaxRolloutEngine,
                supports_jax_rollout_lane,
            )

            policy = self.get_policy()
            env = self.workers.local_worker().env
            ok, reason = supports_jax_rollout_lane(policy, env)
            if not ok:
                raise ValueError(
                    "config.env_backend='jax' but the device rollout "
                    f"lane is unavailable: {reason}"
                )
            N = int(self.config.get("num_envs_per_worker", 1)) * max(
                1, int(self.config.get("num_workers", 0))
            )
            T = int(self.config.get("rollout_fragment_length", 200))
            if N * T != int(self.config["train_batch_size"]):
                raise ValueError(
                    "jax rollout lane needs train_batch_size == "
                    "num_envs_per_worker * max(1, num_workers) * "
                    f"rollout_fragment_length, got {N * T} != "
                    f"{self.config['train_batch_size']}"
                )
            eng = JaxRolloutEngine(
                policy,
                env,
                N,
                T,
                seed=self.config.get("seed"),
                postprocess="gae",
                standardize_advantages=True,
            )
            self._jax_rollout_engine = eng
            # Algorithm._collect_rollout_metrics drains these — the
            # lane's episode returns come back with the stats readback
            self._extra_metric_sources.append(eng.get_metrics)
        return eng

    def _training_step_jax_rollout(self) -> Dict:
        """One training_step on the device rollout lane: K ×
        [rollout(T) + GAE + the num_sgd_iter-epoch nest] with zero
        rollout H2D — fused into ONE dispatch when
        ``jax_fused_rollout`` (default), or rollout / learn as two
        dispatches otherwise (the benchmark's middle lane)."""
        from ray_tpu.execution.train_ops import (
            NUM_AGENT_STEPS_TRAINED,
            NUM_ENV_STEPS_TRAINED,
        )

        eng = self._jax_engine()
        policy = self.get_policy()
        bsize = eng.batch_size
        K = self._resolve_superstep_k()
        fused = bool(
            self.config.get("jax_fused_rollout", True)
        ) and getattr(policy, "supports_superstep", False)

        if fused:
            feed = eng.superstep_feed()
            infos, carry, metrics, skipped = (
                policy.learn_rollout_superstep(K, bsize, feed, k_max=K)
            )
            eng.advance(carry, metrics)
            # host-side KL adaptation applies to the drained
            # per-update stats in order (the one chain of staleness —
            # docs/data_plane.md)
            for info_i in infos:
                info_i.update(policy.after_learn_on_batch(info_i))
            info = infos[-1]
            for s in skipped:
                if s:
                    self._counters["num_nan_batches_skipped"] += 1
                    self._recovery.note_skipped_batch()
            n_updates = K
        else:
            info = {}
            for _ in range(K):
                batch, bsize = eng.rollout()
                info = policy.learn_on_device_batch(
                    eng.learn_batch(batch), bsize
                )
            n_updates = K

        info["cur_lr"] = policy.coeff_values.get("lr")
        steps = n_updates * bsize
        self._counters[NUM_ENV_STEPS_SAMPLED] += steps
        self._counters[NUM_AGENT_STEPS_SAMPLED] += steps
        self._counters[NUM_ENV_STEPS_TRAINED] += steps
        self._counters[NUM_AGENT_STEPS_TRAINED] += steps
        timestep = self._counters[NUM_ENV_STEPS_SAMPLED]
        if self.workers.num_remote_workers() > 0:
            self.workers.sync_weights(
                global_vars={"timestep": timestep}
            )
        else:
            self.workers.local_worker().set_global_vars(
                {"timestep": timestep}
            )
        return {DEFAULT_POLICY_ID: info}

    # -- pipelined sampling (config.sample_prefetch) ---------------------

    def _use_sample_prefetch(self) -> bool:
        return (
            int(self.config.get("sample_prefetch") or 0) > 0
            and self.workers.num_remote_workers() > 0
            # multi-policy batches need per-policy prepare/learn
            # plumbing; they stay on the synchronous path
            and not self.config.get("policies")
        )

    def _resolve_superstep_k(self) -> int:
        """K of the fused superstep contract for the prefetch loop
        (docs/data_plane.md): one training_step consumes K prefetched
        device batches as ONE compiled K-update program. Resolved once
        (sharding.superstep.resolve_superstep) and demoted to 1 when
        the policy can't ride the scan."""
        k = self.__dict__.get("_superstep_k")
        if k is None:
            from ray_tpu.sharding.superstep import resolve_superstep

            k = resolve_superstep(
                self.config, self.config.get("_mesh")
            )
            if k > 1 and not getattr(
                self.get_policy(), "supports_superstep", False
            ):
                k = 1
            self._superstep_k = k
        return k

    def _build_sample_pipeline(self) -> None:
        from ray_tpu.execution.device_feed import DeviceFeeder
        from ray_tpu.execution.rollout_ops import SamplePrefetcher

        policy = self.get_policy()
        depth = max(
            1,
            int(self.config.get("sample_prefetch") or 1),
            self._resolve_superstep_k(),
        )
        feeder = DeviceFeeder(policy.batch_shardings, capacity=depth)
        # fixed-row contract for stacking: a superstep scans K batches
        # of identical shape, so prefetched trees trim to the largest
        # div-multiple at or under train_batch_size (prepare_batch
        # already guarantees ≥ that many rows — the prefetcher
        # collects at least train_batch_size steps)
        div = max(1, policy.n_shards) * max(
            1, getattr(policy, "_unroll_T", 1)
        )
        fixed_rows = (
            int(self.config["train_batch_size"]) // div
        ) * div

        def deliver(batch):
            # runs on the prefetch thread, overlapping the SGD nest:
            # standardize + host-tree assembly here, device transfer on
            # the feeder thread, learn on the driver thread
            _standardize_advantages(batch)
            # resilience choke point for the pipelined path, mirroring
            # train_one_step's: chaos injection counts learn batches
            # here, and the nan guard skips a poisoned batch BEFORE it
            # crosses to the device (docs/resilience.md)
            if self._fault_injector is not None:
                self._fault_injector.on_learn(batch)
            if self.config.get("nan_guard"):
                from ray_tpu.resilience.recovery import batch_is_finite

                if not batch_is_finite(batch):
                    self._counters["num_nan_batches_skipped"] += 1
                    self._recovery.note_skipped_batch()
                    return
            tree, bsize = policy.prepare_batch(batch)
            if self._superstep_k > 1 and fixed_rows > 0:
                from ray_tpu.ops.framestack import FRAMES as _FRAMES

                if _FRAMES in tree:
                    # frame-pool batches have per-batch pool sizes and
                    # can't stack — this run falls back to per-update
                    self._superstep_k = 1
                elif bsize > fixed_rows:
                    T = max(1, getattr(policy, "_unroll_T", 1))
                    tree = {
                        c: (
                            v[: fixed_rows // T]
                            if c.startswith("__chunk__")
                            else v[:fixed_rows]
                        )
                        for c, v in tree.items()
                    }
                    bsize = fixed_rows
            feeder.put(tree, (bsize, batch.env_steps(), batch.count))

        self._prefetch_feeder = feeder
        self._sample_pipeline = SamplePrefetcher(
            self.workers,
            target_steps=int(self.config["train_batch_size"]),
            deliver=deliver,
            max_in_flight=int(
                self.config.get(
                    "max_requests_in_flight_per_rollout_worker", 2
                )
            ),
        )
        # elastic fleet: the pipeline's request manager is the
        # rotation drains remove workers from, and its in-flight
        # counts are the controller's idleness signal
        if self._fleet is not None:
            self._fleet.register_manager(self._sample_pipeline.manager)

    def _next_prefetched(self):
        """Block for the next prefetched device batch, keeping the
        pipeline healthy (dead-worker recovery) while waiting."""
        import time as _time

        from ray_tpu.util import tracing

        pipe = self._sample_pipeline
        t_wait0 = _time.time()
        while True:
            if not pipe.healthy():
                raise pipe.error or RuntimeError(
                    "sample pipeline thread died"
                )
            self._recover_pipeline_workers(pipe)
            try:
                item = self._prefetch_feeder.get(timeout=1.0)
                break
            except queue.Empty:
                continue
        # how long the learner sat starved waiting on the pipeline —
        # ~0 when the prefetch overlap is doing its job
        tracing.record_span(
            "learner:queue_wait", t_wait0, _time.time()
        )
        return item

    def _training_step_prefetch(self) -> Dict:
        from ray_tpu.execution.train_ops import (
            NUM_AGENT_STEPS_TRAINED,
            NUM_ENV_STEPS_TRAINED,
        )

        if self._sample_pipeline is None:
            self._build_sample_pipeline()
        pipe = self._sample_pipeline

        dev, (bsize, env_steps, rows) = self._next_prefetched()
        policy = self.get_policy()

        K = self._resolve_superstep_k()
        if K > 1:
            # superstep over prefetched device batches: one
            # training_step = one dispatch = K updates, zero H2D here
            # (the feeder already moved each batch; the stacker is a
            # device-side reshuffle). Host-side KL adaptation applies
            # to the drained per-update stats in order — one chain of
            # staleness, documented in docs/data_plane.md.
            batches = [(dev, bsize, env_steps, rows)]
            while len(batches) < K:
                d2, (b2, e2, r2) = self._next_prefetched()
                batches.append((d2, b2, e2, r2))
            sizes = {b[1] for b in batches}
            if len(sizes) == 1:
                from ray_tpu import sharding as sharding_lib

                stack_fn = self.__dict__.get("_superstep_stack_fn")
                if stack_fn is None:
                    stack_fn = self._superstep_stack_fn = (
                        sharding_lib.build_stack_fn(
                            policy.mesh,
                            K,
                            label=f"superstep_stack[{K}]",
                        )
                    )
                stacked = stack_fn(*[b[0] for b in batches])
                infos, _, skipped = policy.learn_superstep(
                    K, bsize, stacked=dict(stacked), k_max=K
                )
                for i, info_i in enumerate(infos):
                    info_i.update(
                        policy.after_learn_on_batch(info_i)
                    )
                info = infos[-1]
                info["cur_lr"] = policy.coeff_values.get("lr")
                for s in skipped:
                    if s:
                        self._counters[
                            "num_nan_batches_skipped"
                        ] += 1
                        self._recovery.note_skipped_batch()
                for _, b2, e2, r2 in batches:
                    self._counters[NUM_ENV_STEPS_SAMPLED] += e2
                    self._counters[NUM_AGENT_STEPS_SAMPLED] += e2
                    self._counters[NUM_ENV_STEPS_TRAINED] += e2
                    self._counters[NUM_AGENT_STEPS_TRAINED] += r2
                self.workers.sync_weights(
                    global_vars={
                        "timestep": self._counters[
                            NUM_ENV_STEPS_SAMPLED
                        ]
                    }
                )
                if self.config.get("observation_filter") not in (
                    None,
                    "NoFilter",
                ):
                    self.workers.sync_filters()
                self._recover_pipeline_workers(pipe)
                return {
                    DEFAULT_POLICY_ID: info,
                    "sample_pipeline": pipe.stats(),
                }
            # ragged sizes (shouldn't happen under the fixed-row
            # contract): learn the collected batches per-update, in
            # arrival order; the last falls through to the common path
            for d2, b2, e2, r2 in batches[:-1]:
                self._counters[NUM_ENV_STEPS_SAMPLED] += e2
                self._counters[NUM_AGENT_STEPS_SAMPLED] += e2
                policy.learn_on_device_batch(d2, b2)
                self._counters[NUM_ENV_STEPS_TRAINED] += e2
                self._counters[NUM_AGENT_STEPS_TRAINED] += r2
            dev, bsize, env_steps, rows = batches[-1]

        self._counters[NUM_ENV_STEPS_SAMPLED] += env_steps
        self._counters[NUM_AGENT_STEPS_SAMPLED] += env_steps

        info = policy.learn_on_device_batch(dev, bsize)
        self._counters[NUM_ENV_STEPS_TRAINED] += env_steps
        self._counters[NUM_AGENT_STEPS_TRAINED] += rows

        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            }
        )
        if self.config.get("observation_filter") not in (
            None,
            "NoFilter",
        ):
            self.workers.sync_filters()
        self._recover_pipeline_workers(pipe)
        return {
            DEFAULT_POLICY_ID: info,
            "sample_pipeline": pipe.stats(),
        }

    def _recover_pipeline_workers(self, pipe) -> None:
        """Dead workers reported by the prefetcher's request manager:
        recreate (no 30 s ping probe — the manager already observed the
        death), ignore, or surface per the failure config."""
        dead = pipe.take_dead_workers()
        if not dead:
            return
        self._counters["num_dead_rollout_workers"] += len(dead)
        if self.config.get("recreate_failed_workers"):
            new = self.workers.replace_failed_workers(dead)
            pipe.add_workers(new)
        elif not self.config.get("ignore_worker_failures"):
            raise ray.core.object_store.RayActorError(
                f"{len(dead)} rollout worker(s) died in the sample "
                "pipeline"
            )

    def on_fleet_change(self, added, removed) -> None:
        """Elastic fleet: joiners enter the prefetch pipeline's
        rotation (they arrive weight+filter-synced from
        ``WorkerSet.add_workers``); drained workers were already
        retired from the registered manager by the FleetController."""
        super().on_fleet_change(added, removed)
        pipe = getattr(self, "_sample_pipeline", None)
        if pipe is not None and added:
            pipe.add_workers(added)

    def on_recovery(self, kind: str) -> None:
        """A checkpoint restore invalidates the prefetch pipeline (its
        thread may be dead — an injected crash in ``deliver`` is how
        the restore got triggered — and its queued batches belong to
        the pre-restore policy): tear it down; the next
        ``training_step`` rebuilds it lazily."""
        super().on_recovery(kind)
        if kind != "restore":
            return
        self._teardown_pipeline()

    def _teardown_pipeline(self) -> None:
        pipe = getattr(self, "_sample_pipeline", None)
        feeder = getattr(self, "_prefetch_feeder", None)
        if pipe is not None:
            pipe.request_stop()
        if feeder is not None:
            feeder.stop()
            self._prefetch_feeder = None
        if pipe is not None:
            pipe.stop()
            self._sample_pipeline = None

    def cleanup(self) -> None:
        # flag-first ordering lives in _teardown_pipeline: a deliver
        # blocked on feeder backpressure only wakes when the feeder
        # stops (its put raises), and the raise must find the stop
        # flag set
        self._teardown_pipeline()
        super().cleanup()
