"""Vanilla Policy Gradient (REINFORCE).

Counterpart of the reference's ``rllib/algorithms/pg/pg.py`` (PGConfig)
and ``pg_torch_policy.py`` (loss = -mean(logp * discounted returns),
advantages from ``post_process_advantages`` with use_gae=use_critic=
False). The whole update is the base JaxPolicy jitted SGD nest with a
one-line loss."""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.evaluation.postprocessing import compute_gae_for_sample_batch
from ray_tpu.execution.rollout_ops import synchronous_parallel_sample
from ray_tpu.execution.train_ops import train_one_step
from ray_tpu.policy.jax_policy import JaxPolicy


class PGConfig(AlgorithmConfig):
    """reference pg.py PGConfig (lr=4e-4, train_batch_size=200)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or PG)
        self.lr = 0.0004
        self.train_batch_size = 200
        self.num_sgd_iter = 1
        # REINFORCE uses raw discounted returns, no baseline
        self.use_gae = False
        self.use_critic = False


class PGJaxPolicy(JaxPolicy):
    """reference pg_torch_policy.py pg_torch_loss."""

    # loss never reads NEXT_OBS; don't ship a second obs column
    _ship_next_obs = False

    def loss(self, params, batch, rng, coeffs):
        dist_inputs, _, _ = self.model_forward_train(params, batch)
        dist = self.dist_class(dist_inputs)
        logp = dist.logp(batch[SampleBatch.ACTIONS])
        advantages = batch[SampleBatch.ADVANTAGES]
        policy_loss = -jnp.mean(logp * advantages)
        total = policy_loss - coeffs["entropy_coeff"] * jnp.mean(
            dist.entropy()
        )
        return total, {
            "policy_loss": policy_loss,
            "entropy": jnp.mean(dist.entropy()),
        }

    def postprocess_trajectory(
        self, sample_batch, other_agent_batches=None, episode=None
    ):
        return compute_gae_for_sample_batch(
            self, sample_batch, other_agent_batches, episode
        )


class PG(Algorithm):
    _default_policy_class = PGJaxPolicy

    @classmethod
    def get_default_config(cls) -> PGConfig:
        return PGConfig(cls)

    def training_step(self) -> Dict:
        train_batch = synchronous_parallel_sample(
            worker_set=self.workers,
            max_env_steps=self.config["train_batch_size"],
        )
        self._counters[NUM_ENV_STEPS_SAMPLED] += train_batch.env_steps()
        self._counters[NUM_AGENT_STEPS_SAMPLED] += train_batch.env_steps()
        train_info = train_one_step(self, train_batch)
        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            }
        )
        return train_info
