from ray_tpu.algorithms.pg.pg import PG, PGConfig, PGJaxPolicy

__all__ = ["PG", "PGConfig", "PGJaxPolicy"]
