"""RNNSAC: recurrent soft actor-critic.

Counterpart of the reference's ``rllib/algorithms/sac/rnnsac.py`` (+
``rnnsac_torch_model.py``, ``rnnsac_torch_policy.py``): SAC where the
actor and both twin Q functions carry their own LSTM over the
observation (and action, for Q) sequence, trained on fixed-length
replayed sequences with an optional burn-in prefix excluded from the
losses.

TPU-first shape: the reference threads seq-lens and per-net state dicts
through three torch optimizers; here each net is a flax module whose
sequence forward is one ``nn.scan`` (reset-masked LSTM carry, zero
initial state — the ``zero_init_states=True`` strategy; the stored-state
strategy is R2D2's corner and out of scope here), and the whole
actor/critic/alpha update over a [B, T] sequence batch stays ONE jitted
shard_map program like flat SAC. With zero-init states the reference's
"forward next-obs sequences with the time-t state" equals our zero-state
next-obs forward exactly.

Replay mirrors R2D2: rollout fragments are chopped into fixed-length
sequences with resets + padding masks (``r2d2.py _fragments_to_sequences``)
and sampled uniformly from a sequence buffer.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.algorithms.r2d2.r2d2 import (
    SequenceReplayBuffer,
    chop_fragment_into_sequences,
)
from ray_tpu.algorithms.sac.sac import (
    SAC,
    SACConfig,
    SACJaxPolicy,
)
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.execution.rollout_ops import synchronous_parallel_sample
from ray_tpu.execution.train_ops import (
    NUM_ENV_STEPS_TRAINED,
)
from ray_tpu.algorithms.algorithm import NUM_ENV_STEPS_SAMPLED
from ray_tpu.models.base import get_activation
from ray_tpu.models.distributions import SquashedGaussian
from jax.sharding import PartitionSpec as P


def _lstm_scan(cell, x, resets, cell_size):
    """Reset-masked LSTM over (B, T, F); zero initial carry made
    device-varying by anchoring to the input (shard_map vma)."""
    B = x.shape[0]
    anchor = 0.0 * x[:, 0, :1]  # (B, 1) zeros, varying
    zeros = jnp.zeros((B, cell_size), jnp.float32) + anchor
    carry0 = (zeros, zeros)

    def step(cell, carry, inputs):
        xt, reset_t = inputs
        keep = (1.0 - reset_t)[:, None]
        carry = (carry[0] * keep, carry[1] * keep)
        carry, y = cell(carry, xt)
        return carry, y

    scan = nn.scan(
        step,
        variable_broadcast="params",
        split_rngs={"params": False},
        in_axes=1,
        out_axes=1,
    )
    carry, y = scan(cell, carry0, (x, resets.astype(jnp.float32)))
    return carry, y


class _RNNActorNet(nn.Module):
    """Dense trunk → LSTM → squashed-Gaussian head, over sequences
    (reference rnnsac policy model: use_lstm wrapper on the actor)."""

    action_dim: int
    hiddens: Sequence[int] = (256,)
    cell_size: int = 64
    activation: str = "relu"

    def setup(self):
        self._fcs = [nn.Dense(h) for h in self.hiddens]
        self._cell = nn.OptimizedLSTMCell(self.cell_size)
        self._head = nn.Dense(2 * self.action_dim)

    def _trunk(self, x):
        act = get_activation(self.activation)
        for fc in self._fcs:
            x = act(fc(x))
        return x

    def __call__(self, obs, resets):
        """obs (B, T, obs…), resets (B, T) → dist inputs (B, T, 2A)."""
        B, T = obs.shape[:2]
        x = self._trunk(obs.astype(jnp.float32).reshape(B, T, -1))
        _, y = _lstm_scan(self._cell, x, resets, self.cell_size)
        return self._head(y)

    def step(self, obs, h, c):
        """One acting step: obs (B, obs…), carried (h, c) → (dist
        inputs (B, 2A), new_h, new_c)."""
        x = self._trunk(
            obs.astype(jnp.float32).reshape(obs.shape[0], -1)
        )
        (new_c, new_h), y = self._cell((c, h), x)
        return self._head(y), new_h, new_c


class _RNNTwinQNet(nn.Module):
    """Two independent recurrent Q functions over (obs, action)
    sequences (reference rnnsac q/twin_q nets with use_lstm)."""

    hiddens: Sequence[int] = (256,)
    cell_size: int = 64
    activation: str = "relu"

    def setup(self):
        self._fcs = {
            name: [nn.Dense(h) for h in self.hiddens]
            for name in ("q1", "q2")
        }
        self._cells = {
            name: nn.OptimizedLSTMCell(self.cell_size)
            for name in ("q1", "q2")
        }
        self._heads = {name: nn.Dense(1) for name in ("q1", "q2")}

    def __call__(self, obs, actions, resets):
        """→ (q1 (B, T), q2 (B, T))."""
        B, T = obs.shape[:2]
        x0 = jnp.concatenate(
            [
                obs.astype(jnp.float32).reshape(B, T, -1),
                actions.astype(jnp.float32).reshape(B, T, -1),
            ],
            axis=-1,
        )
        act = get_activation(self.activation)
        qs = []
        for name in ("q1", "q2"):
            x = x0
            for fc in self._fcs[name]:
                x = act(fc(x))
            _, y = _lstm_scan(
                self._cells[name], x, resets, self.cell_size
            )
            qs.append(self._heads[name](y)[..., 0])
        return qs[0], qs[1]


class RNNSACConfig(SACConfig):
    """reference rnnsac.py RNNSACConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or RNNSAC)
        self.replay_sequence_length = 20
        self.replay_burn_in = 0
        self.zero_init_states = True
        # capacity counts SEQUENCES here (SAC's inherited value counts
        # flat transitions; 2000 sequences ≈ 40k transitions, matching
        # R2D2's default)
        self.replay_buffer_config = {
            **(getattr(self, "replay_buffer_config", None) or {}),
            "capacity": 2000,
        }
        self.policy_model_config = {
            **(getattr(self, "policy_model_config", None) or {}),
            "lstm_cell_size": 64,
        }
        self.q_model_config = {
            **(getattr(self, "q_model_config", None) or {}),
            "lstm_cell_size": 64,
        }

    def training(
        self,
        *,
        replay_sequence_length: Optional[int] = None,
        replay_burn_in: Optional[int] = None,
        **kwargs,
    ) -> "RNNSACConfig":
        super().training(**kwargs)
        if replay_sequence_length is not None:
            self.replay_sequence_length = replay_sequence_length
        if replay_burn_in is not None:
            self.replay_burn_in = replay_burn_in
        return self


class RNNSACJaxPolicy(SACJaxPolicy):
    """Sequence-shaped fused actor/critic/alpha update. Train batches
    are stacked fixed-length sequences (leading dim = sequence)."""

    # sequence batches carry per-chunk recurrent state; keep the
    # one-update-per-dispatch path (legacy stacked chain AND the
    # generic superstep)
    supports_stacked_learn = False
    _superstep_opt_out = True

    def _make_nets(self, pm_cfg, qm_cfg):
        actor = _RNNActorNet(
            self.action_dim,
            tuple(pm_cfg.get("fcnet_hiddens", (256,))),
            int(pm_cfg.get("lstm_cell_size", 64)),
            pm_cfg.get("fcnet_activation", "relu"),
        )
        critic = _RNNTwinQNet(
            tuple(qm_cfg.get("fcnet_hiddens", (256,))),
            int(qm_cfg.get("lstm_cell_size", 64)),
            qm_cfg.get("fcnet_activation", "relu"),
        )
        return actor, critic

    def _init_net_params(self, r1, r2):
        obs_shape = tuple(self.observation_space.shape)
        dummy_obs = jnp.zeros((2, 3) + obs_shape, jnp.float32)
        dummy_act = jnp.zeros((2, 3, self.action_dim), jnp.float32)
        dummy_resets = jnp.zeros((2, 3), jnp.float32)
        return (
            self.actor.init(r1, dummy_obs, dummy_resets),
            self.critic.init(r2, dummy_obs, dummy_act, dummy_resets),
        )

    def get_initial_state(self):
        cell = int(
            (self.config.get("policy_model_config") or {}).get(
                "lstm_cell_size", 64
            )
        )
        return [
            np.zeros(cell, np.float32),  # h
            np.zeros(cell, np.float32),  # c
        ]

    # -- acting (recurrent step) ------------------------------------------

    def _build_action_fn(self):
        actor = self.actor
        low, high = self.low, self.high
        exploration = self.exploration

        def fn(params, obs, h, c, rng, explore, coeffs, expl_state):
            dist_inputs, new_h, new_c = actor.apply(
                params["actor"], obs, h, c,
                method=_RNNActorNet.step,
            )
            dist = SquashedGaussian(dist_inputs, low=low, high=high)
            actions, logp, expl_state = exploration.sample_fn(
                dist, rng, explore, coeffs, expl_state
            )
            return (
                actions,
                new_h,
                new_c,
                {SampleBatch.ACTION_LOGP: logp},
                expl_state,
            )

        return jax.jit(fn, static_argnames=("explore",))

    def compute_actions(
        self, obs_batch, state_batches=None, explore=True, **kwargs
    ):
        if self._action_fn is None:
            self._action_fn = self._build_action_fn()
        self.exploration.update_coeffs(
            self.coeff_values, self.global_timestep
        )
        params = self.exploration.params_for_inference(self, explore)
        self._rng, rng = jax.random.split(self._rng)
        obs = jnp.asarray(obs_batch)
        bsize = int(obs.shape[0])
        if not state_batches:
            init = self.get_initial_state()
            state_batches = [
                np.tile(s[None], (bsize, 1)) for s in init
            ]
        h = jnp.asarray(state_batches[0], jnp.float32)
        c = jnp.asarray(state_batches[1], jnp.float32)
        if self._expl_state_batch != bsize:
            self._expl_state = self.exploration.initial_state(bsize)
            self._expl_state_batch = bsize
        actions, new_h, new_c, extra, self._expl_state = (
            self._action_fn(
                params, obs, h, c, rng, bool(explore),
                self._coeff_array(), self._expl_state,
            )
        )
        return (
            np.asarray(actions),
            [np.asarray(new_h), np.asarray(new_c)],
            {k: np.asarray(v) for k, v in extra.items()},
        )

    # -- learning ----------------------------------------------------------
    # The fused actor/critic/alpha device_fn is SACJaxPolicy's; the
    # three hooks below make it sequence-shaped.

    def _batch_to_train_tree(self, samples):
        tree = super()._batch_to_train_tree(samples)
        tree["resets"] = np.asarray(samples["resets"], np.float32)
        tree["mask"] = np.asarray(samples["mask"], np.float32)
        return tree

    def _seq_resets(self, batch):
        resets = batch["resets"].astype(jnp.float32)
        not_done = 1.0 - batch[SampleBatch.TERMINATEDS].astype(
            jnp.float32
        )
        # next-obs sequences: the boundary AFTER a done row starts the
        # next episode, so shift dones into the resets stream
        resets_tp1 = jnp.concatenate(
            [resets[:, :1], (1.0 - not_done)[:, :-1]], axis=1
        )
        return resets, jnp.maximum(resets_tp1, resets)

    def _net_forward(self, net, params, *args, resets=None):
        return net.apply(params, *args, resets)

    def _loss_mask(self, batch):
        mask = batch["mask"].astype(jnp.float32)
        burn_in = int(self.config.get("replay_burn_in", 0))
        if burn_in > 0:
            T = mask.shape[1]
            mask = mask * (
                jnp.arange(T)[None, :] >= burn_in
            ).astype(jnp.float32)
        return mask


class RNNSAC(SAC):
    """Sequence-replay SAC trainer (reference rnnsac.py RNNSAC):
    fragments chop into fixed-length sequences like R2D2; the policy's
    polyak target update happens inside the fused step, so no separate
    target sync is needed."""

    _default_policy_class = RNNSACJaxPolicy

    @classmethod
    def get_default_config(cls) -> RNNSACConfig:
        return RNNSACConfig(cls)

    def setup(self, config: Dict) -> None:
        if not config.get("zero_init_states", True):
            raise ValueError(
                "RNNSAC supports only zero_init_states=True (the "
                "stored-state strategy is R2D2's corner — "
                "r2d2.py _fragments_to_sequences)"
            )
        super().setup(config)
        rb = config.get("replay_buffer_config") or {}
        self.local_replay_buffer = None  # SAC's flat buffer unused
        self.seq_buffer = SequenceReplayBuffer(
            rb.get("capacity", 2000), seed=config.get("seed")
        )

    def _fragments_to_sequences(self, batch: SampleBatch) -> None:
        """The shared chopper with SAC's columns (adds NEXT_OBS)."""
        T = int(self.config.get("replay_sequence_length", 20))
        for _, seq in chop_fragment_into_sequences(
            batch,
            T,
            (
                SampleBatch.OBS,
                SampleBatch.NEXT_OBS,
                SampleBatch.ACTIONS,
                SampleBatch.REWARDS,
                SampleBatch.TERMINATEDS,
            ),
            first_row_is_reset=True,
        ):
            self.seq_buffer.add_sequence(seq)

    def training_step(self) -> Dict:
        config = self.config
        batch = synchronous_parallel_sample(
            worker_set=self.workers,
            max_env_steps=config.get("rollout_fragment_length", 20),
        )
        self._counters[NUM_ENV_STEPS_SAMPLED] += batch.env_steps()
        if hasattr(batch, "policy_batches"):
            batch = batch.policy_batches[DEFAULT_POLICY_ID]
        self._fragments_to_sequences(batch)

        train_info: Dict = {}
        num_seqs = max(
            1,
            int(config["train_batch_size"])
            // int(config.get("replay_sequence_length", 20)),
        )
        if (
            self._counters[NUM_ENV_STEPS_SAMPLED]
            >= config.get("num_steps_sampled_before_learning_starts", 0)
            and len(self.seq_buffer) >= num_seqs
        ):
            seqs = self.seq_buffer.sample(num_seqs)
            policy = self.get_policy()
            info = policy.learn_on_batch(SampleBatch(seqs))
            train_info = {DEFAULT_POLICY_ID: info}
            self._counters[NUM_ENV_STEPS_TRAINED] += int(
                seqs["mask"].sum()
            )
        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            }
        )
        return train_info
