from ray_tpu.algorithms.sac.sac import SAC, SACConfig, SACJaxPolicy

__all__ = ["SAC", "SACConfig", "SACJaxPolicy"]
