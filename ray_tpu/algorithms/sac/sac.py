"""SAC: soft actor-critic with twin Q, auto-tuned entropy temperature.

Counterpart of the reference's ``rllib/algorithms/sac/sac.py:274`` (config;
SAC extends DQN's off-policy training_step) and
``sac_torch_policy.py`` (actor/critic/alpha losses with three optimizers).
TPU-first: the whole update — critic step, actor step, alpha step, polyak
target blend — is ONE jitted shard_map program; the three optimizers are
three optax states advanced inside it.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_tpu import sharding as sharding_lib
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.algorithms.dqn.dqn import DQN, DQNConfig
from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.models.base import get_activation
from ray_tpu.models.distributions import SquashedGaussian
from ray_tpu.policy.jax_policy import JaxPolicy, _tree_to_device


class _ActorNet(nn.Module):
    action_dim: int
    hiddens: Sequence[int] = (256, 256)
    activation: str = "relu"

    @nn.compact
    def __call__(self, obs):
        act = get_activation(self.activation)
        x = obs.astype(jnp.float32).reshape(obs.shape[0], -1)
        for i, h in enumerate(self.hiddens):
            x = act(nn.Dense(h, name=f"fc_{i}")(x))
        return nn.Dense(2 * self.action_dim, name="out")(x)


class _TwinQNet(nn.Module):
    hiddens: Sequence[int] = (256, 256)
    activation: str = "relu"

    @nn.compact
    def __call__(self, obs, actions):
        act = get_activation(self.activation)
        x0 = jnp.concatenate(
            [
                obs.astype(jnp.float32).reshape(obs.shape[0], -1),
                actions.astype(jnp.float32).reshape(
                    actions.shape[0], -1
                ),
            ],
            axis=-1,
        )
        qs = []
        for name in ("q1", "q2"):
            x = x0
            for i, h in enumerate(self.hiddens):
                x = act(nn.Dense(h, name=f"{name}_fc_{i}")(x))
            qs.append(nn.Dense(1, name=f"{name}_out")(x).squeeze(-1))
        return qs[0], qs[1]


class SACConfig(DQNConfig):
    """reference sac.py:274."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.twin_q = True
        self.tau = 5e-3
        self.initial_alpha = 1.0
        self.target_entropy = "auto"
        self.optimization = {
            "actor_learning_rate": 3e-4,
            "critic_learning_rate": 3e-4,
            "entropy_learning_rate": 3e-4,
        }
        self.train_batch_size = 256
        self.rollout_fragment_length = 1
        self.num_steps_sampled_before_learning_starts = 1500
        self.target_network_update_freq = 0
        self.q_model_config = {"fcnet_hiddens": [256, 256]}
        self.policy_model_config = {"fcnet_hiddens": [256, 256]}
        self.n_step = 1
        self.grad_clip = None
        self.replay_buffer_config = {
            "capacity": 100000,
            "prioritized_replay": False,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
        }

    def training(
        self,
        *,
        twin_q: Optional[bool] = None,
        tau: Optional[float] = None,
        initial_alpha: Optional[float] = None,
        target_entropy=None,
        optimization: Optional[Dict] = None,
        q_model_config: Optional[Dict] = None,
        policy_model_config: Optional[Dict] = None,
        **kwargs,
    ) -> "SACConfig":
        super().training(**kwargs)
        if twin_q is not None:
            self.twin_q = twin_q
        if tau is not None:
            self.tau = tau
        if initial_alpha is not None:
            self.initial_alpha = initial_alpha
        if target_entropy is not None:
            self.target_entropy = target_entropy
        if optimization is not None:
            self.optimization.update(optimization)
        if q_model_config is not None:
            self.q_model_config = q_model_config
        if policy_model_config is not None:
            self.policy_model_config = policy_model_config
        return self


class SACJaxPolicy(JaxPolicy):
    """Actor/critic/alpha losses fused into one jitted update
    (reference sac_torch_policy.py actor_critic_loss + three optimizers)."""

    # rollout workers act with the actor net alone — don't pull the
    # critic/target towers off-device every weight sync
    inference_weight_keys = ("actor",)

    @property
    def supports_stacked_learn(self) -> bool:
        """Whether k replay updates may fuse into one lax.scan dispatch
        (learn_on_stacked_batch). Only safe when the subclass kept
        THIS class's update body: the fused scan is built from
        SACJaxPolicy._device_update_fn, so a subclass that replaces
        _build_learn_fn with its own loss (CQL's min-Q penalty, CRR's
        weighted regression) must not be chained through it. The
        recurrent subclass opts out explicitly (sequence state columns
        need per-chunk handling)."""
        return (
            type(self)._build_learn_fn is SACJaxPolicy._build_learn_fn
            and type(self)._device_update_fn
            is SACJaxPolicy._device_update_fn
        )

    def __init__(self, observation_space, action_space, config):
        # Bypass JaxPolicy model construction: SAC has its own nets.
        from ray_tpu.policy.policy import Policy

        Policy.__init__(self, observation_space, action_space, config)
        self.action_dim = int(np.prod(action_space.shape))
        self.low = float(np.min(action_space.low))
        self.high = float(np.max(action_space.high))

        self.sharding_backend = config.get("sharding_backend", "mesh")
        self.mesh = sharding_lib.resolve_mesh(config)
        self.n_shards = sharding_lib.num_shards(self.mesh)
        self._param_sharding = sharding_lib.replicated(self.mesh)
        self._data_sharding = sharding_lib.batch_sharded(self.mesh)

        pm_cfg = config.get("policy_model_config") or {}
        qm_cfg = config.get("q_model_config") or {}
        self.actor, self.critic = self._make_nets(pm_cfg, qm_cfg)

        seed = int(config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._rng, r1, r2 = jax.random.split(self._rng, 3)
        actor_params, critic_params = self._init_net_params(r1, r2)
        log_alpha = jnp.asarray(
            np.log(config.get("initial_alpha", 1.0)), jnp.float32
        )
        self.params = _tree_to_device(
            {
                "actor": actor_params,
                "critic": critic_params,
                "log_alpha": log_alpha,
            },
            self._param_sharding,
        )
        self.aux_state = _tree_to_device(
            {"target_critic": critic_params}, self._param_sharding
        )

        opt = config.get("optimization") or {}
        self._tx_actor = optax.adam(opt.get("actor_learning_rate", 3e-4))
        self._tx_critic = optax.adam(
            opt.get("critic_learning_rate", 3e-4)
        )
        self._tx_alpha = optax.adam(
            opt.get("entropy_learning_rate", 3e-4)
        )
        self.opt_state = _tree_to_device(
            {
                "actor": self._tx_actor.init(self.params["actor"]),
                "critic": self._tx_critic.init(self.params["critic"]),
                "log_alpha": self._tx_alpha.init(
                    self.params["log_alpha"]
                ),
            },
            self._param_sharding,
        )

        te = config.get("target_entropy", "auto")
        self.target_entropy = (
            -float(self.action_dim) if te in (None, "auto") else float(te)
        )
        self.tau = float(config.get("tau", 5e-3))
        self.gamma = float(config.get("gamma", 0.99))
        self.n_step = int(config.get("n_step", 1))

        self.coeff_values = {}
        self._learn_fns = {}
        self._multi_learn_fns = {}
        self._action_fn = None
        self.num_grad_updates = 0
        # device-side flattened actor snapshots maintained by the
        # fused multi-update path for round-trip-free weight sync
        self._flat_actor_dev = None
        self._flat_actor_ready = None

        # SAC's squashed-Gaussian sampling IS its exploration (the
        # reference uses StochasticSampling for SAC too); the strategy
        # object exists for the uniform hook surface (state, weights).
        self._init_exploration()

    def get_initial_state(self):
        return []

    # -- net construction (overridden by RNNSAC) -------------------------

    def _make_nets(self, pm_cfg, qm_cfg):
        actor = _ActorNet(
            self.action_dim,
            tuple(pm_cfg.get("fcnet_hiddens", (256, 256))),
            pm_cfg.get("fcnet_activation", "relu"),
        )
        critic = _TwinQNet(
            tuple(qm_cfg.get("fcnet_hiddens", (256, 256))),
            qm_cfg.get("fcnet_activation", "relu"),
        )
        return actor, critic

    def _init_net_params(self, r1, r2):
        dummy_obs = jnp.zeros(
            (2,) + tuple(self.observation_space.shape), jnp.float32
        )
        dummy_act = jnp.zeros((2, self.action_dim), jnp.float32)
        return (
            self.actor.init(r1, dummy_obs),
            self.critic.init(r2, dummy_obs, dummy_act),
        )

    # -- inference -------------------------------------------------------

    def _build_action_fn(self):
        actor = self.actor
        low, high = self.low, self.high
        exploration = self.exploration

        def fn(params, obs, rng, explore, coeffs, expl_state):
            dist_inputs = actor.apply(params["actor"], obs)
            dist = SquashedGaussian(dist_inputs, low=low, high=high)
            actions, logp, expl_state = exploration.sample_fn(
                dist, rng, explore, coeffs, expl_state
            )
            return (
                actions,
                {SampleBatch.ACTION_LOGP: logp},
                expl_state,
            )

        return jax.jit(fn, static_argnames=("explore",))

    def compute_actions(
        self, obs_batch, state_batches=None, explore=True, **kwargs
    ):
        if self._action_fn is None:
            self._action_fn = self._build_action_fn()
        self.exploration.update_coeffs(
            self.coeff_values, self.global_timestep
        )
        params = self.exploration.params_for_inference(self, explore)
        self._rng, rng = jax.random.split(self._rng)
        obs = jnp.asarray(obs_batch)
        if self.exploration.needs_last_obs:
            self._last_obs = obs
        bsize = int(obs.shape[0])
        if self._expl_state_batch != bsize:
            self._expl_state = self.exploration.initial_state(bsize)
            self._expl_state_batch = bsize
        actions, extra, self._expl_state = self._action_fn(
            params, obs, rng, bool(explore),
            self._coeff_array(), self._expl_state,
        )
        return (
            np.asarray(actions),
            [],
            {k: np.asarray(v) for k, v in extra.items()},
        )

    # -- learning --------------------------------------------------------

    # Hooks the recurrent subclass overrides so ONE fused device_fn
    # serves both flat and sequence SAC:

    def _seq_resets(self, batch):
        """→ (resets for time-t forwards, resets for next-obs
        forwards); None for feedforward nets."""
        return None, None

    def _net_forward(self, net, params, *args, resets=None):
        """Apply an actor/critic net; feedforward nets ignore resets."""
        return net.apply(params, *args)

    def _loss_mask(self, batch):
        """Per-element validity mask for the losses (None = all)."""
        return None

    def _device_update_fn(self, batch_size=None, with_frames=False):
        """The single-update body shared by the per-batch program, the
        legacy fused multi-update scan, and the generic superstep
        (``JaxPolicy.learn_superstep``) — all run inside shard_map.
        ``batch_size``/``with_frames`` are part of the uniform
        signature; SAC's bespoke nets ignore both (flat obs only)."""
        actor, critic = self.actor, self.critic
        tx_a, tx_c, tx_al = (
            self._tx_actor,
            self._tx_critic,
            self._tx_alpha,
        )
        gamma, tau = self.gamma**self.n_step, self.tau
        target_entropy = self.target_entropy
        low, high = self.low, self.high
        axis = sharding_lib.data_axis(self.mesh)

        def device_fn(params, opt_state, aux, batch, rng, coeffs):
            obs = batch[SampleBatch.OBS].astype(jnp.float32)
            next_obs = batch[SampleBatch.NEXT_OBS].astype(jnp.float32)
            rewards = batch[SampleBatch.REWARDS].astype(jnp.float32)
            not_done = 1.0 - batch[SampleBatch.TERMINATEDS].astype(
                jnp.float32
            )
            actions = batch[SampleBatch.ACTIONS].astype(jnp.float32)
            resets_t, resets_tp1 = self._seq_resets(batch)
            mask = self._loss_mask(batch)
            if mask is None:
                mean = jnp.mean
            else:
                denom = jnp.maximum(jnp.sum(mask), 1.0)

                def mean(x):
                    return jnp.sum(x * mask) / denom

            rng = jax.random.fold_in(
                rng, jax.lax.axis_index(axis)
            )
            rng_t, rng_a = jax.random.split(rng)
            alpha = jnp.exp(params["log_alpha"])

            # ---- critic update ----
            next_dist = SquashedGaussian(
                self._net_forward(
                    actor, params["actor"], next_obs,
                    resets=resets_tp1,
                ),
                low=low, high=high,
            )
            next_a, next_logp = next_dist.sampled_action_logp(rng_t)
            tq1, tq2 = self._net_forward(
                critic, aux["target_critic"], next_obs, next_a,
                resets=resets_tp1,
            )
            target_q = jnp.minimum(tq1, tq2) - alpha * next_logp
            td_target = jax.lax.stop_gradient(
                rewards + gamma * not_done * target_q
            )

            def critic_loss(cp):
                q1, q2 = self._net_forward(
                    critic, cp, obs, actions, resets=resets_t
                )
                return (
                    mean(jnp.square(q1 - td_target))
                    + mean(jnp.square(q2 - td_target))
                ), (q1, q2)

            (c_loss, (q1, q2)), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True
            )(params["critic"])
            c_grads = jax.lax.pmean(c_grads, axis)
            c_upd, c_opt = tx_c.update(
                c_grads, opt_state["critic"], params["critic"]
            )
            new_critic = optax.apply_updates(params["critic"], c_upd)

            # ---- actor update (uses the fresh critic) ----
            def actor_loss(ap):
                dist = SquashedGaussian(
                    self._net_forward(
                        actor, ap, obs, resets=resets_t
                    ),
                    low=low, high=high,
                )
                a, logp = dist.sampled_action_logp(rng_a)
                aq1, aq2 = self._net_forward(
                    critic, new_critic, obs, a, resets=resets_t
                )
                return mean(
                    alpha * logp - jnp.minimum(aq1, aq2)
                ), logp

            (a_loss, logp_pi), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True
            )(params["actor"])
            a_grads = jax.lax.pmean(a_grads, axis)
            a_upd, a_opt = tx_a.update(
                a_grads, opt_state["actor"], params["actor"]
            )
            new_actor = optax.apply_updates(params["actor"], a_upd)

            # ---- alpha update ----
            def alpha_loss(log_alpha):
                return -mean(
                    log_alpha
                    * jax.lax.stop_gradient(logp_pi + target_entropy)
                )

            al_loss, al_grad = jax.value_and_grad(alpha_loss)(
                params["log_alpha"]
            )
            al_grad = jax.lax.pmean(al_grad, axis)
            al_upd, al_opt = tx_al.update(
                al_grad, opt_state["log_alpha"], params["log_alpha"]
            )
            new_log_alpha = optax.apply_updates(
                params["log_alpha"], al_upd
            )

            # ---- polyak target blend (reference tau soft update) ----
            new_target = jax.tree_util.tree_map(
                lambda t, o: (1.0 - tau) * t + tau * o,
                aux["target_critic"],
                new_critic,
            )

            new_params = {
                "actor": new_actor,
                "critic": new_critic,
                "log_alpha": new_log_alpha,
            }
            new_opt = {
                "actor": a_opt,
                "critic": c_opt,
                "log_alpha": al_opt,
            }
            new_aux = {"target_critic": new_target}
            stats = {
                "actor_loss": a_loss,
                "critic_loss": c_loss,
                "alpha_loss": al_loss,
                "alpha_value": alpha,
                "mean_q": mean(jnp.minimum(q1, q2)),
                "total_loss": a_loss + c_loss + al_loss,
            }
            stats = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, axis), stats
            )
            return new_params, new_opt, new_aux, stats

        return device_fn

    def _build_learn_fn(self, batch_size: int):
        return self._wrap_update_program(
            self._device_update_fn(batch_size), batch_size
        )

    # -- superstep contract (JaxPolicy.learn_superstep) ------------------

    @property
    def supports_superstep(self) -> bool:
        """The generic superstep scans THIS policy's own
        ``_device_update_fn`` — so unlike the legacy stacked path
        (``supports_stacked_learn``, which fuses the SAC body
        specifically), subclasses with their own update bodies
        (CQL's min-Q penalty, CRR's weighted regression) chain safely
        too. Only wholesale learn-program replacements and explicit
        opt-outs (RNNSAC's sequence state handling) are excluded."""
        return (
            not self._superstep_opt_out
            and self.sharding_backend == "mesh"
            and type(self)._build_learn_fn is SACJaxPolicy._build_learn_fn
        )

    def _learn_coeffs(self):
        return {}  # the per-update path passes no coefficients

    def _updates_per_learn_call(self, batch_size: int) -> int:
        return 1

    @property
    def _td_refresh_uses_rng(self) -> bool:
        return True  # compute_td_error splits for the target resample

    def _after_superstep(self) -> None:
        # fused chains move the actor without refreshing the flat
        # device snapshots — drop them so sync can't ship stale weights
        self._flat_actor_dev = None
        self._flat_actor_ready = None

    def _build_multi_learn_fn(self, batch_size: int, k: int):
        """K replay updates fused into ONE program: ``lax.scan`` threads
        (params, opt_state, target) through k sequential updates over a
        stacked (k, batch, ...) replay sample, so one dispatch (one
        tunnel round trip, one H2D transfer) buys k SGD steps. This is
        the TPU-shaped counterpart of the reference's training_intensity
        update loop (``dqn.py:336`` sample-and-learn rounds), which
        pays a full dispatch per update."""
        device_fn = self._device_update_fn()
        axis = sharding_lib.data_axis(self.mesh)

        def multi_fn(params, opt_state, aux, stacked, rng, coeffs):
            def body(carry, batch_k):
                params, opt_state, aux, rng = carry
                rng, sub = jax.random.split(rng)
                p, o, a, stats = device_fn(
                    params, opt_state, aux, batch_k, sub, coeffs
                )
                return (p, o, a, rng), stats

            (params, opt_state, aux, _), stats = jax.lax.scan(
                body, (params, opt_state, aux, rng), stacked
            )
            # report the final update's stats (a mean over the chain
            # would smear k distinct optimization states together)
            stats = jax.tree_util.tree_map(lambda x: x[-1], stats)
            # flattened post-chain actor, computed on device for free:
            # weight sync reads THIS single vector instead of pulling
            # the param tree leaf by leaf (each device interaction
            # pays the full tunnel round trip)
            flat_actor = jnp.concatenate(
                [
                    x.reshape(-1).astype(jnp.float32)
                    for x in jax.tree_util.tree_leaves(
                        params["actor"]
                    )
                ]
            )
            return params, opt_state, aux, stats, flat_actor

        sharded = jax.shard_map(
            multi_fn,
            mesh=self.mesh,
            in_specs=(P(), P(), P(), P(None, axis), P(), P()),
            out_specs=(P(), P(), P(), P(), P()),
        )
        label = f"multi_learn[{type(self).__name__}:{batch_size}x{k}]"
        if self.sharding_backend == "mesh":
            rep = self._param_sharding
            dat = sharding_lib.batch_sharded(self.mesh, ndim_prefix=2)
            return sharding_lib.sharded_jit(
                sharded,
                in_specs=(rep, rep, rep, dat, rep, rep),
                out_specs=(rep, rep, rep, rep, rep),
                donate_argnums=(1,),
                label=label,
            )
        return sharding_lib.sharded_jit(
            sharded, donate_argnums=(1,), label=label
        )

    def learn_on_stacked_batch(
        self,
        stacked: Dict[str, np.ndarray],
        k: int,
        batch_size: int,
        *,
        defer_stats: bool = False,
    ) -> Dict:
        """Run k fused updates on a host tree of (k, batch, ...) arrays
        (one vectorized replay gather, reshaped). See
        :meth:`_build_multi_learn_fn`."""
        key = (batch_size, k)
        fn = self._multi_learn_fns.get(key)
        if fn is None:
            fn = self._build_multi_learn_fn(batch_size, k)
            self._multi_learn_fns[key] = fn
        sharding = sharding_lib.batch_sharded(self.mesh, ndim_prefix=2)
        if not any(
            isinstance(v, jax.Array) for v in stacked.values()
        ):
            # host-gathered chains cross H2D here; device-resident
            # replay hands jax arrays through (already resident)
            from ray_tpu.telemetry import metrics as telemetry_metrics

            telemetry_metrics.add_h2d_bytes(
                "learn", sharding_lib.tree_nbytes(stacked)
            )
        dev = jax.device_put(stacked, sharding)
        self._rng, rng = jax.random.split(self._rng)
        (
            self.params,
            self.opt_state,
            self.aux_state,
            stats,
            flat_actor,
        ) = fn(
            self.params, self.opt_state, self.aux_state, dev, rng, {}
        )
        # rotate the sync source: the PREVIOUS chain's actor (surely
        # computed by now) serves the next weight sync without waiting
        # on this chain — one round of staleness, same as sample_async
        self._flat_actor_ready = getattr(
            self, "_flat_actor_dev", None
        )
        self._flat_actor_dev = flat_actor
        self.num_grad_updates += k
        if defer_stats:
            return stats
        stats = jax.device_get(stats)
        return {k2: float(v) for k2, v in stats.items()}

    def _actor_unflatten(self, vec: np.ndarray):
        """Host-side inverse of the device-side actor flatten."""
        leaves, treedef = jax.tree_util.tree_flatten(
            self.params["actor"]
        )
        sizes = [int(np.prod(x.shape)) for x in leaves]
        parts = np.split(np.asarray(vec), np.cumsum(sizes)[:-1])
        return jax.tree_util.tree_unflatten(
            treedef,
            [
                p.reshape(x.shape).astype(np.float32)
                for p, x in zip(parts, leaves)
            ],
        )

    def get_inference_weights(self):
        flat = getattr(self, "_flat_actor_ready", None)
        if flat is None:
            flat = getattr(self, "_flat_actor_dev", None)
        if flat is not None:
            return {"actor": self._actor_unflatten(jax.device_get(flat))}
        return super().get_inference_weights()

    def set_weights(self, weights) -> None:
        # any externally-set params invalidate the device-side flat
        # actor snapshots the fused path maintains
        self._flat_actor_dev = None
        self._flat_actor_ready = None
        super().set_weights(weights)

    def _td_error_device_fn(self):
        """Signed per-sample TD error of the min-twin critic vs the
        soft TD target — shared by ``compute_td_error`` (plain jit)
        and the superstep's in-scan prioritized refresh."""
        actor, critic = self.actor, self.critic
        gamma = self.gamma**self.n_step
        low, high = self.low, self.high

        def fn(params, aux, batch, rng):
            obs = batch[SampleBatch.OBS].astype(jnp.float32)
            next_obs = batch[SampleBatch.NEXT_OBS].astype(
                jnp.float32
            )
            rewards = batch[SampleBatch.REWARDS].astype(jnp.float32)
            not_done = 1.0 - batch[
                SampleBatch.TERMINATEDS
            ].astype(jnp.float32)
            actions = batch[SampleBatch.ACTIONS].astype(jnp.float32)
            alpha = jnp.exp(params["log_alpha"])
            next_dist = SquashedGaussian(
                actor.apply(params["actor"], next_obs),
                low=low,
                high=high,
            )
            next_a, next_logp = next_dist.sampled_action_logp(rng)
            tq1, tq2 = critic.apply(
                aux["target_critic"], next_obs, next_a
            )
            target_q = jnp.minimum(tq1, tq2) - alpha * next_logp
            td_target = rewards + gamma * not_done * target_q
            q1, q2 = critic.apply(params["critic"], obs, actions)
            return jnp.minimum(q1, q2) - td_target

        return fn

    def compute_td_error(self, samples) -> np.ndarray:
        """Per-sample |TD error| of the min-twin critic vs the soft TD
        target, for prioritized-replay priority refresh (reference
        sac_torch_policy keeps ``policy.td_error`` from the loss)."""
        if not hasattr(self, "_td_error_fn"):
            self._td_error_fn = jax.jit(self._td_error_device_fn())
        batch = self._td_input_tree(samples)
        self._rng, rng = jax.random.split(self._rng)
        td = self._td_error_fn(self.params, self.aux_state, batch, rng)
        return np.abs(np.asarray(td))

    def learn_on_device_batch(
        self, dev_batch, batch_size: int, *, defer_stats: bool = False
    ) -> Dict:
        """SAC's compiled fn threads aux_state (target critic) through the
        update, so phase 2 is overridden; phase 1 (prepare_batch) and
        learn_on_batch's composition are inherited from JaxPolicy.
        ``defer_stats`` matches the base contract: skip the blocking
        stats fetch so chained updates (training_intensity, learner
        threads) pipeline on-device."""
        fn = self.learn_fn(batch_size)
        self._rng, rng = jax.random.split(self._rng)
        self.params, self.opt_state, self.aux_state, stats = fn(
            self.params, self.opt_state, self.aux_state, dev_batch,
            rng, {},
        )
        # single-update path moves the actor without refreshing the
        # fused path's flat snapshots — drop them so sync can't ship
        # stale weights
        self._flat_actor_dev = None
        self._flat_actor_ready = None
        self.num_grad_updates += 1
        if defer_stats:
            return stats
        if self.config.get("deferred_stats"):
            # same one-call lag as the JaxPolicy base
            # (docs/data_plane.md): return the previous update's
            # stats so this dispatch never blocks on its own program
            prev = self.__dict__.get("_lagged_stats")
            self.__dict__["_lagged_stats"] = stats
            if prev is None:
                return {}
            stats = jax.device_get(prev)
        else:
            stats = jax.device_get(stats)
        return {k: float(v) for k, v in stats.items()}

    def update_target(self) -> None:
        """No-op: polyak blending happens inside the learn program."""

    def _batch_to_train_tree(self, samples: SampleBatch):
        keys = [
            SampleBatch.OBS,
            SampleBatch.NEXT_OBS,
            SampleBatch.ACTIONS,
            SampleBatch.REWARDS,
            SampleBatch.TERMINATEDS,
        ]
        out = {}
        for k in keys:
            if k not in samples:
                continue
            v = np.asarray(samples[k])
            if v.dtype == np.float64:
                # MuJoCo obs arrive f64; the loss casts to f32 on
                # device anyway — cast host-side and halve the H2D
                # bytes
                v = v.astype(np.float32)
            out[k] = v
        return out


class SAC(DQN):
    _default_policy_class = SACJaxPolicy

    @classmethod
    def get_default_config(cls) -> SACConfig:
        return SACConfig(cls)
