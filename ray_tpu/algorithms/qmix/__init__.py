from ray_tpu.algorithms.qmix.qmix import QMIX, QMIXConfig

__all__ = ["QMIX", "QMIXConfig"]
