"""MARWIL (advantage-weighted imitation) and BC (behavior cloning).

Counterpart of the reference's ``rllib/algorithms/marwil/marwil.py``
(MARWILConfig; trains from offline JSON input) and
``marwil_torch_policy.py`` (exponentially advantage-weighted logp loss
with a moving-average squared-advantage normalizer; BC is MARWIL with
beta=0 — ``rllib/algorithms/bc/bc.py``).

The moving-average normalizer is host-side state fed into the jitted
loss as a traced scalar coefficient and updated from the returned
``adv_sqd_mean`` stat after each learn call (MARWIL's default
num_sgd_iter=1 makes this exactly the reference's per-SGD-step update).
ADVANTAGES in the batch are plain discounted returns (use_gae=False,
use_critic=False — reference marwil_tf_policy.py PostprocessAdvantages).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.evaluation.postprocessing import compute_gae_for_sample_batch
from ray_tpu.execution.train_ops import train_one_step
from ray_tpu.policy.jax_policy import JaxPolicy


class MARWILConfig(AlgorithmConfig):
    """reference marwil.py MARWILConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self.beta = 1.0
        self.vf_coeff = 1.0
        self.bc_logstd_coeff = 0.0
        self.moving_average_sqd_adv_norm_start = 100.0
        self.moving_average_sqd_adv_norm_update_rate = 1e-8
        self.lr = 1e-4
        self.train_batch_size = 2000
        self.num_sgd_iter = 1
        self.use_gae = False
        self.use_critic = False
        self.off_policy_estimation_methods = ["is", "wis"]

    def training(
        self,
        *,
        beta: Optional[float] = None,
        vf_coeff: Optional[float] = None,
        bc_logstd_coeff: Optional[float] = None,
        moving_average_sqd_adv_norm_start: Optional[float] = None,
        moving_average_sqd_adv_norm_update_rate: Optional[float] = None,
        **kwargs,
    ) -> "MARWILConfig":
        super().training(**kwargs)
        if beta is not None:
            self.beta = beta
        if vf_coeff is not None:
            self.vf_coeff = vf_coeff
        if bc_logstd_coeff is not None:
            self.bc_logstd_coeff = bc_logstd_coeff
        if moving_average_sqd_adv_norm_start is not None:
            self.moving_average_sqd_adv_norm_start = (
                moving_average_sqd_adv_norm_start
            )
        if moving_average_sqd_adv_norm_update_rate is not None:
            self.moving_average_sqd_adv_norm_update_rate = (
                moving_average_sqd_adv_norm_update_rate
            )
        return self

class BCConfig(MARWILConfig):
    """reference bc.py BCConfig: MARWIL with beta=0 (no advantage
    weighting, no value learning)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self.beta = 0.0
        self.vf_coeff = 0.0


class MARWILJaxPolicy(JaxPolicy):
    """reference marwil_torch_policy.py loss."""

    # loss never reads NEXT_OBS; don't ship a second obs column
    _ship_next_obs = False

    def _init_coeffs(self):
        self.coeff_values["ma_sqd_adv_norm"] = float(
            self.config.get("moving_average_sqd_adv_norm_start", 100.0)
        )

    def loss(self, params, batch, rng, coeffs):
        cfg = self.config
        beta = float(cfg.get("beta", 1.0))
        dist_inputs, values, _ = self.model_forward_train(params, batch)
        dist = self.dist_class(dist_inputs)
        logp = dist.logp(batch[SampleBatch.ACTIONS])

        stats = {}
        if beta != 0.0:
            returns = batch[SampleBatch.ADVANTAGES]
            adv = returns - values
            adv_sqd_mean = jnp.mean(jnp.square(adv))
            exp_advs = jax.lax.stop_gradient(
                jnp.exp(
                    beta
                    * (
                        adv
                        / (
                            1e-8
                            + jnp.sqrt(coeffs["ma_sqd_adv_norm"])
                        )
                    )
                )
            )
            v_loss = 0.5 * adv_sqd_mean
            stats["adv_sqd_mean"] = adv_sqd_mean
            stats["vf_loss"] = v_loss
        else:
            exp_advs = 1.0
            v_loss = 0.0

        p_loss = -jnp.mean(exp_advs * logp)
        total = p_loss + float(cfg.get("vf_coeff", 1.0)) * v_loss
        stats.update(
            policy_loss=p_loss,
            entropy=jnp.mean(dist.entropy()),
        )
        return total, stats

    def after_learn_on_batch(self, stats: Dict[str, float]) -> Dict:
        """Advance the moving-average squared-advantage normalizer
        (reference updates the torch buffer inside the loss; here the
        scalar rides the traced coeffs dict)."""
        if "adv_sqd_mean" in stats:
            rate = float(
                self.config.get(
                    "moving_average_sqd_adv_norm_update_rate", 1e-8
                )
            )
            cur = self.coeff_values["ma_sqd_adv_norm"]
            self.coeff_values["ma_sqd_adv_norm"] = cur + rate * (
                stats["adv_sqd_mean"] - cur
            )
            return {
                "moving_average_sqd_adv_norm": self.coeff_values[
                    "ma_sqd_adv_norm"
                ]
            }
        return {}

    def postprocess_trajectory(
        self, sample_batch, other_agent_batches=None, episode=None
    ):
        # ADVANTAGES := discounted cumulative rewards (no GAE/critic),
        # bootstrapped by V(last obs) on truncation.
        return compute_gae_for_sample_batch(
            self, sample_batch, other_agent_batches, episode
        )



class MARWIL(Algorithm):
    _default_policy_class = MARWILJaxPolicy

    @classmethod
    def get_default_config(cls) -> MARWILConfig:
        return MARWILConfig(cls)

    def setup(self, config: Dict) -> None:
        super().setup(config)
        from ray_tpu.offline.offline_ops import setup_offline_reader

        self._reader = setup_offline_reader(config)
        self._estimators = []
        if self._reader is not None:
            from ray_tpu.offline import (
                ImportanceSampling,
                WeightedImportanceSampling,
            )

            methods = config.get(
                "off_policy_estimation_methods", ["is", "wis"]
            )
            gamma = config.get("gamma", 0.99)
            pol = self.get_policy()
            if "is" in methods:
                self._estimators.append(ImportanceSampling(pol, gamma))
            if "wis" in methods:
                self._estimators.append(
                    WeightedImportanceSampling(pol, gamma)
                )

    def _next_offline_batch(self) -> SampleBatch:
        from ray_tpu.data.sample_batch import concat_samples

        target = int(self.config.get("train_batch_size", 2000))
        out, steps = [], 0
        policy = self.get_policy()
        while steps < target:
            b = self._reader.next()
            # A written line concatenates multiple episodes; discounted
            # returns must NOT leak across their boundaries, so
            # postprocess each episode separately.
            for ep in b.split_by_episode():
                ep = policy.postprocess_trajectory(ep)
                out.append(ep)
                steps += ep.count
        return concat_samples(out)

    def training_step(self) -> Dict:
        if self._reader is not None:
            train_batch = self._next_offline_batch()
        else:
            from ray_tpu.execution.rollout_ops import (
                synchronous_parallel_sample,
            )

            train_batch = synchronous_parallel_sample(
                worker_set=self.workers,
                max_env_steps=self.config["train_batch_size"],
            )
        self._counters[NUM_ENV_STEPS_SAMPLED] += train_batch.env_steps()
        self._counters[NUM_AGENT_STEPS_SAMPLED] += (
            train_batch.env_steps()
        )
        info = train_one_step(self, train_batch)
        # Off-policy estimation of the learned policy vs the behavior
        # data (reference marwil.py wires "is"/"wis" estimators).
        if self._estimators:
            if isinstance(info, dict) and DEFAULT_POLICY_ID in info:
                sub = info[DEFAULT_POLICY_ID]
            else:
                sub = info
            batch = (
                train_batch
                if not hasattr(train_batch, "policy_batches")
                else train_batch.policy_batches[DEFAULT_POLICY_ID]
            )
            for est in self._estimators:
                name = type(est).__name__
                try:
                    sub[f"off_policy_estimation/{name}"] = est.estimate(
                        batch
                    )
                except Exception as e:
                    if not getattr(self, "_est_warned", False):
                        self._est_warned = True
                        import warnings

                        warnings.warn(
                            f"off-policy estimation ({name}) failed "
                            f"and is disabled for this run: {e!r} — "
                            "does the dataset carry ACTION_LOGP?"
                        )
        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            }
        )
        return info


class BC(MARWIL):
    @classmethod
    def get_default_config(cls) -> BCConfig:
        return BCConfig(cls)
