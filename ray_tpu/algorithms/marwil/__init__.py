from ray_tpu.algorithms.marwil.marwil import (
    BC,
    BCConfig,
    MARWIL,
    MARWILConfig,
    MARWILJaxPolicy,
)

__all__ = ["MARWIL", "MARWILConfig", "MARWILJaxPolicy", "BC", "BCConfig"]
