"""Algorithm: Trainable subclass owning WorkerSet(s) and the training loop.

Counterpart of the reference's ``rllib/algorithms/algorithm.py:134``
(``setup :312``, ``step :547``, ``evaluate :650``, ``training_step :841``,
``save_checkpoint :1438``, ``__getstate__ :2186``).
"""

from __future__ import annotations

import collections
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Type

import numpy as np

import ray_tpu as ray
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID
from ray_tpu.env.registry import get_env_creator
from ray_tpu.evaluation.metrics import summarize_episodes
from ray_tpu.evaluation.worker_set import WorkerSet
from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.tune.trainable import Trainable

NUM_ENV_STEPS_SAMPLED = "num_env_steps_sampled"
NUM_AGENT_STEPS_SAMPLED = "num_agent_steps_sampled"


class Algorithm(Trainable):
    _default_policy_class = None

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return AlgorithmConfig(cls)

    def __init__(self, config=None, env=None, logger_creator=None, **kwargs):
        if isinstance(config, AlgorithmConfig):
            config = config.to_dict()
        config = dict(config or {})
        if env is not None:
            config.setdefault("env", env)
        defaults = self.get_default_config().to_dict()
        merged = {**defaults, **config}
        super().__init__(merged, logger_creator)

    def get_default_policy_class(self, config: Dict):
        return self._default_policy_class

    # -- setup -----------------------------------------------------------

    def setup(self, config: Dict) -> None:
        """reference algorithm.py:312."""
        self.callbacks = None
        cb_cls = config.get("callbacks_class")
        if cb_cls:
            self.callbacks = cb_cls()
        self._counters: Dict[str, int] = collections.defaultdict(int)
        self._timers: Dict[str, float] = collections.defaultdict(float)
        self._episode_history: List = []
        # run telemetry (docs/observability.md): activate BEFORE the
        # WorkerSet exists so the very first remote submission already
        # carries trace context; None when the config leaves it off
        from ray_tpu import telemetry as telemetry_lib

        self._telemetry = telemetry_lib.init_from_config(config)
        # iteration start stamps, for export_timeline(last_n=...)
        self._iteration_marks: collections.deque = collections.deque(
            maxlen=1024
        )
        # optional jax.profiler capture of the first N iterations
        # (telemetry(profile_iters=N); no-op fallback where the
        # profiler is unavailable — and numerics-neutral either way,
        # bit-parity-tested against telemetry off)
        tc = config.get("telemetry_config") or {}
        self._profile_iters = int(tc.get("profile_iters", 0) or 0)
        self._profiling = False
        # resilience layer (docs/resilience.md): the driver-side chaos
        # injector (None when inert) and the recovery manager step()
        # consults on failure — always present, inert until the config
        # arms it via AlgorithmConfig.fault_tolerance(...)
        from ray_tpu.resilience import faults as faults_lib
        from ray_tpu.resilience.recovery import RecoveryManager

        self._fault_injector = faults_lib.from_config(config)
        self._recovery = RecoveryManager(self)

        env_spec = config.get("env")
        env_creator = get_env_creator(env_spec) if env_spec else None
        policy_cls = self.get_default_policy_class(config)

        # Multi-controller (DCN) bring-up: when RAY_TPU_COORDINATOR is
        # set, every host running this same script joins the jax
        # distributed runtime FIRST, so the learner mesh below spans
        # all hosts' devices and gradient pmean rides ICI within a host
        # and DCN across (reference: torch.distributed init in
        # train/torch/config.py:83 / NCCL group setup).
        from ray_tpu.parallel import distributed as dist_lib

        dist_lib.initialize()

        # learner mesh (driver-side policies): built through the
        # backend the config selects — the sharding runtime's
        # ("batch",) mesh by default, the legacy ("data",) mesh for
        # the pmap fallback (docs/sharding.md)
        n_learner = config.get("learner_devices")
        import jax

        from ray_tpu import sharding as sharding_lib

        # sharding(hosts=N) — the multi-host learner fleet
        # (docs/fleet.md): the mesh spans the GLOBAL device view of
        # the N-process jax.distributed runtime just joined above;
        # strict resolution fails fast when the runtime geometry and
        # the config promise disagree
        hosts = sharding_lib.resolve_hosts(config, strict=True)
        devices = jax.devices()
        if n_learner:
            if hosts > 1:
                raise ValueError(
                    "learner_devices cannot trim a multi-host mesh "
                    f"(hosts={hosts}): every process's devices "
                    "participate; shrink the fleet by host instead"
                )
            devices = devices[:n_learner]
        if config.get("sharding_backend", "mesh") == "pmap":
            if hosts > 1:
                raise ValueError(
                    "sharding(hosts=N) requires the 'mesh' backend — "
                    "the pmap path is single-process only"
                )
            config["_mesh"] = mesh_lib.make_mesh(devices=devices)
        else:
            # model_parallel (docs/sharding.md): a 2-D (data x model)
            # mesh — params of rule-declaring models split across M
            # shards instead of replicating on every device
            mp = sharding_lib.resolve_model_parallel(
                config, devices, strict=True
            )
            if mp:
                config["_mesh"] = sharding_lib.get_mesh(
                    devices=devices,
                    axis_shapes=[
                        ("batch", len(devices) // mp),
                        ("model", mp),
                    ],
                )
            else:
                config["_mesh"] = sharding_lib.get_mesh(
                    devices=devices
                )

        policy_specs = None
        policy_mapping_fn = config.get("policy_mapping_fn")
        if config.get("policies"):
            policy_specs = {}
            for pid, spec in config["policies"].items():
                if isinstance(spec, (tuple, list)):
                    cls, obs_sp, act_sp, overrides = spec
                    policy_specs[pid] = (
                        cls or policy_cls,
                        obs_sp,
                        act_sp,
                        overrides or {},
                    )
                else:
                    probe = env_creator(
                        config.get("env_config") or {}
                    )
                    policy_specs[pid] = (
                        policy_cls,
                        probe.observation_space,
                        probe.action_space,
                        {},
                    )

        self.workers = WorkerSet(
            env_creator=env_creator,
            policy_cls=policy_cls,
            policy_specs=policy_specs,
            policy_mapping_fn=policy_mapping_fn,
            config=config,
            num_workers=int(config.get("num_workers", 0)),
        )
        # non-worker episode sources (the device rollout lane's
        # engine, drained fleet workers): callables returning
        # RolloutMetrics lists, read by _collect_rollout_metrics
        self._extra_metric_sources: List[Callable] = []
        # elastic fleet (docs/resilience.md "elastic fleets &
        # preemption"): the FleetController's monitor thread is owned
        # HERE — daemonized at setup, stop()-joined at cleanup — and
        # its fleet mutations apply only through reconcile() on the
        # driver thread between training-step rounds
        self._fleet = None
        if config.get("elastic") and int(
            config.get("num_workers", 0)
        ) > 0:
            from ray_tpu.autoscaler.fleet import FleetController

            self._fleet = FleetController(self, self.workers, config)
            self._extra_metric_sources.append(
                self._fleet.take_drained_metrics
            )
        # continuous checkpoint streaming (resilience/streamer.py):
        # background param/opt-state snapshots every few supersteps,
        # bounding work-lost-on-driver-crash to ~1 superstep
        self._ckpt_streamer = None
        if config.get("checkpoint_streaming"):
            from ray_tpu.resilience.streamer import CheckpointStreamer

            root = config.get("checkpoint_root") or os.path.join(
                self.logdir, "resilience"
            )
            self._ckpt_streamer = CheckpointStreamer(
                self,
                CheckpointStreamer.stream_root(root),
                every=int(
                    config.get("checkpoint_stream_interval", 1) or 1
                ),
            )
        self.evaluation_workers: Optional[WorkerSet] = None
        if config.get("evaluation_interval"):
            eval_config = {
                **config,
                **(config.get("evaluation_config") or {}),
                "num_workers": 0,
                # Never mirror evaluation rollouts into the offline
                # dataset — they come from a different (often
                # deterministic) distribution than training samples.
                "output": (config.get("evaluation_config") or {}).get(
                    "output"
                ),
                # Nor re-run an input factory (a PolicyServerInput
                # would try to bind the same port twice).
                "input": (config.get("evaluation_config") or {}).get(
                    "input"
                ),
            }
            self.evaluation_workers = WorkerSet(
                env_creator=env_creator,
                policy_cls=policy_cls,
                policy_specs=policy_specs,
                policy_mapping_fn=policy_mapping_fn,
                config=eval_config,
                num_workers=int(
                    config.get("evaluation_num_workers", 0)
                ),
            )
        # the compiled-program registry (sharding/registry.py): every
        # executable this config lowers, predicted up-front — AOT
        # pre-seeding, warmup and dispatch-diet coverage all walk this
        # one list (tests/test_dispatch_diet.py asserts completeness).
        # With an AOT cache configured, sweep the warmable specs now so
        # a restarted driver seeds its executables before train().
        from ray_tpu.sharding import registry as registry_lib

        self.program_registry = registry_lib.for_algorithm(self)
        if config.get("aot_cache_dir"):
            self.program_registry.sweep()

    # -- training iteration ---------------------------------------------

    def training_step(self) -> Dict:
        """Override point (reference algorithm.py:841)."""
        raise NotImplementedError

    def _replay_tree_plane(self) -> str:
        """Which prioritized-replay tree implementation serves this
        run's draws: "device" | "host" (one plane), "mixed" (multiple
        buffers disagree — e.g. a spilled shard), or "none" (no
        prioritized buffer in play)."""
        planes = set()
        for shard in getattr(self, "replay_shards", None) or ():
            plane = getattr(shard, "tree_plane", None)
            if plane:
                planes.add(plane)
        buf = getattr(self, "local_replay_buffer", None)
        for b in (getattr(buf, "buffers", None) or {}).values():
            plane = getattr(b, "tree_plane", None)
            if plane:
                planes.add(plane)
        if not planes:
            return "none"
        if len(planes) == 1:
            return planes.pop()
        return "mixed"

    def step(self) -> Dict:
        """reference algorithm.py:547 (incl. worker-failure handling)."""
        from ray_tpu import telemetry as telemetry_lib
        from ray_tpu.util import tracing

        config = self.config
        t0 = time.time()
        self._iteration_marks.append(t0)
        learn_before = telemetry_lib.metrics.learn_steps_total()
        superstep_before = telemetry_lib.metrics.counter_total(
            telemetry_lib.metrics.SUPERSTEP_UPDATES_TOTAL
        )
        h2d_before = telemetry_lib.metrics.h2d_bytes_by_path()
        d2h_before = telemetry_lib.metrics.d2h_bytes_by_path()
        results: Dict[str, Any] = {}
        train_info: Dict[str, Any] = {}
        min_t = config.get("min_time_s_per_iteration")
        min_ts = config.get("min_sample_timesteps_per_iteration") or 0
        ts_before = self._counters[NUM_ENV_STEPS_SAMPLED]
        self._recovery.begin_iteration()
        self._maybe_start_profile()
        # the iteration span is the driver-side root every remote
        # submission in this iteration parents under
        with tracing.start_span(
            "train:iteration", iteration=self._iteration + 1
        ):
            while True:
                try:
                    info = self.training_step()
                    if info:
                        train_info = info
                except Exception as e:
                    # resilience protocol (docs/resilience.md): worker
                    # death → bounded probe + recreate + degraded
                    # continue (per the recreate/ignore flags);
                    # restartable driver failure → restore the latest
                    # periodic checkpoint or stream tail; anything
                    # unhandled — or beyond the max_failures budget —
                    # propagates
                    if not self._recovery.handle_failure(e):
                        raise
                    continue
                # elastic fleet + checkpoint stream hooks run BETWEEN
                # training-step rounds — the only point where the
                # WorkerSet may change shape, and the superstep
                # boundary the stream snapshots ride
                if self._fleet is not None:
                    self._fleet.reconcile()
                if self._ckpt_streamer is not None:
                    self._ckpt_streamer.offer()
                done_t = (
                    min_t is None or (time.time() - t0) >= min_t
                )
                done_ts = (
                    self._counters[NUM_ENV_STEPS_SAMPLED] - ts_before
                    >= min_ts
                )
                if done_t and done_ts:
                    break
            # periodic checkpoint cadence (inside the iteration span,
            # so its recovery:checkpoint span lands in this
            # iteration's telemetry window)
            self._recovery.maybe_checkpoint()
        t_train_end = time.time()
        self._maybe_stop_profile()

        results["info"] = {
            "learner": train_info,
            **{k: v for k, v in self._counters.items()},
        }
        # per-stage learner timers (device transfer / compile / step,
        # Policy.last_learn_timers) — sharding-backend A/Bs read these
        # straight from train() results instead of a profiler
        learn_timers: Dict[str, Dict[str, float]] = {}
        lw = self.workers.local_worker()
        for pid, pol in (getattr(lw, "policy_map", None) or {}).items():
            t = getattr(pol, "last_learn_timers", None)
            if t:
                learn_timers[pid] = dict(t)
        if learn_timers:
            results["info"]["timers"] = learn_timers
        # resilience roll-up: restart/recovery/skip counts + time lost
        # to recovery this iteration (span-derived recovery_s appears
        # in info/telemetry too when tracing runs); with an elastic
        # fleet / checkpoint stream running, their per-iteration state
        # rides along under info/recovery/fleet and .../stream
        recovery_info = self._recovery.stats()
        if self._fleet is not None:
            recovery_info["fleet"] = self._fleet.stats()
        if self._ckpt_streamer is not None:
            recovery_info["stream"] = self._ckpt_streamer.stats()
        results["info"]["recovery"] = recovery_info
        # per-iteration telemetry roll-up: throughput gauges always
        # (they're process-local and near-free), the span-derived
        # stage times + overlap fraction only when tracing runs
        throughput = telemetry_lib.metrics.record_iteration_throughput(
            # max(0): a mid-iteration checkpoint restore can rewind
            # the sampled-steps counter below its iteration-start value
            env_steps=float(
                max(
                    0,
                    self._counters[NUM_ENV_STEPS_SAMPLED] - ts_before,
                )
            ),
            learn_steps=(
                telemetry_lib.metrics.learn_steps_total()
                - learn_before
            ),
            wall_s=t_train_end - t0,
        )
        runtime_vals = telemetry_lib.metrics.sample_runtime_gauges()
        # compiled-program ledger (docs/observability.md "device
        # ledger"): per-program FLOPs / HBM bytes / execution counts /
        # MFU / recompile causes, in every result while the ledger runs
        if telemetry_lib.device.enabled():
            results["info"]["device_ledger"] = (
                telemetry_lib.device.snapshot()
            )
        if tracing.is_enabled():
            # roll up THIS iteration's window first: worker rollout
            # spans ride the result messages and are harvested (→
            # recorded driver-side) within the same iteration that
            # consumes their batches, so blanket-deferring the window
            # an iteration (the old behavior) threw away data it
            # already had — the synchronous path never needs the lag.
            # Only when the pipelined path's sampling for this window
            # is still in flight at the edge (no sample span landed in
            # it yet) fall back to the previous, now-settled window —
            # `window_iterations_ago` says which one this is.
            spans = tracing.get_spans()
            # late-harvest accounting (fleetview satellite): a span
            # first seen THIS iteration whose interval ended before a
            # window opened missed that window's roll-up entirely —
            # credit its full duration to the window we report now
            # instead of dropping it (late_stage_times)
            seen = getattr(self, "_rollup_seen_span_ids", frozenset())
            fresh = [
                s for s in spans if s.get("span_id") not in seen
            ]
            self._rollup_seen_span_ids = frozenset(
                s.get("span_id") for s in spans
            )
            # spans from before the first window ever rolled up (worker
            # init, compile warmup) belong to NO window — not late
            first = getattr(self, "_first_window_start", None)
            if first is None:
                self._first_window_start = first = t0

            def _late_for(window_start):
                out = []
                for s in fresh:
                    end = s.get("end") or s.get("start")
                    if end is None:
                        continue
                    if first <= end <= window_start:
                        out.append(s)
                return out

            rollup = telemetry_lib.iteration_rollup(
                spans, t0, t_train_end, late=_late_for(t0)
            )
            lag = 0
            prev = getattr(self, "_prev_iter_window", None)
            if rollup["sample_s"] == 0.0 and prev is not None:
                settled = telemetry_lib.iteration_rollup(
                    spans, *prev, late=_late_for(prev[0])
                )
                if settled["sample_s"] > 0.0:
                    rollup, lag = settled, 1
            rollup["window_iterations_ago"] = lag
            # per-iteration H2D bytes by path (docs/data_plane.md):
            # feeder/learn/replay_insert deltas next to the stage busy
            # times — the byte diet of device-resident replay is read
            # directly off `learn` (≈0) vs `replay_insert` here
            h2d_after = telemetry_lib.metrics.h2d_bytes_by_path()
            h2d = {
                p: h2d_after.get(p, 0.0) - h2d_before.get(p, 0.0)
                for p in set(h2d_after) | set(h2d_before)
            }
            d2h_after = telemetry_lib.metrics.d2h_bytes_by_path()
            d2h = {
                p: d2h_after.get(p, 0.0) - d2h_before.get(p, 0.0)
                for p in set(d2h_after) | set(d2h_before)
            }
            learn_delta = (
                telemetry_lib.metrics.learn_steps_total()
                - learn_before
            )
            superstep_delta = (
                telemetry_lib.metrics.counter_total(
                    telemetry_lib.metrics.SUPERSTEP_UPDATES_TOTAL
                )
                - superstep_before
            )
            env_steps_iter = float(
                max(
                    0,
                    self._counters[NUM_ENV_STEPS_SAMPLED] - ts_before,
                )
            )
            backend = config.get("env_backend", "actor")
            results["info"]["telemetry"] = {
                **rollup,
                **throughput,
                **runtime_vals,
                "h2d_bytes": {**h2d, "total": sum(h2d.values())},
                # which rollout lane produced this iteration's samples
                # and what it cost over the wire (docs/pipeline.md):
                # the jax lane's bytes are its key stacks (path
                # "rollout", ≈0); the actor lane's rollout batches
                # cross on the feeder/learn paths
                "rollout_lane": {
                    "backend": backend,
                    "env_steps": env_steps_iter,
                    "h2d_bytes": (
                        h2d.get("rollout", 0.0)
                        if backend == "jax"
                        else h2d.get("feeder", 0.0)
                        + h2d.get("learn", 0.0)
                    ),
                },
                # prioritized-replay plane (docs/data_plane.md
                # "device sum tree"): which tree implementation served
                # this iteration's draws, the sample path's H2D
                # payload (0 under the device tree — only the
                # generator's raw uniform stream crosses, reported
                # apart), and the PER refresh's remaining D2H (the
                # |td| pull that feeds the host alpha-power)
                "replay": {
                    "tree": self._replay_tree_plane(),
                    "sample_h2d_bytes": h2d.get("replay_sample", 0.0),
                    "rng_h2d_bytes": h2d.get("replay_rng", 0.0),
                    "d2h_bytes": d2h.get("replay_priorities", 0.0),
                },
                # superstep contract (docs/data_plane.md): how many of
                # this iteration's learner updates rode a fused
                # K-per-dispatch program
                "superstep": {
                    "updates": superstep_delta,
                    "learn_steps": learn_delta,
                    "fused_fraction": (
                        superstep_delta / learn_delta
                        if learn_delta
                        else 0.0
                    ),
                },
            }
        self._prev_iter_window = (t0, t_train_end)
        results.update(self._collect_rollout_metrics())
        from ray_tpu.execution.train_ops import (
            NUM_ENV_STEPS_TRAINED as _TRAINED,
        )

        results[_TRAINED] = self._counters[_TRAINED]
        results["num_env_steps_sampled"] = self._counters[
            NUM_ENV_STEPS_SAMPLED
        ]
        results["timesteps_total"] = self._counters[NUM_ENV_STEPS_SAMPLED]
        self._timesteps_total = self._counters[NUM_ENV_STEPS_SAMPLED]

        if (
            self.evaluation_workers is not None
            and self.config.get("evaluation_interval")
            and (self._iteration + 1)
            % self.config["evaluation_interval"]
            == 0
        ):
            results["evaluation"] = self.evaluate()
        # feed the dashboard-lite results ring (reference: the tune/job
        # dashboard modules read equivalent state from the GCS)
        try:
            from ray_tpu.dashboard import publish_result

            publish_result(
                {"training_iteration": self._iteration + 1, **results}
            )
        except Exception:
            pass
        if self.callbacks is not None:
            self.callbacks.on_train_result(
                algorithm=self, result=results
            )
        return results

    def _maybe_start_profile(self) -> None:
        """Begin the ``telemetry(profile_iters=N)`` capture on the
        first iteration: ``jax.profiler.start_trace`` into
        ``<logdir>/jax_profile`` when the profiler is available, a
        silent no-op otherwise (the capture must never change what the
        run computes — bit-parity-tested)."""
        if self._profile_iters <= 0 or self._profiling:
            return
        try:
            import jax.profiler

            path = os.path.join(self.logdir, "jax_profile")
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            self._profiling = True
        except Exception:
            # unavailable/unsupported backend: disarm instead of
            # retrying every iteration
            self._profile_iters = 0

    def _maybe_stop_profile(self) -> None:
        if not self._profiling:
            return
        self._profile_iters -= 1
        if self._profile_iters > 0:
            return
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception:
            pass
        self._profiling = False

    def on_recovery(self, kind: str) -> None:
        """Hook: the RecoveryManager just absorbed a failure of
        ``kind`` (``"workers"`` or ``"restore"``). Subclasses rebuild
        whatever driver-side machinery the failure invalidated (PPO:
        the sample pipeline; IMPALA: the learner thread)."""

    def on_fleet_change(self, added: List, removed: List) -> None:
        """Hook: the FleetController just changed the fleet —
        ``added`` workers joined (already weight+filter-synced),
        ``removed`` drained out. Subclasses wire joiners into (and
        drained workers out of) whatever persistent sampling machinery
        they run (PPO: the prefetch pipeline's request manager; IMPALA:
        the sampler rotation). The synchronous paths need nothing:
        they re-read ``workers.remote_workers()`` every round."""

    def _collect_rollout_metrics(self) -> Dict:
        episodes = []
        if self.workers.num_remote_workers() > 0:
            for eps in ray.get(
                [
                    w.get_metrics.remote()
                    for w in self.workers.remote_workers()
                ]
            ):
                episodes.extend(eps)
        lw = self.workers.local_worker()
        if lw is not None:
            episodes.extend(lw.get_metrics())
        # non-worker episode sources (the device rollout lane's
        # engine): callables returning RolloutMetrics lists
        for src in getattr(self, "_extra_metric_sources", ()):
            episodes.extend(src())
        # smooth over a sliding window (reference metrics smoothing)
        self._episode_history.extend(episodes)
        window = self.config.get(
            "metrics_num_episodes_for_smoothing", 100
        )
        self._episode_history = self._episode_history[-window:]
        summary = summarize_episodes(
            self._episode_history if self._episode_history else []
        )
        summary["episodes_this_iter"] = len(episodes)
        self._episodes_total += len(episodes)
        summary["episodes_total"] = self._episodes_total
        return summary

    # -- evaluation ------------------------------------------------------

    def evaluate(self) -> Dict:
        """reference algorithm.py:650 — fans out across the evaluation
        workers when ``evaluation_num_workers > 0``; weights AND
        observation-filter statistics sync to every eval worker first
        (stale MeanStd stats under-report the policy)."""
        assert self.evaluation_workers is not None
        weights = self.workers.local_worker().get_weights()
        filters = self.workers.local_worker().get_filters()
        lw = self.evaluation_workers.local_worker()
        lw.set_weights(weights)
        lw.sync_filters(filters)
        remote = self.evaluation_workers.remote_workers()
        if remote:
            weights_ref = ray.put(weights)
            ray.get(
                [w.set_weights.remote(weights_ref) for w in remote]
                + [w.sync_filters.remote(filters) for w in remote]
            )
        duration = self.config.get("evaluation_duration", 10)
        episodes = []
        if remote:
            # Round-robin sample rounds across the eval fleet until we
            # have the requested number of episodes.
            while len(episodes) < duration:
                ray.get([w.sample.remote() for w in remote])
                for eps in ray.get(
                    [w.get_metrics.remote() for w in remote]
                ):
                    episodes.extend(eps)
        else:
            while len(episodes) < duration:
                lw.sample()
                episodes.extend(lw.get_metrics())
        return summarize_episodes(episodes)

    def export_timeline(
        self, path: str, last_n: Optional[int] = None
    ) -> str:
        """Write the chrome://tracing JSON of the run's recorded spans
        (telemetry must be on with ``trace=True`` — or
        ``RAY_TPU_TRACE=1`` — or the file holds whatever little was
        recorded). ``last_n`` keeps only the last N train iterations,
        bounded by the span buffer (``RAY_TPU_TRACE_BUFFER``). Load at
        chrome://tracing or https://ui.perfetto.dev."""
        from ray_tpu.util import tracing

        since = None
        marks = getattr(self, "_iteration_marks", None)
        if last_n and marks:
            since = marks[-min(int(last_n), len(marks))]
        return tracing.export_chrome_trace(path, since=since)

    def compute_single_action(
        self, observation, state=None, policy_id=DEFAULT_POLICY_ID,
        explore: Optional[bool] = None, **kwargs,
    ):
        """reference algorithm.py compute_single_action."""
        policy = self.get_policy(policy_id)
        worker = self.workers.local_worker()
        if worker.preprocessor is not None:
            observation = worker.preprocessor.transform(observation)
        filt = worker.filters.get(policy_id)
        if filt is not None:
            observation = filt(observation, update=False)
        explore = (
            self.config.get("explore", True)
            if explore is None
            else explore
        )
        action, state_out, _ = policy.compute_single_action(
            observation, state, explore=explore
        )
        if state:
            return action, state_out, {}
        return action

    def get_policy(self, policy_id: str = DEFAULT_POLICY_ID):
        return self.workers.local_worker().policy_map[policy_id]

    # -- checkpointing ---------------------------------------------------

    def __getstate__(self) -> Dict:
        """reference algorithm.py:2186."""
        state = {
            "worker": self.workers.local_worker().save(),
            "counters": dict(self._counters),
            "episodes_total": self._episodes_total,
        }
        return state

    def __setstate__(self, state: Dict) -> None:
        self.workers.local_worker().restore(state["worker"])
        self._counters = collections.defaultdict(
            int, state.get("counters", {})
        )
        self._episodes_total = state.get("episodes_total", 0)
        # push restored weights to rollout workers
        self.workers.sync_weights()

    @staticmethod
    def _atomic_write(path: str, write_fn) -> None:
        """Delegate to the shared helper (``util.atomic_io``, the one
        RTA009-sanctioned implementation). Directory sync stays with
        the caller: ``save_checkpoint`` batches several files and
        issues ONE ``_fsync_dir`` at the end."""
        from ray_tpu.util.atomic_io import atomic_write

        atomic_write(path, write_fn, sync_dir=False)

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        """reference algorithm.py:1438. Alongside the state, a
        metadata file records the algorithm name and config so
        :meth:`from_checkpoint` can rebuild without the caller
        knowing either (reference checkpoint ``rllib_checkpoint.json``).
        Every file lands atomically (temp + ``os.replace``): a crash
        mid-save cannot corrupt an existing checkpoint, and the
        metadata file — written LAST — marks the checkpoint complete."""
        import json

        state = self.__getstate__()
        self._atomic_write(
            os.path.join(checkpoint_dir, "algorithm_state.pkl"),
            lambda f: pickle.dump(state, f),
        )
        from ray_tpu.core import serialization as _ser

        # cloudpickle (env creators etc.); runtime-injected keys
        # ("_mesh", ...) hold live device objects and are
        # rebuilt by setup(), so they stay out of the file
        config_blob = _ser.dumps(
            {
                k: v
                for k, v in self.config.items()
                if not k.startswith("_")
            }
        )
        self._atomic_write(
            os.path.join(checkpoint_dir, "algorithm_config.pkl"),
            lambda f: f.write(config_blob),
        )
        meta = {
            "type": "Algorithm",
            "algorithm_class": type(self).__name__,
            "algorithm_name": getattr(
                self, "_registry_name", None
            ) or type(self).__name__,
        }
        self._atomic_write(
            os.path.join(checkpoint_dir, "rllib_checkpoint.json"),
            lambda f: f.write(json.dumps(meta).encode()),
        )
        # fsync the DIRECTORY: the per-file fsync+replace above makes
        # each file's content durable, but the renames themselves live
        # in the directory inode — without this a host crash can leave
        # a directory whose entries still point at the old (or no)
        # files even though the data blocks hit disk
        self._fsync_dir(checkpoint_dir)
        self._prune_old_checkpoints(checkpoint_dir)
        return checkpoint_dir

    @staticmethod
    def _fsync_dir(path: str) -> None:
        from ray_tpu.util.atomic_io import fsync_dir

        fsync_dir(path)

    def _prune_old_checkpoints(self, checkpoint_dir: str) -> None:
        """Prune sibling ``checkpoint_*`` directories down to the
        newest ``keep_checkpoints_num`` (the reference knob). The one
        just written always survives; None/0 keeps everything."""
        keep = self.config.get("keep_checkpoints_num")
        if not keep or keep < 1:
            return
        import shutil

        current = os.path.abspath(checkpoint_dir)
        parent = os.path.dirname(current)
        try:
            siblings = sorted(
                os.path.join(parent, d)
                for d in os.listdir(parent)
                if d.startswith("checkpoint_")
                and os.path.isdir(os.path.join(parent, d))
            )
        except OSError:
            return
        # zero-padded names sort chronologically; newest last
        victims = [d for d in siblings if d != current][
            : max(0, len(siblings) - int(keep))
        ]
        for d in victims:
            shutil.rmtree(d, ignore_errors=True)
        if victims:
            self._fsync_dir(parent)

    @classmethod
    def from_checkpoint(cls, checkpoint_path: str) -> "Algorithm":
        """Rebuild a ready-to-run Algorithm from a checkpoint
        directory alone (reference ``Algorithm.from_checkpoint``,
        algorithm.py:315): the stored metadata names the algorithm,
        the stored config reconstructs it, and the state restores
        into it."""
        import json

        meta_path = os.path.join(
            checkpoint_path, "rllib_checkpoint.json"
        )
        algo_cls = cls
        if cls is Algorithm:
            if not os.path.exists(meta_path):
                raise ValueError(
                    f"{checkpoint_path!r} has no rllib_checkpoint.json;"
                    " call from_checkpoint on the concrete class or"
                    " re-save with this version"
                )
            with open(meta_path) as f:
                meta = json.load(f)
            from ray_tpu.algorithms.registry import (
                get_algorithm_class,
            )

            algo_cls = get_algorithm_class(meta["algorithm_name"])
        from ray_tpu.core import serialization as _ser

        with open(
            os.path.join(checkpoint_path, "algorithm_config.pkl"), "rb"
        ) as f:
            config = _ser.loads(f.read())
        algo = algo_cls(config=config)
        algo.load_checkpoint(checkpoint_path)
        return algo

    def load_checkpoint(self, checkpoint_path: str) -> None:
        if os.path.isdir(checkpoint_path):
            checkpoint_path = os.path.join(
                checkpoint_path, "algorithm_state.pkl"
            )
        with open(checkpoint_path, "rb") as f:
            state = pickle.load(f)
        self.__setstate__(state)

    def export_policy_model(
        self, export_dir: str, policy_id: str = DEFAULT_POLICY_ID
    ) -> None:
        self.get_policy(policy_id).export_checkpoint(export_dir)

    def cleanup(self) -> None:
        # an interrupted profile_iters capture must not leak an open
        # jax.profiler session into the next run in this process
        if getattr(self, "_profiling", False):
            self._profile_iters = 0
            self._maybe_stop_profile()
        # the fleet monitor observes the WorkerSet: stop (and join) it
        # before the workers it watches go away
        if getattr(self, "_fleet", None) is not None:
            self._fleet.stop()
        if getattr(self, "_ckpt_streamer", None) is not None:
            self._ckpt_streamer.stop()
        if hasattr(self, "workers"):
            self.workers.stop()
        if getattr(self, "evaluation_workers", None) is not None:
            self.evaluation_workers.stop()
