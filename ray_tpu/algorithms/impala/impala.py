"""IMPALA: V-trace off-policy actor-learner.

Counterpart of the reference's ``rllib/algorithms/impala/impala.py``
(config ``:344`` make_learner_thread, ``training_step :614``, weight
broadcast ``:645``) and the V-trace torch policy
(``vtrace_torch_policy.py`` + ``vtrace_torch.py:127,251``).

TPU-first design:
  - rollout workers emit FIXED (T,)-length unrolls that may span episode
    boundaries (``_fixed_unrolls``); no zero-padding or seq-len machinery —
    dones inside the fragment drive the V-trace discount resets;
  - the learner thread consumes whole unroll batches and runs ONE jitted
    program: model forward over (B·T), V-trace associative scan, loss,
    gradient, optimizer;
  - sampling and learning overlap: the shared
    ``execution.parallel_requests.AsyncRequestsManager`` keeps every
    worker saturated with ``sample.remote`` calls and harvests them
    with ``ray.wait`` to feed the thread's queue, while weights
    broadcast back to the workers that produced each batch (reference
    impala.py:645 + parallel_requests.py).
"""

from __future__ import annotations

import queue
import time
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

import ray_tpu as ray
from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.execution.learner_thread import LearnerThread
from ray_tpu.execution.parallel_requests import AsyncRequestsManager
from ray_tpu.execution.train_ops import (
    NUM_AGENT_STEPS_TRAINED,
    NUM_ENV_STEPS_TRAINED,
)
from ray_tpu.ops.vtrace import vtrace_from_logits
from ray_tpu.policy.jax_policy import JaxPolicy


class IMPALAConfig(AlgorithmConfig):
    """reference impala.py ImpalaConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.lr = 0.0005
        self.rollout_fragment_length = 50
        self.train_batch_size = 500
        self.num_workers = 2
        self.vtrace = True
        self.vtrace_clip_rho_threshold = 1.0
        self.vtrace_clip_pg_rho_threshold = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.entropy_coeff_schedule = None
        self.grad_clip = 40.0
        self.broadcast_interval = 1
        self.learner_queue_size = 16
        self.max_sample_requests_in_flight_per_worker = 2
        self.min_time_s_per_iteration = 1
        # >0 routes sample refs through aggregation actors that concat
        # fragments to train batches off-driver (reference
        # impala.py:874 process_experiences_tree_aggregation)
        self.num_aggregation_workers = 0

    def training(
        self,
        *,
        vtrace: Optional[bool] = None,
        vtrace_clip_rho_threshold: Optional[float] = None,
        vtrace_clip_pg_rho_threshold: Optional[float] = None,
        vf_loss_coeff: Optional[float] = None,
        entropy_coeff: Optional[float] = None,
        entropy_coeff_schedule=None,
        broadcast_interval: Optional[int] = None,
        learner_queue_size: Optional[int] = None,
        max_sample_requests_in_flight_per_worker: Optional[int] = None,
        **kwargs,
    ) -> "IMPALAConfig":
        super().training(**kwargs)
        if vtrace is not None:
            self.vtrace = vtrace
        if vtrace_clip_rho_threshold is not None:
            self.vtrace_clip_rho_threshold = vtrace_clip_rho_threshold
        if vtrace_clip_pg_rho_threshold is not None:
            self.vtrace_clip_pg_rho_threshold = (
                vtrace_clip_pg_rho_threshold
            )
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        if entropy_coeff_schedule is not None:
            self.entropy_coeff_schedule = entropy_coeff_schedule
        if broadcast_interval is not None:
            self.broadcast_interval = broadcast_interval
        if learner_queue_size is not None:
            self.learner_queue_size = learner_queue_size
        if max_sample_requests_in_flight_per_worker is not None:
            self.max_sample_requests_in_flight_per_worker = (
                max_sample_requests_in_flight_per_worker
            )
        return self

    def aggregation(
        self, *, num_aggregation_workers: Optional[int] = None, **kwargs
    ) -> "IMPALAConfig":
        if num_aggregation_workers is not None:
            self.num_aggregation_workers = num_aggregation_workers
        return self


class ImpalaJaxPolicy(JaxPolicy):
    """V-trace policy-gradient loss over fixed (B, T) unrolls
    (reference vtrace_torch_policy.py VTraceLoss)."""

    def __init__(self, observation_space, action_space, config):
        config = dict(config)
        # One SGD pass over the whole unroll batch per learner step
        # (reference IMPALA semantics: minibatch_buffer, num_sgd_iter=1).
        T = int(config.get("rollout_fragment_length", 50))
        config.setdefault("num_sgd_iter", 1)
        config["sgd_minibatch_size"] = max(
            1, int(config.get("train_batch_size", 500)) // T
        )
        super().__init__(observation_space, action_space, config)
        self.unroll_len = T
        # IMPALA train rows are whole (T,)-fragments shaped by
        # _batch_to_train_tree; time-major handling lives in the loss
        # (_forward_unrolls), so the base class's flat-row unroll
        # chopping and T-multiple tiling must not apply.
        self._unroll_T = 1

    def _batch_to_train_tree(self, samples: SampleBatch) -> Dict[str, np.ndarray]:
        """Reshape flat rows → (num_unrolls, T, ...) + bootstrap obs."""
        T = self.unroll_len
        n = (samples.count // T) * T
        num = n // T

        def shape_col(v):
            v = np.asarray(v)[:n]
            return v.reshape((num, T) + v.shape[1:])

        from ray_tpu.ops.framestack import FRAME_IDX, FRAMES

        if FRAMES in samples:
            # worker-compressed fragments (compress_for_shipping):
            # ship the pool through; the (B, T+1) index column carries
            # obs AND the bootstrap stack (idx[-1]+1 by construction)
            idx = np.asarray(samples[FRAME_IDX], np.int32)[
                :n
            ].reshape(num, T)
            obs_cols = {
                FRAMES: np.asarray(samples[FRAMES]),
                FRAME_IDX: np.concatenate(
                    [idx, idx[:, -1:] + 1], axis=1
                ),
            }
        else:
            obs_cols = None
        out = {
            SampleBatch.ACTIONS: shape_col(samples[SampleBatch.ACTIONS]),
            SampleBatch.REWARDS: shape_col(
                samples[SampleBatch.REWARDS]
            ).astype(np.float32),
            SampleBatch.TERMINATEDS: shape_col(
                samples[SampleBatch.TERMINATEDS]
            ).astype(np.float32),
            # episode boundary of either kind (the reference's "dones"
            # drives both the V-trace discount and, for recurrent
            # models, the hidden-state reset)
            "dones": (
                shape_col(samples[SampleBatch.TERMINATEDS]).astype(
                    np.float32
                )
                + shape_col(
                    samples.get(
                        SampleBatch.TRUNCATEDS,
                        np.zeros(samples.count, np.float32),
                    )
                ).astype(np.float32)
            ).clip(max=1.0),
            SampleBatch.ACTION_LOGP: shape_col(
                samples[SampleBatch.ACTION_LOGP]
            ).astype(np.float32),
        }
        if obs_cols is not None:
            out.update(obs_cols)
            return out
        out[SampleBatch.OBS] = shape_col(samples[SampleBatch.OBS])
        out["bootstrap_obs"] = shape_col(
            samples[SampleBatch.NEXT_OBS]
        )[:, -1]
        return self._maybe_dedup_unroll_framestack(out)

    def _maybe_dedup_unroll_framestack(self, out):
        """Unroll-shaped variant of the base policy's framestack dedup:
        each (T,)-unroll plus its bootstrap obs is a sliding window of
        T + k frames (broken only at in-fragment episode resets, which
        the ``dones`` column marks), so the device transfer drops from
        (B, T+1) full k-stacks to ~(T + k) single frames per unroll.
        The (B, T+1) index column rebuilds OBS and bootstrap_obs on
        device (``_rebuild_obs_from_frames`` override)."""
        obs = out[SampleBatch.OBS]
        if (
            not self.config.get("dedup_framestack", True)
            or obs.ndim != 5
            or not 2 <= obs.shape[-1] <= 8
            or obs.nbytes
            < self.config.get("dedup_framestack_min_bytes", 1 << 20)
        ):
            return out
        from ray_tpu.ops.framestack import (
            FRAME_IDX,
            FRAMES,
            decompose_segmented_obs,
        )

        B, T = obs.shape[:2]
        ext = np.concatenate(
            [obs, out["bootstrap_obs"][:, None]], axis=1
        ).reshape((B * (T + 1),) + obs.shape[2:])
        seg = np.zeros(B * (T + 1), bool)
        seg[:: T + 1] = True  # each unroll starts a fresh window
        # the obs AFTER a done row is a reset obs (new window); the
        # bootstrap pseudo-row always slides (terminal next_obs does)
        dones = out["dones"][:, : T - 1] > 0
        seg.reshape(B, T + 1)[:, 1:T] |= dones
        dec = decompose_segmented_obs(ext, seg)
        if dec is None:
            return out
        stream, idx = dec
        out = dict(out)
        del out[SampleBatch.OBS]
        del out["bootstrap_obs"]
        out[FRAMES] = stream
        out[FRAME_IDX] = idx.reshape(B, T + 1)
        return out

    def _rebuild_obs_from_frames(self, frames, batch, stack_k):
        from ray_tpu.ops.framestack import FRAME_IDX, build_stacks

        batch = dict(batch)
        idx = batch.pop(FRAME_IDX)
        B, T1 = idx.shape
        stacks = build_stacks(frames, idx.reshape(-1), stack_k)
        stacks = stacks.reshape((B, T1) + stacks.shape[1:])
        batch[SampleBatch.OBS] = stacks[:, :-1]
        batch["bootstrap_obs"] = stacks[:, -1]
        return batch

    def _forward_unrolls(self, params, batch):
        """Forward the (B, T) fragment batch and its bootstrap obs in
        ONE pass over T+1 steps. Recurrent models run time-major with a
        zero fragment-start state and within-fragment resets driven by
        terminateds (dones already reset the V-trace discounts; this
        makes the hidden state agree). → (dist_inputs flattened over
        the T real steps, values (B, T), bootstrap_value (B,))."""
        obs = batch[SampleBatch.OBS]
        B, T = obs.shape[0], obs.shape[1]
        obs_ext = jnp.concatenate(
            [obs, batch["bootstrap_obs"][:, None]], axis=1
        )
        if self.model.is_recurrent:
            # episodes end by termination OR truncation; the hidden
            # state must reset at both (the rollout side did)
            dones = batch["dones"].astype(jnp.float32)
            resets = jnp.concatenate(
                [jnp.ones((B, 1), jnp.float32), dones], axis=1
            )
            state0 = self._zero_initial_state(obs_ext, B)
            dist_all, val_all, _ = self.model.apply(
                params, obs_ext, state0, resets=resets
            )
        else:
            flat = obs_ext.reshape((B * (T + 1),) + obs.shape[2:])
            dist_all, val_all, _ = self.model_forward(params, flat)
        dist_all = dist_all.reshape((B, T + 1) + dist_all.shape[1:])
        val_all = val_all.reshape(B, T + 1)
        dist_inputs = dist_all[:, :T].reshape(
            (B * T,) + dist_all.shape[2:]
        )
        return dist_inputs, val_all[:, :T], val_all[:, -1]

    def loss(self, params, batch, rng, coeffs):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        obs = batch[SampleBatch.OBS]
        B, T = obs.shape[0], obs.shape[1]

        dist_inputs, values, bootstrap_value = self._forward_unrolls(
            params, batch
        )
        values = values.reshape(B * T)
        dist = self.dist_class(dist_inputs)

        actions = batch[SampleBatch.ACTIONS]
        flat_actions = actions.reshape((B * T,) + actions.shape[2:])
        target_logp = dist.logp(flat_actions)
        entropy = dist.entropy()

        vtr = vtrace_from_logits(
            behaviour_action_log_probs=batch[SampleBatch.ACTION_LOGP],
            target_action_log_probs=target_logp.reshape(B, T),
            discounts=gamma * (1.0 - batch["dones"]),
            rewards=batch[SampleBatch.REWARDS],
            values=values.reshape(B, T),
            bootstrap_value=bootstrap_value,
            clip_rho_threshold=cfg.get("vtrace_clip_rho_threshold", 1.0),
            clip_pg_rho_threshold=cfg.get(
                "vtrace_clip_pg_rho_threshold", 1.0
            ),
        )
        pi_loss = -jnp.mean(
            vtr.pg_advantages * target_logp.reshape(B, T)
        )
        vf_loss = 0.5 * jnp.mean(
            jnp.square(vtr.vs - values.reshape(B, T))
        )
        entropy_mean = jnp.mean(entropy)
        total = (
            pi_loss
            + cfg.get("vf_loss_coeff", 0.5) * vf_loss
            - coeffs["entropy_coeff"] * entropy_mean
        )
        stats = {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy_mean,
            "vtrace_mean_rho_clip": jnp.mean(
                jnp.exp(
                    jnp.clip(
                        target_logp.reshape(B, T)
                        - batch[SampleBatch.ACTION_LOGP],
                        -10,
                        10,
                    )
                )
            ),
        }
        return total, stats


@ray.remote
class AggregatorWorker:
    """Off-driver batch concatenation (reference impala.py:946
    AggregatorWorker + execution/tree_agg.py): rollout fragments are
    routed here by reference and concatenated to full train batches in
    the aggregator's process, so the concat/copy work moves off the
    driver thread (on this single-host object plane the values still
    stage through driver shm; cross-node transfer is the DCN layer's
    job)."""

    def __init__(self, target_size: int):
        self.target_size = int(target_size)
        self._buf = []
        self._steps = 0

    def aggregate(self, batch):
        from ray_tpu.data.sample_batch import concat_samples

        self._buf.append(batch)
        self._steps += batch.env_steps()
        if self._steps < self.target_size:
            return None
        out = concat_samples(self._buf)
        self._buf = []
        self._steps = 0
        return out


class IMPALA(Algorithm):
    _default_policy_class = ImpalaJaxPolicy

    @classmethod
    def get_default_config(cls) -> IMPALAConfig:
        return IMPALAConfig(cls)

    def setup(self, config: Dict) -> None:
        config["_fixed_unrolls"] = True
        super().setup(config)
        # The learner thread publishes host weights every
        # broadcast_interval of ITS steps; the driver broadcasts the
        # published blob without ever touching the device (a driver-side
        # get_weights would both pull params through the TPU tunnel and
        # serialize against the learner's on-device program queue).
        self._learner_thread = LearnerThread(
            self.get_policy(),
            inqueue_size=config.get("learner_queue_size", 16),
            publish_weights_every=max(
                1, int(config.get("broadcast_interval", 1))
            ),
        )
        self._learner_thread.start()
        # fragment accumulator: feed the learner whole train batches
        # (reference impala.py:614 concatenates sample batches to
        # train_batch_size before the learner queue), halving dispatch
        # and prepare_batch counts vs per-fragment feeding
        self._frag_buf: list = []
        self._frag_steps = 0
        self._train_ready: list = []  # concat batches awaiting queue room
        # weight-broadcast bookkeeping: published version each worker has
        self._worker_weight_ver: Dict = {}
        self._weights_ref = None
        self._weights_ref_ver = -1
        n_agg = int(config.get("num_aggregation_workers", 0))
        self._aggregators = [
            AggregatorWorker.remote(config.get("train_batch_size", 500))
            for _ in range(n_agg)
        ]
        self._agg_rr = 0
        self._agg_in_flight: list = []
        # worker polling rides the shared AsyncRequestsManager
        # (reference parallel_requests.py feeding impala.py:614): refs
        # mode when aggregation actors consume the fragment refs
        # directly, values mode otherwise
        self._sample_manager = AsyncRequestsManager(
            self.workers.remote_workers(),
            max_remote_requests_in_flight_per_worker=int(
                config.get(
                    "max_sample_requests_in_flight_per_worker", 2
                )
            ),
            return_object_refs=bool(self._aggregators),
            name="impala_sampler",
        )
        # elastic fleet: drains pull workers out of this rotation and
        # the controller reads its in-flight counts for idleness
        if self._fleet is not None:
            self._fleet.register_manager(self._sample_manager)

    def on_fleet_change(self, added, removed) -> None:
        """Elastic fleet: joiners enter the sampler rotation
        immediately (training_step's heal-drift add would catch them a
        round later); drained workers were already retired from the
        manager by the FleetController — just drop their stale
        weight-version bookkeeping."""
        super().on_fleet_change(added, removed)
        mgr = getattr(self, "_sample_manager", None)
        if mgr is not None and added:
            mgr.add_workers(added)
        for w in removed:
            self._worker_weight_ver.pop(id(w), None)

    def on_recovery(self, kind: str) -> None:
        """After a checkpoint restore the old learner thread is dead
        (that is usually WHY the restore ran): rebuild it around the
        restored policy so the actor-learner loop can continue."""
        super().on_recovery(kind)
        if kind != "restore":
            return
        lt = getattr(self, "_learner_thread", None)
        if lt is not None and lt.is_alive():
            lt.stop()
        self._learner_thread = LearnerThread(
            self.get_policy(),
            inqueue_size=self.config.get("learner_queue_size", 16),
            publish_weights_every=max(
                1, int(self.config.get("broadcast_interval", 1))
            ),
        )
        self._learner_thread.start()

    def training_step(self) -> Dict:
        """reference impala.py:614."""
        workers = self.workers.remote_workers()
        lt = self._learner_thread
        if not lt.is_alive():
            # surface the thread's parked exception (an injected crash
            # or a real learner bug) — with restore_on_failure set,
            # Algorithm.step's recovery path restores the latest
            # checkpoint and on_recovery rebuilds the thread
            raise lt.error or RuntimeError("learner thread died")

        if not workers:
            # degenerate synchronous mode (num_workers=0, tests):
            # accumulate local samples to a full train batch
            from ray_tpu.data.sample_batch import concat_samples

            collected = []
            steps = 0
            target = self.config.get("train_batch_size", 500)
            while steps < target:
                b = self.workers.local_worker().sample()
                collected.append(b)
                steps += b.env_steps()
            batch = concat_samples(collected)
            self._counters[NUM_ENV_STEPS_SAMPLED] += batch.env_steps()
            lt.add_batch(batch)
        else:
            # drain buffered train batches FIRST so backpressure
            # clears as soon as the learner makes queue room
            while self._train_ready:
                if lt.add_batch(self._train_ready[0], block=False):
                    self._train_ready.pop(0)
                else:
                    break
            # keep each worker saturated with sample requests — unless
            # the learner is backed up (backpressure: stop asking for
            # fragments we'd only buffer on the driver)
            mgr = self._sample_manager
            # heal drift: workers recreated by Algorithm.step's generic
            # failure path join the rotation here (no-op for known ones)
            mgr.add_workers(workers)
            backlogged = len(self._train_ready) >= 4
            if not backlogged:
                mgr.submit_available()

            if mgr.in_flight():
                ready = mgr.get_ready(timeout=2.0)
            else:
                # fully backpressured: nothing in flight to wait on —
                # give the learner a beat instead of spinning
                time.sleep(0.05)
                ready = {}
            target = int(self.config.get("train_batch_size", 500))
            for w, items in ready.items():
                for item in items:
                    if self._aggregators:
                        # tree aggregation (refs mode): hand the
                        # fragment ref to an aggregation actor; the
                        # concat to a full train batch happens in ITS
                        # process, not the driver's. Marshalling
                        # happens synchronously at .remote(), so the
                        # fragment ref can be freed right after — and
                        # a crashed worker's errored ref re-raises
                        # here, which drops the worker like the value
                        # mode harvest does.
                        agg = self._aggregators[
                            self._agg_rr % len(self._aggregators)
                        ]
                        self._agg_rr += 1
                        try:
                            self._agg_in_flight.append(
                                agg.aggregate.remote(item)
                            )
                        except (
                            ray.core.object_store.RayActorError,
                            ray.core.object_store.WorkerCrashedError,
                            ray.core.object_store.RayTaskError,
                        ):
                            mgr.report_dead(w)
                            continue
                        finally:
                            ray.free([item])
                    else:
                        batch = item
                        self._counters[NUM_ENV_STEPS_SAMPLED] += (
                            batch.env_steps()
                        )
                        # accumulate fragments into whole train batches
                        # (reference impala.py:614 — the learner
                        # consumes train_batch_size, not fragments)
                        self._frag_buf.append(batch)
                        self._frag_steps += batch.env_steps()
                        if self._frag_steps >= target:
                            from ray_tpu.data.sample_batch import (
                                concat_samples,
                            )

                            self._train_ready.append(
                                concat_samples(self._frag_buf)
                            )
                            self._frag_buf = []
                            self._frag_steps = 0
                    # broadcast the learner-published weights back to
                    # the producer (reference
                    # update_workers_if_necessary, impala.py:645) —
                    # cheap: no device access here
                    self._maybe_broadcast(w)
                    if not backlogged:
                        mgr.submit(worker=w)
            self._handle_dead_workers(mgr)

            # feed complete train batches; keep what the queue won't take
            while self._train_ready:
                if lt.add_batch(self._train_ready[0], block=False):
                    self._train_ready.pop(0)
                else:
                    break

        # collect aggregated train batches (tree-aggregation mode)
        if self._agg_in_flight:
            ready_agg, _ = ray.wait(
                self._agg_in_flight,
                num_returns=len(self._agg_in_flight),
                timeout=0,
            )
            for r in ready_agg:
                self._agg_in_flight.remove(r)
                try:
                    agg_batch = ray.get(r)
                finally:
                    ray.free([r])
                if agg_batch is not None:
                    self._counters[NUM_ENV_STEPS_SAMPLED] += (
                        agg_batch.env_steps()
                    )
                    lt.add_batch(agg_batch, block=False)

        # drain learner results
        learner_info = {}
        while True:
            try:
                steps, info = lt.outqueue.get_nowait()
            except queue.Empty:
                break
            self._counters[NUM_ENV_STEPS_TRAINED] += steps
            self._counters[NUM_AGENT_STEPS_TRAINED] += steps
            learner_info = info
        if not learner_info:
            learner_info = lt.learner_info
        return {
            DEFAULT_POLICY_ID: learner_info,
            "learner_queue": lt.stats(),
            "sample_manager": self._sample_manager.stats(),
        }

    def _handle_dead_workers(self, mgr: AsyncRequestsManager) -> None:
        """Drop-and-report protocol for the async loop: a dead worker
        leaves the sampling rotation (the manager already stopped
        submitting to it); recreate replacements when configured, never
        abort the actor-learner loop."""
        dead = mgr.take_dead_workers()
        if not dead:
            return
        self._counters["num_dead_rollout_workers"] += len(dead)
        if self.config.get("recreate_failed_workers"):
            new = self.workers.replace_failed_workers(dead)
            mgr.add_workers(new)
        else:
            self.workers.remove_workers(dead)

    def _maybe_broadcast(self, w) -> None:
        """Ship the learner thread's latest published weights to worker
        ``w`` if it hasn't seen that version yet. One ``ray.put`` per
        version; ``set_weights.remote`` marshals synchronously, so the
        previous version's blob can be freed when superseded."""
        pub = self._learner_thread.published_weights()
        if pub is None:
            return
        ver, host_w = pub
        if self._worker_weight_ver.get(id(w), 0) >= ver:
            return
        if self._weights_ref_ver != ver:
            if self._weights_ref is not None:
                ray.free([self._weights_ref])
            self._weights_ref = ray.put(host_w)
            self._weights_ref_ver = ver
        w.set_weights.remote(
            self._weights_ref,
            {"timestep": self._counters[NUM_ENV_STEPS_SAMPLED]},
        )
        self._worker_weight_ver[id(w)] = ver

    def cleanup(self) -> None:
        if hasattr(self, "_learner_thread"):
            self._learner_thread.stop()
        if getattr(self, "_weights_ref", None) is not None:
            try:
                ray.free([self._weights_ref])
            except Exception:
                pass
            self._weights_ref = None
        for a in getattr(self, "_aggregators", []):
            try:
                ray.kill(a)
            except Exception:
                pass
        super().cleanup()
