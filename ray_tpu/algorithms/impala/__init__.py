from ray_tpu.algorithms.impala.impala import (
    IMPALA,
    IMPALAConfig,
    ImpalaJaxPolicy,
)

__all__ = ["IMPALA", "IMPALAConfig", "ImpalaJaxPolicy"]
