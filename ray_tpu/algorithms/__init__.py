from ray_tpu.algorithms.algorithm import Algorithm
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.algorithms.registry import get_algorithm_class, register_algorithm

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "get_algorithm_class",
    "register_algorithm",
]
