from ray_tpu.algorithms.cql.cql import CQL, CQLConfig, CQLJaxPolicy

__all__ = ["CQL", "CQLConfig", "CQLJaxPolicy"]
