"""CQL: Conservative Q-Learning for offline RL.

Counterpart of the reference's ``rllib/algorithms/cql/cql.py`` (config:
bc_iters, temperature, num_actions, min_q_weight, lagrangian) and
``cql_torch_policy.py`` (the entropy-version CQL penalty: logsumexp over
{uniform-random, current-policy, next-state-policy} action Q values with
importance correction, added to the SAC critic loss; BC-warmup actor for
the first ``bc_iters`` steps).

One jitted shard_map program per step, like SAC; the BC-warmup switch is
a traced select on a step counter carried in aux_state, so warmup→SAC
transition never recompiles."""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ray_tpu import sharding as sharding_lib

from ray_tpu.algorithms.marwil.marwil import MARWIL
from ray_tpu.algorithms.sac.sac import SAC, SACConfig, SACJaxPolicy
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.models.distributions import SquashedGaussian
from ray_tpu.policy.jax_policy import _tree_to_device


class CQLConfig(SACConfig):
    """reference cql.py CQLConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.bc_iters = 20000
        self.temperature = 1.0
        self.num_actions = 10
        self.min_q_weight = 5.0
        self.lagrangian = False
        self.num_steps_sampled_before_learning_starts = 0
        self.off_policy_estimation_methods = []

    def training(
        self,
        *,
        bc_iters: Optional[int] = None,
        temperature: Optional[float] = None,
        num_actions: Optional[int] = None,
        min_q_weight: Optional[float] = None,
        **kwargs,
    ) -> "CQLConfig":
        super().training(**kwargs)
        if bc_iters is not None:
            self.bc_iters = bc_iters
        if temperature is not None:
            self.temperature = temperature
        if num_actions is not None:
            self.num_actions = num_actions
        if min_q_weight is not None:
            self.min_q_weight = min_q_weight
        return self


class CQLJaxPolicy(SACJaxPolicy):
    """reference cql_torch_policy.py cql_loss."""

    def __init__(self, observation_space, action_space, config):
        super().__init__(observation_space, action_space, config)
        # step counter for the BC-warmup switch rides aux_state
        self.aux_state = dict(
            self.aux_state, step=jnp.zeros((), jnp.int32)
        )
        self.aux_state = _tree_to_device(
            self.aux_state, self._param_sharding
        )

    def _device_update_fn(self, batch_size=None, with_frames=False):
        """CQL's own single-update body: the generic superstep scans
        THIS (min-Q penalty included), so chained CQL updates fuse
        correctly — the legacy SAC stacked path never could."""
        actor, critic = self.actor, self.critic
        tx_a, tx_c, tx_al = (
            self._tx_actor,
            self._tx_critic,
            self._tx_alpha,
        )
        gamma, tau = self.gamma**self.n_step, self.tau
        target_entropy = self.target_entropy
        low, high = self.low, self.high
        mesh = self.mesh
        axis = sharding_lib.data_axis(mesh)
        cfg = self.config
        bc_iters = int(cfg.get("bc_iters", 20000))
        cql_temp = float(cfg.get("temperature", 1.0))
        num_actions = int(cfg.get("num_actions", 10))
        min_q_weight = float(cfg.get("min_q_weight", 5.0))
        act_dim = self.action_dim
        # log density of the uniform proposal over the action box:
        # (1/(high-low))^d (reference uses log(0.5^d) for [-1,1]).
        # Host math on static space bounds — computed once here, not
        # per trace inside the device body (RTA002).
        random_density = -float(act_dim) * np.log(high - low)

        def q_repeat(cp, obs, actions_rep):
            """Q for (B*num_actions) actions against repeated obs."""
            B = obs.shape[0]
            n_rep = actions_rep.shape[0] // B
            obs_rep = jnp.repeat(obs, n_rep, axis=0)
            q1, q2 = critic.apply(cp, obs_rep, actions_rep)
            return q1.reshape(B, n_rep), q2.reshape(B, n_rep)

        def device_fn(params, opt_state, aux, batch, rng, coeffs):
            obs = batch[SampleBatch.OBS].astype(jnp.float32)
            next_obs = batch[SampleBatch.NEXT_OBS].astype(jnp.float32)
            rewards = batch[SampleBatch.REWARDS].astype(jnp.float32)
            not_done = 1.0 - batch[SampleBatch.TERMINATEDS].astype(
                jnp.float32
            )
            actions = batch[SampleBatch.ACTIONS].astype(jnp.float32)
            B = obs.shape[0]
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
            rng_t, rng_a, rng_r, rng_c, rng_n = jax.random.split(rng, 5)
            alpha = jnp.exp(params["log_alpha"])

            # ---- critic TD target (reference cql_torch_policy: policy
            # next action, NO entropy term in the target) ----
            next_dist = SquashedGaussian(
                actor.apply(params["actor"], next_obs), low=low, high=high
            )
            next_a, _ = next_dist.sampled_action_logp(rng_t)
            tq1, tq2 = critic.apply(
                aux["target_critic"], next_obs, next_a
            )
            target_q = jnp.minimum(tq1, tq2)
            td_target = jax.lax.stop_gradient(
                rewards + gamma * not_done * target_q
            )

            # sampled actions for the conservative penalty
            rand_actions = jax.random.uniform(
                rng_r, (B * num_actions, act_dim), minval=low, maxval=high
            )
            cur_dist = SquashedGaussian(
                actor.apply(params["actor"], obs), low=low, high=high
            )

            def sample_repeat(dist, rng_k):
                rngs = jax.random.split(rng_k, num_actions)
                acts, logps = jax.vmap(
                    lambda r: dist.sampled_action_logp(r)
                )(rngs)  # (num_actions, B, act_dim), (num_actions, B)
                acts = jnp.swapaxes(acts, 0, 1).reshape(
                    B * num_actions, act_dim
                )
                logps = jnp.swapaxes(logps, 0, 1)  # (B, num_actions)
                return acts, logps

            cur_acts, cur_logp = sample_repeat(cur_dist, rng_c)
            next_acts, next_logp = sample_repeat(next_dist, rng_n)

            def critic_loss(cp):
                q1, q2 = critic.apply(cp, obs, actions)
                td1 = jnp.mean(jnp.square(q1 - td_target))
                td2 = jnp.mean(jnp.square(q2 - td_target))
                q1_rand, q2_rand = q_repeat(cp, obs, rand_actions)
                q1_cur, q2_cur = q_repeat(cp, obs, cur_acts)
                q1_next, q2_next = q_repeat(cp, obs, next_acts)
                stop = jax.lax.stop_gradient
                cat1 = jnp.concatenate(
                    [
                        q1_rand - random_density,
                        q1_next - stop(next_logp),
                        q1_cur - stop(cur_logp),
                    ],
                    axis=1,
                )
                cat2 = jnp.concatenate(
                    [
                        q2_rand - random_density,
                        q2_next - stop(next_logp),
                        q2_cur - stop(cur_logp),
                    ],
                    axis=1,
                )
                min_q1 = (
                    jax.nn.logsumexp(cat1 / cql_temp, axis=1).mean()
                    * min_q_weight
                    * cql_temp
                    - q1.mean() * min_q_weight
                )
                min_q2 = (
                    jax.nn.logsumexp(cat2 / cql_temp, axis=1).mean()
                    * min_q_weight
                    * cql_temp
                    - q2.mean() * min_q_weight
                )
                loss = td1 + td2 + min_q1 + min_q2
                return loss, (q1, td1 + td2, min_q1 + min_q2)

            (c_loss, (q1, td_loss, cql_pen)), c_grads = (
                jax.value_and_grad(critic_loss, has_aux=True)(
                    params["critic"]
                )
            )
            c_grads = jax.lax.pmean(c_grads, axis)
            c_upd, c_opt = tx_c.update(
                c_grads, opt_state["critic"], params["critic"]
            )
            new_critic = optax.apply_updates(params["critic"], c_upd)

            # ---- actor: BC warmup for bc_iters steps, then SAC ----
            in_warmup = aux["step"] < bc_iters

            def actor_loss(ap):
                dist = SquashedGaussian(
                    actor.apply(ap, obs), low=low, high=high
                )
                a_pi, logp_pi = dist.sampled_action_logp(rng_a)
                bc_logp = dist.logp(actions)
                aq1, aq2 = critic.apply(new_critic, obs, a_pi)
                sac_loss = jnp.mean(
                    alpha * logp_pi - jnp.minimum(aq1, aq2)
                )
                bc_loss = jnp.mean(alpha * logp_pi - bc_logp)
                return (
                    jnp.where(in_warmup, bc_loss, sac_loss),
                    logp_pi,
                )

            (a_loss, logp_pi), a_grads = jax.value_and_grad(
                actor_loss, has_aux=True
            )(params["actor"])
            a_grads = jax.lax.pmean(a_grads, axis)
            a_upd, a_opt = tx_a.update(
                a_grads, opt_state["actor"], params["actor"]
            )
            new_actor = optax.apply_updates(params["actor"], a_upd)

            # ---- alpha ----
            def alpha_loss(log_alpha):
                return -jnp.mean(
                    log_alpha
                    * jax.lax.stop_gradient(logp_pi + target_entropy)
                )

            al_loss, al_grad = jax.value_and_grad(alpha_loss)(
                params["log_alpha"]
            )
            al_grad = jax.lax.pmean(al_grad, axis)
            al_upd, al_opt = tx_al.update(
                al_grad, opt_state["log_alpha"], params["log_alpha"]
            )
            new_log_alpha = optax.apply_updates(
                params["log_alpha"], al_upd
            )

            new_target = jax.tree_util.tree_map(
                lambda t, o: (1.0 - tau) * t + tau * o,
                aux["target_critic"],
                new_critic,
            )
            new_params = {
                "actor": new_actor,
                "critic": new_critic,
                "log_alpha": new_log_alpha,
            }
            new_opt = {
                "actor": a_opt,
                "critic": c_opt,
                "log_alpha": al_opt,
            }
            new_aux = {
                "target_critic": new_target,
                "step": aux["step"] + 1,
            }
            stats = {
                "actor_loss": a_loss,
                "critic_loss": c_loss,
                "td_loss": td_loss,
                "cql_penalty": cql_pen,
                "alpha_value": alpha,
                "mean_q": jnp.mean(q1),
                "in_bc_warmup": in_warmup.astype(jnp.float32),
                "total_loss": a_loss + c_loss + al_loss,
            }
            stats = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, axis), stats
            )
            return new_params, new_opt, new_aux, stats

        return device_fn


class CQL(SAC):
    """Offline training loop: batches come from the JsonReader (or the
    replay buffer when trained online — reference cql.py keeps SAC's
    training_step and swaps the input)."""

    _default_policy_class = CQLJaxPolicy

    @classmethod
    def get_default_config(cls) -> CQLConfig:
        return CQLConfig(cls)

    def setup(self, config: Dict) -> None:
        if config.get("lagrangian"):
            raise NotImplementedError(
                "Lagrangian CQL (learned alpha_prime) is not "
                "implemented; use the fixed min_q_weight penalty"
            )
        super().setup(config)
        from ray_tpu.offline.offline_ops import setup_offline_reader

        self._reader = setup_offline_reader(config)

    def training_step(self) -> Dict:
        if self._reader is None:
            return super().training_step()
        from ray_tpu.offline.offline_ops import offline_training_step

        return offline_training_step(self)
