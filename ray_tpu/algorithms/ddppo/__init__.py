from ray_tpu.algorithms.ddppo.ddppo import DDPPO, DDPPOConfig

__all__ = ["DDPPO", "DDPPOConfig"]
