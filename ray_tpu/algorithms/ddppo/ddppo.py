"""DD-PPO: decentralized distributed PPO.

Counterpart of the reference's ``rllib/algorithms/ddppo/ddppo.py:157``
(Wijmans et al. 2020): every rollout worker both samples AND learns —
gradients are allreduced among the workers (the reference sets up a
torch.distributed gloo/nccl group, ``:260-275``; per-worker
``_sample_and_train_torch_distributed :331``) so no central learner or
weight broadcast exists; the driver only coordinates and aggregates
metrics.

TPU-first disposition: on a TPU pod the reference's NCCL allreduce
among GPU workers IS the jax multi-controller mesh (every host learns,
gradient pmean over ICI/DCN — see tests/_multihost_worker.py for that
path). This module supplies the CPU-fleet analog over the actor group:
each decentralized SGD epoch computes one gradient per worker over its
locally held (GAE-postprocessed, advantage-standardized) batch, the
driver allreduces (mean) and pushes the update back — the
driver-as-root gloo topology of parallel/collectives.HostGroup."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import ray_tpu as ray
from ray_tpu.algorithms.algorithm import (
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.ppo.ppo import PPO, PPOConfig, PPOJaxPolicy
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID
from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED

import jax


class DDPPOConfig(PPOConfig):
    """reference ddppo.py DDPPOConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPPO)
        self.num_workers = 2
        self.num_sgd_iter = 10
        self.sgd_minibatch_size = 0  # whole local batch per epoch
        self.rollout_fragment_length = 100
        self.train_batch_size = -1  # per-worker rollout IS the batch


class DDPPO(PPO):
    _default_policy_class = PPOJaxPolicy

    @classmethod
    def get_default_config(cls) -> DDPPOConfig:
        return DDPPOConfig(cls)

    def setup(self, config: Dict) -> None:
        if int(config.get("num_workers", 0)) < 1:
            raise ValueError(
                "DDPPO is decentralized: it requires num_workers >= 1 "
                "(reference ddppo.py validates the same)"
            )
        # every worker learns; fixed train_batch_size is meaningless
        config["train_batch_size"] = -1
        super().setup(config)

    def training_step(self) -> Dict:
        """reference ddppo.py:283 training_step."""
        workers = self.workers.remote_workers()
        num_sgd_iter = int(self.config.get("num_sgd_iter", 10))

        # 1. every worker samples + postprocesses + holds its batch
        steps = ray.get(
            [w.sample_and_hold.remote() for w in workers]
        )
        total = int(sum(steps))
        self._counters[NUM_ENV_STEPS_SAMPLED] += total
        self._counters[NUM_AGENT_STEPS_SAMPLED] += total

        # 2. decentralized SGD: per epoch, one gradient per worker over
        # its local batch, mean-allreduced and applied everywhere
        stats_last: Dict = {}
        for _ in range(num_sgd_iter):
            outs = ray.get(
                [w.grads_on_held_batch.remote() for w in workers]
            )
            grads_list = [g for g, _ in outs]
            stats_last = {
                k: float(
                    np.mean([s.get(k, np.nan) for _, s in outs])
                )
                for k in outs[0][1]
            }
            leaves = [
                jax.tree_util.tree_leaves(g) for g in grads_list
            ]
            treedef = jax.tree_util.tree_structure(grads_list[0])
            mean_leaves = [
                np.mean([l[i] for l in leaves], axis=0)
                for i in range(len(leaves[0]))
            ]
            mean_grads = jax.tree_util.tree_unflatten(
                treedef, mean_leaves
            )
            gref = ray.put(mean_grads)
            ray.get(
                [w.apply_gradients.remote(gref) for w in workers]
            )
            ray.free([gref])
        self._counters[NUM_ENV_STEPS_TRAINED] += total

        # 3. advance worker-side schedules (lr/entropy/exploration read
        # global_timestep) and merge observation-filter stats — the
        # jobs PPO's sync_weights/sync_filters do centrally
        global_vars = {
            "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
        }
        ray.get(
            [
                w.set_global_vars.remote(global_vars)
                for w in workers
            ]
        )
        if self.config.get("observation_filter") not in (
            None,
            "NoFilter",
        ):
            self.workers.sync_filters()

        # 4. keep the (checkpointing/evaluating) local worker in sync
        # with the decentralized fleet — ALWAYS: a stale local worker
        # would also be re-broadcast by recreate_failed_workers after a
        # crash, resetting the whole fleet to init weights
        wref = workers[0].get_weights.remote()
        weights = ray.get(wref)
        ray.free([wref])
        self.workers.local_worker().set_weights(weights)
        self.workers.local_worker().set_global_vars(global_vars)
        return {DEFAULT_POLICY_ID: stats_last}
