from ray_tpu.algorithms.maddpg.maddpg import MADDPG, MADDPGConfig

__all__ = ["MADDPG", "MADDPGConfig"]
