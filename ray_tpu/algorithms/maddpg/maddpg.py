"""MADDPG: multi-agent DDPG with centralized critics.

Counterpart of the reference's ``rllib/algorithms/maddpg/maddpg.py``
(Lowe et al. 2017): each agent has a deterministic actor over its own
observation, and a CENTRALIZED critic Q_i(s_all, a_all) trained with the
other agents' target actions — decentralized execution, centralized
training.

TPU-first shape: all agents' actors and critics are stacked along a
leading agent axis and the whole multi-agent update — every critic's TD
step, every actor's policy gradient through its own critic, both polyak
blends — is ONE jitted program vmapped over agents (the reference
builds N separate torch graphs). Collection is the same driver-side
joint collector pattern as QMIX."""

from __future__ import annotations

from typing import Dict, List, Optional

import flax.linen as nn
import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID
from ray_tpu.env.registry import get_env_creator
from ray_tpu.evaluation.metrics import RolloutMetrics
from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED
from ray_tpu.models.base import get_activation


class _Actor(nn.Module):
    act_dim: int
    low: float
    high: float
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, obs):
        act = get_activation("relu")
        x = obs.astype(jnp.float32)
        for i, h in enumerate(self.hiddens):
            x = act(nn.Dense(h, name=f"fc_{i}")(x))
        raw = jnp.tanh(nn.Dense(self.act_dim, name="out")(x))
        mid = (self.high + self.low) / 2.0
        half = (self.high - self.low) / 2.0
        return mid + half * raw


class _CentralCritic(nn.Module):
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, joint_obs, joint_actions):
        act = get_activation("relu")
        x = jnp.concatenate(
            [
                joint_obs.astype(jnp.float32),
                joint_actions.astype(jnp.float32),
            ],
            axis=-1,
        )
        for i, h in enumerate(self.hiddens):
            x = act(nn.Dense(h, name=f"fc_{i}")(x))
        return nn.Dense(1, name="q")(x).squeeze(-1)


class MADDPGConfig(AlgorithmConfig):
    """reference maddpg.py MADDPGConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or MADDPG)
        self.actor_hiddens = [64, 64]
        self.critic_hiddens = [64, 64]
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.tau = 0.01
        self.gamma = 0.95
        self.train_batch_size = 64
        self.rollout_fragment_length = 16
        self.buffer_size = 10000
        self.num_steps_sampled_before_learning_starts = 500
        self.exploration_stddev = 0.1

    def training(
        self,
        *,
        actor_lr: Optional[float] = None,
        critic_lr: Optional[float] = None,
        tau: Optional[float] = None,
        buffer_size: Optional[int] = None,
        num_steps_sampled_before_learning_starts: Optional[int] = None,
        exploration_stddev: Optional[float] = None,
        **kwargs,
    ) -> "MADDPGConfig":
        super().training(**kwargs)
        if actor_lr is not None:
            self.actor_lr = actor_lr
        if critic_lr is not None:
            self.critic_lr = critic_lr
        if tau is not None:
            self.tau = tau
        if buffer_size is not None:
            self.buffer_size = buffer_size
        if num_steps_sampled_before_learning_starts is not None:
            self.num_steps_sampled_before_learning_starts = (
                num_steps_sampled_before_learning_starts
            )
        if exploration_stddev is not None:
            self.exploration_stddev = exploration_stddev
        return self


class MADDPG(Algorithm):
    @classmethod
    def get_default_config(cls) -> MADDPGConfig:
        return MADDPGConfig(cls)

    def setup(self, config: Dict) -> None:
        env_spec = config.get("env")
        super().setup(dict(config, env=None))
        self.env = get_env_creator(env_spec)(
            config.get("env_config") or {}
        )
        obs, _ = self.env.reset(seed=config.get("seed"))
        self.agent_ids: List = sorted(obs.keys())
        self.n_agents = len(self.agent_ids)
        a0 = self.agent_ids[0]
        self.obs_dim = int(np.prod(np.asarray(obs[a0]).shape))
        space = getattr(self.env, "action_space", None)
        if isinstance(space, dict):
            space = space[a0]
        elif isinstance(space, gym.spaces.Dict):
            space = next(iter(space.spaces.values()))
        assert isinstance(space, gym.spaces.Box), (
            "MADDPG requires Box agent actions"
        )
        self.act_dim = int(np.prod(space.shape))
        self.low = float(np.min(space.low))
        self.high = float(np.max(space.high))
        self._cur_obs = obs
        self._episode_reward = 0.0
        self._episode_len = 0

        seed = int(config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._np_rng = np.random.default_rng(seed)
        self.actor = _Actor(
            self.act_dim,
            self.low,
            self.high,
            tuple(config.get("actor_hiddens", (64, 64))),
        )
        self.critic = _CentralCritic(
            tuple(config.get("critic_hiddens", (64, 64)))
        )

        # stacked per-agent parameters via vmapped init
        n = self.n_agents
        self._rng, ra, rc = jax.random.split(self._rng, 3)
        dummy_obs = jnp.zeros((2, self.obs_dim), jnp.float32)
        dummy_jobs = jnp.zeros(
            (2, self.obs_dim * n), jnp.float32
        )
        dummy_jact = jnp.zeros((2, self.act_dim * n), jnp.float32)
        actor_params = jax.vmap(
            lambda r: self.actor.init(r, dummy_obs)
        )(jax.random.split(ra, n))
        critic_params = jax.vmap(
            lambda r: self.critic.init(r, dummy_jobs, dummy_jact)
        )(jax.random.split(rc, n))
        self.params = {"actor": actor_params, "critic": critic_params}
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params
        )
        self._tx_a = optax.adam(float(config.get("actor_lr", 1e-3)))
        self._tx_c = optax.adam(float(config.get("critic_lr", 1e-3)))
        self.opt_state = {
            "actor": self._tx_a.init(actor_params),
            "critic": self._tx_c.init(critic_params),
        }
        self._buffer: List[Dict] = []
        self._buffer_idx = 0
        self._act_fn = None
        self._learn_fn = None

    # -- acting -----------------------------------------------------------

    def _actions(self, obs_stack: np.ndarray, explore: bool):
        if self._act_fn is None:

            def fn(params, obs, rng, stddev):
                # vmap actors over the agent axis
                acts = jax.vmap(self.actor.apply)(
                    params["actor"], obs[:, None]
                ).squeeze(1)  # (n, act_dim)
                noise = stddev * jax.random.normal(rng, acts.shape)
                return jnp.clip(acts + noise, self.low, self.high)

            self._act_fn = jax.jit(fn)
        self._rng, rng = jax.random.split(self._rng)
        stddev = (
            float(self.config.get("exploration_stddev", 0.1))
            if explore
            else 0.0
        )
        return np.asarray(
            self._act_fn(
                self.params,
                jnp.asarray(obs_stack),
                rng,
                jnp.asarray(stddev, jnp.float32),
            )
        )

    def _collect(self, num_steps: int) -> None:
        cap = int(self.config.get("buffer_size", 10000))
        for _ in range(num_steps):
            obs_stack = np.stack(
                [
                    np.asarray(self._cur_obs[a], np.float32).reshape(-1)
                    for a in self.agent_ids
                ]
            )
            acts = self._actions(obs_stack, explore=True)
            action_dict = {
                a: acts[i] for i, a in enumerate(self.agent_ids)
            }
            next_obs, rewards, terms, truncs, _ = self.env.step(
                action_dict
            )
            terminated = bool(terms.get("__all__", False))
            done = terminated or bool(truncs.get("__all__", False))
            rew_vec = np.asarray(
                [rewards.get(a, 0.0) for a in self.agent_ids],
                np.float32,
            )
            next_stack = (
                np.stack(
                    [
                        np.asarray(
                            next_obs.get(a, self._cur_obs[a]),
                            np.float32,
                        ).reshape(-1)
                        for a in self.agent_ids
                    ]
                )
                if next_obs
                else obs_stack
            )
            row = {
                "obs": obs_stack,
                "actions": acts.astype(np.float32),
                "rewards": rew_vec,
                "next_obs": next_stack,
                # bootstrap mask uses TERMINATION only: a time-limit
                # truncation must still bootstrap Q(s')
                "done": np.float32(terminated),
            }
            if len(self._buffer) < cap:
                self._buffer.append(row)
            else:
                self._buffer[self._buffer_idx] = row
            self._buffer_idx = (self._buffer_idx + 1) % cap
            self._episode_reward += float(rew_vec.sum())
            self._episode_len += 1
            self._counters[NUM_ENV_STEPS_SAMPLED] += 1
            self._counters[NUM_AGENT_STEPS_SAMPLED] += self.n_agents
            if done:
                self._episode_history.append(
                    RolloutMetrics(
                        self._episode_len, self._episode_reward
                    )
                )
                self._episodes_total += 1
                self._episode_reward = 0.0
                self._episode_len = 0
                next_obs, _ = self.env.reset()
            self._cur_obs = next_obs

    # -- learning ---------------------------------------------------------

    def _build_learn_fn(self):
        gamma = float(self.config.get("gamma", 0.95))
        tau = float(self.config.get("tau", 0.01))
        actor, critic = self.actor, self.critic
        tx_a, tx_c = self._tx_a, self._tx_c
        n = self.n_agents

        def fn(params, target_params, opt_state, batch):
            obs = batch["obs"]  # (B, n, d)
            next_obs = batch["next_obs"]
            actions = batch["actions"]  # (B, n, a)
            B = obs.shape[0]
            joint_obs = obs.reshape(B, -1)
            joint_next_obs = next_obs.reshape(B, -1)
            joint_actions = actions.reshape(B, -1)

            # target joint next actions from all target actors
            next_acts = jax.vmap(
                actor.apply, in_axes=(0, 1), out_axes=1
            )(target_params["actor"], next_obs)  # (B, n, a)
            joint_next_acts = next_acts.reshape(B, -1)

            # per-agent centralized critic TD targets
            tq = jax.vmap(
                lambda cp: critic.apply(
                    cp, joint_next_obs, joint_next_acts
                )
            )(target_params["critic"])  # (n, B)
            y = jax.lax.stop_gradient(
                batch["rewards"].T
                + gamma * (1.0 - batch["done"])[None, :] * tq
            )  # (n, B)

            def critic_loss(cps):
                q = jax.vmap(
                    lambda cp: critic.apply(
                        cp, joint_obs, joint_actions
                    )
                )(cps)  # (n, B)
                return jnp.mean(jnp.square(q - y)), q

            (c_loss, q), c_grads = jax.value_and_grad(
                critic_loss, has_aux=True
            )(params["critic"])
            c_upd, c_opt = tx_c.update(
                c_grads, opt_state["critic"], params["critic"]
            )
            new_critic = optax.apply_updates(params["critic"], c_upd)

            # actor gradients: each agent maximizes ITS critic with its
            # own action substituted into the joint action
            def actor_loss(aps):
                my_acts = jax.vmap(
                    actor.apply, in_axes=(0, 1), out_axes=1
                )(aps, obs)  # (B, n, a)

                def one_agent(i):
                    # substitute agent i's fresh action, others logged
                    mixed = actions.at[:, i, :].set(my_acts[:, i, :])
                    cp_i = jax.tree_util.tree_map(
                        lambda x: x[i], new_critic
                    )
                    return -jnp.mean(
                        critic.apply(
                            cp_i, joint_obs, mixed.reshape(B, -1)
                        )
                    )

                losses = jnp.stack(
                    [one_agent(i) for i in range(n)]
                )
                return jnp.sum(losses)

            a_loss, a_grads = jax.value_and_grad(actor_loss)(
                params["actor"]
            )
            a_upd, a_opt = tx_a.update(
                a_grads, opt_state["actor"], params["actor"]
            )
            new_actor = optax.apply_updates(params["actor"], a_upd)

            new_target = jax.tree_util.tree_map(
                lambda t, o: (1.0 - tau) * t + tau * o,
                target_params,
                {"actor": new_actor, "critic": new_critic},
            )
            stats = {
                "critic_loss": c_loss,
                "actor_loss": a_loss,
                "mean_q": jnp.mean(q),
            }
            return (
                {"actor": new_actor, "critic": new_critic},
                new_target,
                {"actor": a_opt, "critic": c_opt},
                stats,
            )

        return jax.jit(fn)

    def training_step(self) -> Dict:
        config = self.config
        self._collect(int(config.get("rollout_fragment_length", 16)))
        train_info: Dict = {}
        if (
            self._counters[NUM_ENV_STEPS_SAMPLED]
            >= config.get("num_steps_sampled_before_learning_starts", 0)
            and len(self._buffer) >= config["train_batch_size"]
        ):
            if self._learn_fn is None:
                self._learn_fn = self._build_learn_fn()
            idx = self._np_rng.integers(
                0, len(self._buffer), config["train_batch_size"]
            )
            rows = [self._buffer[i] for i in idx]
            batch = {
                k: jnp.asarray(np.stack([r[k] for r in rows]))
                for k in rows[0]
            }
            (
                self.params,
                self.target_params,
                self.opt_state,
                stats,
            ) = self._learn_fn(
                self.params, self.target_params, self.opt_state, batch
            )
            stats = {
                k: float(v) for k, v in jax.device_get(stats).items()
            }
            train_info = {DEFAULT_POLICY_ID: stats}
            self._counters[NUM_ENV_STEPS_TRAINED] += int(
                config["train_batch_size"]
            )
        return train_info

    def __getstate__(self) -> Dict:
        return {
            "params": jax.device_get(self.params),
            "target_params": jax.device_get(self.target_params),
            "opt_state": jax.device_get(self.opt_state),
            "counters": dict(self._counters),
            "episodes_total": self._episodes_total,
        }

    def __setstate__(self, state: Dict) -> None:
        import collections

        self.params = jax.device_put(state["params"])
        self.target_params = jax.device_put(state["target_params"])
        self.opt_state = jax.device_put(state["opt_state"])
        self._counters = collections.defaultdict(
            int, state.get("counters", {})
        )
        self._episodes_total = state.get("episodes_total", 0)

    def cleanup(self) -> None:
        try:
            self.env.close()
        except Exception:
            pass
        super().cleanup()
