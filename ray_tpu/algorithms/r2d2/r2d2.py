"""R2D2: Recurrent Replay Distributed DQN.

Counterpart of the reference's ``rllib/algorithms/r2d2/r2d2.py``
(Kapturowski et al. 2019: sequence replay with stored recurrent states,
burn-in, the invertible value-rescaling h-function) and
``r2d2_torch_policy.py`` (r2d2_loss).

TPU-first: replay stores FIXED-length (T,) sequences with their stored
initial LSTM state (the "stored state" strategy; zero_init_states=True
gives the zero-state strategy) — fixed shapes mean one compiled loss
program; the whole sequence loss (burn-in forward with stopped
gradients folded in via masking, double-Q targets over (B, T), h-scaled
TD) is one jitted program."""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.algorithms.algorithm import NUM_ENV_STEPS_SAMPLED
from ray_tpu.algorithms.dqn.dqn import DQN, DQNConfig, DQNJaxPolicy
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.execution.rollout_ops import synchronous_parallel_sample
from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED


def h_function(x, epsilon: float = 1e-3):
    """Invertible value rescaling (reference r2d2_torch_policy.py:209):
    h(x) = sign(x) * (sqrt(|x|+1) - 1) + eps*x."""
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + epsilon * x


def h_inverse(x, epsilon: float = 1e-3):
    """Closed-form inverse of h (reference r2d2_torch_policy.py:220):
    h⁻¹(x) = [2εx + (2ε+1) ∓ sqrt(±4εx + (2ε+1)²)] / (2ε²), the sign
    choice depending on x's sign."""
    two_eps = 2.0 * epsilon
    if_pos = (
        two_eps * x
        + (two_eps + 1.0)
        - jnp.sqrt(4.0 * epsilon * x + (two_eps + 1.0) ** 2)
    ) / (2.0 * epsilon**2)
    if_neg = (
        two_eps * x
        - (two_eps + 1.0)
        + jnp.sqrt(-4.0 * epsilon * x + (two_eps + 1.0) ** 2)
    ) / (2.0 * epsilon**2)
    return jnp.where(x < 0.0, if_neg, if_pos)


def chop_fragment_into_sequences(
    batch, T: int, columns, *, first_row_is_reset: bool = True
):
    """Chop a flat rollout fragment into fixed-length sequence dicts
    with ``resets`` (episode-restart flags from EPS_ID changes) and a
    right-zero ``mask`` column. Shared by R2D2 and RNNSAC. Integer
    columns keep their dtype; everything else casts to float32.

    The fragment's first row only counts as a restart when
    ``first_row_is_reset`` (the zero-init strategy): with stored state,
    the sampler's state_in at offset 0 is already correct (zero iff a
    real episode start), and a forced reset would wipe mid-episode
    carries. Yields ``(start_row, seq_dict)`` so callers can attach
    stored-state columns."""
    n = batch.count
    eps_ids = np.asarray(
        batch.get(SampleBatch.EPS_ID, np.zeros(n, np.int64))
    )
    resets_all = np.zeros(n, np.float32)
    resets_all[0] = 1.0 if first_row_is_reset else 0.0
    resets_all[1:] = (eps_ids[1:] != eps_ids[:-1]).astype(np.float32)
    out = []
    for start in range(0, n, T):
        end = min(start + T, n)
        L = end - start
        seq: Dict[str, np.ndarray] = {}
        for k in columns:
            v = np.asarray(batch[k])[start:end]
            if L < T:  # right-zero-pad to the fixed length
                pad = np.zeros((T - L,) + v.shape[1:], v.dtype)
                v = np.concatenate([v, pad], axis=0)
            seq[k] = (
                v
                if np.issubdtype(v.dtype, np.integer)
                else v.astype(np.float32)
            )
        mask = np.zeros(T, np.float32)
        mask[:L] = 1.0
        seq["mask"] = mask
        resets = resets_all[start:end]
        if L < T:
            resets = np.concatenate(
                [resets, np.zeros(T - L, np.float32)]
            )
        seq["resets"] = resets
        out.append((start, seq))
    return out


class SequenceReplayBuffer:
    """Uniform replay over fixed-length sequences with stored initial
    recurrent state (reference replay_sequence_length storage mode of
    ``utils/replay_buffers``)."""

    def __init__(self, capacity_sequences: int, seed=None):
        self.capacity = capacity_sequences
        self._storage: List[Dict[str, np.ndarray]] = []
        self._idx = 0
        self._rng = np.random.default_rng(seed)
        self.num_added = 0

    def add_sequence(self, seq: Dict[str, np.ndarray]) -> None:
        if len(self._storage) < self.capacity:
            self._storage.append(seq)
        else:
            self._storage[self._idx] = seq
        self._idx = (self._idx + 1) % self.capacity
        self.num_added += 1

    def __len__(self):
        return len(self._storage)

    def sample(self, num_sequences: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, len(self._storage), num_sequences)
        seqs = [self._storage[i] for i in idx]
        return {
            k: np.stack([s[k] for s in seqs]) for k in seqs[0].keys()
        }


class R2D2Config(DQNConfig):
    """reference r2d2.py R2D2Config."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or R2D2)
        self.replay_sequence_length = 20
        self.replay_burn_in = 0
        self.zero_init_states = True
        self.use_h_function = True
        self.h_function_epsilon = 1e-3
        self.train_batch_size = 16  # sequences per draw
        self.rollout_fragment_length = 20
        self.num_steps_sampled_before_learning_starts = 500
        self.target_network_update_freq = 1000
        self.model = {"use_lstm": True, "lstm_cell_size": 64}
        self.replay_buffer_config = {"capacity": 2000}  # sequences

    def training(
        self,
        *,
        replay_sequence_length: Optional[int] = None,
        replay_burn_in: Optional[int] = None,
        zero_init_states: Optional[bool] = None,
        use_h_function: Optional[bool] = None,
        **kwargs,
    ) -> "R2D2Config":
        super().training(**kwargs)
        if replay_sequence_length is not None:
            self.replay_sequence_length = replay_sequence_length
        if replay_burn_in is not None:
            self.replay_burn_in = replay_burn_in
        if zero_init_states is not None:
            self.zero_init_states = zero_init_states
        if use_h_function is not None:
            self.use_h_function = use_h_function
        return self


class R2D2JaxPolicy(DQNJaxPolicy):
    """Sequence double-Q loss with burn-in over the recurrent model
    (reference r2d2_torch_policy.py r2d2_loss). The model's Q head is
    the recurrent wrapper's logits head."""

    _supports_recurrent = True

    def __init__(self, observation_space, action_space, config):
        config = dict(config)
        model = dict(config.get("model") or {})
        model.setdefault("use_lstm", True)
        config["model"] = model
        # one SGD pass over the whole sequence batch per learn call
        config.setdefault("num_sgd_iter", 1)
        config["sgd_minibatch_size"] = config.get("train_batch_size", 16)
        super().__init__(observation_space, action_space, config)
        self.seq_len = int(config.get("replay_sequence_length", 20))
        self.burn_in = int(config.get("replay_burn_in", 0))
        # R2D2 train rows are WHOLE stored sequences (leading dim =
        # sequence index, columns already (B, T, ...)) — the base
        # class's flat-row unroll chopping and its T-multiple
        # tiling/trim in prepare_batch must not apply.
        self._unroll_T = 1

    def _batch_to_train_tree(self, samples):
        """Sequences arrive pre-stacked as (B, T, ...) from the
        SequenceReplayBuffer."""
        drop = {SampleBatch.INFOS, SampleBatch.SEQ_LENS}
        return {
            k: np.asarray(v)
            for k, v in samples.items()
            if k not in drop and np.asarray(v).dtype != object
        }

    def loss_with_aux(self, params, aux, batch, rng, coeffs):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        use_h = cfg.get("use_h_function", True)
        h_eps = cfg.get("h_function_epsilon", 1e-3)
        burn_in = self.burn_in

        obs = batch[SampleBatch.OBS]  # (B, T, ...)
        B, T = obs.shape[0], obs.shape[1]
        state0 = (batch["state_in_0"], batch["state_in_1"])  # (B, C)
        resets = batch["resets"]  # (B, T) 1.0 where episode restarted
        actions = batch[SampleBatch.ACTIONS].astype(jnp.int32)
        rewards = batch[SampleBatch.REWARDS]
        not_done = 1.0 - batch[SampleBatch.TERMINATEDS]
        mask = batch["mask"]  # (B, T) valid rows

        # online and target forward over the WHOLE sequence (burn-in is
        # folded in by masking the loss, matching the reference's
        # seq_mask[:, :burn_in] = False)
        q_flat, _, _ = self.model.apply(params, obs, state0, resets=resets)
        q = q_flat.reshape(B, T, -1)
        tq_flat, _, _ = self.model.apply(
            aux["target_params"], obs, state0, resets=resets
        )
        tq = jax.lax.stop_gradient(tq_flat.reshape(B, T, -1))

        q_sel = jnp.take_along_axis(
            q, actions[..., None], axis=-1
        ).squeeze(-1)  # (B, T)

        # double-Q one-step targets within the sequence: target value of
        # t+1 under online argmax
        online_next_argmax = jnp.argmax(q[:, 1:], axis=-1)  # (B, T-1)
        tq_next = jnp.take_along_axis(
            tq[:, 1:], online_next_argmax[..., None], axis=-1
        ).squeeze(-1)  # (B, T-1)
        if use_h:
            tq_next = h_inverse(tq_next, h_eps)
        target = rewards[:, :-1] + gamma * not_done[:, :-1] * tq_next
        if use_h:
            target = h_function(target, h_eps)
        target = jax.lax.stop_gradient(target)

        td_error = q_sel[:, :-1] - target  # (B, T-1)
        # valid-step mask: drop burn-in prefix, padding, the last step
        # (no bootstrap successor inside the sequence), and steps whose
        # successor starts a new episode (truncation boundary — its
        # "next Q" belongs to a different episode).
        valid = mask[:, :-1] * (1.0 - resets[:, 1:])
        if burn_in > 0:
            valid = valid * (
                jnp.arange(T - 1)[None, :] >= burn_in
            ).astype(valid.dtype)
        n_valid = jnp.maximum(valid.sum(), 1.0)
        huber = jnp.where(
            jnp.abs(td_error) < 1.0,
            0.5 * jnp.square(td_error),
            jnp.abs(td_error) - 0.5,
        )
        loss = (huber * valid).sum() / n_valid
        stats = {
            "mean_q": (q_sel[:, :-1] * valid).sum() / n_valid,
            "mean_td_error": (td_error * valid).sum() / n_valid,
        }
        return loss, stats


class R2D2(DQN):
    _default_policy_class = R2D2JaxPolicy

    @classmethod
    def get_default_config(cls) -> R2D2Config:
        return R2D2Config(cls)

    def setup(self, config: Dict) -> None:
        super().setup(config)
        rb = config.get("replay_buffer_config") or {}
        self.local_replay_buffer = None  # DQN's flat buffer unused
        self.seq_buffer = SequenceReplayBuffer(
            rb.get("capacity", 2000), seed=config.get("seed")
        )
        self._last_target_update = 0

    def _fragments_to_sequences(self, batch: SampleBatch) -> None:
        """Chop a rollout fragment into fixed-length sequences with the
        stored (or zero) initial state, resets, and padding mask."""
        cfg = self.config
        T = int(cfg.get("replay_sequence_length", 20))
        zero_init = bool(cfg.get("zero_init_states", True))
        policy = self.get_policy()
        cell = policy.model.initial_state(1)
        for start, seq in chop_fragment_into_sequences(
            batch,
            T,
            (
                SampleBatch.OBS,
                SampleBatch.ACTIONS,
                SampleBatch.REWARDS,
                SampleBatch.TERMINATEDS,
            ),
            first_row_is_reset=zero_init,
        ):
            if zero_init or f"state_in_0" not in batch:
                seq["state_in_0"] = np.zeros_like(
                    np.asarray(cell[0][0])
                )
                seq["state_in_1"] = np.zeros_like(
                    np.asarray(cell[1][0])
                )
            else:
                seq["state_in_0"] = np.asarray(
                    batch["state_in_0"]
                )[start]
                seq["state_in_1"] = np.asarray(
                    batch["state_in_1"]
                )[start]
            self.seq_buffer.add_sequence(seq)

    def training_step(self) -> Dict:
        config = self.config
        batch = synchronous_parallel_sample(
            worker_set=self.workers,
            max_env_steps=config.get("rollout_fragment_length", 20),
        )
        self._counters[NUM_ENV_STEPS_SAMPLED] += batch.env_steps()
        if hasattr(batch, "policy_batches"):
            batch = batch.policy_batches[DEFAULT_POLICY_ID]
        self._fragments_to_sequences(batch)

        train_info: Dict = {}
        if (
            self._counters[NUM_ENV_STEPS_SAMPLED]
            >= config.get("num_steps_sampled_before_learning_starts", 0)
            and len(self.seq_buffer) >= config["train_batch_size"]
        ):
            seqs = self.seq_buffer.sample(config["train_batch_size"])
            policy = self.get_policy()
            info = policy.learn_on_batch(SampleBatch(seqs))
            train_info = {DEFAULT_POLICY_ID: info}
            steps = int(seqs["mask"].sum())
            self._counters[NUM_ENV_STEPS_TRAINED] += steps
            if (
                self._counters[NUM_ENV_STEPS_TRAINED]
                - self._last_target_update
                >= config.get("target_network_update_freq", 1000)
            ):
                policy.update_target()
                self._last_target_update = self._counters[
                    NUM_ENV_STEPS_TRAINED
                ]
                self._counters["num_target_updates"] += 1
        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            }
        )
        return train_info
