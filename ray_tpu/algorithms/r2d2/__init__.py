from ray_tpu.algorithms.r2d2.r2d2 import R2D2, R2D2Config, R2D2JaxPolicy

__all__ = ["R2D2", "R2D2Config", "R2D2JaxPolicy"]
