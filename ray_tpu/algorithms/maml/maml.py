"""MAML: model-agnostic meta-learning for RL.

Counterpart of the reference's ``rllib/algorithms/maml/maml.py``
(Finn et al. 2017): meta-train a policy initialization such that ONE
inner policy-gradient step on a new task's data yields a good
task-specific policy. The reference splits inner adaptation across
workers and assembles the meta-update with torch autograd through the
inner step; here the entire meta-objective —

    meta_loss(θ) = Σ_tasks ppo_surrogate(θ - α·∇pg_loss(θ, pre_m),
                                          post_m)

— is ONE jitted program: ``jax.grad`` differentiates straight through
the inner SGD update (the second-order MAML term the reference needs
create_graph=True for), vmapped over the task batch. This is the
TPU-native shape of meta-RL: meta-gradients are just composed
transforms.

Env contract (reference maml_env API): ``sample_tasks(n)`` and
``set_task(task)``. ``PointGoalEnv`` below is the standard 2D
point-navigation task distribution used for tests."""

from __future__ import annotations

from typing import Dict, List, Optional

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID
from ray_tpu.env.registry import get_env_creator
from ray_tpu.evaluation.metrics import RolloutMetrics
from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED
from ray_tpu.models.catalog import ModelCatalog
from ray_tpu.models.distributions import DiagGaussian


class PointGoalEnv(gym.Env):
    """2D point navigation with per-task goals (the reference's
    point_env family): obs = position, reward = -distance to the
    task's goal."""

    def __init__(self, config=None):
        config = config or {}
        self.horizon = int(config.get("horizon", 20))
        self.goal_radius = float(config.get("goal_radius", 1.0))
        self._rng = np.random.default_rng(config.get("seed", 0))
        self.goal = np.array([0.5, 0.5], np.float32)
        self.observation_space = gym.spaces.Box(
            -np.inf, np.inf, (2,), np.float32
        )
        self.action_space = gym.spaces.Box(
            -0.2, 0.2, (2,), np.float32
        )

    def sample_tasks(self, n: int) -> List[np.ndarray]:
        angles = self._rng.uniform(0, 2 * np.pi, n)
        return [
            np.array(
                [
                    self.goal_radius * np.cos(a),
                    self.goal_radius * np.sin(a),
                ],
                np.float32,
            )
            for a in angles
        ]

    def set_task(self, task: np.ndarray) -> None:
        self.goal = np.asarray(task, np.float32)

    def reset(self, *, seed=None, options=None):
        self.pos = np.zeros(2, np.float32)
        self._t = 0
        return self.pos.copy(), {}

    def step(self, action):
        a = np.clip(np.asarray(action, np.float32), -0.2, 0.2)
        self.pos = self.pos + a
        self._t += 1
        reward = -float(np.linalg.norm(self.pos - self.goal))
        truncated = self._t >= self.horizon
        return self.pos.copy(), reward, False, truncated, {}


def linear_feature_baseline(obs_l, ret_l):
    """Per-task linear value baseline (the rllab/reference MAML
    ``LinearFeatureBaseline``): least-squares fit of the discounted
    returns on ``[obs, obs², t, t², t³, 1]`` across the task's
    episodes, subtracted from the returns to form advantages.

    Raw discounted returns are dominated by the timestep (early steps
    have more remaining horizon than late ones regardless of the
    actions taken), which buries the policy-gradient signal of the
    tiny per-task batches MAML adapts on; the fitted baseline removes
    that component and meta-training converges in a fraction of the
    iterations."""
    rets = np.concatenate(ret_l)

    def feats(obs):
        t = np.arange(len(obs), dtype=np.float32)[:, None] / 100.0
        o = obs.reshape(len(obs), -1)
        return np.concatenate(
            [o, o**2, t, t**2, t**3, np.ones_like(t)], axis=1
        )

    f = np.concatenate([feats(o) for o in obs_l])
    reg = 1e-5 * np.eye(f.shape[1], dtype=np.float32)
    try:
        w = np.linalg.solve(f.T @ f + reg, f.T @ rets)
        return rets - f @ w
    except np.linalg.LinAlgError:
        return rets - rets.mean()


def build_act_fn(model, dist_cls):
    """Jitted (params, obs, rng) → (sampled action, logp) for host-side
    rollout loops. Shared by MAML and MBMPO."""

    def fn(params, obs, rng):
        dist_inputs, _, _ = model.apply(params, obs)
        return dist_cls(dist_inputs).sampled_action_logp(rng)

    return jax.jit(fn)


def build_meta_objective(model, dist_cls, tx, *, inner_lr, clip, inner_steps):
    """The MAML meta-objective as composed JAX transforms: inner PG
    adaptation differentiated through (second-order term included),
    PPO-clipped surrogate outside, vmapped over the task batch.

    Shared by MAML (tasks = env task distribution) and MBMPO (tasks =
    dynamics-ensemble members). Returns ``(adapted_jit, meta_step_jit)``
    where batches are dicts with obs/actions/logp/advantages columns —
    per-task stacked (leading task axis) for ``meta_step``."""

    def pg_loss(params, batch):
        dist_inputs, _, _ = model.apply(params, batch["obs"])
        logp = dist_cls(dist_inputs).logp(batch["actions"])
        return -jnp.mean(logp * batch["advantages"])

    def adapted(params, pre):
        """θ' after `inner_steps` inner PG steps; the meta-gradients
        flow through every one (second-order MAML)."""
        for _ in range(inner_steps):
            grads = jax.grad(pg_loss)(params, pre)
            params = jax.tree_util.tree_map(
                lambda p, g: p - inner_lr * g, params, grads
            )
        return params

    def surrogate(params, batch):
        dist_inputs, _, _ = model.apply(params, batch["obs"])
        logp = dist_cls(dist_inputs).logp(batch["actions"])
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        return -jnp.mean(
            jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv,
            )
        )

    def meta_loss(params, pre_batches, post_batches):
        def one_task(pre, post):
            return surrogate(adapted(params, pre), post)

        losses = jax.vmap(one_task)(pre_batches, post_batches)
        return jnp.mean(losses)

    def meta_step(params, opt_state, pre_batches, post_batches):
        loss, grads = jax.value_and_grad(meta_loss)(
            params, pre_batches, post_batches
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(adapted), jax.jit(meta_step)


class MAMLConfig(AlgorithmConfig):
    """reference maml.py MAMLConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or MAML)
        self.inner_lr = 0.1
        self.meta_lr = 1e-3
        self.num_tasks_per_iteration = 8
        self.rollouts_per_task = 4
        self.clip_param = 0.3
        self.inner_adaptation_steps = 1
        self.model = {"fcnet_hiddens": [64, 64]}

    def training(
        self,
        *,
        inner_lr: Optional[float] = None,
        meta_lr: Optional[float] = None,
        num_tasks_per_iteration: Optional[int] = None,
        rollouts_per_task: Optional[int] = None,
        **kwargs,
    ) -> "MAMLConfig":
        super().training(**kwargs)
        if inner_lr is not None:
            self.inner_lr = inner_lr
        if meta_lr is not None:
            self.meta_lr = meta_lr
        if num_tasks_per_iteration is not None:
            self.num_tasks_per_iteration = num_tasks_per_iteration
        if rollouts_per_task is not None:
            self.rollouts_per_task = rollouts_per_task
        return self


class MAML(Algorithm):
    @classmethod
    def get_default_config(cls) -> MAMLConfig:
        return MAMLConfig(cls)

    def setup(self, config: Dict) -> None:
        env_spec = config.get("env")
        super().setup(dict(config, env=None))
        self.env = get_env_creator(env_spec)(
            config.get("env_config") or {}
        )
        assert hasattr(self.env, "sample_tasks") and hasattr(
            self.env, "set_task"
        ), "MAML requires a task-distribution env (sample_tasks/set_task)"
        obs_space = self.env.observation_space
        act_space = self.env.action_space
        assert isinstance(act_space, gym.spaces.Box)
        self.act_dim = int(np.prod(act_space.shape))

        model_config = dict(config.get("model") or {})
        self.dist_cls = DiagGaussian
        self.model = ModelCatalog.get_model(
            obs_space, act_space, 2 * self.act_dim, model_config
        )
        seed = int(config.get("seed") or 0)
        self._rng = jax.random.PRNGKey(seed)
        self._np_rng = np.random.default_rng(seed)
        self._rng, init_rng = jax.random.split(self._rng)
        dummy = jnp.zeros((2,) + obs_space.shape, jnp.float32)
        self.params = self.model.init(init_rng, dummy)
        self._tx = optax.adam(float(config.get("meta_lr", 1e-3)))
        self.opt_state = self._tx.init(self.params)
        self._meta_fn = None
        self._act_fn = None

    # -- rollouts ---------------------------------------------------------

    def _rollout_envs(self, num: int) -> List:
        """``num`` env instances on the CURRENT task for lockstep
        batched rollouts: index 0 is ``self.env`` itself; the rest are
        deep copies with their RNG re-seeded (a straight copy would
        replay identical stochasticity in every parallel episode)."""
        import copy

        envs = [self.env]
        for _ in range(num - 1):
            e = copy.deepcopy(self.env)
            for attr in ("_rng", "np_random"):
                if hasattr(e, attr):
                    try:
                        setattr(
                            e,
                            attr,
                            np.random.default_rng(
                                int(self._np_rng.integers(2**31))
                            ),
                        )
                    except Exception:
                        pass
            envs.append(e)
        return envs

    def _policy_rollouts(self, params, num: int) -> Dict[str, np.ndarray]:
        """Collect `num` episodes on the env's CURRENT task with the
        given params; returns stacked (N*T,) columns with
        baseline-corrected discounted returns as advantages
        (``linear_feature_baseline``, the reference's
        LinearFeatureBaseline role).

        The `num` episodes run in LOCKSTEP over env copies, so each
        step is ONE batched jitted act call instead of `num` — the
        rollout loop is dispatch-bound on a fast host, and this cuts
        the per-meta-iteration wall clock ~4x at rollouts_per_task=4."""
        if self._act_fn is None:
            self._act_fn = build_act_fn(self.model, self.dist_cls)
        gamma = float(self.config.get("gamma", 0.99))
        envs = self._rollout_envs(num)
        obs = [e.reset()[0] for e in envs]
        ep_obs = [[] for _ in envs]
        ep_act = [[] for _ in envs]
        ep_logp = [[] for _ in envs]
        ep_rew = [[] for _ in envs]
        alive = list(range(num))
        self._rng, ep_rng = jax.random.split(self._rng)
        step_t = 0
        while alive:
            sub = jax.random.fold_in(ep_rng, step_t)
            step_t += 1
            obs_b = np.stack(
                [np.asarray(obs[i], np.float32) for i in alive]
            )
            a_b, logp_b = self._act_fn(params, jnp.asarray(obs_b), sub)
            a_b = np.asarray(a_b)
            logp_b = np.asarray(logp_b)
            still = []
            for j, i in enumerate(alive):
                ep_obs[i].append(obs_b[j])
                ep_act[i].append(a_b[j])
                ep_logp[i].append(float(logp_b[j]))
                o, r, term, trunc, _ = envs[i].step(a_b[j])
                ep_rew[i].append(float(r))
                obs[i] = o
                if not (term or trunc):
                    still.append(i)
            alive = still
        from ray_tpu.evaluation.postprocessing import discount_cumsum

        obs_l, act_l, logp_l, ret_l = [], [], [], []
        total_steps = 0
        ep_rewards = []
        for i in range(num):
            ret = discount_cumsum(
                np.asarray(ep_rew[i], np.float32), gamma
            ).astype(np.float32)
            obs_l.append(np.stack(ep_obs[i]))
            act_l.append(np.stack(ep_act[i]))
            logp_l.append(np.asarray(ep_logp[i], np.float32))
            ret_l.append(ret)
            total_steps += len(ep_rew[i])
            ep_rewards.append(float(np.sum(ep_rew[i])))
        self._counters[NUM_ENV_STEPS_SAMPLED] += total_steps
        self._counters[NUM_AGENT_STEPS_SAMPLED] += total_steps
        adv = linear_feature_baseline(obs_l, ret_l)
        adv = (adv - adv.mean()) / max(1e-4, adv.std())
        batch = {
            "obs": np.concatenate(obs_l),
            "actions": np.concatenate(act_l),
            "logp": np.concatenate(logp_l),
            "advantages": adv.astype(np.float32),
        }
        return batch, ep_rewards

    # -- the meta-objective (one jitted program) --------------------------

    def _build_meta_fn(self):
        self._adapted_jit, meta_step = build_meta_objective(
            self.model,
            self.dist_cls,
            self._tx,
            inner_lr=float(self.config.get("inner_lr", 0.1)),
            clip=float(self.config.get("clip_param", 0.3)),
            inner_steps=int(
                self.config.get("inner_adaptation_steps", 1)
            ),
        )
        return meta_step

    def _adapt(self, pre_batch):
        """θ' from the jitted inner update on a host batch."""
        if self._meta_fn is None:
            self._meta_fn = self._build_meta_fn()
        return self._adapted_jit(
            self.params,
            {k: jnp.asarray(v) for k, v in pre_batch.items()},
        )

    def adapt_to_task(self, task) -> Dict:
        """One inner adaptation on a (new) task; returns pre/post
        rollout stats (the meta-test procedure)."""
        per_task = int(self.config.get("rollouts_per_task", 4))
        self.env.set_task(task)
        pre, pre_rews = self._policy_rollouts(self.params, per_task)
        post, post_rews = self._policy_rollouts(
            self._adapt(pre), per_task
        )
        return {
            "pre_reward": float(np.mean(pre_rews)),
            "post_reward": float(np.mean(post_rews)),
        }

    def training_step(self) -> Dict:
        config = self.config
        n_tasks = int(config.get("num_tasks_per_iteration", 8))
        per_task = int(config.get("rollouts_per_task", 4))
        if self._meta_fn is None:
            self._meta_fn = self._build_meta_fn()

        tasks = self.env.sample_tasks(n_tasks)
        pre_list, post_list = [], []
        pre_rewards, post_rewards = [], []
        for task in tasks:
            self.env.set_task(task)
            pre, pre_rews = self._policy_rollouts(
                self.params, per_task
            )
            post, post_rews = self._policy_rollouts(
                self._adapt(pre), per_task
            )
            pre_rewards.append(float(np.mean(pre_rews)))
            post_rewards.extend(post_rews)
            pre_list.append(pre)
            post_list.append(post)

        def stack(batches):
            sizes = {len(b["obs"]) for b in batches}
            if len(sizes) != 1:
                raise ValueError(
                    "MAML's vmapped meta-objective needs equal-size "
                    f"task batches, got lengths {sorted(sizes)}: the "
                    "task env must use fixed-length (truncated) "
                    "episodes so every task contributes "
                    "rollouts_per_task * horizon steps"
                )
            return {
                k: jnp.asarray(
                    np.stack([b[k] for b in batches])
                )
                for k in batches[0]
            }

        self.params, self.opt_state, loss = self._meta_fn(
            self.params,
            self.opt_state,
            stack(pre_list),
            stack(post_list),
        )
        self._counters[NUM_ENV_STEPS_TRAINED] += sum(
            len(b["obs"]) for b in pre_list + post_list
        )
        # every post-adaptation EPISODE feeds the standard metrics
        horizon = int(
            (self.config.get("env_config") or {}).get("horizon", 20)
        )
        for r in post_rewards:
            self._episode_history.append(RolloutMetrics(horizon, r))
            self._episodes_total += 1
        return {
            DEFAULT_POLICY_ID: {
                "meta_loss": float(loss),
                "pre_adapt_reward": float(np.mean(pre_rewards)),
                "post_adapt_reward": float(np.mean(post_rewards)),
                "adaptation_delta": float(
                    np.mean(post_rewards) - np.mean(pre_rewards)
                ),
            }
        }

    def __getstate__(self) -> Dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "counters": dict(self._counters),
            "episodes_total": self._episodes_total,
        }

    def __setstate__(self, state: Dict) -> None:
        import collections

        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])
        self._counters = collections.defaultdict(
            int, state.get("counters", {})
        )
        self._episodes_total = state.get("episodes_total", 0)

    def cleanup(self) -> None:
        try:
            self.env.close()
        except Exception:
            pass
        super().cleanup()


# default example-env registration so tuned_examples yamls resolve it
from ray_tpu.env.registry import register_env  # noqa: E402

register_env("PointGoal-v0", lambda cfg: PointGoalEnv(cfg))
