from ray_tpu.algorithms.maml.maml import (
    MAML,
    MAMLConfig,
    PointGoalEnv,
)

__all__ = ["MAML", "MAMLConfig", "PointGoalEnv"]
