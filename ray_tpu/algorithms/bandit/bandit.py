"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Counterpart of the reference's ``rllib/algorithms/bandit/bandit.py``
(BanditLinUCB/BanditLinTS) and the OnlineLinearRegression arm model
(``bandit_torch_model.py:12``): per-arm exact Bayesian linear
regression over the context, with UCB or posterior-sampling action
scores.

TPU-first: all arms' sufficient statistics live as stacked tensors
(precision: (A, d, d), moment: (A, d)) and BOTH the per-step scoring
and the batched rank-1 update are single jitted programs — no per-arm
Python loops."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.execution.rollout_ops import synchronous_parallel_sample
from ray_tpu.policy.policy import Policy


class BanditConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class)
        self.framework_str = "jax"
        self.rollout_fragment_length = 1
        self.train_batch_size = 1
        self.lambda_reg = 0.1  # ridge prior precision
        self.min_time_s_per_iteration = None


class BanditLinUCBConfig(BanditConfig):
    """reference bandit.py:64."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or BanditLinUCB)
        self.ucb_coeff = 1.0


class BanditLinTSConfig(BanditConfig):
    """reference bandit.py:41."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or BanditLinTS)
        self.sample_theta_std = 1.0


class LinearBanditPolicy(Policy):
    """Stacked per-arm online linear regression (reference
    OnlineLinearRegression) with jitted score + update programs."""

    exploit = "ucb"  # or "ts"

    def __init__(self, observation_space, action_space, config: Dict):
        super().__init__(observation_space, action_space, config)
        if not isinstance(action_space, gym.spaces.Discrete):
            raise ValueError("bandits require a Discrete action space")
        self.num_arms = int(action_space.n)
        self.dim = int(np.prod(observation_space.shape))
        lam = float(config.get("lambda_reg", 0.1))
        # precision (A, d, d) starts at lambda*I; moment vector (A, d)
        self.precision = jnp.tile(
            (lam * jnp.eye(self.dim))[None], (self.num_arms, 1, 1)
        )
        self.moment = jnp.zeros((self.num_arms, self.dim))
        self._rng = jax.random.PRNGKey(int(config.get("seed") or 0))
        self.ucb_coeff = float(config.get("ucb_coeff", 1.0))
        self.ts_std = float(config.get("sample_theta_std", 1.0))
        self._score_fn = None
        self._update_fn = None

    # -- scoring ----------------------------------------------------------

    def _build_score_fn(self):
        exploit = self.exploit
        ucb_coeff = self.ucb_coeff
        ts_std = self.ts_std

        def fn(precision, moment, ctx, rng, explore):
            # ctx: (B, d). theta_a = P_a^-1 b_a for every arm at once.
            cov = jnp.linalg.inv(precision)  # (A, d, d)
            theta = jnp.einsum("aij,aj->ai", cov, moment)  # (A, d)
            mean = jnp.einsum("bd,ad->ba", ctx, theta)  # (B, A)
            if not explore:
                return jnp.argmax(mean, axis=-1), mean
            if exploit == "ucb":
                var = jnp.einsum("bi,aij,bj->ba", ctx, cov, ctx)
                score = mean + ucb_coeff * jnp.sqrt(
                    jnp.maximum(var, 1e-12)
                )
            else:  # Thompson sampling from N(theta, std^2 * cov)
                chol = jnp.linalg.cholesky(
                    cov + 1e-8 * jnp.eye(cov.shape[-1])[None]
                )
                noise = jax.random.normal(
                    rng, (theta.shape[0], theta.shape[1])
                )
                theta_s = theta + ts_std * jnp.einsum(
                    "aij,aj->ai", chol, noise
                )
                score = jnp.einsum("bd,ad->ba", ctx, theta_s)
            return jnp.argmax(score, axis=-1), score

        return jax.jit(fn, static_argnames=("explore",))

    def compute_actions(
        self, obs_batch, state_batches=None, explore=True, **kwargs
    ):
        if self._score_fn is None:
            self._score_fn = self._build_score_fn()
        ctx = jnp.asarray(obs_batch, jnp.float32).reshape(
            len(obs_batch), -1
        )
        self._rng, rng = jax.random.split(self._rng)
        actions, _ = self._score_fn(
            self.precision, self.moment, ctx, rng, bool(explore)
        )
        return np.asarray(actions), [], {}

    # -- learning: exact posterior update ---------------------------------

    def _build_update_fn(self):
        num_arms = self.num_arms

        def fn(precision, moment, ctx, actions, rewards):
            # batched rank-1 updates, scattered per arm via one-hot
            onehot = jax.nn.one_hot(actions, num_arms)  # (B, A)
            outer = jnp.einsum("bi,bj->bij", ctx, ctx)  # (B, d, d)
            precision = precision + jnp.einsum(
                "ba,bij->aij", onehot, outer
            )
            moment = moment + jnp.einsum(
                "ba,b,bi->ai", onehot, rewards, ctx
            )
            return precision, moment

        return jax.jit(fn)

    def learn_on_batch(self, samples: SampleBatch) -> Dict:
        if self._update_fn is None:
            self._update_fn = self._build_update_fn()
        ctx = jnp.asarray(
            samples[SampleBatch.OBS], jnp.float32
        ).reshape(samples.count, -1)
        actions = jnp.asarray(samples[SampleBatch.ACTIONS], jnp.int32)
        rewards = jnp.asarray(
            samples[SampleBatch.REWARDS], jnp.float32
        )
        self.precision, self.moment = self._update_fn(
            self.precision, self.moment, ctx, actions, rewards
        )
        return {
            "update_count": int(samples.count),
            "mean_reward": float(rewards.mean()),
        }

    # -- state ------------------------------------------------------------

    def get_weights(self):
        return {
            "precision": np.asarray(self.precision),
            "moment": np.asarray(self.moment),
        }

    def set_weights(self, weights) -> None:
        self.precision = jnp.asarray(weights["precision"])
        self.moment = jnp.asarray(weights["moment"])


class _UCBPolicy(LinearBanditPolicy):
    exploit = "ucb"


class _TSPolicy(LinearBanditPolicy):
    exploit = "ts"


class _BanditBase(Algorithm):
    def training_step(self) -> Dict:
        batch = synchronous_parallel_sample(
            worker_set=self.workers,
            max_env_steps=self.config.get("train_batch_size", 1),
        )
        if hasattr(batch, "policy_batches"):
            batch = batch.policy_batches[DEFAULT_POLICY_ID]
        self._counters[NUM_ENV_STEPS_SAMPLED] += batch.env_steps()
        self._counters[NUM_AGENT_STEPS_SAMPLED] += batch.env_steps()
        info = self.get_policy().learn_on_batch(batch)
        self.workers.sync_weights()
        return {DEFAULT_POLICY_ID: info}


class BanditLinUCB(_BanditBase):
    _default_policy_class = _UCBPolicy

    @classmethod
    def get_default_config(cls) -> BanditLinUCBConfig:
        return BanditLinUCBConfig(cls)


class BanditLinTS(_BanditBase):
    _default_policy_class = _TSPolicy

    @classmethod
    def get_default_config(cls) -> BanditLinTSConfig:
        return BanditLinTSConfig(cls)
