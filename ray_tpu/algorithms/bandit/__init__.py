from ray_tpu.algorithms.bandit.bandit import (
    BanditLinTS,
    BanditLinTSConfig,
    BanditLinUCB,
    BanditLinUCBConfig,
)

__all__ = [
    "BanditLinTS",
    "BanditLinTSConfig",
    "BanditLinUCB",
    "BanditLinUCBConfig",
]
